"""Device-resident table epochs: versioned double-buffered publication.

The serving path used to re-upload every PolicyTables leaf after each
control-plane publish (device_put of ~hundreds of MB of numpy per
flip).  This store keeps TWO device-resident epochs ping-ponging, the
device analog of the realized/backup map shuffle
(pkg/datapath/ipcache/listener.go:167):

  * `publish(tables, delta)` installs the new generation into the
    SPARE epoch.  With a TableDelta covering the spare's stamp, the
    update is a compact jitted scatter (`tables.at[idx].set(rows)`,
    donate_argnums on the spare pytree so XLA patches the resident
    buffers in place) — bytes shipped are proportional to the CHANGE,
    not the world.  Without a delta (shape-class change, stale spare)
    it falls back to a full upload.
  * in-flight batches dispatched against the CURRENT epoch finish on
    it untouched; only the spare's buffers are donated.
  * `check_current` raises for tables whose epoch has since been
    donated — the device-side extension of
    FleetCompiler.check_tables_current's one-flip window.

Replication: pass `shardings` (a PolicyTables pytree of NamedSharding)
and every chip of a mesh receives the same scatter — tables are
replicated across the mesh (engine/sharded.py), so one delta updates
the whole fleet of chips.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from cilium_tpu import faultinject, tracing
from cilium_tpu.compiler.delta import TableDelta, tables_nbytes
from cilium_tpu.compiler.tables import (
    COLD_LEAVES,
    PolicyTables,
    split_hot,
    tables_layout_version,
)
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics

log = get_logger("publish")

# low bits of a layout stamp carrying the hashed-table pack widths;
# the high bits are the hot/cold coldness mask (see
# tables_layout_version) — the store compares pack widths across the
# delta/epoch seam and owns the coldness decision itself
_LAYOUT_LANES_MASK = (1 << 22) - 1


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1): the single size-class
    rounding every scatter/repair/re-split payload shares, so the
    jit caches keyed on padded shapes can never drift apart."""
    size = 1
    while size < n:
        size <<= 1
    return size


def _pad_pow2(update):
    """Pad scatter payloads to the next power of two by repeating the
    last entry (duplicate identical writes are deterministic), so the
    jitted updater recompiles per size CLASS instead of per size."""
    k = len(update.values)
    size = next_pow2(k)
    if size == k:
        return update.idx, update.values
    pad = size - k
    idx = tuple(
        np.concatenate([i, np.repeat(i[-1:], pad)]) for i in update.idx
    )
    values = np.concatenate(
        [update.values, np.repeat(update.values[-1:], pad, axis=0)]
    )
    return idx, values


def _chip_resident_bytes(dev_tables) -> Dict[int, int]:
    """Actual per-device resident bytes of a device table pytree,
    summed from each leaf's addressable shards — the measured (not
    modeled) per-chip HBM footprint of one epoch.  On a sharded
    store the identity-major leaves contribute 1/num_shards per
    chip; replicated leaves contribute their full size everywhere."""
    import jax

    per: Dict[int, int] = {}
    for leaf in jax.tree.leaves(dev_tables):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for sh in shards:
            ordinal = int(sh.device.id)
            per[ordinal] = per.get(ordinal, 0) + int(sh.data.nbytes)
    return per


@dataclass
class PublishStats:
    epoch: int
    mode: str  # "full" | "delta"
    bytes_h2d: int
    seconds: float
    scatter_leaves: int = 0
    replaced_leaves: int = 0


class StaleEpochError(ValueError):
    pass


class DeviceTableStore:
    """Two device table epochs with scatter-delta publication.

    With `hot_only=True` every published epoch carries only the HOT
    leaf plane (compiler.tables.HOT_LEAVES — the words the fused
    hashed-probe kernels can ever gather); the COLD leaves (the 32 MB
    port_slot and the dense allow bitmap, the two largest tables by
    an order of magnitude) never reach the device, and deltas
    touching them are filtered before the scatter.  Epochs carry a
    layout stamp (tables_layout_version): a delta recorded against a
    different pack width or leaf split than the resident spare is
    refused and the publish falls back to a full upload."""

    def __init__(
        self,
        shardings: Optional[PolicyTables] = None,
        hot_only: bool = False,
        shardings_fn=None,
        partition_digest: int = 0,
        transform_fn=None,
        delta_transform_fn=None,
    ) -> None:
        self._lock = threading.Lock()
        # each slot: dict(tables=<device pytree>, stamp=int,
        # epoch=int, layout=int, chip_bytes={ordinal: bytes},
        # host=<the transformed host pytree the epoch was placed
        # from — retained as the repair scatter's value source>)
        self._slots = [None, None]
        self._cur = 0
        self._epoch = 0
        self._shardings = shardings
        # shape-aware sharding resolver (tables → NamedShardings
        # pytree, e.g. compiler.partition.table_shardings bound to a
        # mesh): the partition rules are declarative but leaf
        # divisibility depends on the published shapes, so the
        # resolved pytree is recomputed per publish
        self._shardings_fn = shardings_fn
        self._hot_only = hot_only
        # rule-table digest (compiler.partition.partition_digest),
        # folded into every epoch's layout stamp: a delta recorded
        # against one partitioning can never scatter into an epoch
        # laid out under another
        self.partition_digest = int(partition_digest)
        # device-layout transform (e.g. the N+1 replica augmentation,
        # compiler.partition.replicate_table_leaves): applied to the
        # host tables before placement; `delta_transform_fn(delta,
        # pre_transform_tables)` rewrites a delta recorded against
        # the un-transformed layout into device coordinates so the
        # scatter path keeps every copy bit-identical
        self._transform_fn = transform_fn
        self._delta_transform_fn = delta_transform_fn
        # the repair scatter (repair_rows) reads its values from the
        # epoch's retained host pytree — only stores with a device
        # layout seam (replica stores) have that consumer; a plain
        # store must not pin two extra full host copies in RAM
        self._retain_host = (
            transform_fn is not None or delta_transform_fn is not None
        )
        # per-chip outage ledger (the re-admission rebalance feed):
        # ordinal -> {"epoch": epoch at mark-out, "missed":
        # [transformed TableDelta...], "needs_full": bool} — every
        # publish that lands while a chip's breaker is open records
        # what that chip missed, so readmission can replay exactly
        # those rows through the delta-scatter path instead of a
        # full upload
        self._out_chips: Dict[int, Dict] = {}
        self._missed_cap = 32
        self._apply_cache: Dict[tuple, object] = {}
        self._apply_nodonate_cache: Dict[tuple, object] = {}
        self._repair_cache: Dict[tuple, object] = {}
        # open relayout window (engine/reshard.py): while set, the
        # SPARE slot holds the migration target epoch (laid out
        # under the NEW digest) and publish() must not consume it —
        # churn deltas patch the LIVE slot in place (non-donated),
        # full publishes replace the live slot AND mark the window
        # broken (the plan's deterministic full-upload-into-target
        # restart)
        self._relayout: Optional[Dict] = None

    # -- device placement ----------------------------------------------------

    def _put(self, value, leaf: Optional[str] = None):
        import jax

        if self._shardings is None:
            return jax.device_put(value)
        sharding = (
            getattr(self._shardings, leaf)
            if leaf is not None and hasattr(self._shardings, leaf)
            else None
        )
        if sharding is None:
            # payload arrays replicate (every chip applies the same
            # scatter); use any leaf's mesh via the generation spec
            sharding = self._shardings.generation
        return jax.device_put(value, sharding)

    def _put_tables(self, tables: PolicyTables):
        import jax

        if self._shardings is None:
            return jax.device_put(tables)
        return jax.tree.map(
            lambda leaf, s: (
                None if leaf is None else jax.device_put(leaf, s)
            ),
            tables,
            self._shardings,
            is_leaf=lambda x: x is None,
        )

    # -- scatter updater -----------------------------------------------------

    def _apply_fn(self, fields: Tuple[str, ...], donate: bool = True):
        """Jitted scatter: patch `fields` of an epoch and stamp the
        new generation.  Cached per field set (payload shapes are
        pow2-padded, so the per-set jit cache stays small).  With
        `donate=False` the input pytree's buffers are NOT consumed —
        the publish-during-relayout path patches the LIVE epoch into
        a fresh pytree while batches may still be in flight against
        the old one (the zero-drain seam)."""
        import jax

        cache = self._apply_cache if donate else self._apply_nodonate_cache
        fn = cache.get(fields)
        if fn is not None:
            return fn

        def apply(tables, payloads, generation):
            kw = {}
            for name, (idx, values) in zip(fields, payloads):
                kw[name] = getattr(tables, name).at[idx].set(values)
            kw["generation"] = generation
            return dataclasses.replace(tables, **kw)

        # jit-cache observability rides the scatter entry point: a
        # payload outside the known pow2 classes shows up as a miss +
        # compile seconds in the same scrape as the publish bytes
        fn = tracing.track_jit(
            jax.jit(apply, donate_argnums=(0,) if donate else ()),
            "publish.scatter" if donate else "publish.scatter_live",
        )
        cache[fields] = fn
        return fn

    # -- publication ---------------------------------------------------------

    def publish(
        self, tables: PolicyTables, delta: Optional[TableDelta] = None
    ) -> Tuple[PolicyTables, PublishStats]:
        """Install `tables` (host arrays) as the new current epoch.
        `delta` must describe every change from the SPARE slot's stamp
        to `tables` (see FleetCompiler.delta_for); anything else —
        or delta=None — forces a full upload."""
        import jax

        with self._lock, tracing.tracer.span(
            "publish.epoch", site="engine.publish"
        ) as sp:
            t0 = time.perf_counter()
            if self._hot_only:
                tables = split_hot(tables)
            pre_transform = tables
            if self._transform_fn is not None:
                tables = self._transform_fn(tables)
            if self._shardings_fn is not None:
                self._shardings = self._shardings_fn(tables)
            layout = tables_layout_version(tables) | (
                self.partition_digest << 32
            )
            spare_i = self._cur ^ 1
            spare = self._slots[spare_i]
            stamp = int(np.asarray(tables.generation))
            if (
                self._relayout is not None
                and not self._relayout.get("broken")
            ):
                # the spare slot is the staged migration target —
                # churn must not consume it (engine/reshard.py keeps
                # the target current through the plan's dual-apply)
                return self._publish_relayout_locked(
                    tables, pre_transform, delta, layout, stamp,
                    sp, t0,
                )
            use_delta = (
                delta is not None
                and spare is not None
                and spare["stamp"] == delta.base_stamp
                and stamp == delta.new_stamp
                # layout guard: a delta's scatter indices are only
                # meaningful against the exact hot/cold + pack-width
                # layout the spare epoch holds (pack widths must
                # match end to end; coldness is the store's own
                # setting, already applied to both sides)
                and spare["layout"] == layout
                and (delta.layout & _LAYOUT_LANES_MASK)
                == (layout & _LAYOUT_LANES_MASK)
            )
            if use_delta and self._delta_transform_fn is not None:
                # rewrite into device coordinates (the delta was
                # recorded against the un-transformed layout; the
                # geometry it maps from is the pre-transform pytree)
                delta = self._delta_transform_fn(delta, pre_transform)
            if use_delta:
                try:
                    dev, stats = self._publish_delta(
                        spare["tables"], tables, delta
                    )
                except faultinject.FaultInjected as exc:
                    # the publish.scatter seam fired: the scatter is
                    # poisoned before the donated apply runs, but the
                    # spare's row bookkeeping can no longer be
                    # trusted either way — de-register the slot and
                    # serve THIS publish through the full-upload
                    # path.  The control plane degrades to bytes
                    # spent, never to a half-patched epoch: the
                    # fallback is the refusal path the chaos/fuzz
                    # schedules assert bit-identity across.
                    self._slots[spare_i] = None
                    use_delta = False
                    metrics.publish_fallback_total.inc()
                    sp.attrs["fallback"] = str(exc)
                    log.warning(
                        "delta publish scatter faulted; falling "
                        "back to full upload",
                        extra={"fields": {"error": str(exc)}},
                    )
                except Exception:
                    # the donated scatter may have consumed the spare
                    # epoch's buffers before failing — de-register the
                    # slot so the next publish full-uploads instead of
                    # scattering into deleted arrays forever
                    self._slots[spare_i] = None
                    self._sample_bytes()
                    raise
                else:
                    # the standby's resident buffers were donated
                    # (patched in place) — HBM reused, not reallocated
                    metrics.device_table_retired_bytes.inc(
                        value=spare.get("nbytes", 0)
                    )
            if not use_delta:
                dev = self._put_tables(tables)
                jax.block_until_ready(dev)
                stats = PublishStats(
                    epoch=0, mode="full", bytes_h2d=tables_nbytes(tables),
                    seconds=0.0,
                )
            self._epoch += 1
            self._slots[spare_i] = {
                "tables": dev, "stamp": stamp, "epoch": self._epoch,
                "nbytes": tables_nbytes(tables), "layout": layout,
                "chip_bytes": _chip_resident_bytes(dev),
                "host": tables if self._retain_host else None,
                "shardings": self._shardings,
            }
            self._cur = spare_i
            stats.epoch = self._epoch
            stats.seconds = time.perf_counter() - t0
            # outage ledger: record what every marked-out chip just
            # missed — a delta publish is replayable row-by-row at
            # readmission; a full upload (or an overflowing miss
            # list) downgrades the rebalance to a whole-slice replay
            for rec in self._out_chips.values():
                if (
                    use_delta
                    and not rec["needs_full"]
                    and len(rec["missed"]) < self._missed_cap
                ):
                    rec["missed"].append(delta)
                else:
                    rec["needs_full"] = True
            self._sample_bytes()
            sp.attrs.update(
                mode=stats.mode, epoch=stats.epoch,
                bytes_h2d=stats.bytes_h2d,
                scatter_leaves=stats.scatter_leaves,
                replaced_leaves=stats.replaced_leaves,
            )
            return dev, stats

    def _publish_relayout_locked(
        self, tables, pre_transform, delta, layout, stamp, sp, t0
    ):
        """Publish while a relayout window is open (caller holds the
        lock).  The spare slot is the staged migration target and
        must not be consumed, so churn lands on the LIVE slot:

          * a valid delta against the live epoch patches it through a
            NON-donated scatter — the previous pytree's buffers stay
            intact for every batch still in flight against them (the
            zero-drain seam; the old pytree is simply dropped when
            the last reference goes);
          * anything else (stale delta, shape-class change, a fault
            on the scatter seam) full-uploads into the live slot and
            marks the window BROKEN: the migration plan observes the
            flag and deterministically restarts as a full upload into
            the target layout.
        """
        import jax

        live_i = self._cur
        live = self._slots[live_i]
        use_delta = (
            delta is not None
            and live is not None
            and live["stamp"] == delta.base_stamp
            and stamp == delta.new_stamp
            and live["layout"] == layout
            and (delta.layout & _LAYOUT_LANES_MASK)
            == (layout & _LAYOUT_LANES_MASK)
        )
        if use_delta and self._delta_transform_fn is not None:
            delta = self._delta_transform_fn(delta, pre_transform)
        if use_delta:
            try:
                dev, stats = self._publish_delta(
                    live["tables"], tables, delta, donate=False
                )
            except faultinject.FaultInjected as exc:
                # nothing was donated — the live epoch is intact,
                # but the scatter path is poisoned: serve this
                # publish as a full upload (which breaks the window
                # below, the plan's deterministic restart trigger)
                use_delta = False
                metrics.publish_fallback_total.inc()
                sp.attrs["fallback"] = str(exc)
                log.warning(
                    "delta publish scatter faulted during relayout; "
                    "falling back to full upload",
                    extra={"fields": {"error": str(exc)}},
                )
        if not use_delta:
            dev = self._put_tables(tables)
            jax.block_until_ready(dev)
            stats = PublishStats(
                epoch=0, mode="full",
                bytes_h2d=tables_nbytes(tables), seconds=0.0,
            )
            self._relayout["broken"] = True
            sp.attrs["relayout_broken"] = True
        self._epoch += 1
        self._slots[live_i] = {
            "tables": dev, "stamp": stamp, "epoch": self._epoch,
            "nbytes": tables_nbytes(tables), "layout": layout,
            "chip_bytes": _chip_resident_bytes(dev),
            "host": tables if self._retain_host else None,
            "shardings": self._shardings,
        }
        stats.epoch = self._epoch
        stats.seconds = time.perf_counter() - t0
        for rec in self._out_chips.values():
            if (
                use_delta
                and not rec["needs_full"]
                and len(rec["missed"]) < self._missed_cap
            ):
                rec["missed"].append(delta)
            else:
                rec["needs_full"] = True
        self._sample_bytes()
        sp.attrs.update(
            mode=stats.mode, epoch=stats.epoch,
            bytes_h2d=stats.bytes_h2d,
            scatter_leaves=stats.scatter_leaves,
            replaced_leaves=stats.replaced_leaves, relayout=True,
        )
        return dev, stats

    # -- live elastic resharding (engine/reshard.py drives these) ------------

    def begin_relayout(
        self, host_aug, moved_rows, shardings, partition_digest
    ) -> Tuple[int, int]:
        """Open a relayout window: install the migration TARGET
        epoch (already transformed/augmented for the target mesh)
        into the SPARE slot while the live epoch keeps serving.

        `moved_rows` ({leaf: (axis, index array)} from
        compiler.partition.reshard_moved_rows) names every augmented
        row whose bytes are NOT device-resident under the source
        column assignment.  The staged device epoch is seeded from
        `host_aug` with those rows ZEROED — the epoch only becomes
        correct as the migration scatters (repair_rows(spare=True))
        stream them in, so cutover bit-identity proves the streamed
        bytes rather than the seed.  The TRUE target host is
        retained on the slot as the scatter's value source.

        Returns (epoch, layout) — the pins every subsequent
        migration step must present."""
        import jax

        with self._lock, tracing.tracer.span(
            "publish.begin_relayout", site="engine.publish"
        ) as sp:
            if self._relayout is not None:
                raise RuntimeError("relayout window already open")
            if self._slots[self._cur] is None:
                raise RuntimeError("no live epoch to reshard from")
            layout = tables_layout_version(host_aug) | (
                int(partition_digest) << 32
            )
            kw = {}
            for name, (axis, idx) in moved_rows.items():
                arr = np.array(np.asarray(getattr(host_aug, name)))
                idx = np.asarray(idx, np.int64)
                if idx.size:
                    arr[(slice(None),) * int(axis) + (idx,)] = 0
                kw[name] = arr
            seed = (
                dataclasses.replace(host_aug, **kw) if kw else host_aug
            )
            dev = jax.tree.map(
                lambda leaf, s: (
                    None if leaf is None else jax.device_put(leaf, s)
                ),
                seed, shardings,
                is_leaf=lambda x: x is None,
            )
            jax.block_until_ready(dev)
            self._epoch += 1
            spare_i = self._cur ^ 1
            self._slots[spare_i] = {
                "tables": dev,
                "stamp": int(np.asarray(host_aug.generation)),
                "epoch": self._epoch,
                "nbytes": tables_nbytes(host_aug),
                "layout": layout,
                "chip_bytes": _chip_resident_bytes(dev),
                "host": host_aug,
                "shardings": shardings,
            }
            self._relayout = {
                "epoch": self._epoch, "layout": layout,
                "broken": False, "shardings": shardings,
                "digest": int(partition_digest),
            }
            self._sample_bytes()
            sp.attrs.update(epoch=self._epoch, layout=layout)
            return self._epoch, layout

    def relayout_state(self) -> Optional[Dict]:
        """{"epoch", "layout", "broken"} of the open relayout
        window, or None — the plan's restart detector."""
        with self._lock:
            rel = self._relayout
            if rel is None:
                return None
            return {
                "epoch": rel["epoch"], "layout": rel["layout"],
                "broken": bool(rel.get("broken")),
            }

    def relayout_update_host(self, host_aug) -> Tuple[int, int]:
        """Replace the staged target epoch's retained host — the
        churn dual-apply: migration scatters issued after this read
        the NEW values (the plan re-queues rows whose contents
        changed), and the staged epoch's generation leaf is
        re-placed on device so its stamp tracks the live world.
        Refused when no window is open or the window broke."""
        import jax

        with self._lock:
            rel = self._relayout
            if rel is None or rel.get("broken"):
                raise RuntimeError(
                    "no open relayout window to update"
                )
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is None or slot["epoch"] != rel["epoch"]:
                raise RuntimeError("staged relayout epoch is gone")
            stamp = int(np.asarray(host_aug.generation))
            gen_dev = jax.device_put(
                np.uint64(np.asarray(host_aug.generation)),
                rel["shardings"].generation,
            )
            # non-donated replace: only the generation leaf is
            # re-placed; the table leaves stay resident and the
            # migration scatters keep patching them
            slot["tables"] = dataclasses.replace(
                slot["tables"], generation=gen_dev
            )
            layout = tables_layout_version(host_aug) | (
                rel["digest"] << 32
            )
            slot["host"] = host_aug
            slot["stamp"] = stamp
            slot["nbytes"] = tables_nbytes(host_aug)
            slot["layout"] = layout
            rel["layout"] = layout
            return slot["epoch"], layout

    def cutover_relayout(
        self,
        shardings_fn=None,
        partition_digest=None,
        transform_fn=None,
        delta_transform_fn=None,
    ) -> int:
        """Flip the staged target epoch live — the reshard cutover.
        Zero-drain by construction: the previous live epoch's
        buffers are never donated or touched; it remains resident as
        the source-layout spare, whose next delta publish is
        layout-refused (the digests differ by ntp) into exactly one
        full upload, after which deltas resume.  Rebinds the store's
        partition seams (sharding resolver, digest, augmentation,
        delta rewrite) so subsequent publishes land under the NEW
        layout.  Refused while broken — the migration must restart
        instead of cutting over to a stale target."""
        with self._lock, tracing.tracer.span(
            "publish.cutover_relayout", site="engine.publish"
        ) as sp:
            rel = self._relayout
            if rel is None:
                raise RuntimeError("no open relayout window")
            if rel.get("broken"):
                raise RuntimeError(
                    "relayout window broken by a full publish; "
                    "cutover refused — restart the migration"
                )
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is None or slot["epoch"] != rel["epoch"]:
                raise RuntimeError(
                    "staged relayout epoch is gone; cutover refused"
                )
            self._cur = spare_i
            self._relayout = None
            self._shardings = rel["shardings"]
            if shardings_fn is not None:
                self._shardings_fn = shardings_fn
            if partition_digest is not None:
                self.partition_digest = int(partition_digest)
            if transform_fn is not None:
                self._transform_fn = transform_fn
            if delta_transform_fn is not None:
                self._delta_transform_fn = delta_transform_fn
            self._retain_host = (
                self._transform_fn is not None
                or self._delta_transform_fn is not None
            )
            # jit entries traced against the source mesh would pin
            # stale executables (and their donated-buffer shapes)
            self._apply_cache.clear()
            self._apply_nodonate_cache.clear()
            self._repair_cache.clear()
            self._sample_bytes()
            sp.attrs.update(
                epoch=slot["epoch"], layout=slot["layout"]
            )
            return slot["epoch"]

    def rollback_relayout(self) -> bool:
        """Abandon the staged target epoch: the spare slot is
        dropped (nothing was ever donated from the live epoch, so
        the fully-consistent source layout keeps serving untouched)
        and the next publish full-uploads into the freed slot.
        Returns True when a window was open."""
        with self._lock:
            rel = self._relayout
            if rel is None:
                return False
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is not None and slot["epoch"] == rel["epoch"]:
                self._slots[spare_i] = None
            self._relayout = None
            self._sample_bytes()
            return True

    def _sample_bytes(self) -> None:
        """cilium_device_table_bytes{epoch}: per-slot resident bytes,
        sampled at every publish (caller holds the lock) — the HBM
        line of the device-resource accounting plane."""
        cur = self._slots[self._cur]
        spare = self._slots[self._cur ^ 1]
        metrics.device_table_bytes.set(
            "live", value=(cur or {}).get("nbytes", 0)
        )
        metrics.device_table_bytes.set(
            "standby", value=(spare or {}).get("nbytes", 0)
        )
        # cilium_device_table_bytes_per_chip{chip}: per-shard
        # resident bytes over both epoch slots — identity-sharded
        # leaves divide across chips, replicated ones repeat, so the
        # per-chip line is what the universe headroom model bounds
        for ordinal, nbytes in sorted(self._chip_bytes_locked().items()):
            metrics.device_table_bytes_per_chip.set(
                str(ordinal), value=nbytes
            )

    def _publish_delta(
        self,
        spare_dev: PolicyTables,
        tables: PolicyTables,
        delta: TableDelta,
        donate: bool = True,
    ):
        import jax

        # the publish.scatter fault seam, probed once per device
        # ordinal holding a slice of the spare epoch (chip-scoped
        # schedules poison the scatter only when their chip is a
        # recipient; unscoped schedules fire on the first probe).
        # publish() catches the FaultInjected and falls back to a
        # full upload — the spare's buffers are still intact here,
        # but its bookkeeping is de-registered conservatively.
        # Nothing-armed (production churn) must not pay the ordinal
        # enumeration: the whole setup gates on the same lock-free
        # emptiness read the fault verbs use.
        if faultinject.any_armed():
            ordinals = sorted(_chip_resident_bytes(spare_dev))
            if ordinals:
                for ordinal in ordinals:
                    faultinject.fire("publish.scatter", chip=ordinal)
            else:
                faultinject.fire("publish.scatter")

        n_scatter = 0
        n_replace = 0
        bytes_h2d = 0
        # hot-only epochs never receive cold-plane payloads — their
        # leaves are None on device and the host arrays are the
        # authority for the cold plane anyway
        skip = set(COLD_LEAVES) if self._hot_only else ()
        # whole-leaf replacements land outside the jit: fresh uploads
        # swapped into the donated pytree (the old leaf is dropped)
        replaced = {}
        for name, arr in delta.replace.items():
            if name in skip:
                continue
            replaced[name] = self._put(arr, name)
            bytes_h2d += np.asarray(arr).nbytes
            n_replace += 1
        base = spare_dev
        if replaced:
            base = dataclasses.replace(base, **replaced)
        fields = tuple(
            sorted(n for n in delta.updates if n not in skip)
        )
        gen_dev = self._put(np.uint64(np.asarray(tables.generation)))
        if fields:
            payloads = []
            for name in fields:
                idx, values = _pad_pow2(delta.updates[name])
                payloads.append(
                    (
                        tuple(self._put(i) for i in idx),
                        self._put(values),
                    )
                )
                bytes_h2d += delta.updates[name].nbytes
                n_scatter += 1
            dev = self._apply_fn(fields, donate=donate)(
                base, tuple(payloads), gen_dev
            )
        else:
            dev = dataclasses.replace(base, generation=gen_dev)
        jax.block_until_ready(dev)
        return dev, PublishStats(
            epoch=0, mode="delta", bytes_h2d=bytes_h2d,
            seconds=0.0, scatter_leaves=n_scatter,
            replaced_leaves=n_replace,
        )

    # -- consumers -----------------------------------------------------------

    def current(self) -> Optional[Tuple[int, PolicyTables]]:
        with self._lock:
            slot = self._slots[self._cur]
            if slot is None:
                return None
            return slot["epoch"], slot["tables"]

    def current_stamp(self) -> Optional[int]:
        with self._lock:
            slot = self._slots[self._cur]
            return None if slot is None else slot["stamp"]

    def get(self, stamp: int) -> Optional[PolicyTables]:
        """The live epoch carrying `stamp`, if still resident (a
        reader that snapshotted an older publish reuses its epoch
        instead of flipping the store backward)."""
        with self._lock:
            for slot in self._slots:
                if slot is not None and slot["stamp"] == stamp:
                    return slot["tables"]
            return None

    def spare_stamp(self) -> Optional[int]:
        """Stamp held by the standby epoch — the base the next delta
        must cover."""
        with self._lock:
            spare = self._slots[self._cur ^ 1]
            return None if spare is None else spare["stamp"]

    def live_stamps(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(
                s["stamp"] for s in self._slots if s is not None
            )

    def chip_bytes(self) -> Dict[int, int]:
        """Measured per-chip resident bytes over both epoch slots —
        the numbers behind cilium_device_table_bytes_per_chip."""
        with self._lock:
            return self._chip_bytes_locked()

    def _chip_bytes_locked(self) -> Dict[int, int]:
        per: Dict[int, int] = {}
        for slot in self._slots:
            for ordinal, nbytes in (
                (slot or {}).get("chip_bytes", {}) or {}
            ).items():
                per[ordinal] = per.get(ordinal, 0) + nbytes
        return per

    # -- per-chip outage / re-admission rebalance ----------------------------

    def mark_chip_out(self, ordinal: int) -> None:
        """Start the outage ledger for a chip whose breaker opened:
        every publish from now on records what this chip missed.
        Idempotent — a re-open after a failed half-open probe keeps
        the original ledger (the chip still misses everything since
        its first failure)."""
        with self._lock:
            self._out_chips.setdefault(
                int(ordinal),
                {"epoch": self._epoch, "missed": [],
                 "needs_full": False},
            )

    def chip_outage(self, ordinal: int) -> Optional[Dict]:
        with self._lock:
            rec = self._out_chips.get(int(ordinal))
            if rec is None:
                return None
            return {
                "epoch": rec["epoch"],
                "missed": list(rec["missed"]),
                "needs_full": rec["needs_full"],
            }

    def readmit_chip(self, ordinal: int) -> Optional[Dict]:
        """Close the outage ledger and return it (the failover
        router converts it into the owned-row repair scatter).  A
        SPARE epoch published during the outage is semantically
        stale on the chip's slice; when the slot retains its host
        pytree (replica stores do — it is the repair value source)
        the record comes back with ``spare_stale`` set and the
        router REPAIRS the chip's whole owned regions of the spare
        from that retained snapshot (`repair_rows(..., spare=True)`)
        — bytes proportional to one chip's slice, not a full
        upload.  Only a plain store without a retained host still
        de-registers the spare (the next publish full-uploads)."""
        with self._lock:
            rec = self._out_chips.pop(int(ordinal), None)
            if rec is None:
                return None
            live = self._slots[self._cur]
            spare = self._slots[self._cur ^ 1]
            # layout pins for the repair scatters: a readmission
            # racing an in-flight migration must repair each epoch
            # against the layout THAT slot actually holds — the
            # caller computed its owned-row sets under one column
            # assignment, and scattering them into an epoch laid out
            # under another (e.g. the staged reshard target) would
            # plant source-layout rows in a target-layout spare
            rec["live_layout"] = (
                None if live is None else live["layout"]
            )
            rec["spare_layout"] = (
                None if spare is None else spare["layout"]
            )
            if spare is not None and spare["epoch"] > rec["epoch"]:
                if spare.get("host") is not None:
                    rec["spare_stale"] = True
                    # the repair must land on THIS epoch: a publish
                    # interleaved before the repair flips the slots
                    # (repair_rows verifies the epoch and refuses);
                    # the store's own counter, not the table stamp —
                    # distinct epochs can share a stamp
                    rec["spare_epoch"] = spare["epoch"]
                else:
                    self._slots[self._cur ^ 1] = None
            return rec

    def restore_outage(self, ordinal: int, rec: Dict) -> None:
        """Put a popped ledger back after a FAILED repair: the
        scatter may have partially landed, so the restored record is
        downgraded to needs_full — the next readmission replays the
        chip's whole owned regions instead of trusting row-level
        bookkeeping the failure invalidated.  Merges with any record
        a concurrent re-open already created."""
        rec["needs_full"] = True
        with self._lock:
            existing = self._out_chips.get(int(ordinal))
            if existing is None:
                self._out_chips[int(ordinal)] = rec
            else:
                existing["epoch"] = min(
                    existing["epoch"], rec["epoch"]
                )
                existing["needs_full"] = True

    def _repair_fn(self, fields: Tuple[str, ...],
                   axes: Tuple[int, ...]):
        """Jitted donated scatter rewriting whole index slices along
        one axis per leaf — the re-admission rebalance's engine
        (same machinery as _apply_fn, but indexing a single interior
        axis so a chip's owned rows repair in one scatter each)."""
        import jax

        key = (fields, axes)
        fn = self._repair_cache.get(key)
        if fn is not None:
            return fn

        def apply(tables, payloads):
            kw = {}
            for name, axis, (idx, values) in zip(
                fields, axes, payloads
            ):
                index = (slice(None),) * axis + (idx,)
                kw[name] = getattr(tables, name).at[index].set(values)
            return dataclasses.replace(tables, **kw)

        fn = tracing.track_jit(
            jax.jit(apply, donate_argnums=(0,)), "publish.repair"
        )
        self._repair_cache[key] = fn
        return fn

    def repair_rows(
        self,
        row_sets: Dict[str, Tuple[int, object]],
        spare: bool = False,
        expect_epoch: Optional[int] = None,
        expect_layout: Optional[int] = None,
    ) -> int:
        """Rewrite `row_sets` ({leaf: (axis, index array)}) of the
        LIVE epoch from its retained host arrays — the re-admission
        rebalance: the rows a chip missed while its breaker was open
        land back on device through the delta-scatter path, bytes
        proportional to the missed change (never a full upload).
        With `spare=True` the STANDBY epoch repairs instead, from
        ITS retained host snapshot — the spare-epoch repair at chip
        readmission that keeps the next publish on the delta path
        (a de-registered spare would cost one full upload).
        `expect_epoch` pins the repair to the slot fill the caller
        observed (readmit_chip's `spare_epoch` — the store's own
        monotonic counter, since distinct epochs can share a table
        stamp): a publish interleaved since then flipped the slots,
        and scattering into whatever occupies the slot NOW would
        leave the stale epoch live-and-unrepaired — the repair
        refuses instead, and the caller's recovery path replays the
        whole slice on the next probe.

        The repaired epoch's buffers are DONATED to the scatter, so
        the caller must not have batches in flight against it (the
        failover router rebalances at stream boundaries, before the
        probe dispatch that re-admits the chip).  Returns bytes
        shipped host→device (also accumulated in
        cilium_rebalance_bytes_h2d_total)."""
        import jax

        with self._lock:
            slot = self._slots[self._cur ^ 1 if spare else self._cur]
            which = "spare" if spare else "live"
            if slot is None:
                raise RuntimeError(f"no {which} epoch to repair")
            if (
                expect_epoch is not None
                and slot["epoch"] != expect_epoch
            ):
                raise RuntimeError(
                    f"{which} epoch changed since readmission "
                    f"(epoch {slot['epoch']} != expected "
                    f"{expect_epoch}); repair refused"
                )
            if (
                expect_layout is not None
                and slot["layout"] != expect_layout
            ):
                # the caller's index arithmetic assumed a different
                # column assignment / pack layout than this epoch
                # actually holds (an in-flight reshard re-laid the
                # slot out) — scattering would plant rows computed
                # under one layout into an epoch keyed by another
                raise RuntimeError(
                    f"{which} epoch layout changed since "
                    f"readmission (layout {slot['layout']:#x} != "
                    f"expected {int(expect_layout):#x}); repair "
                    "refused"
                )
            host = slot.get("host")
            if host is None:
                raise RuntimeError(
                    f"{which} epoch retains no host source; repair "
                    "requires a publish through this store"
                )
            # payloads must land on the SLOT's mesh, not the store's
            # current one: during a relayout the staged epoch lives
            # on the target mesh while self._shardings still resolves
            # against the source — mixing meshes in one jit call is
            # an error, so each slot remembers its own shardings
            slot_sh = slot.get("shardings", self._shardings)

            def put(value):
                import jax as _jax

                if slot_sh is None:
                    return _jax.device_put(value)
                return _jax.device_put(value, slot_sh.generation)

            fields, axes, payloads = [], [], []
            bytes_h2d = 0
            for name in sorted(row_sets):
                axis, idx = row_sets[name]
                idx = np.asarray(idx, np.int64)
                if idx.size == 0:
                    continue
                # pow2-pad by repeating the last index (duplicate
                # identical writes are deterministic) so the repair
                # jit recompiles per size class, like _pad_pow2
                size = next_pow2(idx.size)
                if size != idx.size:
                    idx = np.concatenate(
                        [idx, np.repeat(idx[-1:], size - idx.size)]
                    )
                values = np.take(
                    np.asarray(getattr(host, name)), idx, axis=axis
                )
                fields.append(name)
                axes.append(int(axis))
                payloads.append((put(idx), put(values)))
                bytes_h2d += idx.nbytes + values.nbytes
            if not fields:
                return 0
            dev = self._repair_fn(tuple(fields), tuple(axes))(
                slot["tables"], tuple(payloads)
            )
            jax.block_until_ready(dev)
            slot["tables"] = dev
            metrics.rebalance_bytes_h2d_total.inc(value=bytes_h2d)
            return bytes_h2d

    @staticmethod
    def _norm(stamp: int) -> int:
        # without jax x64 the device generation leaf truncates to its
        # low 32 bits (the publish counter); stamps are store-scoped,
        # so comparing the counter bits stays unambiguous
        return int(stamp) & 0xFFFFFFFF

    def holds(self, tables) -> bool:
        """True when `tables` IS one of the live (undonated) epoch
        pytrees.  Object identity, not stamp comparison: a HOST
        snapshot can share a stamp with a lagging device epoch while
        its own stacked buffers have been rewritten — such tables
        must fall through to the compiler's staleness check."""
        with self._lock:
            return any(
                slot is not None and slot["tables"] is tables
                for slot in self._slots
            )

    def check_current(self, tables) -> None:
        """Raise unless `tables` is one of the two live epochs: older
        epochs' buffers have been donated to a newer publish and may
        have been overwritten in place."""
        raw = getattr(tables, "generation", None)
        stamp = self._norm(
            int(np.asarray(raw)) if raw is not None else 0
        )
        live = self.live_stamps()
        if not live or stamp in {self._norm(s) for s in live}:
            return
        raise StaleEpochError(
            f"stale device epoch: generation {stamp} is no longer "
            f"resident (live epochs: {live}) — its buffers were "
            f"donated to a newer publish"
        )


# ---------------------------------------------------------------------------
# Double-buffered async batch dispatch
# ---------------------------------------------------------------------------


class AsyncBatchDispatcher:
    """The epoch ping-pong machinery applied to BATCHES instead of
    tables: a bounded staging pipeline that overlaps the host pack of
    batch N+1 with the device compute of batch N.

      * `submit(host_args, meta)` runs `pack_fn` (encode + H2D
        staging — the host half) and `dispatch_fn` (a non-blocking
        jit enqueue — the device half), then drains AT MOST the
        batches beyond `depth` in FIFO order, so at any time up to
        `depth + 1` batches are in flight: one computing, one being
        packed.
      * results come back ONE BATCH BEHIND through the values
        returned from submit()/flush(): `(meta, result, exc)` tuples
        in exact submission order — consumers that fold events /
        flow records / telemetry per batch keep their ordering and
        per-batch counts unchanged relative to synchronous dispatch.
      * a failure at pack/enqueue time OR at drain (readback) time is
        captured as `exc` on that batch's tuple instead of poisoning
        the pipeline — the caller decides failover (the daemon serves
        the batch from the bit-identical host path).

    Overlap accounting: `pack_s` (host-side staging time), `block_s`
    (time spent blocked waiting on device results) and `wall_s`
    (first submit → flush) let callers derive the device-busy
    fraction during sustained dispatch (bench's
    overlap_efficiency_pct)."""

    def __init__(self, pack_fn, dispatch_fn, depth: int = 1) -> None:
        from collections import deque

        self.pack_fn = pack_fn
        self.dispatch_fn = dispatch_fn
        self.depth = max(int(depth), 0)
        self._pending = deque()
        self.pack_s = 0.0
        self.block_s = 0.0
        self._t_first = None
        self._t_last = None
        self.submitted = 0
        self.failed = 0

    def _drain_one(self):
        import jax

        meta, out, exc = self._pending.popleft()
        if exc is None:
            t0 = time.perf_counter()
            try:
                jax.block_until_ready(out)
            except Exception as drain_exc:  # device died mid-compute
                out, exc = None, drain_exc
                self.failed += 1
            dt = time.perf_counter() - t0
            self.block_s += dt
            if isinstance(meta, dict):
                meta.setdefault("perf", {})["drain_s"] = dt
        self._t_last = time.perf_counter()
        return meta, out, exc

    def submit(self, host_args: tuple, meta=None) -> list:
        """Stage + enqueue one batch; returns the drained (meta,
        result, exc) tuples that completed (possibly empty).

        Per-batch phase stamps: when `meta` is a dict, the pack /
        enqueue / drain durations this dispatcher already measures
        for the overlap aggregates are ALSO written into
        `meta["perf"]` — the perf plane's per-batch phase windows
        ride the existing bookkeeping instead of re-timing."""
        if self._t_first is None:
            self._t_first = time.perf_counter()
        self.submitted += 1
        out, exc = None, None
        t0 = time.perf_counter()
        try:
            dev_args = self.pack_fn(*host_args)
        except Exception as pack_exc:
            exc = pack_exc
            self.failed += 1
        dt_pack = time.perf_counter() - t0
        self.pack_s += dt_pack
        if isinstance(meta, dict):
            meta.setdefault("perf", {})["pack_s"] = dt_pack
        if exc is None:
            t1 = time.perf_counter()
            try:
                out = self.dispatch_fn(*dev_args)
            except Exception as disp_exc:
                out, exc = None, disp_exc
                self.failed += 1
            if isinstance(meta, dict):
                meta["perf"]["enqueue_s"] = (
                    time.perf_counter() - t1
                )
        self._pending.append((meta, out, exc))
        done = []
        while len(self._pending) > self.depth:
            done.append(self._drain_one())
        return done

    def flush(self) -> list:
        """Drain every in-flight batch, in order."""
        done = []
        while self._pending:
            done.append(self._drain_one())
        return done

    @property
    def wall_s(self) -> float:
        if self._t_first is None or self._t_last is None:
            return 0.0
        return self._t_last - self._t_first

    def overlap_efficiency_pct(self, device_seconds: float) -> float:
        """Device-busy fraction during sustained dispatch, given an
        independently measured estimate of pure device seconds for
        the submitted batches (e.g. sync per-batch latency × count).
        100% = the host pack was fully hidden behind device compute."""
        if self.wall_s <= 0:
            return 0.0
        return min(100.0, 100.0 * device_seconds / self.wall_s)
