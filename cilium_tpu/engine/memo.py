"""Verdict memoization: intra-batch tuple dedup + a device-resident
policy-verdict cache with epoch-stamped invalidation.

The fused pipeline's remaining gathers sit near the per-leaf byte
floor (PR 6/7), so the next multiplier comes from *not gathering at
all* for tuples the device has already decided — the TPU analog of an
established conntrack hit bypassing `policy_can_access` entirely
(bpf_lxc.c), restructured for batch execution the way PagedAttention
restructures the KV table into fixed-size cache slots:

  * **Level A — intra-batch dedup.**  Real traffic is Zipf-skewed:
    millions of tuples, few distinct policy keys.  Inside the jit the
    post-ipcache policy keys (identity index, endpoint, direction,
    dport, proto — three packed u32 words) are sorted
    (`jax.lax.sort`, 3 key columns), duplicates collapse into groups,
    and the expensive lattice gather chain runs only on the group
    REPRESENTATIVES (a static `rep_cap`-sized compaction); verdict
    words scatter back to every duplicate.  CT/LB/ipcache stages and
    the per-tuple counter/telemetry scatters still run on the full
    batch, so counts stay exact.
  * **Level B — cross-batch device cache.**  A hashed bucket-row
    table (the same row machinery as the L4 entry tables) maps policy
    key -> the packed lattice verdict words (`j << 16 | proxy` plus
    the three probe bits — everything the combine and the counter
    scatter consume).  Representatives probe the cache first; hits
    skip the lattice entirely, misses compact again (`miss_cap`),
    evaluate, and insert.  A probe compares ALL THREE key words, so a
    bucket collision can only cost a miss, never alias two keys.

Static-shape honesty: XLA cannot shrink arrays dynamically, so both
compactions are fixed-capacity.  With `rep_cap == batch` overflow is
impossible and bit-identity is unconditional; a tuned-down capacity
can overflow on an adversarial batch, in which case the kernel
REFUSES the batch — carried state (counters, telemetry, cache) is
committed only when `overflow == 0`, the stats row reports the
overflow, and the host wrapper re-dispatches the batch through the
uncached reference program.  The optimistic fast path + detected
fallback is the same shape as the dispatch breaker's host-fold
failover.

Invalidation: the cache is valid for exactly one published epoch.
`VerdictCache.ensure(stamp)` compares the caller's epoch stamp (the
publish generation + table layout + partition digest — the same
stamp surface `DeviceTableStore` uses to refuse cross-layout deltas)
and flushes on any change, so a delta publish, a pack-width repack or
a partition change can never serve a stale verdict.  Chip
kill/readmission flushes too (`ChipFailoverRouter.attach_verdict_
cache`) — routing changes are provably verdict-neutral, but the
flush keeps the staleness argument trivially airtight across the
repair scatter's in-place epoch rewrite.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

# k0 holds the dense identity index, which the compilers bound below
# L4H_WILD_IDX (< 2^22) — the all-ones word can never be a real key,
# so it doubles as the empty-lane sentinel
EMPTY = np.uint32(0xFFFFFFFF)

# words per cache entry: 3 key words + 2 value words
CACHE_KEY_WORDS = 3
CACHE_WORDS = 5

# per-row hit-rank word: one trailing u32 per bucket row, a 4-bit
# recency nibble per lane (entries <= 8).  A hit bumps its lane's
# nibble (saturating at 15); an insert into a FULL bucket evicts the
# lane with the LOWEST nibble — least-recently-hit — and resets the
# victim's nibble, so a hot entry survives colliding cold inserts.
# The word is heuristic metadata only: key/value words never depend
# on it, so a lost rank update can cost a future miss, never a wrong
# verdict.
RANK_NIBBLE_BITS = 4
RANK_NIBBLE_MAX = 15
RANK_MAX_LANES = 8

# stats vector columns (u32 [5]) every memo kernel returns
STAT_UNIQUE = 0  # distinct policy keys in the batch (dedup groups)
STAT_HIT = 1  # tuples whose representative hit the cache
STAT_INSERT = 2  # cache entries inserted (missed representatives)
STAT_OVERFLOW = 3  # groups/misses beyond the static capacities
STAT_TUPLES = 4  # batch tuples the stats row covers
STATS = 5


def cache_layout(rows):
    """(entries, has_rank, subword) of a cache-row array, solved from
    the row width alone — the widths are mutually exclusive by
    construction: legacy 5E, rank layout 5E + 1, SUB-WORD layout
    4E + ceil(E/8) + 1 (the three probe bits of every entry packed
    into a NIBBLE plane instead of a full value word; E a multiple
    of 8)."""
    w = int(rows.shape[-1])
    for e in (8, 16, 32):
        if w == 4 * e + e // 8 + 1:
            return e, True, True
    if w % CACHE_WORDS == 0:
        return w // CACHE_WORDS, False, False
    return (w - 1) // CACHE_WORDS, True, False


def cache_entries(rows) -> int:
    """Entries per bucket row, derived from the row width — probe
    and insert share the layout through the array shape itself, the
    same contract as the hashed L4 entry tables."""
    return cache_layout(rows)[0]


def has_rank_word(rows) -> bool:
    """True when the row layout carries the trailing hit-rank word.
    Legacy 5e-wide rows keep the rotation-eviction behavior — the
    layouts are distinguishable by width alone, so probe/insert
    never need a flag."""
    return cache_layout(rows)[1]


def make_cache_rows(
    n_rows: int = 1 << 12, entries: int = 8, subword: bool = False
) -> np.ndarray:
    """Host-side empty cache: [n_rows + 1, W] u32 — per lane 3 key
    words + the value words (EMPTY-filled) plus ONE trailing
    hit-rank word per row (zeroed: all lanes equally cold).  With
    `subword` the second value word (three probe bits) lives in a
    packed NIBBLE plane (W = 4*entries + entries//8 + 1 instead of
    5*entries + 1) — the verdict-cache key/value lanes shrink to the
    bits a probe actually reads.  Row `n_rows` is the SCRATCH row:
    invalid/overflow inserts are routed there so the jitted insert
    scatter needs no masking; probes mask the bucket index to
    [0, n_rows) and can never read it."""
    if n_rows & (n_rows - 1):
        raise ValueError(f"cache rows must be a power of two: {n_rows}")
    if subword:
        if entries % 8:
            raise ValueError(
                "sub-word cache rows need entries % 8 == 0"
            )
        rows = np.full(
            (n_rows + 1, 4 * entries + entries // 8 + 1),
            EMPTY, np.uint32,
        )
        # nibble plane + rank word start cold/zero
        rows[:, 4 * entries :] = 0
        return rows
    rows = np.full(
        (n_rows + 1, CACHE_WORDS * entries + 1), EMPTY, np.uint32
    )
    rows[:, -1] = 0
    return rows


def memo_key_words(idx, known, l3_bit, ep, dirn, dport, proto, xp=None):
    """The three packed u32 policy-key words.  `dport`/`proto` must
    already be clipped to their table ranges (the same clip _probes
    applies) so keys collapse exactly when probes would.  `l3_bit`
    may be None (no l3-plane ipcache on this path)."""
    import jax.numpy as jnp

    xp = xp or jnp
    u32 = lambda a: a.astype(xp.uint32)
    k0 = u32(idx)
    k1 = (
        (u32(dport) << xp.uint32(16))
        | (u32(proto) << xp.uint32(8))
        | (u32(known) << xp.uint32(1))
    )
    if l3_bit is not None:
        k1 = k1 | u32(l3_bit)
    k2 = (u32(ep) << xp.uint32(1)) | u32(dirn)
    return k0, k1, k2


def pack_value_words(probe1, probe2, probe3, proxy, j):
    """Lattice outputs -> (v0, v1): v0 = j << 16 | proxy (the exact
    packing of the hashed entry tables' value word), v1 = the three
    probe bits.  The combine and the counter scatter reconstruct
    everything per tuple from these plus per-tuple state."""
    import jax.numpy as jnp

    v0 = (j.astype(jnp.uint32) << jnp.uint32(16)) | (
        proxy.astype(jnp.uint32) & jnp.uint32(0xFFFF)
    )
    v1 = (
        probe1.astype(jnp.uint32)
        | (probe2.astype(jnp.uint32) << jnp.uint32(1))
        | (probe3.astype(jnp.uint32) << jnp.uint32(2))
    )
    return v0, v1


def unpack_value_words(v0, v1):
    import jax.numpy as jnp

    proxy = (v0 & jnp.uint32(0xFFFF)).astype(jnp.int32)
    j = (v0 >> jnp.uint32(16)).astype(jnp.int32)
    probe1 = (v1 & jnp.uint32(1)).astype(bool)
    probe2 = ((v1 >> jnp.uint32(1)) & jnp.uint32(1)).astype(bool)
    probe3 = ((v1 >> jnp.uint32(2)) & jnp.uint32(1)).astype(bool)
    return probe1, probe2, probe3, proxy, j


def dedup_groups(k0, k1, k2, rep_cap: int):
    """Level A (traced): sort the key words, collapse duplicates.

    Returns a dict:
      srow        i32 [B]  original row of each sorted position
      gid         i32 [B]  group id per sorted position (ascending)
      n_unique    i32 []   distinct keys in the batch
      rep_orig    i32 [rep_cap + 1]  original row of each group's
                  representative (first member in sort order); slot
                  rep_cap is scratch
      rep_valid   bool [rep_cap]
      overflow    i32 []   groups beyond rep_cap (0 = exact cover)
    """
    import jax
    import jax.numpy as jnp

    b = k0.shape[0]
    row = jnp.arange(b, dtype=jnp.int32)
    sk0, sk1, sk2, srow = jax.lax.sort(
        (k0, k1, k2, row), num_keys=3
    )
    new = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (sk0[1:] != sk0[:-1])
            | (sk1[1:] != sk1[:-1])
            | (sk2[1:] != sk2[:-1]),
        ]
    )
    gid = jnp.cumsum(new.astype(jnp.int32)) - 1
    n_unique = gid[-1] + 1
    (rep_pos,) = jnp.nonzero(new, size=rep_cap, fill_value=0)
    rep_valid = jnp.arange(rep_cap) < n_unique
    rep_orig = jnp.concatenate(
        [srow[rep_pos], jnp.zeros((1,), jnp.int32)]
    )
    overflow = jnp.maximum(n_unique - rep_cap, 0)
    return dict(
        srow=srow, gid=gid, n_unique=n_unique, rep_orig=rep_orig,
        rep_valid=rep_valid, overflow=overflow,
    )


def rank_nibbles(rank_word, entries):
    """[U] rank words -> [U, entries] per-lane recency nibbles
    (lanes beyond RANK_MAX_LANES share nibbles modulo 8 — callers
    disable LRU eviction past 8 lanes)."""
    import jax.numpy as jnp

    shifts = jnp.uint32(RANK_NIBBLE_BITS) * (
        jnp.arange(entries, dtype=jnp.uint32) % RANK_MAX_LANES
    )
    return (
        (rank_word[:, None] >> shifts[None, :])
        & jnp.uint32(RANK_NIBBLE_MAX)
    ).astype(jnp.int32)


def bucket_insert_lanes(empty, bucket, entries, rank_word=None):
    """Per-key insert lane + validity for same-batch inserts.
    `empty` is the [U, entries] EMPTY-key-lane mask of each key's
    gathered bucket row (owner-masked in the partitioned kernel —
    non-owners route to the scratch row anyway).

    Same-bucket keys gather the SAME row, so every per-key input
    here is bucket-uniform, and the base lane must stay that way:
    the bucket's first empty lane, else — with a `rank_word` — the
    LEAST-RECENTLY-HIT lane (lowest recency nibble; the per-row
    hit-rank word is bucket-uniform too), else a BUCKET-derived
    rotation.  Never a per-key hash way, whose per-key variance
    would let two same-bucket inserts collide on one lane when the
    bucket is full.  Ranking each key within its bucket (one tiny
    [U] sort) and rotating by the rank then yields DISTINCT
    (bucket, lane) targets for ranks < entries, so entry words stay
    atomic even though XLA leaves duplicate-index scatter order
    implementation-defined (interleaved key/value words from two
    entries would alias).  Keys ranked past the lane count get
    ok=False and must route to the scratch row (they just miss next
    batch).  Shared by the single-chip and partitioned memo
    kernels."""
    import jax
    import jax.numpy as jnp

    u = bucket.shape[0]
    pos = jnp.arange(u, dtype=jnp.int32)
    sb, sidx = jax.lax.sort(
        (bucket.astype(jnp.uint32), pos), num_keys=1
    )
    newb = jnp.concatenate(
        [jnp.ones((1,), bool), sb[1:] != sb[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(newb, pos, 0))
    rank = jnp.zeros(u, jnp.int32).at[sidx].set(pos - seg_start)
    if rank_word is not None and entries <= RANK_MAX_LANES:
        # coldest-first lane permutation: empty lanes (score -1)
        # ahead of occupied lanes ordered by recency nibble — the
        # k-th same-bucket insert takes the k-th coldest lane, so
        # the hottest lane is the LAST to be overwritten and ranks
        # still map to distinct lanes (score is bucket-uniform, the
        # permutation is too)
        score = jnp.where(
            empty, jnp.int32(-1), rank_nibbles(rank_word, entries)
        )
        order = jnp.argsort(score, axis=1).astype(jnp.int32)
        lane = jnp.take_along_axis(
            order,
            jnp.clip(rank, 0, entries - 1)[:, None],
            axis=1,
        )[:, 0]
    else:
        first_empty = jnp.argmax(empty, axis=1).astype(jnp.int32)
        full_rot = (
            bucket.astype(jnp.uint32) % jnp.uint32(entries)
        ).astype(jnp.int32)
        base_lane = jnp.where(
            jnp.any(empty, axis=1), first_empty, full_rot
        )
        lane = (base_lane + rank) % jnp.int32(entries)
    return lane, rank < entries


def cache_probe(cache_rows, k0, k1, k2, valid):
    """Level B probe (traced): one bucket-row gather per key + lane
    compares over ALL THREE key words — a colliding key can only
    miss, never alias.  Returns (hit, v0, v1, bucket, ins_lane,
    ins_ok, hit_lane, rank_word): `ins_lane` is the lane an insert
    of this key should take (bucket_insert_lanes: bucket-uniform
    base — first empty, else least-recently-hit — + rank within the
    bucket); `ins_ok` False means the bucket already absorbed
    `entries` same-batch inserts and this key must skip (scratch
    row); `hit_lane`/`rank_word` feed apply_rank_updates (zeros on
    the legacy rank-less layout)."""
    import jax.numpy as jnp

    from cilium_tpu.engine import subword as sw
    from cilium_tpu.engine.hashtable import fnv1a_device

    e, ranked, subw = cache_layout(cache_rows)
    n_rows = cache_rows.shape[0] - 1  # last row is scratch
    h = fnv1a_device(jnp.stack([k0, k1, k2], axis=1))
    bucket = (h & jnp.uint32(n_rows - 1)).astype(jnp.int32)
    rowv = cache_rows[bucket]  # [U, W] — 1 gather
    lane_hit = (
        (rowv[:, :e] == k0[:, None])
        & (rowv[:, e : 2 * e] == k1[:, None])
        & (rowv[:, 2 * e : 3 * e] == k2[:, None])
    )
    hit = jnp.any(lane_hit, axis=1) & valid
    v0 = jnp.sum(
        jnp.where(lane_hit, rowv[:, 3 * e : 4 * e], 0),
        axis=1, dtype=jnp.uint32,
    )
    if subw:
        # the nibble plane unpacks in-jit (sub-word hot lanes); the
        # three probe bits fit a nibble exactly
        v1_lanes = sw.unpack_lanes(
            rowv[:, 4 * e : 4 * e + e // 8], 4, e, xp=jnp
        )
        rank_col = 4 * e + e // 8
    else:
        v1_lanes = rowv[:, 4 * e : 5 * e]
        rank_col = CACHE_WORDS * e
    v1 = jnp.sum(
        jnp.where(lane_hit, v1_lanes, 0), axis=1, dtype=jnp.uint32
    )
    hit_lane = jnp.argmax(lane_hit, axis=1).astype(jnp.int32)
    rank_word = (
        rowv[:, rank_col]
        if ranked
        else jnp.zeros(bucket.shape, jnp.uint32)
    )
    ins_lane, ins_ok = bucket_insert_lanes(
        rowv[:, :e] == EMPTY, bucket, e,
        rank_word=(rank_word if ranked else None),
    )
    return hit, v0, v1, bucket, ins_lane, ins_ok, hit_lane, rank_word


def apply_rank_updates(
    cache_rows, bucket, hit, hit_lane, rank_word,
    ins_row, ins_lane, ins_rank_word, do_insert,
):
    """Maintain the per-row hit-rank word (traced).  Two commuting
    `.add` scatters on the rank column:

      * every HIT bumps its lane's recency nibble by one, saturating
        at 15 — at most one bump per (row, lane) per batch, because
        representatives are distinct keys and a lane holds one key,
        so the guard is exact (no nibble carry is possible);
      * every INSERT subtracts its target lane's current nibble
        exactly (uint32 wraparound subtract borrows nothing past the
        nibble), resetting the victim to cold — the entry must earn
        its heat through hits, so a stream of colliding cold inserts
        churns one lane instead of walking over the hot ones.

    All adds commute, so XLA's implementation-defined duplicate-index
    order cannot corrupt the word.  No-op on the legacy rank-less
    layout (or past RANK_MAX_LANES lanes)."""
    import jax.numpy as jnp

    e, ranked, subw = cache_layout(cache_rows)
    if not ranked or e > RANK_MAX_LANES:
        return cache_rows
    col = (4 * e + e // 8) if subw else CACHE_WORDS * e
    nb = jnp.uint32(RANK_NIBBLE_BITS)
    # hit bump
    h_shift = nb * (hit_lane.astype(jnp.uint32) % RANK_MAX_LANES)
    h_nib = (rank_word >> h_shift) & jnp.uint32(RANK_NIBBLE_MAX)
    h_delta = jnp.where(
        hit & (h_nib < RANK_NIBBLE_MAX),
        jnp.uint32(1) << h_shift,
        jnp.uint32(0),
    )
    # insert reset (scratch-routed rows get delta from the scratch
    # rank word, which stays 0 — harmless either way)
    i_shift = nb * (ins_lane.astype(jnp.uint32) % RANK_MAX_LANES)
    i_nib = (ins_rank_word >> i_shift) & jnp.uint32(RANK_NIBBLE_MAX)
    i_delta = jnp.where(
        do_insert,
        jnp.uint32(0) - (i_nib << i_shift),
        jnp.uint32(0),
    )
    return (
        cache_rows
        .at[bucket, col].add(h_delta)
        .at[ins_row, col].add(i_delta)
    )


def cache_insert(
    cache_rows, bucket, lane, k0, k1, k2, v0, v1, do_insert
):
    """Scatter entries into their bucket rows (traced).  Entries with
    `do_insert` False land on the scratch row — no masking inside the
    scatter.  Callers must pass lanes from `bucket_insert_lanes` so
    no two inserted entries share one (bucket, lane): XLA's
    duplicate-index scatter order is implementation-defined, and a
    split decision could interleave one entry's key words with
    another's value words."""
    import jax.numpy as jnp

    e, _ranked, subw = cache_layout(cache_rows)
    n_rows = cache_rows.shape[0] - 1
    b = jnp.where(do_insert, bucket, n_rows)
    if not subw:
        rows_idx = jnp.concatenate([b] * CACHE_WORDS)
        lanes_idx = jnp.concatenate(
            [lane + c * e for c in range(CACHE_WORDS)]
        )
        vals = jnp.concatenate([k0, k1, k2, v0, v1])
        return cache_rows.at[rows_idx, lanes_idx].set(vals)
    # sub-word layout: the three key words + v0 scatter as whole
    # lanes; v1 lands in its NIBBLE via a commuting add-delta (two
    # same-batch inserts into one row share the nibble WORD but
    # never the nibble — bucket_insert_lanes guarantees distinct
    # lanes, so the wraparound deltas compose exactly)
    rows_idx = jnp.concatenate([b] * 4)
    lanes_idx = jnp.concatenate([lane + c * e for c in range(4)])
    vals = jnp.concatenate([k0, k1, k2, v0])
    out = cache_rows.at[rows_idx, lanes_idx].set(vals)
    word_col = 4 * e + lane // 8
    shift = (jnp.uint32(4) * (lane.astype(jnp.uint32) % 8))
    old = (cache_rows[b, word_col] >> shift) & jnp.uint32(0xF)
    delta = ((v1 & jnp.uint32(0xF)) - old) << shift
    return out.at[b, word_col].add(
        jnp.where(do_insert, delta, jnp.uint32(0))
    )


def pad_rep(x, mp):
    """Gather per-representative values at padded miss positions:
    append one zero scratch slot, then index by `mp` (miss positions
    whose fill value points at the scratch).  The one padded-gather
    idiom both memo kernels build their insert columns from."""
    import jax.numpy as jnp

    return jnp.concatenate([x, jnp.zeros((1,), x.dtype)])[mp]


def scatter_back(g, rep_cap, hit, cv0, cv1, miss_pos, mv0, mv1):
    """Representative value words -> per-tuple columns: cache hits
    keep the cached pair, misses take the fresh evaluation (the
    scratch slot `rep_cap` absorbs fill positions), then every
    duplicate receives its group representative's words through the
    sorted-row scatter.  Returns (v0, v1, tuple_hit) — [B] columns.
    Shared by the single-chip and partitioned memo kernels: this is
    the index arithmetic the bit-identity argument rests on, so it
    lives in ONE place."""
    import jax.numpy as jnp

    rv0 = jnp.concatenate(
        [jnp.where(hit, cv0, 0), jnp.zeros((1,), jnp.uint32)]
    ).at[miss_pos].set(mv0)
    rv1 = jnp.concatenate(
        [jnp.where(hit, cv1, 0), jnp.zeros((1,), jnp.uint32)]
    ).at[miss_pos].set(mv1)
    hit_p = jnp.concatenate([hit, jnp.zeros((1,), bool)])
    gg = jnp.minimum(g["gid"], rep_cap - 1)
    srow = g["srow"]
    b = srow.shape[0]
    v0 = jnp.zeros(b, jnp.uint32).at[srow].set(rv0[gg])
    v1 = jnp.zeros(b, jnp.uint32).at[srow].set(rv1[gg])
    tuple_hit = jnp.zeros(b, bool).at[srow].set(hit_p[gg])
    return v0, v1, tuple_hit


def memo_lattice(
    pol,
    cache_rows,
    idx,
    known,
    l3_bit,
    ep,
    dirn,
    dport,
    proto,
    rep_cap: int,
    miss_cap: Optional[int] = None,
    insert: bool = True,
):
    """The two-level memoized lattice (traced): dedup -> cache probe
    on representatives -> miss compaction -> lattice gathers on the
    missed representatives only -> scatter back to every tuple.

    `dport`/`proto` must be pre-clipped; `l3_bit` None when no
    l3-plane word is available (the L3 probe then gathers
    l3_allow_bits for missed representatives).

    Returns (probe1, probe2, probe3, proxy, j, hit, cache_rows',
    stats) — the first five per-tuple [B], matching the _probes
    contract; `hit` bool [B] is the per-tuple cache-hit flag; `stats`
    u32 [STATS].  When stats[STAT_OVERFLOW] != 0 the per-tuple
    outputs are UNSPECIFIED and cache_rows' equals the input — the
    caller must re-dispatch through the uncached program."""
    import jax.numpy as jnp

    from cilium_tpu.engine.verdict import TupleBatch, _probes

    if miss_cap is None:
        miss_cap = rep_cap
    b = idx.shape[0]
    k0, k1, k2 = memo_key_words(
        idx, known, l3_bit, ep, dirn, dport, proto
    )
    g = dedup_groups(k0, k1, k2, rep_cap)
    rep_orig = g["rep_orig"]  # [rep_cap + 1]
    r = rep_orig[:rep_cap]
    rk0, rk1, rk2 = k0[r], k1[r], k2[r]
    (
        hit, cv0, cv1, bucket, ins_lane, ins_ok, hit_lane, rank_word,
    ) = cache_probe(cache_rows, rk0, rk1, rk2, g["rep_valid"])

    # -- miss compaction: lattice gathers only for missed reps ----------
    miss = g["rep_valid"] & ~hit
    n_miss = jnp.sum(miss.astype(jnp.int32))
    (miss_pos,) = jnp.nonzero(miss, size=miss_cap, fill_value=rep_cap)
    m_orig = rep_orig[jnp.minimum(miss_pos, rep_cap)]
    mb = TupleBatch(
        ep_index=ep[m_orig],
        identity=jnp.zeros(m_orig.shape, jnp.uint32),  # idx-form
        dport=dport[m_orig],
        proto=proto[m_orig],
        direction=dirn[m_orig],
        is_fragment=jnp.zeros(m_orig.shape, bool),
    )
    m_known = (idx[m_orig], known[m_orig]) + (
        (l3_bit[m_orig],) if l3_bit is not None else ()
    )
    p1m, p2m, p3m, proxym, jm, _ = _probes(pol, mb, idx_known=m_known)
    mv0, mv1 = pack_value_words(p1m, p2m, p3m, proxym, jm)

    # -- rep values -> per-tuple scatter-back ---------------------------
    v0, v1, tuple_hit = scatter_back(
        g, rep_cap, hit, cv0, cv1, miss_pos, mv0, mv1
    )

    # -- insert missed reps, commit only when nothing overflowed --------
    overflow = g["overflow"] + jnp.maximum(n_miss - miss_cap, 0)
    ok = overflow == 0
    if insert:
        mp = jnp.minimum(miss_pos, rep_cap)
        do_ins = (
            jnp.arange(miss_cap) < n_miss
        ) & pad_rep(ins_ok, mp)
        n_rows = cache_rows.shape[0] - 1
        ins_row = jnp.where(
            do_ins & ok, pad_rep(bucket, mp), n_rows
        )
        # hit-rank maintenance first (the LRU eviction metadata),
        # then the entry scatter; an overflow discards BOTH through
        # the same where — carried state commits only when ok
        ranked = apply_rank_updates(
            cache_rows, bucket, hit & ok, hit_lane, rank_word,
            ins_row, pad_rep(ins_lane, mp),
            pad_rep(rank_word, mp), do_ins & ok,
        )
        inserted = cache_insert(
            ranked,
            pad_rep(bucket, mp), pad_rep(ins_lane, mp),
            pad_rep(rk0, mp), pad_rep(rk1, mp), pad_rep(rk2, mp),
            mv0, mv1,
            do_ins & ok,
        )
        cache_out = jnp.where(ok, inserted, cache_rows)
        n_insert = jnp.sum(do_ins.astype(jnp.int32))
    else:
        cache_out = cache_rows
        n_insert = jnp.zeros((), jnp.int32)

    probe1, probe2, probe3, proxy, j = unpack_value_words(v0, v1)
    stats = jnp.stack(
        [
            g["n_unique"].astype(jnp.uint32),
            jnp.sum(tuple_hit, dtype=jnp.uint32),
            n_insert.astype(jnp.uint32),
            overflow.astype(jnp.uint32),
            jnp.uint32(b),
        ]
    )
    return (
        probe1, probe2, probe3, proxy, j, tuple_hit, cache_out, stats,
    )


def make_lattice_memo_fn(rep_cap, miss_cap, cell):
    """A `lattice_fn` for engine.datapath._datapath_core: replaces
    the probe chain with the memoized lattice.  Side outputs (updated
    cache, stats, per-tuple hit flags) land in `cell` — tracing is
    sequential, so the outer kernel reads them after the core call
    and threads the cache into the next half-batch."""
    import jax.numpy as jnp

    from cilium_tpu.engine.verdict import _index_identity

    def fn(pol, resolved, idx_known):
        l3_bit = None
        if idx_known is not None:
            idx, known = idx_known[0], idx_known[1]
            if len(idx_known) > 2:
                l3_bit = idx_known[2]
        else:
            idx, known = _index_identity(pol, resolved)
        dport = jnp.clip(resolved.dport, 0, 65535).astype(jnp.int32)
        proto = jnp.clip(resolved.proto, 0, 255).astype(jnp.int32)
        (
            probe1, probe2, probe3, proxy, j, hit, cache_out, stats,
        ) = memo_lattice(
            pol, cell["cache"], idx, known, l3_bit,
            resolved.ep_index, resolved.direction, dport, proto,
            rep_cap=rep_cap, miss_cap=miss_cap,
        )
        cell["cache"] = cache_out
        cell["stats"] = (
            stats if "stats" not in cell else cell["stats"] + stats
        )
        cell.setdefault("hits", []).append(hit)
        return probe1, probe2, probe3, proxy, j, idx

    return fn


# ---------------------------------------------------------------------------
# jitted memo programs
# ---------------------------------------------------------------------------

_KERNEL_CACHE = {}


def memo_evaluate_kernel(rep_cap: int, miss_cap: Optional[int] = None):
    """Jitted memoized lattice evaluator — the daemon serving shape
    (engine.verdict.evaluate_batch with the memo plane in front).

    fn(tables, batch, cache_rows) ->
        (Verdicts, cache_rows', hit bool [B], stats u32 [STATS])

    Not donated: the dispatch retry/breaker path may re-dispatch the
    same cache buffer after a transient failure."""
    import jax
    import jax.numpy as jnp

    miss_cap = rep_cap if miss_cap is None else miss_cap
    key = ("evaluate", rep_cap, miss_cap)
    fn = _KERNEL_CACHE.get(key)
    if fn is not None:
        return fn

    def kernel(tables, batch, cache_rows):
        from cilium_tpu.engine.verdict import (
            _combine,
            _index_identity,
        )

        idx, known = _index_identity(tables, batch)
        dport = jnp.clip(batch.dport, 0, 65535).astype(jnp.int32)
        proto = jnp.clip(batch.proto, 0, 255).astype(jnp.int32)
        (
            probe1, probe2, probe3, proxy, j, hit, cache_out, stats,
        ) = memo_lattice(
            tables, cache_rows, idx, known, None,
            batch.ep_index, batch.direction, dport, proto,
            rep_cap=rep_cap, miss_cap=miss_cap,
        )
        v = _combine(probe1, probe2, probe3, proxy, batch.is_fragment)
        return v, cache_out, hit, stats

    fn = jax.jit(kernel)
    _KERNEL_CACHE[key] = fn
    return fn


def memo_pair_packed4_kernel(
    rep_cap: int, miss_cap: Optional[int] = None
):
    """Jitted memoized HEADLINE shape: both packed4 half-batches in
    one staged [2, 4, B] array through the fused per-direction
    pipeline with the memoized lattice, counters + [2, T] telemetry
    riding the dispatch — the cached sibling of
    datapath_step_accum_pair_telem_packed4_stacked.

    fn(tables, pair, cache_rows, acc, telem) ->
        (out_i, out_e, acc', telem', cache_rows', hit_i, hit_e,
         stats u32 [STATS])

    acc/telem/cache are donated; ALL carried state commits only when
    stats[STAT_OVERFLOW] == 0 (otherwise returned unchanged — the
    caller re-dispatches through the uncached program)."""
    import jax
    import jax.numpy as jnp

    miss_cap = rep_cap if miss_cap is None else miss_cap
    key = ("pair4", rep_cap, miss_cap)
    fn = _KERNEL_CACHE.get(key)
    if fn is not None:
        return fn

    def kernel(tables, pair, cache_rows, acc, telem):
        from cilium_tpu.engine.datapath import (
            _datapath_core,
            flow_batch_from_packed4,
        )
        from cilium_tpu.engine.verdict import _counter_cols
        from cilium_tpu.maps.policymap import EGRESS, INGRESS

        cell = {"cache": cache_rows}
        out_i, (v_i, res_i, j_i, idx_i), trow_i = _datapath_core(
            tables, flow_batch_from_packed4(pair[0]),
            with_counters=True, emit_sec_id=False,
            static_direction=INGRESS, defer_counters=True,
            collect_telemetry=True,
            lattice_fn=make_lattice_memo_fn(rep_cap, miss_cap, cell),
        )
        out_e, (v_e, res_e, j_e, idx_e), trow_e = _datapath_core(
            tables, flow_batch_from_packed4(pair[1]),
            with_counters=True, emit_sec_id=False,
            static_direction=EGRESS, defer_counters=True,
            collect_telemetry=True,
            lattice_fn=make_lattice_memo_fn(rep_cap, miss_cap, cell),
        )
        stats = cell["stats"]
        hit_i, hit_e = cell["hits"]
        ok = stats[STAT_OVERFLOW] == 0
        okw = ok.astype(jnp.uint32)
        kg = tables.policy.l4_meta.shape[2]
        ep_i, d_i, c_i, w_i = _counter_cols(v_i, res_i, j_i, idx_i, kg)
        ep_e, d_e, c_e, w_e = _counter_cols(v_e, res_e, j_e, idx_e, kg)
        acc = acc.at[
            jnp.concatenate([ep_i, ep_e]),
            jnp.concatenate([d_i, d_e]),
            jnp.concatenate([c_i, c_e]),
        ].add(jnp.concatenate([w_i, w_e]) * okw)
        telem = telem + (trow_i + trow_e) * okw
        cache_out = jnp.where(ok, cell["cache"], cache_rows)
        return (
            out_i, out_e, acc, telem, cache_out, hit_i, hit_e, stats,
        )

    fn = jax.jit(kernel, donate_argnums=(2, 3, 4))
    _KERNEL_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# host wrapper: epoch-stamped invalidation + observability
# ---------------------------------------------------------------------------


class VerdictCache:
    """Host-side owner of the device cache rows: epoch-stamped
    invalidation (the DeviceTableStore refusal seam applied to cached
    verdicts), flush/hit/miss/insert accounting into the metrics
    registry, and a `cache.flush` span event on every
    stamp-triggered flush.

    `stamp` is any hashable identifying the exact table world the
    cached verdicts were computed under — callers pass the publish
    generation + layout version (+ partition digest on a mesh); ANY
    change flushes.  `rows_factory`/`sharding` parameterize the
    device layout (the partitioned evaluator's [dp, tp, R+1, lanes]
    block rides the same wrapper)."""

    def __init__(
        self,
        n_rows: int = 1 << 12,
        entries: int = 8,
        rows_factory=None,
        sharding=None,
        subword: bool = False,
    ) -> None:
        self._lock = threading.Lock()
        self._factory = rows_factory or (
            lambda: make_cache_rows(n_rows, entries, subword=subword)
        )
        self._sharding = sharding
        self._stamp = None
        # a just-allocated buffer is as empty as a flushed one: the
        # first ensure() adopts its stamp without a phantom flush
        # event / second allocation
        self._fresh = True
        self._rows = self._put(self._factory())
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.flushes = 0
        self.overflows = 0
        self.tuples = 0
        self.unique = 0

    def _put(self, rows):
        import jax

        if self._sharding is None:
            return jax.device_put(rows)
        return jax.device_put(rows, self._sharding)

    @property
    def stamp(self):
        return self._stamp

    @property
    def rows(self):
        return self._rows

    @rows.setter
    def rows(self, value):
        # a direct write means the buffer carries real entries again
        self._rows = value
        self._fresh = False

    def nbytes(self) -> int:
        return int(np.prod(self._rows.shape)) * 4

    def acquire(self):
        """Atomically read (stamp, rows) for one dispatch: the pair
        the kernel probes must belong to ONE epoch — a concurrent
        publish between ensure() and the rows read would otherwise
        hand out another epoch's entries."""
        with self._lock:
            return self._stamp, self._rows

    def commit(self, stamp, rows) -> bool:
        """Write kernel-updated rows back IFF the cache still holds
        `stamp` — a publish that flushed mid-dispatch wins, and the
        rows derived from the pre-publish cache are dropped instead
        of resurrecting stale entries under the new stamp."""
        with self._lock:
            if stamp != self._stamp:
                return False
            self._rows = rows
            self._fresh = False
            return True

    def ensure(self, stamp) -> bool:
        """Make the cache valid for `stamp`: flushes when the epoch
        stamp changed (delta publish, repack, partition change, chip
        readmission — anything that could make a cached verdict
        stale).  Returns True when the cache was invalidated."""
        with self._lock:
            if stamp == self._stamp:
                return False
            if self._fresh:
                # the buffer is already empty (fresh construction or
                # an explicit flush()); adopt the new stamp without a
                # second reallocation/flush event
                self._stamp = stamp
                return True
            self._flush_locked(
                reason="epoch-stamp", old=self._stamp, new=stamp
            )
            self._stamp = stamp
            return True

    def flush(self, reason: str = "explicit") -> None:
        with self._lock:
            self._flush_locked(reason=reason)
            self._stamp = None

    def _flush_locked(self, reason: str, old=None, new=None) -> None:
        from cilium_tpu import tracing
        from cilium_tpu.metrics import registry as metrics

        self._rows = self._put(self._factory())
        self._fresh = True
        self.flushes += 1
        metrics.verdict_cache_flushes_total.inc()
        tracing.add_event(
            "cache.flush", reason=reason,
            old_stamp=str(old), new_stamp=str(new),
        )

    def account(self, stats) -> dict:
        """Fold one batch's on-device stats row into the counters +
        metrics registry.  Returns the host dict (a batch that
        overflowed contributes only its overflow count — its hit
        and insert numbers were discarded with the batch)."""
        from cilium_tpu.metrics import registry as metrics

        s = np.asarray(stats).astype(np.int64)
        row = {
            "unique": int(s[STAT_UNIQUE]),
            "hits": int(s[STAT_HIT]),
            "insertions": int(s[STAT_INSERT]),
            "overflow": int(s[STAT_OVERFLOW]),
            "tuples": int(s[STAT_TUPLES]),
        }
        with self._lock:
            if row["overflow"]:
                self.overflows += row["overflow"]
                return row
            misses = row["tuples"] - row["hits"]
            self.hits += row["hits"]
            self.misses += misses
            self.insertions += row["insertions"]
            self.tuples += row["tuples"]
            self.unique += row["unique"]
        if row["hits"]:
            metrics.verdict_cache_hits_total.inc(value=row["hits"])
        if misses:
            metrics.verdict_cache_misses_total.inc(value=misses)
        if row["insertions"]:
            metrics.verdict_cache_insertions_total.inc(
                value=row["insertions"]
            )
        return row

    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def dedup_factor(self) -> float:
        with self._lock:
            return self.tuples / self.unique if self.unique else 1.0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "insertions": self.insertions,
                "flushes": self.flushes,
                "overflows": self.overflows,
                "hit_rate": (
                    self.hits / (self.hits + self.misses)
                    if (self.hits + self.misses)
                    else 0.0
                ),
                "dedup_factor": (
                    self.tuples / self.unique if self.unique else 1.0
                ),
                "bytes": self.nbytes(),
                "stamp": str(self._stamp),
            }
