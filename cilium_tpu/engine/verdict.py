"""Jitted batched verdict kernel (the TPU datapath).

Computes, for every (endpoint, identity, dport, proto, direction)
tuple in a batch, the 3-probe lattice of bpf/lib/policy.h:46 against
the compiled PolicyTables — fully vectorized:

  * identity hash-probe  → searchsorted over the sorted id universe;
  * L4 key hash-probe    → broadcast compare against the endpoint's
    padded (dport<<8|proto) key row (K is small, so the [B, K] compare
    is cheap VPU work and XLA fuses the argmax reduction into it);
  * per-endpoint map selection (the PROG_ARRAY tail call,
    bpf/bpf_lxc.c:1039) → gather along the endpoint axis.

Everything is integer (u32/i32) — no floats anywhere near the verdict,
so device results are bit-identical to the host oracle by construction
(SURVEY.md §7 hard part 5).

The batch axis is embarrassingly parallel (packets across nodes in the
reference ≙ tuples across TPU chips): `make_sharded_evaluator` shards
it over a `jax.sharding.Mesh` with the tables replicated, which keeps
all collective traffic at zero during evaluation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.engine.oracle import (
    MATCH_FRAG_DROP,
    MATCH_L3,
    MATCH_L4,
    MATCH_L4_WILD,
    MATCH_NONE,
)


@jax.tree_util.register_pytree_node_class
@dataclass
class TupleBatch:
    """A batch of flow tuples (the SearchContext of the datapath)."""

    ep_index: jax.Array  # i32 [B] index into the endpoint axis
    identity: jax.Array  # u32 [B] src id (ingress) / dst id (egress)
    dport: jax.Array  # i32 [B] destination port, host order
    proto: jax.Array  # i32 [B] IP protocol number
    direction: jax.Array  # i32 [B] 0=ingress 1=egress
    is_fragment: jax.Array  # bool [B]

    def tree_flatten(self):
        return (
            (
                self.ep_index,
                self.identity,
                self.dport,
                self.proto,
                self.direction,
                self.is_fragment,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def from_numpy(
        ep_index,
        identity,
        dport,
        proto,
        direction,
        is_fragment=None,
    ) -> "TupleBatch":
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        return TupleBatch(
            ep_index=jnp.asarray(ep_index, dtype=jnp.int32),
            identity=jnp.asarray(identity, dtype=jnp.uint32),
            dport=jnp.asarray(dport, dtype=jnp.int32),
            proto=jnp.asarray(proto, dtype=jnp.int32),
            direction=jnp.asarray(direction, dtype=jnp.int32),
            is_fragment=jnp.asarray(is_fragment, dtype=bool),
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class Verdicts:
    """Per-tuple results, dtype-stable for bit-compare with the oracle."""

    allowed: jax.Array  # u8 [B] 0/1
    proxy_port: jax.Array  # u16-valued i32 [B] (0 = plain allow)
    match_kind: jax.Array  # u8 [B] MATCH_* codes

    def tree_flatten(self):
        return ((self.allowed, self.proxy_port, self.match_kind), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _verdict_kernel(tables: PolicyTables, batch: TupleBatch) -> Verdicts:
    n = tables.id_table.shape[0]

    # -- identity probe: raw u32 id → dense index ---------------------------
    idx = jnp.searchsorted(tables.id_table, batch.identity)
    idx = jnp.clip(idx, 0, n - 1).astype(jnp.int32)
    known = tables.id_table[idx] == batch.identity
    word = idx >> 5
    bit = (idx & 31).astype(jnp.uint32)

    # -- L4 key probe: match the endpoint's padded key row ------------------
    portkey = (
        (batch.dport.astype(jnp.uint32) << 8)
        | batch.proto.astype(jnp.uint32)
    )
    key_rows = tables.l4_ports[batch.ep_index, batch.direction]  # [B, K]
    key_match = key_rows == portkey[:, None]  # [B, K]
    has_port = jnp.any(key_match, axis=1)
    j = jnp.argmax(key_match, axis=1).astype(jnp.int32)  # first (only) hit

    # -- probe 1: exact (identity, dport, proto) ----------------------------
    exact_words = tables.l4_allow_bits[
        batch.ep_index, batch.direction, j, word
    ]
    exact_bit = ((exact_words >> bit) & 1).astype(bool)
    probe1 = known & has_port & exact_bit

    # -- probe 2: L3-only (identity, 0, 0) ----------------------------------
    l3_words = tables.l3_allow_bits[batch.ep_index, batch.direction, word]
    probe2 = known & ((l3_words >> bit) & 1).astype(bool)

    # -- probe 3: wildcard (0, dport, proto) --------------------------------
    wild = tables.l4_wild[batch.ep_index, batch.direction, j].astype(bool)
    probe3 = has_port & wild

    # -- lattice combine (policy.h:62-109 order; fragments skip L4 probes) --
    frag = batch.is_fragment
    p1 = probe1 & ~frag
    p3 = probe3 & ~frag
    allowed = p1 | probe2 | p3

    proxy = tables.l4_proxy[batch.ep_index, batch.direction, j].astype(
        jnp.int32
    )
    proxy_out = jnp.where(p1 | (~probe2 & p3), proxy, 0)
    proxy_out = jnp.where(allowed, proxy_out, 0)

    kind = jnp.where(
        p1,
        MATCH_L4,
        jnp.where(
            probe2,
            MATCH_L3,
            jnp.where(
                p3,
                MATCH_L4_WILD,
                jnp.where(frag, MATCH_FRAG_DROP, MATCH_NONE),
            ),
        ),
    ).astype(jnp.uint8)

    return Verdicts(
        allowed=allowed.astype(jnp.uint8),
        proxy_port=proxy_out,
        match_kind=kind,
    )


evaluate_batch = jax.jit(_verdict_kernel)


def make_sharded_evaluator(mesh: Optional[jax.sharding.Mesh] = None,
                           batch_axis: str = "batch"):
    """Return a jitted evaluator with the batch axis sharded over the
    mesh and tables replicated (SURVEY.md §2.9: flow batches shard like
    packets shard across nodes; tables replicate like BPF maps
    replicate per node).

    With `mesh=None` this degrades to the single-device evaluator.
    """
    if mesh is None:
        return evaluate_batch

    replicated = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()
    )
    batch_sharded = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(batch_axis)
    )

    table_shardings = PolicyTables(
        id_table=replicated,
        l4_ports=replicated,
        l4_proxy=replicated,
        l4_allow_bits=replicated,
        l4_wild=replicated,
        l3_allow_bits=replicated,
    )
    batch_shardings = TupleBatch(
        ep_index=batch_sharded,
        identity=batch_sharded,
        dport=batch_sharded,
        proto=batch_sharded,
        direction=batch_sharded,
        is_fragment=batch_sharded,
    )
    out_shardings = Verdicts(
        allowed=batch_sharded,
        proxy_port=batch_sharded,
        match_kind=batch_sharded,
    )
    return jax.jit(
        _verdict_kernel,
        in_shardings=(table_shardings, batch_shardings),
        out_shardings=out_shardings,
    )
