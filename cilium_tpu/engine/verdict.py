"""Jitted batched verdict kernel (the TPU datapath).

Computes, for every (endpoint, identity, dport, proto, direction)
tuple in a batch, the 3-probe lattice of bpf/lib/policy.h:46 against
the compiled PolicyTables — fully vectorized:

  * identity hash-probe  → one direct-table gather (id_direct);
  * L4 key hash-probe    → proto remap + (proto slot, dport) direct
    slot-table gather — O(1) instead of per-endpoint key scans;
  * per-endpoint map selection (the PROG_ARRAY tail call,
    bpf/bpf_lxc.c:1039) → gather along the endpoint axis.

Random 1M-element HBM gathers cost ~20-30 ms on TPU via XLA, so the
kernel is engineered down to 6 gathers total; see compiler/tables.py
for the fused layouts.

Everything is integer (u32/i32) — no floats anywhere near the verdict,
so device results are bit-identical to the host oracle by construction
(SURVEY.md §7 hard part 5).

The batch axis is embarrassingly parallel (packets across nodes in the
reference ≙ tuples across TPU chips): `make_sharded_evaluator` shards
it over a `jax.sharding.Mesh` with the tables replicated, which keeps
all collective traffic at zero during evaluation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.engine.oracle import (
    MATCH_FRAG_DROP,
    MATCH_L3,
    MATCH_L4,
    MATCH_L4_WILD,
    MATCH_NONE,
)


@jax.tree_util.register_pytree_node_class
@dataclass
class TupleBatch:
    """A batch of flow tuples (the SearchContext of the datapath)."""

    ep_index: jax.Array  # i32 [B] index into the endpoint axis
    identity: jax.Array  # u32 [B] src id (ingress) / dst id (egress)
    dport: jax.Array  # i32 [B] destination port, host order
    proto: jax.Array  # i32 [B] IP protocol number
    direction: jax.Array  # i32 [B] 0=ingress 1=egress
    is_fragment: jax.Array  # bool [B]

    def tree_flatten(self):
        return (
            (
                self.ep_index,
                self.identity,
                self.dport,
                self.proto,
                self.direction,
                self.is_fragment,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def from_numpy(
        ep_index,
        identity,
        dport,
        proto,
        direction,
        is_fragment=None,
    ) -> "TupleBatch":
        """Single-transfer upload: one [6, B] u32 pack instead of six
        device_puts (each pays the transport's ~100 ms round trip —
        see FlowBatch.from_numpy)."""
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        packed = np.empty((6, b), dtype=np.uint32)
        packed[0] = np.asarray(ep_index).astype(np.uint32, copy=False)
        packed[1] = np.asarray(identity, np.uint32)
        packed[2] = np.asarray(dport).astype(np.uint32, copy=False)
        packed[3] = np.asarray(proto).astype(np.uint32, copy=False)
        packed[4] = np.asarray(direction).astype(
            np.uint32, copy=False
        )
        packed[5] = np.asarray(is_fragment).astype(np.uint32)
        return _unpack_tuple_batch(jnp.asarray(packed))


def _tuple_batch_from_packed(packed) -> "TupleBatch":
    return TupleBatch(
        ep_index=packed[0].astype(jnp.int32),
        identity=packed[1],
        dport=packed[2].astype(jnp.int32),
        proto=packed[3].astype(jnp.int32),
        direction=packed[4].astype(jnp.int32),
        is_fragment=packed[5].astype(bool),
    )


# jitted splitter for TupleBatch.from_numpy's single-transfer pack
_unpack_tuple_batch = jax.jit(_tuple_batch_from_packed)


@jax.tree_util.register_pytree_node_class
@dataclass
class Verdicts:
    """Per-tuple results, dtype-stable for bit-compare with the oracle."""

    allowed: jax.Array  # u8 [B] 0/1
    proxy_port: jax.Array  # u16-valued i32 [B] (0 = plain allow)
    match_kind: jax.Array  # u8 [B] MATCH_* codes

    def tree_flatten(self):
        return ((self.allowed, self.proxy_port, self.match_kind), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _index_identity(tables: PolicyTables, batch: TupleBatch):
    """Identity half of index resolution: raw u32 id → dense index
    (1 gather from the small direct table).  Returns (idx, known)."""
    from cilium_tpu.compiler.tables import LOCAL_ID_BASE, NO_INDEX

    n = tables.id_table.shape[0]
    direct_sz = tables.id_direct.shape[0]
    lo_len = tables.id_lo_len.astype(jnp.uint32)

    # id_direct is two dense regions: [0, lo_len) for cluster-scope
    # ids, [lo_len, end) for local CIDR ids offset by LOCAL_ID_BASE.
    ident = batch.identity.astype(jnp.uint32)
    is_local = ident >= jnp.uint32(LOCAL_ID_BASE)
    local_off = ident - jnp.uint32(LOCAL_ID_BASE)
    pos = jnp.where(is_local, lo_len + local_off, ident)
    in_range = jnp.where(
        is_local,
        local_off < jnp.uint32(direct_sz) - lo_len,
        ident < lo_len,
    )
    pos = jnp.minimum(pos, jnp.uint32(direct_sz - 1)).astype(jnp.int32)
    v = tables.id_direct[pos]
    known = in_range & (v != jnp.uint32(NO_INDEX))
    idx = jnp.where(known, v, jnp.uint32(n - 1)).astype(jnp.int32)
    return idx, known


def _index(tables: PolicyTables, batch: TupleBatch):
    """Index resolution: O(1) direct-table gathers only.

    Returns (idx, word, bit, known, j, has_port) — the global identity
    index / bit position and the global L4 slot of each tuple, all
    derived from small replicated tables (no touch of the big
    allow-bit tensors, so the identity-sharded path can reuse this and
    offset `word` per shard)."""
    from cilium_tpu.compiler.tables import NO_SLOT

    idx, known = _index_identity(tables, batch)
    word = idx >> 5
    bit = (idx & 31).astype(jnp.uint32)

    # -- L4 key probe: (proto, dport) → global slot (1 gather) --------------
    # port_slot is indexed by the RAW proto byte (one 65536-entry row
    # per proto, 32 MB); only the identity-sharded mesh evaluator
    # still probes through it — the single-chip kernels resolve the
    # slot from the hashed entry table's value word instead.
    proto = jnp.clip(batch.proto, 0, 255).astype(jnp.int32)
    dport = jnp.clip(batch.dport, 0, 65535).astype(jnp.int32)
    slot16 = tables.port_slot[proto, dport]
    has_port = slot16 != jnp.uint16(NO_SLOT)
    j = jnp.where(has_port, slot16, 0).astype(jnp.int32)
    return idx, word, bit, known, j, has_port


def l4hash_probe_keys(entry_words, ep, dirn, idx, dport, proto):
    """(w0, w1) probe key words for either hashed-entry layout —
    build side and probe side MUST stay one implementation.  `idx`
    may carry the L4H_WILD_IDX sentinel; the compact layout remaps it
    to its own 18-bit sentinel."""
    from cilium_tpu.compiler.tables import (
        L4C_WILD_IDX18,
        L4H_WILD_IDX,
        l4c_key0,
        l4c_key1,
        l4h_key0,
        l4h_key1,
    )

    if entry_words == 2:
        idx18 = jnp.where(
            idx == jnp.uint32(L4H_WILD_IDX),
            jnp.uint32(L4C_WILD_IDX18),
            idx.astype(jnp.uint32),
        )
        return l4c_key0(idx18, dport), l4c_key1(dport, proto, ep, dirn)
    return l4h_key0(idx, dirn, ep), l4h_key1(dport, proto, ep)


def l4hash_row_parts(rows, w0, w1, entry_words, owns=None):
    """Lane compares against pre-gathered hashed-entry rows, either
    layout, with an optional ownership mask (the routed mesh kernels
    gather each row on its owning shard only and psum these parts).
    Returns (found [B], val u32 [B]) — val is `j << 16 | proxy` in
    the 3-word layout and the bare slot index `j` in the compact one
    (decode with l4hash_value_decode)."""
    from cilium_tpu.compiler.tables import L4C_CMP_MASK

    e = rows.shape[1] // entry_words
    if entry_words == 2:
        hit = (rows[:, :e] == w0[:, None]) & (
            (rows[:, e : 2 * e] & jnp.uint32(L4C_CMP_MASK))
            == w1[:, None]
        )
        vals = (rows[:, e : 2 * e] >> jnp.uint32(19)) & jnp.uint32(
            0xFFF
        )
    else:
        hit = (rows[:, :e] == w0[:, None]) & (
            rows[:, e : 2 * e] == w1[:, None]
        )
        vals = rows[:, 2 * e : 3 * e]
    if owns is not None:
        hit = hit & owns[:, None]
    val = jnp.sum(jnp.where(hit, vals, 0), axis=1, dtype=jnp.uint32)
    return jnp.any(hit, axis=1), val


def l4hash_stash_parts(stash, w0, w1, entry_words):
    """Broadcast-compare half of the probe (the stash replicates on a
    mesh — added AFTER the row-part psum).  Same value contract as
    l4hash_row_parts."""
    from cilium_tpu.compiler.tables import L4C_CMP_MASK

    stash = jnp.asarray(stash)
    if entry_words == 2:
        s_hit = (stash[None, :, 0] == w0[:, None]) & (
            (stash[None, :, 1] & jnp.uint32(L4C_CMP_MASK))
            == w1[:, None]
        )
        vals = (stash[None, :, 1] >> jnp.uint32(19)) & jnp.uint32(
            0xFFF
        )
    else:
        s_hit = (stash[None, :, 0] == w0[:, None]) & (
            stash[None, :, 1] == w1[:, None]
        )
        vals = stash[None, :, 2]
    val = jnp.sum(
        jnp.where(s_hit, vals, 0), axis=1, dtype=jnp.uint32
    )
    return jnp.any(s_hit, axis=1), val


def l4hash_value_decode(
    tables, ep, dirn, probe1, val1, hit3, val3, entry_words
):
    """Fold the exact/wild probe values into (proxy, j) — the shared
    terminal step of every lattice probe.  The 3-word layout splits
    the matched value word; the compact layout takes the matched slot
    index and reconstructs the proxy port with ONE l4_meta element
    gather (the plane the lowering keeps bit-equal to the dropped
    per-entry copy — gated by repack_l4_subword at pack time)."""
    val = jnp.where(probe1, val1, val3)
    if entry_words == 3:
        return (
            (val & jnp.uint32(0xFFFF)).astype(jnp.int32),
            (val >> jnp.uint32(16)).astype(jnp.int32),
        )
    j = val.astype(jnp.int32)
    meta = tables.l4_meta[ep, dirn, j]
    proxy = jnp.where(
        probe1 | hit3, (meta >> jnp.uint32(1)).astype(jnp.int32), 0
    )
    return proxy, j


def _l4hash_probe(hash_rows, hash_stash, ep, dirn, idx, dport, proto):
    """One probe of a hashed L4 entry table: a single row gather +
    lane compares (+ a small stash broadcast).  Returns (hit bool
    [B], value u32 [B] — `j << 16 | proxy_port` in the 3-word layout,
    the bare slot index in the compact 2-word one).  The entry count
    per bucket derives from the row width and the layout from the
    stash width (compiler.tables.l4_entry_words) — probe and build
    share the layout through the array shapes themselves."""
    from cilium_tpu.compiler.tables import l4_entry_words
    from cilium_tpu.engine.hashtable import fnv1a_device

    entry_words = l4_entry_words(hash_stash)
    w0, w1 = l4hash_probe_keys(
        entry_words, ep, dirn, idx, dport, proto
    )
    h = fnv1a_device(jnp.stack([w0, w1], axis=1))
    n_rows = hash_rows.shape[0]
    b = (h & jnp.uint32(n_rows - 1)).astype(jnp.int32)
    rows = jnp.asarray(hash_rows)[b]  # [B, lanes] — 1 gather
    found, val = l4hash_row_parts(rows, w0, w1, entry_words)
    s_found, s_val = l4hash_stash_parts(
        hash_stash, w0, w1, entry_words
    )
    return found | s_found, val + s_val


def _probes(tables: PolicyTables, batch: TupleBatch, idx_known=None):
    """The three map probes of policy.h:46, vectorized.  Returns
    (probe1, probe2, probe3, proxy, j, idx).

    With the hashed entry table present (the FleetCompiler always
    builds it), the exact probe and the wildcard probe are each ONE
    row gather; the slot index for counters and the proxy port ride
    in the matched entry's value word, so neither port_slot nor the
    dense bitmap is touched.  `idx_known=(idx, known[, l3_bit])`
    supplies a pre-resolved identity index (e.g. from an idx-form
    ipcache) and skips the id_direct gather; with `l3_bit` (the
    identity's per-endpoint L3-allow bit, from an l3-plane ipcache)
    the L3 probe gather disappears too."""
    from cilium_tpu.compiler.tables import L4H_WILD_IDX

    l3_bit = None
    if idx_known is not None:
        idx, known = idx_known[0], idx_known[1]
        if len(idx_known) > 2:
            l3_bit = idx_known[2]
    else:
        idx, known = _index_identity(tables, batch)
    word = idx >> 5
    bit = (idx & 31).astype(jnp.uint32)
    proto = jnp.clip(batch.proto, 0, 255).astype(jnp.int32)
    dport = jnp.clip(batch.dport, 0, 65535).astype(jnp.int32)

    if tables.l4_hash_rows is not None:
        # -- probes 1+3: two row gathers from the hashed entry table ----
        # (an unknown identity resolves to the in-range fallback idx
        # and probe1 is masked by `known`; a real idx never equals the
        # wildcard sentinel — the compilers bound the identity axis
        # below L4H_WILD_IDX)
        from cilium_tpu.compiler.tables import l4_entry_words

        entry_words = l4_entry_words(tables)
        hit1, val1 = _l4hash_probe(
            tables.l4_hash_rows, tables.l4_hash_stash,
            batch.ep_index, batch.direction,
            idx.astype(jnp.uint32), dport, proto,
        )
        wild_idx = jnp.full(
            idx.shape, jnp.uint32(L4H_WILD_IDX), jnp.uint32
        )
        hit3, val3 = _l4hash_probe(
            tables.l4_wild_rows, tables.l4_wild_stash,
            batch.ep_index, batch.direction, wild_idx,
            dport, proto,
        )
        probe1 = known & hit1
        probe3 = hit3
        proxy, j = l4hash_value_decode(
            tables, batch.ep_index, batch.direction,
            probe1, val1, hit3, val3, entry_words,
        )
    else:
        # dense fallback (hand-built tables without the hash)
        from cilium_tpu.compiler.tables import NO_SLOT

        slot16 = tables.port_slot[proto, dport]
        has_port = slot16 != jnp.uint16(NO_SLOT)
        j = jnp.where(has_port, slot16, 0).astype(jnp.int32)
        exact_words = tables.l4_allow_bits[
            batch.ep_index, batch.direction, j, word
        ]
        exact_bit = ((exact_words >> bit) & 1).astype(bool)
        meta = tables.l4_meta[batch.ep_index, batch.direction, j]
        proxy = (meta >> 1).astype(jnp.int32)
        wild = (meta & 1).astype(bool)
        probe1 = known & has_port & exact_bit
        probe3 = has_port & wild

    # -- probe 2: L3-only (identity, 0, 0) ----------------------------------
    if l3_bit is not None:
        probe2 = known & l3_bit
    else:
        l3_words = tables.l3_allow_bits[
            batch.ep_index, batch.direction, word
        ]
        probe2 = known & ((l3_words >> bit) & 1).astype(bool)

    return probe1, probe2, probe3, proxy, j, idx


def _combine(probe1, probe2, probe3, proxy, frag) -> Verdicts:
    """Lattice combine (policy.h:62-109 order; fragments skip L4
    probes)."""
    p1 = probe1 & ~frag
    p3 = probe3 & ~frag
    allowed = p1 | probe2 | p3

    proxy_out = jnp.where(p1 | (~probe2 & p3), proxy, 0)
    proxy_out = jnp.where(allowed, proxy_out, 0)

    kind = jnp.where(
        p1,
        MATCH_L4,
        jnp.where(
            probe2,
            MATCH_L3,
            jnp.where(
                p3,
                MATCH_L4_WILD,
                jnp.where(frag, MATCH_FRAG_DROP, MATCH_NONE),
            ),
        ),
    ).astype(jnp.uint8)

    return Verdicts(
        allowed=allowed.astype(jnp.uint8),
        proxy_port=proxy_out,
        match_kind=kind,
    )


def _verdict_kernel(tables: PolicyTables, batch: TupleBatch) -> Verdicts:
    probe1, probe2, probe3, proxy, _, _ = _probes(tables, batch)
    return _combine(probe1, probe2, probe3, proxy, batch.is_fragment)


def _counter_cols(v, batch, j, idx, kg: int):
    """Scatter ingredients for the per-entry counters: returns
    (ep_index, direction, col, weight) — shared by the in-kernel
    accumulate and the paired-dispatch merged scatter so the two can
    never diverge."""
    hit_l4 = (v.match_kind == MATCH_L4) | (v.match_kind == MATCH_L4_WILD)
    hit_l3 = v.match_kind == MATCH_L3
    col = jnp.where(hit_l4, j, kg + idx)
    weight = (hit_l4 | hit_l3).astype(jnp.uint32)
    return batch.ep_index, batch.direction, col, weight


def _accumulate_counters(v, batch, j, idx, acc, kg: int):
    """Scatter the batch's lattice hits into the carried counter
    buffer (policy_entry packets, policy.h:66-68) — ONE scatter: the
    L4 slot axis and the L3 identity axis share a flat column space
    ([0, Kg) = L4 slots, [Kg, Kg+N) = L3 identities; a tuple matches
    at most one entry, policy.h's single matched policy_entry).
    `kg` is the static slot count (tables.l4_meta.shape[2]).  Callers
    donate the buffer across batches (XLA updates in place) instead of
    materializing fresh [E, 2, N] tensors per batch."""
    ep, d, col, weight = _counter_cols(v, batch, j, idx, kg)
    return acc.at[ep, d, col].add(weight)


# ---------------------------------------------------------------------------
# On-device telemetry: per-direction stage/drop accounting
# ---------------------------------------------------------------------------
# Column space of the [2, TELEM_COLS] u32 telemetry accumulator the
# instrumented datapath kernels carry alongside the per-entry counter
# buffer (row 0 = ingress, row 1 = egress).  The columns partition the
# batch by stage outcome, so the host fold can reconstruct
# cilium_drop_count_total{reason,direction} /
# cilium_policy_verdict_total / cilium_forward_count_total without
# pulling per-tuple verdict columns off the device:
#
#   * TOTAL/FORWARDED/DENIED: final combine outcome;
#   * DROP_*: disjoint drop attribution (prefilter first, then the
#     lattice's frag/policy split — bpf/lib/common.h reason codes);
#   * MATCH_*: the lattice verdict histogram (the per-tuple
#     match_kind, summed);
#   * LB/CT/IPCACHE/PROXY: intermediate stage outcomes (DNAT applied,
#     conntrack state, world fallback, proxy redirect).
TELEM_TOTAL = 0
TELEM_FORWARDED = 1
TELEM_DENIED = 2
TELEM_DROP_PREFILTER = 3
TELEM_DROP_POLICY = 4
TELEM_DROP_FRAG = 5
TELEM_MATCH_L4 = 6
TELEM_MATCH_L3 = 7
TELEM_MATCH_L4_WILD = 8
TELEM_MATCH_NONE = 9
TELEM_MATCH_FRAG = 10
TELEM_LB_DNAT = 11
TELEM_CT_NEW = 12
TELEM_CT_ESTABLISHED = 13
TELEM_CT_REPLY = 14
TELEM_CT_RELATED = 15
TELEM_CT_BYPASS_ALLOW = 16
TELEM_CT_DELETE = 17
TELEM_IPCACHE_WORLD = 18
TELEM_PROXY_REDIRECT = 19
TELEM_COLS = 20

TELEM_NAMES = (
    "total",
    "forwarded",
    "denied",
    "drop_prefilter",
    "drop_policy",
    "drop_frag",
    "match_l4",
    "match_l3",
    "match_l4_wild",
    "match_none",
    "match_frag",
    "lb_dnat",
    "ct_new",
    "ct_established",
    "ct_reply",
    "ct_related",
    "ct_bypass_allow",
    "ct_delete",
    "ipcache_world",
    "proxy_redirect",
)


def make_telemetry_buffers():
    """Zeroed [2, TELEM_COLS] u32 device telemetry accumulator
    (direction-major, TELEM_* columns) — carried and donated across
    batches like the counter buffer; fold host-side with
    cilium_tpu.telemetry.fold_telemetry."""
    return jnp.zeros((2, TELEM_COLS), jnp.uint32)


def telemetry_masks(
    pre_dropped,
    ct_result,
    match_kind,
    allowed,
    ct_delete,
    proxy_port,
    lb_slave,
    ipcache_miss,
    xp=jnp,
):
    """The TELEM_* column masks as a list of bool [B] arrays, in
    column order.  One implementation serves BOTH the traced device
    kernel (xp=jnp) and the numpy host fold (xp=np): the bit-identity
    gate between the on-device accumulator and the host per-stage
    histogram holds by construction.

    All inputs are the DatapathVerdicts columns of the same names
    (any integer/bool dtype)."""
    from cilium_tpu.ct.table import (
        CT_ESTABLISHED,
        CT_NEW,
        CT_RELATED,
        CT_REPLY,
    )

    allowed = allowed.astype(bool)
    pre = pre_dropped.astype(bool)
    kind = match_kind
    denied = ~allowed
    post = denied & ~pre  # lattice-attributed drops
    pass_ct = (ct_result == CT_REPLY) | (ct_result == CT_RELATED)
    pol_allow = (
        (kind == MATCH_L4)
        | (kind == MATCH_L3)
        | (kind == MATCH_L4_WILD)
    )
    return [
        xp.ones(allowed.shape, bool),
        allowed,
        denied,
        pre,
        post & (kind == MATCH_NONE),
        post & (kind == MATCH_FRAG_DROP),
        kind == MATCH_L4,
        kind == MATCH_L3,
        kind == MATCH_L4_WILD,
        kind == MATCH_NONE,
        kind == MATCH_FRAG_DROP,
        lb_slave > 0,
        ct_result == CT_NEW,
        ct_result == CT_ESTABLISHED,
        ct_result == CT_REPLY,
        ct_result == CT_RELATED,
        pass_ct & ~pol_allow & ~pre,
        ct_delete.astype(bool),
        ipcache_miss.astype(bool),
        (proxy_port > 0) & allowed,
    ]


def make_counter_buffers(tables: PolicyTables):
    """Zeroed device counter buffer [E, 2, Kg + N] u32 — L4 slot
    columns first, then L3 identity columns (split with
    split_counters)."""
    e_count, _, k = tables.l4_meta.shape
    n = tables.id_table.shape[0]
    return jnp.zeros((e_count, 2, k + n), jnp.uint32)


def split_counters(acc, tables: PolicyTables):
    """Flat accumulator → (l4 [E, 2, Kg], l3 [E, 2, N]) views."""
    k = tables.l4_meta.shape[2]
    return acc[:, :, :k], acc[:, :, k:]


def _verdict_kernel_with_counters(tables: PolicyTables, batch: TupleBatch):
    """Verdicts + fresh per-batch counters (allocates; for one-shot
    callers and tests — streaming paths use the donated-accumulator
    variants)."""
    probe1, probe2, probe3, proxy, j, idx = _probes(tables, batch)
    v = _combine(probe1, probe2, probe3, proxy, batch.is_fragment)
    acc = make_counter_buffers(tables)
    acc = _accumulate_counters(
        v, batch, j, idx, acc, tables.l4_meta.shape[2]
    )
    l4_counts, l3_counts = split_counters(acc, tables)
    return v, l4_counts, l3_counts


evaluate_batch = jax.jit(_verdict_kernel)


def _verdict_kernel_from_ips(lpm_tables, policy_tables, src_ips, batch):
    """Fused datapath: derive the source identity from the raw IP via
    the DIR-24-8 ipcache (bpf_netdev.c's identity derivation before
    the tail call into the policy program), then run the lattice.
    IPs that miss the ipcache resolve to identity 0 (unknown)."""
    from cilium_tpu.ipcache.lpm import _lookup_kernel

    ids = _lookup_kernel(lpm_tables, src_ips.astype(jnp.uint32))
    resolved = TupleBatch(
        ep_index=batch.ep_index,
        identity=ids,
        dport=batch.dport,
        proto=batch.proto,
        direction=batch.direction,
        is_fragment=batch.is_fragment,
    )
    return _verdict_kernel(policy_tables, resolved)


evaluate_batch_from_ips = jax.jit(_verdict_kernel_from_ips)


def make_sharded_evaluator(mesh: Optional[jax.sharding.Mesh] = None,
                           batch_axis: str = "batch"):
    """Return a jitted evaluator with the batch axis sharded over the
    mesh and tables replicated (SURVEY.md §2.9: flow batches shard like
    packets shard across nodes; tables replicate like BPF maps
    replicate per node).

    With `mesh=None` this degrades to the single-device evaluator.
    """
    if mesh is None:
        return evaluate_batch

    replicated = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()
    )
    batch_sharded = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(batch_axis)
    )

    table_shardings = PolicyTables(
        id_table=replicated,
        id_direct=replicated,
        id_lo_len=replicated,
        port_slot=replicated,
        l4_meta=replicated,
        l4_allow_bits=replicated,
        l3_allow_bits=replicated,
        generation=replicated,
        l4_hash_rows=replicated,
        l4_hash_stash=replicated,
        l4_wild_rows=replicated,
        l4_wild_stash=replicated,
    )
    batch_shardings = TupleBatch(
        ep_index=batch_sharded,
        identity=batch_sharded,
        dport=batch_sharded,
        proto=batch_sharded,
        direction=batch_sharded,
        is_fragment=batch_sharded,
    )
    out_shardings = Verdicts(
        allowed=batch_sharded,
        proxy_port=batch_sharded,
        match_kind=batch_sharded,
    )
    return jax.jit(
        _verdict_kernel,
        in_shardings=(table_shardings, batch_shardings),
        out_shardings=out_shardings,
    )
