"""Live elastic resharding: stop-free mesh growth/shrink with
incremental row migration, mid-migration fault tolerance, and
rollback.

A shard-count change is DATA MOVEMENT, not a redeploy.  The N+1
augmented replica layout is ntp-invariant in total shape (a sharded
axis [S] is [2S] under any table-axis size — compiler/partition.py),
so re-sharding tp_src -> tp_dst is a pure index permutation of the
augmented layout, and the permutation's owned-row delta
(partition.reshard_moved_rows / datapath_reshard_moved_rows) names
exactly the augmented rows whose bytes are not already resident
under the target column assignment.  A ReshardPlan treats that delta
as a migration work queue:

  * `begin()` opens a relayout window on the policy replica store
    (DeviceTableStore.begin_relayout) and, when a fused plane is
    attached, the DatapathStore — the standby epoch slot is seeded
    with the target layout, every MOVED row zeroed, while the live
    epoch keeps serving untouched (epoch double-buffering is the
    cutover seam);
  * `step()` streams one bounded-byte batch of moved rows into the
    staged epoch through the SAME scatter machinery chip
    re-admission uses (repair_rows / relayout_scatter), probing the
    `reshard.migrate` fault site once per target-column chip it is
    about to write;
  * `on_publish()` is the churn dual-apply: a control-plane publish
    during the window patches the LIVE epoch in place (the stores'
    publish-during-relayout path, non-donated — zero drain) and the
    plan folds the same change into the staged TARGET host,
    re-queueing every augmented row whose contents changed
    (re-streaming an already-migrated row is always safe).  Churn
    the window cannot absorb (geometry change, full upload, a
    publish nobody dual-applied) deterministically RESTARTS the
    migration as a full streamed upload into the target layout —
    never a half-consistent cutover;
  * `cutover()` flips both stores (the staged epoch becomes live
    under the new layout stamp, the old live epoch stays resident as
    the source-layout spare whose next delta publish is
    layout-refused into exactly one full upload), re-aims the router
    (ChipFailoverRouter.adopt_reshard), and closes any armed shadow
    window `stale` (ShadowPlane.notify_cutover) — the serving stream
    never drains;
  * a chip kill mid-migration (`reshard.migrate` firing, or a real
    breaker event) either COMPLETES via the survivors' replica
    copies — the dead column's own rows are dropped from the queue,
    its data remains reachable through the backup copies streamed to
    its right neighbour, and the breaker bank routes reads there
    after cutover — or ROLLS BACK by dropping the staged epoch (the
    fully-consistent source layout was never touched).

Simulation boundary: on the virtual CPU mesh every SPMD scatter
lands on all devices, so what the plan measures is the migration
TRAFFIC a real topology would ship — `reshard_bytes_h2d` counts the
streamed moved-owner rows (O(rows whose owner changed), never
O(world) except on an explicit full restart), benched against the
stop-the-world full-upload comparator in tools/reshardprof.py.  The
target mesh keeps every surviving source column on its original
devices (reshard_target_mesh), so "retained row, zero bytes" is a
statement about real placement, not bookkeeping.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu import faultinject, tracing
from cilium_tpu.compiler import partition
from cilium_tpu.compiler.tables import split_hot
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics

log = get_logger("reshard")

# default per-step streaming budget: raw payload bytes per migration
# step (pow2 padding in the scatter path can at most double it)
DEFAULT_STEP_BYTES = 1 << 20


def reshard_target_mesh(router, target_tp: int):
    """Build the target mesh for a table-axis resize, keeping every
    SURVIVING source column on its original devices (column identity
    is what makes a retained row genuinely device-resident) and
    assigning new columns the next free devices."""
    import jax
    from jax.sharding import Mesh

    target_tp = int(target_tp)
    dp, tp_src = router.dp, router.tp
    devs_by_id = {int(d.id): d for d in jax.devices()}
    used = {int(x) for x in router.ordinals.ravel()}
    free = [d for d in jax.devices() if int(d.id) not in used]
    grow_cols = max(0, target_tp - tp_src)
    if len(free) < dp * grow_cols:
        raise ValueError(
            f"reshard to tp={target_tp} needs {dp * grow_cols} free "
            f"devices, have {len(free)}"
        )
    grid = np.empty((dp, target_tp), dtype=object)
    for r in range(dp):
        for c in range(target_tp):
            if c < tp_src:
                grid[r, c] = devs_by_id[int(router.ordinals[r, c])]
            else:
                grid[r, c] = free.pop(0)
    return Mesh(grid, (router.batch_axis, router.table_axis))


class ReshardPlan:
    """One live migration tp_src -> tp_dst over a ChipFailoverRouter.

    Drive it with `run()` (begin -> bounded steps -> cutover), or
    call `begin()` / `step()` / `cutover()` / `rollback()` yourself
    to interleave serving, churn (`on_publish`) and fault injection
    between steps.  `on_fault` picks the mid-migration chip-kill
    policy: "complete" (drop the dead column's own rows — its data
    survives in the replica copies streamed to its backup owner —
    and open its breakers so post-cutover routing reads the backups)
    or "rollback" (drop the staged epoch; the source layout never
    stopped serving)."""

    def __init__(
        self,
        router,
        target_mesh,
        step_bytes: int = DEFAULT_STEP_BYTES,
        on_fault: str = "complete",
        dtables=None,
        shadow=None,
    ) -> None:
        if on_fault not in ("complete", "rollback"):
            raise ValueError(
                f"on_fault must be 'complete' or 'rollback', got "
                f"{on_fault!r}"
            )
        self.router = router
        self.target_mesh = target_mesh
        self.step_bytes = max(int(step_bytes), 1)
        self.on_fault = on_fault
        self.shadow = shadow
        self.table_axis = router.table_axis
        self.ntp_src = int(router.tp)
        self.ntp_dst = int(target_mesh.shape[self.table_axis])
        # un-augmented fused datapath world (required when the
        # router has a datapath plane attached); refreshed by
        # on_publish(dtables=...)
        self._dtables = dtables
        self._pending: deque = deque()
        self._policy_host = None  # staged TARGET augmented host
        self._pins: Optional[Tuple[int, int]] = None  # (epoch, layout)
        self._live_stamp_seen = None
        self._dp_epoch_seen = None
        self._dead_cols: set = set()
        self.state = "idle"  # idle|migrating|done|rolled_back
        self.stats = {
            "steps": 0, "bytes_h2d": 0, "restarts": 0,
            "dead_cols": [], "outcome": None, "ms": 0.0,
            "queued_items": 0,
        }
        self._t0 = None

    # -- work-queue construction ---------------------------------------------

    def _enqueue(self, plane: str, key, axis: int, idx, block):
        """Split one leaf's row set into bounded-byte chunks.
        `block` is the augmented rows-per-target-column stride (None
        for replicated leaves, which land on every column)."""
        idx = np.asarray(idx, np.int64)
        if idx.size == 0:
            return
        host = (
            self._policy_host if plane == "policy"
            else self._dp_host()
        )
        leaf = (
            getattr(host, key) if plane == "policy"
            else getattr(getattr(host, key[0]), key[1])
        )
        arr = np.asarray(leaf)
        row_bytes = max(arr.nbytes // max(arr.shape[axis], 1), 1)
        per = max(1, self.step_bytes // row_bytes)
        for lo in range(0, idx.size, per):
            chunk = idx[lo: lo + per]
            cols = (
                tuple(range(self.ntp_dst)) if block is None
                else tuple(
                    int(c) for c in np.unique(chunk // block)
                )
            )
            self._pending.append({
                "plane": plane, "key": key, "axis": int(axis),
                "idx": chunk, "block": block, "cols": cols,
                "bytes": int(chunk.size * row_bytes + chunk.nbytes),
            })
            self.stats["queued_items"] += 1

    def _dp_host(self):
        slot = self.router.dp_store._slots[
            self.router.dp_store._cur ^ 1
        ]
        return slot["host"]

    def _policy_tables(self, tables):
        """The store-visible host layout of `tables` (hot split when
        the store is hot-only), before augmentation."""
        store = self.router.store
        return split_hot(tables) if store._hot_only else tables

    # -- lifecycle -----------------------------------------------------------

    def begin(self) -> "ReshardPlan":
        """Open the relayout windows and build the moved-row queue.
        The live epochs keep serving; nothing is drained."""
        if self.state != "idle":
            raise RuntimeError(f"plan already {self.state}")
        router = self.router
        store = router.store
        if store._transform_fn is None:
            raise ValueError(
                "resharding requires a replica store "
                "(engine.sharded.make_replica_store)"
            )
        self._t0 = time.perf_counter()
        t = self._policy_tables(router._tables)
        moved = partition.reshard_moved_rows(
            t, self.ntp_src, self.ntp_dst, self.table_axis
        )
        host_aug = partition.replicate_table_leaves(
            t, self.ntp_dst, self.table_axis
        )
        shardings = partition.table_shardings(
            self.target_mesh, host_aug, self.table_axis
        )
        digest = partition.replica_partition_digest(
            self.table_axis, ntp=self.ntp_dst
        )
        self._policy_host = host_aug
        self._pins = store.begin_relayout(
            host_aug, moved, shardings, digest
        )
        for name, (axis, idx) in sorted(moved.items()):
            n_aug = int(
                np.asarray(getattr(host_aug, name)).shape[axis]
            )
            self._enqueue(
                "policy", name, axis, idx, n_aug // self.ntp_dst
            )
        if router.dp_store is not None:
            if self._dtables is None:
                raise ValueError(
                    "router has a fused datapath plane: pass "
                    "dtables (the un-augmented fused world) to "
                    "ReshardPlan"
                )
            dmoved = router.dp_store.begin_relayout(
                self._dtables, self.target_mesh
            )
            dhost = self._dp_host()
            for (fam, leaf), (axis, idx) in sorted(dmoved.items()):
                n_aug = int(
                    np.asarray(
                        getattr(getattr(dhost, fam), leaf)
                    ).shape[axis]
                )
                self._enqueue(
                    "datapath", (fam, leaf), axis, idx,
                    n_aug // self.ntp_dst,
                )
            self._dp_epoch_seen = router.dp_store.epoch
        self._live_stamp_seen = store.current_stamp()
        self.state = "migrating"
        tracing.add_event(
            "reshard.begin", ntp_src=self.ntp_src,
            ntp_dst=self.ntp_dst,
            queued=len(self._pending),
        )
        return self

    def pending(self) -> int:
        return len(self._pending)

    # -- churn dual-apply ----------------------------------------------------

    def on_publish(self, tables, dtables=None) -> None:
        """Fold a control-plane publish (which just patched the LIVE
        epochs through the stores' relayout-aware path) into the
        staged TARGET: rebuild the target augmented host, diff it
        against the kept one, and re-queue every augmented row whose
        contents changed.  Churn the window cannot absorb marks the
        plan for a deterministic full-into-target restart."""
        if self.state != "migrating":
            return
        store = self.router.store
        rel = store.relayout_state()
        if rel is None or rel["broken"]:
            self._restart_full()
            return
        t = self._policy_tables(tables)
        new_aug = partition.replicate_table_leaves(
            t, self.ntp_dst, self.table_axis
        )
        old_aug = self._policy_host
        rep = partition.replica_axes(t, self.ntp_dst, self.table_axis)
        queue: List[Tuple[str, int, np.ndarray, Optional[int]]] = []
        for f in dataclasses.fields(type(new_aug)):
            name = f.name
            if name == "generation":
                continue
            old = getattr(old_aug, name)
            new = getattr(new_aug, name)
            if old is None and new is None:
                continue
            if (
                old is None
                or new is None
                or np.asarray(old).shape != np.asarray(new).shape
            ):
                # a leaf appeared/vanished/resized: layout change
                self._restart_full()
                return
            old_np, new_np = np.asarray(old), np.asarray(new)
            axis = rep.get(name)
            if axis is not None:
                mo = np.moveaxis(old_np, axis, 0)
                mn = np.moveaxis(new_np, axis, 0)
                chg = np.flatnonzero(
                    np.any(
                        mn.reshape(mn.shape[0], -1)
                        != mo.reshape(mo.shape[0], -1),
                        axis=1,
                    )
                )
                if chg.size:
                    queue.append((
                        name, axis, chg,
                        new_np.shape[axis] // self.ntp_dst,
                    ))
            elif not np.array_equal(old_np, new_np):
                queue.append((
                    name, 0,
                    np.arange(new_np.shape[0], dtype=np.int64),
                    None,
                ))
        self._policy_host = new_aug
        self._pins = store.relayout_update_host(new_aug)
        for name, axis, idx, block in queue:
            self._enqueue("policy", name, axis, idx, block)
        self._live_stamp_seen = store.current_stamp()
        if self.router.dp_store is not None and dtables is not None:
            self._dtables = dtables
            changed = self.router.dp_store.relayout_update(dtables)
            if changed is None:
                self._restart_full()
                return
            dhost = self._dp_host()
            for (fam, leaf), (axis, idx) in sorted(changed.items()):
                n_aug = int(
                    np.asarray(
                        getattr(getattr(dhost, fam), leaf)
                    ).shape[axis]
                )
                self._enqueue(
                    "datapath", (fam, leaf), axis, idx,
                    n_aug // self.ntp_dst,
                )
            self._dp_epoch_seen = self.router.dp_store.epoch

    # -- restart / drift -----------------------------------------------------

    def _drifted(self) -> bool:
        """True when the live world moved without a dual-apply (a
        publish nobody routed through on_publish, or a window marked
        broken): the staged target can no longer be trusted to
        converge, so the plan restarts instead of cutting over."""
        store = self.router.store
        rel = store.relayout_state()
        if rel is None or rel["broken"]:
            return True
        if store.current_stamp() != self._live_stamp_seen:
            return True
        if self.router.dp_store is not None:
            drel = self.router.dp_store.relayout_state()
            if drel is None or drel["broken"]:
                return True
            if self.router.dp_store.epoch != self._dp_epoch_seen:
                return True
        return False

    def _restart_full(self) -> None:
        """The deterministic refusal path: drop the staged epoch and
        re-open the window as a FULL streamed upload into the target
        layout (every augmented replica row queued as moved) from
        the router's current world.  Still stop-free — the live
        epoch serves throughout; only the byte bill becomes
        O(world)."""
        router = self.router
        router.store.rollback_relayout()
        if router.dp_store is not None:
            router.dp_store.rollback_relayout()
        self._pending.clear()
        self._dead_cols.clear()
        self.stats["restarts"] += 1
        metrics.reshard_total.inc("restart_full")
        tracing.add_event(
            "reshard.restart_full", ntp_dst=self.ntp_dst
        )
        t = self._policy_tables(router._tables)
        host_aug = partition.replicate_table_leaves(
            t, self.ntp_dst, self.table_axis
        )
        rep = partition.replica_axes(
            t, self.ntp_dst, self.table_axis
        )
        moved_all = {
            name: (
                axis,
                np.arange(
                    np.asarray(getattr(host_aug, name)).shape[axis],
                    dtype=np.int64,
                ),
            )
            for name, axis in rep.items()
        }
        shardings = partition.table_shardings(
            self.target_mesh, host_aug, self.table_axis
        )
        digest = partition.replica_partition_digest(
            self.table_axis, ntp=self.ntp_dst
        )
        self._policy_host = host_aug
        self._pins = router.store.begin_relayout(
            host_aug, moved_all, shardings, digest
        )
        for name, (axis, idx) in sorted(moved_all.items()):
            n_aug = int(
                np.asarray(getattr(host_aug, name)).shape[axis]
            )
            self._enqueue(
                "policy", name, axis, idx, n_aug // self.ntp_dst
            )
        if router.dp_store is not None:
            dmoved = router.dp_store.begin_relayout(
                self._dtables, self.target_mesh
            )
            dhost = self._dp_host()
            drep = partition.datapath_all_replica_axes(
                self._dtables, self.ntp_dst, self.table_axis
            )
            for (fam, leaf), axis in sorted(drep.items()):
                n_aug = int(
                    np.asarray(
                        getattr(getattr(dhost, fam), leaf)
                    ).shape[axis]
                )
                self._enqueue(
                    "datapath", (fam, leaf), axis,
                    np.arange(n_aug, dtype=np.int64),
                    n_aug // self.ntp_dst,
                )
            self._dp_epoch_seen = router.dp_store.epoch
        self._live_stamp_seen = router.store.current_stamp()

    # -- fault handling ------------------------------------------------------

    def _target_ordinals_of_col(self, col: int) -> List[int]:
        axes = list(self.target_mesh.axis_names)
        out = []
        for idx, dev in np.ndenumerate(self.target_mesh.devices):
            coord = dict(zip(axes, idx))
            if coord[self.table_axis] == col:
                out.append(int(dev.id))
        return out

    def _col_of_ordinal(self, ordinal: int) -> Optional[int]:
        axes = list(self.target_mesh.axis_names)
        for idx, dev in np.ndenumerate(self.target_mesh.devices):
            if int(dev.id) == int(ordinal):
                return int(dict(zip(axes, idx))[self.table_axis])
        return None

    def _handle_fault(self, exc, probed_col: int) -> Optional[dict]:
        """A chip died (fault site fired) mid-migration.  The fault
        domain is the target table COLUMN — the unit of data
        placement the migration streams to.  Returns a terminal
        status dict on rollback, None to continue (complete-leg)."""
        ordinal = getattr(exc, "chip", None)
        col = (
            self._col_of_ordinal(ordinal)
            if ordinal is not None else None
        )
        if col is None:
            col = int(probed_col)
        if self.on_fault == "rollback":
            # a REAL chip in the serving mesh still failed: open its
            # breakers so the (untouched) source layout degrades
            # through the normal replica routing, then drop the
            # staged epoch
            for o in self._target_ordinals_of_col(col):
                if (self.router.ordinals == o).any():
                    self.router.bank.record_failure(
                        o, f"reshard.migrate fault: {exc}"
                    )
            self.rollback(reason=f"fault on column {col}")
            return dict(self.stats)
        # complete via survivors: the dead column's OWN rows stop
        # streaming (nothing will read them — routing excludes dead
        # owners), but the backup copies of its slice, resident in
        # its right neighbour's region, keep streaming, so the data
        # stays reachable post-cutover
        self._dead_cols.add(col)
        self.stats["dead_cols"] = sorted(self._dead_cols)
        kept = deque()
        for item in self._pending:
            if item["block"] is None:
                kept.append(item)
                continue
            idx = item["idx"]
            mask = (idx // item["block"]) != col
            if mask.all():
                kept.append(item)
            elif mask.any():
                item = dict(item, idx=idx[mask])
                item["cols"] = tuple(
                    int(c)
                    for c in np.unique(
                        item["idx"] // item["block"]
                    )
                )
                kept.append(item)
        self._pending = kept
        for o in self._target_ordinals_of_col(col):
            self.router.bank.record_failure(
                o, f"reshard.migrate fault: {exc}"
            )
        tracing.add_event(
            "reshard.chip_fault", col=col,
            action="complete_via_replicas",
        )
        log.warning(
            "chip fault mid-migration; completing via replica "
            "copies",
            extra={"fields": {"column": col}},
        )
        return None

    # -- migration steps -----------------------------------------------------

    def step(self) -> dict:
        """Stream one bounded-byte batch of queued rows into the
        staged target epoch.  Returns a status dict ({"done": bool,
        "bytes": int, ...}); a rollback-leg fault makes the plan
        terminal (state == "rolled_back")."""
        if self.state != "migrating":
            raise RuntimeError(f"plan is {self.state}, not migrating")
        if self._drifted():
            self._restart_full()
        if not self._pending:
            return {"done": True, "bytes": 0}
        batch = []
        budget = self.step_bytes
        while self._pending and (not batch or budget > 0):
            item = self._pending.popleft()
            batch.append(item)
            budget -= item["bytes"]
        cols = sorted({c for it in batch for c in it["cols"]})
        # the fault seam, probed once per target-column chip this
        # step is about to write (chip-scoped schedules fire when
        # their chip is a recipient); nothing-armed serving pays one
        # lock-free emptiness read
        if faultinject.any_armed():
            for c in cols:
                if c in self._dead_cols:
                    continue
                for o in self._target_ordinals_of_col(c):
                    try:
                        faultinject.fire("reshard.migrate", chip=o)
                    except faultinject.FaultInjected as exc:
                        terminal = self._handle_fault(exc, c)
                        if terminal is not None:
                            return dict(terminal, done=True)
                        # re-filter THIS step's batch too
                        batch = [
                            dict(
                                it,
                                idx=it["idx"][
                                    (it["idx"] // it["block"])
                                    != c
                                ],
                            )
                            if it["block"] is not None
                            else it
                            for it in batch
                        ]
                        batch = [
                            it for it in batch if it["idx"].size
                        ]
        policy_sets: Dict[str, Tuple[int, np.ndarray]] = {}
        dp_sets: Dict[tuple, Tuple[int, np.ndarray]] = {}
        for it in batch:
            tgt = policy_sets if it["plane"] == "policy" else dp_sets
            prev = tgt.get(it["key"])
            if prev is None:
                tgt[it["key"]] = (it["axis"], it["idx"])
            else:
                tgt[it["key"]] = (
                    it["axis"],
                    np.unique(
                        np.concatenate([prev[1], it["idx"]])
                    ),
                )
        bytes_h2d = 0
        if policy_sets:
            bytes_h2d += self.router.store.repair_rows(
                policy_sets, spare=True,
                expect_epoch=self._pins[0],
                expect_layout=self._pins[1],
            )
        if dp_sets:
            bytes_h2d += self.router.dp_store.relayout_scatter(
                dp_sets
            )
        self.stats["steps"] += 1
        self.stats["bytes_h2d"] += bytes_h2d
        metrics.reshard_steps_total.inc()
        metrics.reshard_bytes_h2d_total.inc(value=bytes_h2d)
        return {
            "done": not self._pending, "bytes": bytes_h2d,
            "cols": cols,
        }

    # -- terminals -----------------------------------------------------------

    def cutover(self) -> dict:
        """Flip both stores to the staged target epoch, re-aim the
        router, and close any armed shadow window stale.  Runs at a
        batch boundary (the caller holds the stream between
        dispatches — ServingPlane.run_at_batch_boundary is the
        serving-path seam); in-flight batches completed on the
        source epoch, whose buffers were never touched."""
        if self.state != "migrating":
            raise RuntimeError(f"plan is {self.state}, not migrating")
        if self._drifted():
            self._restart_full()
            if self._pending:
                # churn forced a full-into-target restart at the
                # brink of cutover: the caller streams the refilled
                # queue and tries again
                return dict(self.stats, deferred=True)
        if self._pending:
            raise RuntimeError(
                f"{len(self._pending)} migration chunks still "
                "queued; stream them before cutover"
            )
        router = self.router
        ntp = self.ntp_dst
        axis = self.table_axis
        mesh = self.target_mesh
        router.store.cutover_relayout(
            shardings_fn=lambda aug: partition.table_shardings(
                mesh, aug, axis
            ),
            partition_digest=partition.replica_partition_digest(
                axis, ntp=ntp
            ),
            transform_fn=lambda t: partition.replicate_table_leaves(
                t, ntp, axis
            ),
            delta_transform_fn=lambda d, pre: partition.replica_delta(
                d, pre, ntp, axis
            ),
        )
        if router.dp_store is not None:
            router.dp_store.cutover_relayout()
        router.adopt_reshard(mesh, dtables=self._dtables)
        if self.shadow is not None:
            self.shadow.notify_cutover()
        self.state = "done"
        self.stats["outcome"] = "cutover"
        self.stats["ms"] = (time.perf_counter() - self._t0) * 1000.0
        metrics.reshard_total.inc("cutover")
        metrics.reshard_seconds.observe(
            self.stats["ms"] / 1000.0
        )
        tracing.add_event(
            "reshard.cutover", ntp_src=self.ntp_src,
            ntp_dst=self.ntp_dst, steps=self.stats["steps"],
            bytes_h2d=self.stats["bytes_h2d"],
            restarts=self.stats["restarts"],
        )
        log.info(
            "reshard cutover complete",
            extra={"fields": {
                "tp": f"{self.ntp_src}->{self.ntp_dst}",
                "steps": self.stats["steps"],
                "bytes_h2d": self.stats["bytes_h2d"],
            }},
        )
        return dict(self.stats)

    def rollback(self, reason: str = "operator") -> dict:
        """Abandon the migration: both staged epochs drop, the
        fully-consistent source layout keeps serving (it was never
        written to), and the plan is terminal."""
        if self.state not in ("migrating", "idle"):
            return dict(self.stats)
        self.router.store.rollback_relayout()
        if self.router.dp_store is not None:
            self.router.dp_store.rollback_relayout()
        self._pending.clear()
        self.state = "rolled_back"
        self.stats["outcome"] = "rollback"
        self.stats["ms"] = (
            (time.perf_counter() - self._t0) * 1000.0
            if self._t0 else 0.0
        )
        metrics.reshard_total.inc("rollback")
        if self._t0:
            metrics.reshard_seconds.observe(
                self.stats["ms"] / 1000.0
            )
        tracing.add_event("reshard.rollback", reason=reason)
        log.warning(
            "reshard rolled back",
            extra={"fields": {"reason": reason}},
        )
        return dict(self.stats)

    def run(self, max_steps: int = 1 << 16) -> dict:
        """begin -> stream -> cutover, in one call.  A rollback-leg
        fault terminates early with outcome "rollback"."""
        if self.state == "idle":
            self.begin()
        steps = 0
        while self.state == "migrating":
            if self._pending:
                self.step()
                steps += 1
                if steps > max_steps:
                    self.rollback(reason="max_steps exceeded")
            else:
                self.cutover()  # deferred restarts loop back
        return dict(self.stats)
