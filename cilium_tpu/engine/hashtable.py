"""Generic device hash table: host-built open addressing, batched
device lookup in a bounded number of gathers.

The device analog of BPF_MAP_TYPE_HASH for multi-word keys (CT tuples,
LB service keys).  Build keeps load factor ≤ 0.5 and records the
maximum linear displacement, so the device probe loop is a FIXED
unroll (max_disp + 1 slots) — bounded like the kernel's map probe,
no data-dependent control flow under jit.

Key layout: u32 [C, KW]; empty slots hold the all-ones key (callers
must never insert it).  Hash: FNV-1a over the key words, computed
identically on host (build) and device (probe).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

EMPTY = np.uint32(0xFFFFFFFF)

FNV_OFFSET = np.uint32(2166136261)
FNV_PRIME = np.uint32(16777619)


def _fnv1a_host(words: np.ndarray) -> np.ndarray:
    """FNV-1a over u32 words, vectorized: words [N, KW] → u32 [N]."""
    h = np.full(words.shape[0], FNV_OFFSET, dtype=np.uint64)
    for w in range(words.shape[1]):
        for shift in (0, 8, 16, 24):
            byte = (words[:, w].astype(np.uint64) >> shift) & 0xFF
            h = ((h ^ byte) * np.uint64(int(FNV_PRIME))) & 0xFFFFFFFF
    return h.astype(np.uint32)


def fnv1a_device(words) -> "jax.Array":
    """Same hash under jit: words u32 [B, KW] → u32 [B]."""
    import jax.numpy as jnp

    h = jnp.full(words.shape[0], FNV_OFFSET, dtype=jnp.uint32)
    for w in range(words.shape[1]):
        for shift in (0, 8, 16, 24):
            byte = (words[:, w] >> shift) & jnp.uint32(0xFF)
            h = (h ^ byte) * FNV_PRIME
    return h


@dataclass
class HashTable:
    """Pytree: keys u32 [C, KW], value_index i32 [C], plus the static
    probe bound."""

    keys: np.ndarray
    value_index: np.ndarray
    max_probes: int

    def tree_flatten(self):
        return ((self.keys, self.value_index), self.max_probes)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            HashTable,
            lambda t: t.tree_flatten(),
            lambda aux, ch: HashTable.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def build_hash_table(keys: np.ndarray, min_capacity: int = 16) -> HashTable:
    """keys u32 [N, KW] (unique) → open-addressed table, linear
    probing, load ≤ 0.5.  value_index[slot] = row in `keys`."""
    n, kw = keys.shape
    capacity = min_capacity
    while capacity < 2 * max(n, 1):
        capacity *= 2
    mask = capacity - 1

    table_keys = np.full((capacity, kw), EMPTY, dtype=np.uint32)
    value_index = np.full(capacity, -1, dtype=np.int32)
    hashes = _fnv1a_host(keys.astype(np.uint32))
    max_disp = 0
    for i in range(n):
        slot = int(hashes[i]) & mask
        disp = 0
        while value_index[slot] >= 0:
            slot = (slot + 1) & mask
            disp += 1
        table_keys[slot] = keys[i]
        value_index[slot] = i
        max_disp = max(max_disp, disp)
    return HashTable(
        keys=table_keys, value_index=value_index, max_probes=max_disp + 1
    )


def lookup_batch(table: HashTable, query: "jax.Array"):
    """query u32 [B, KW] → (found bool [B], index i32 [B]).

    Fixed max_probes-step linear probe; each step is KW gathers + a
    compare.  `index` is the row passed to build_hash_table (-1-safe:
    callers must gate on `found`)."""
    import jax.numpy as jnp

    capacity, kw = table.keys.shape
    mask = jnp.uint32(capacity - 1)
    h = fnv1a_device(query) & mask

    found = jnp.zeros(query.shape[0], dtype=bool)
    index = jnp.zeros(query.shape[0], dtype=jnp.int32)
    keys = jnp.asarray(table.keys)
    value_index = jnp.asarray(table.value_index)
    slot = h.astype(jnp.int32)
    for _ in range(table.max_probes):
        row = keys[slot]  # [B, KW]
        hit = jnp.all(row == query, axis=1) & ~found
        index = jnp.where(hit, value_index[slot], index)
        found = found | hit
        slot = (slot + 1) & jnp.int32(capacity - 1)
    # A query equal to the all-ones EMPTY sentinel would "hit" empty
    # slots and return index=-1; current CT/LB key packings can't
    # produce it, but mask it out so a future caller fails safe.
    is_sentinel = jnp.all(query == jnp.uint32(EMPTY), axis=1)
    found = found & ~is_sentinel
    index = jnp.where(is_sentinel, 0, index)
    return found, index
