"""Generic device hash table: host-built open addressing, batched
device lookup in ONE windowed gather plus a fixed-size stash compare.

The device analog of BPF_MAP_TYPE_HASH for multi-word keys (CT tuples,
LB service keys).  Three TPU-first properties:

  * the probe is a FIXED window of PROBE_WINDOW consecutive slots
    fetched as a single [B, P, KW] gather — the window is contiguous
    in HBM (P slots × KW u32 = one or two cache lines), so the whole
    probe costs ~one random gather instead of max_probes × KW
    scattered ones.
  * keys that cannot place within their window (hash-cluster tails,
    adversarial collisions) go to a FIXED-size stash region appended
    to the table; lookup broadcast-compares the stash against every
    query.  The stash bounds worst-case behavior the way the kernel's
    per-cpu overflow lists do, without data-dependent control flow.
  * every shape — capacity, stash, window — is pinned by the caller,
    so churn rebuilds of equal-envelope maps produce identical jit
    cache keys (no mid-replay retrace).  Placement is vectorized
    (round-based claim resolution over NumPy arrays), so building a
    64k-entry table is milliseconds, not a Python insertion loop.
    Lookup correctness does not depend on insertion order because the
    probe never early-terminates on empty slots.

Key layout: u32 [C + S, KW] (main region then stash); empty slots
hold the all-ones key (callers must never insert it).  Hash: FNV-1a
over the key words, computed identically on host (build) and device
(probe).  Deletion (by a future incremental builder) is clearing the
slot back to EMPTY — safe for the same no-early-termination reason.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

EMPTY = np.uint32(0xFFFFFFFF)

FNV_OFFSET = np.uint32(2166136261)
FNV_PRIME = np.uint32(16777619)


def trim_pow2_prefix(arr: np.ndarray, used: int) -> np.ndarray:
    """THE stash-trim helper: slice a front-filled overflow stash (or
    any capacity-allocated table) to the smallest pow2 prefix holding
    its `used` occupied rows (never below 1 row — probes expect a
    non-empty axis).  Probes broadcast-compare EVERY stash row
    against every tuple, so capacity rows with never-matching
    sentinels are pure hot-path waste; trimming is bit-identity-safe
    by construction.  One implementation serves the policy hash
    stashes, CT v4/v6, LB inline v4/v6 and the ipcache — callers
    count their own emptiness sentinel and pass `used`."""
    size = 1
    while size < max(used, 1):
        size <<= 1
    return arr[:size]


def _fnv1a_host(words: np.ndarray) -> np.ndarray:
    """FNV-1a over u32 words, vectorized: words [N, KW] → u32 [N]."""
    h = np.full(words.shape[0], FNV_OFFSET, dtype=np.uint64)
    for w in range(words.shape[1]):
        for shift in (0, 8, 16, 24):
            byte = (words[:, w].astype(np.uint64) >> shift) & 0xFF
            h = ((h ^ byte) * np.uint64(int(FNV_PRIME))) & 0xFFFFFFFF
    return h.astype(np.uint32)


def fnv1a_device(words) -> "jax.Array":
    """Same hash under jit: words u32 [B, KW] → u32 [B]."""
    import jax.numpy as jnp

    h = jnp.full(words.shape[0], FNV_OFFSET, dtype=jnp.uint32)
    for w in range(words.shape[1]):
        for shift in (0, 8, 16, 24):
            byte = (words[:, w] >> shift) & jnp.uint32(0xFF)
            h = (h ^ byte) * FNV_PRIME
    return h


@dataclass
class HashTable:
    """Pytree: keys u32 [C+S, KW], value_index i32 [C+S]; capacity of
    the main region and the probe bound are static aux."""

    keys: np.ndarray
    value_index: np.ndarray
    max_probes: int
    capacity: int  # main-region slots; rows [capacity:] are the stash

    def tree_flatten(self):
        return (
            (self.keys, self.value_index),
            (self.max_probes, self.capacity),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            HashTable,
            lambda t: t.tree_flatten(),
            lambda aux, ch: HashTable.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


PROBE_WINDOW = 8
STASH_SIZE = 128
# capacity ≥ LOAD_FACTOR_INV × entries keeps window-placement
# leftovers well under STASH_SIZE (measured: 13 leftovers for 64k
# random keys at load 0.25 vs 538 at load 0.5)
LOAD_FACTOR_INV = 4
_MAX_GROWTH_DOUBLINGS = 2


def _place_vectorized(
    hashes: np.ndarray, capacity: int, window: int
) -> "tuple[np.ndarray, np.ndarray]":
    """Round-based vectorized placement: each round, every unplaced
    key claims slot (h + disp) & mask; the first claimant of a free
    slot (stable sort order) wins.  Returns (slot-per-key with -1 for
    unplaced, indices of unplaced keys)."""
    mask = capacity - 1
    n = len(hashes)
    slot_of = np.full(n, -1, np.int64)
    occupied = np.zeros(capacity, bool)
    remaining = np.arange(n)
    h = hashes.astype(np.int64)
    for disp in range(window):
        if not len(remaining):
            break
        cand = (h[remaining] + disp) & mask
        order = np.argsort(cand, kind="stable")
        cs = cand[order]
        first = np.ones(len(cs), bool)
        first[1:] = cs[1:] != cs[:-1]
        ok = first & ~occupied[cs]
        winner_rows = order[ok]
        slot_of[remaining[winner_rows]] = cs[ok]
        occupied[cs[ok]] = True
        keep = np.ones(len(remaining), bool)
        keep[winner_rows] = False
        remaining = remaining[keep]
    return slot_of, remaining


def build_hash_table(keys: np.ndarray, min_capacity: int = 16) -> HashTable:
    """keys u32 [N, KW] (unique) → windowed open-addressed table with
    stash.  Callers that need churn-invariant shapes pass a pinned
    `min_capacity` ≥ LOAD_FACTOR_INV × their max entry count; the
    build only grows past it (and changes shape) if the stash
    overflows, and raises after _MAX_GROWTH_DOUBLINGS so adversarial
    hash-collision sets fail loudly instead of doubling to OOM."""
    n, kw = keys.shape
    capacity = min_capacity
    while capacity < LOAD_FACTOR_INV * max(n, 1):
        capacity *= 2
    hashes = _fnv1a_host(keys.astype(np.uint32))
    for attempt in range(_MAX_GROWTH_DOUBLINGS + 1):
        slots, leftovers = _place_vectorized(hashes, capacity, PROBE_WINDOW)
        if len(leftovers) <= STASH_SIZE:
            break
        capacity *= 2
    else:
        raise ValueError(
            f"hash table build failed: {len(leftovers)} keys unplaced "
            f"after growing to capacity {capacity} (adversarial "
            f"collisions?)"
        )
    table_keys = np.full((capacity + STASH_SIZE, kw), EMPTY, dtype=np.uint32)
    value_index = np.full(capacity + STASH_SIZE, -1, dtype=np.int32)
    placed = slots >= 0
    table_keys[slots[placed]] = keys[placed]
    value_index[slots[placed]] = np.flatnonzero(placed).astype(np.int32)
    table_keys[capacity : capacity + len(leftovers)] = keys[leftovers]
    value_index[capacity : capacity + len(leftovers)] = leftovers.astype(
        np.int32
    )
    return HashTable(
        keys=table_keys,
        value_index=value_index,
        max_probes=PROBE_WINDOW,
        capacity=capacity,
    )


def lookup_batch(table: HashTable, query: "jax.Array"):
    """query u32 [B, KW] → (found bool [B], index i32 [B]).

    The whole probe window is ONE [B, P, KW] gather over consecutive
    slots (HBM-contiguous), then a vectorized compare; the stash is a
    static slice broadcast-compared against every query (no gather).
    `index` is the row passed to build_hash_table (-1-safe: callers
    must gate on `found`)."""
    import jax.numpy as jnp

    capacity = table.capacity
    kw = table.keys.shape[1]
    p = table.max_probes
    mask = jnp.int32(capacity - 1)
    h = (fnv1a_device(query).astype(jnp.int32)) & mask

    keys = jnp.asarray(table.keys)
    value_index = jnp.asarray(table.value_index)

    slots = (h[:, None] + jnp.arange(p, dtype=jnp.int32)[None, :]) & mask
    rows = keys[:capacity][slots]  # [B, P, KW], one gather
    hits = jnp.all(rows == query[:, None, :], axis=2)  # [B, P]
    found = jnp.any(hits, axis=1)
    pos = jnp.argmax(hits, axis=1).astype(jnp.int32)
    hit_slot = (h + pos) & mask
    index = jnp.where(found, value_index[hit_slot], 0).astype(jnp.int32)

    # stash: [S, KW] static slice vs [B, 1, KW] — pure VPU compare,
    # no gather; empty stash rows are the EMPTY sentinel and can't
    # match (sentinel queries are masked below)
    stash_keys = keys[capacity:]  # [S, KW]
    stash_hits = jnp.all(
        stash_keys[None, :, :] == query[:, None, :], axis=2
    )  # [B, S]
    stash_found = jnp.any(stash_hits, axis=1)
    stash_pos = jnp.argmax(stash_hits, axis=1).astype(jnp.int32)
    stash_index = value_index[capacity:][stash_pos]
    index = jnp.where(stash_found & ~found, stash_index, index)
    found = found | stash_found

    # A query equal to the all-ones EMPTY sentinel would "hit" empty
    # slots and return index=-1; current CT/LB key packings can't
    # produce it, but mask it out so a future caller fails safe.
    is_sentinel = jnp.all(query == jnp.uint32(EMPTY), axis=1)
    found = found & ~is_sentinel
    index = jnp.where(is_sentinel, 0, index)
    return found, index
