"""Device conntrack lookup over compiled snapshots.

The host CTMap stays authoritative (it mutates); batches evaluate
against a compiled snapshot in a fixed number of gathers, and the
results (new flows, counters) are applied back on host — the same
split as the reference, where the BPF map is written by the kernel and
read/GC'd from userspace asynchronously.

Lookup reproduces ct_lookup4's probe order under the batch: reverse
tuple first (REPLY/RELATED precedence), then forward, else NEW.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from cilium_tpu.ct.table import (
    CT_ESTABLISHED,
    CT_INGRESS,
    CT_EGRESS,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CTMap,
    CTTuple,
    TUPLE_F_IN,
    TUPLE_F_OUT,
    TUPLE_F_RELATED,
    TUPLE_F_SERVICE,
)
from cilium_tpu.engine.hashtable import (
    HashTable,
    build_hash_table,
    lookup_batch,
)


def _pack_key(t: CTTuple) -> Tuple[int, int, int, int]:
    """CTTuple → 4 u32 words (daddr, saddr, dport<<16|sport,
    nexthdr<<8|flags) — the struct layout of common.h:359 collapsed."""
    return (
        t.daddr & 0xFFFFFFFF,
        t.saddr & 0xFFFFFFFF,
        ((t.dport & 0xFFFF) << 16) | (t.sport & 0xFFFF),
        ((t.nexthdr & 0xFF) << 8) | (t.flags & 0xFF),
    )


@dataclass
class CTSnapshot:
    """Compiled CT table: hash table over packed tuple words +
    per-entry state needed by the datapath."""

    table: HashTable
    rev_nat_index: np.ndarray  # u16 [N]
    slave: np.ndarray  # u16 [N]
    related: np.ndarray  # u8 [N] entry carries TUPLE_F_RELATED

    def tree_flatten(self):
        return (
            (self.table, self.rev_nat_index, self.slave, self.related),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            CTSnapshot,
            lambda t: t.tree_flatten(),
            lambda aux, ch: CTSnapshot.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def compile_ct(ct: CTMap) -> CTSnapshot:
    """Snapshot the host CT into device tables.  Capacity is pinned to
    the map's max-entries envelope (pow2 ≥ LOAD_FACTOR_INV×max —
    pkg/maps/ctmap/ctmap.go:71's 64k default ⇒ 256k slots), so the
    snapshot SHAPES are identical across churn rebuilds and the fused
    step never re-jits mid-replay; window-placement leftovers land in
    the table's fixed stash rather than forcing a capacity change."""
    entries = list(ct.entries.items())
    if entries:
        keys = np.array(
            [_pack_key(k) for k, _ in entries], dtype=np.uint32
        )
    else:
        keys = np.zeros((0, 4), dtype=np.uint32)
    from cilium_tpu.engine.hashtable import LOAD_FACTOR_INV

    min_capacity = 16
    while min_capacity < LOAD_FACTOR_INV * max(ct.max_entries, 1):
        min_capacity *= 2
    table = build_hash_table(keys, min_capacity=min_capacity)
    # value rows padded to the fixed envelope as well — every array
    # shape in the snapshot must be churn-invariant (see above)
    n_rows = max(ct.max_entries, len(entries), 1)
    rev_nat = np.zeros(n_rows, dtype=np.uint16)
    slave = np.zeros(n_rows, dtype=np.uint16)
    related = np.zeros(n_rows, dtype=np.uint8)
    if entries:
        rev_nat[: len(entries)] = [e.rev_nat_index for _, e in entries]
        slave[: len(entries)] = [e.slave for _, e in entries]
        related[: len(entries)] = [
            1 if (k.flags & TUPLE_F_RELATED) else 0 for k, _ in entries
        ]
    return CTSnapshot(
        table=table, rev_nat_index=rev_nat, slave=slave, related=related
    )


def _pack_batch(daddr, saddr, dport, sport, proto, flags):
    import jax.numpy as jnp

    w2 = (dport.astype(jnp.uint32) << 16) | sport.astype(jnp.uint32)
    w3 = (proto.astype(jnp.uint32) << 8) | flags.astype(jnp.uint32)
    return jnp.stack(
        [daddr.astype(jnp.uint32), saddr.astype(jnp.uint32), w2, w3],
        axis=1,
    )


def ct_lookup_batch(
    snapshot: CTSnapshot,
    daddr,
    saddr,
    dport,
    sport,
    proto,
    direction,  # i32 [B]: 0=ingress 1=egress 2=service
    related_icmp=None,  # bool [B]: ICMP-error tuples (conntrack.h:349)
):
    """Returns (result u8 [B]: CT_NEW/ESTABLISHED/REPLY/RELATED,
    rev_nat u16-as-i32 [B], slave i32 [B])."""
    import jax.numpy as jnp

    base_flags = jnp.where(
        direction == CT_INGRESS,
        TUPLE_F_OUT,
        jnp.where(direction == CT_EGRESS, TUPLE_F_IN, TUPLE_F_SERVICE),
    ).astype(jnp.uint32)
    if related_icmp is not None:
        # ICMP errors probe the RELATED-flagged tuple, exactly as the
        # host lookup sets TUPLE_F_RELATED before probing
        base_flags = base_flags | jnp.where(
            jnp.asarray(related_icmp), jnp.uint32(TUPLE_F_RELATED), 0
        ).astype(jnp.uint32)

    # reverse probe: swapped addrs/ports, IN flag flipped
    rev_flags = base_flags ^ jnp.uint32(TUPLE_F_IN)
    rev_q = _pack_batch(saddr, daddr, sport, dport, proto, rev_flags)
    fwd_q = _pack_batch(daddr, saddr, dport, sport, proto, base_flags)

    rev_found, rev_idx = lookup_batch(snapshot.table, rev_q)
    fwd_found, fwd_idx = lookup_batch(snapshot.table, fwd_q)

    related = jnp.asarray(snapshot.related)
    rev_related = related[rev_idx].astype(bool) & rev_found
    fwd_related = related[fwd_idx].astype(bool) & fwd_found
    result = jnp.where(
        rev_found,
        jnp.where(rev_related, CT_RELATED, CT_REPLY),
        jnp.where(
            fwd_found,
            jnp.where(fwd_related, CT_RELATED, CT_ESTABLISHED),
            CT_NEW,
        ),
    ).astype(jnp.uint8)

    idx = jnp.where(rev_found, rev_idx, fwd_idx)
    hit = rev_found | fwd_found
    rev_nat = jnp.where(
        hit, jnp.asarray(snapshot.rev_nat_index)[idx], 0
    ).astype(jnp.int32)
    slave = jnp.where(hit, jnp.asarray(snapshot.slave)[idx], 0).astype(
        jnp.int32
    )
    return result, rev_nat, slave


def apply_new_flows(
    ct: CTMap,
    results: np.ndarray,
    daddr,
    saddr,
    dport,
    sport,
    proto,
    direction,
    now: int = 0,
) -> int:
    """Create host CT entries for batch tuples that resolved CT_NEW
    and were allowed (caller pre-filters) — ct_create4 on CT_NEW
    (bpf_lxc.c:844).  Duplicates within the batch collapse."""
    n = 0
    for i in np.nonzero(results == CT_NEW)[0]:
        tup = CTTuple(
            int(daddr[i]), int(saddr[i]), int(dport[i]), int(sport[i]),
            int(proto[i]),
        )
        d = int(direction[i])
        key_flags = (
            TUPLE_F_OUT if d == CT_INGRESS
            else TUPLE_F_IN if d == CT_EGRESS else TUPLE_F_SERVICE
        )
        key = CTTuple(
            tup.daddr, tup.saddr, tup.dport, tup.sport, tup.nexthdr,
            key_flags,
        )
        if key in ct.entries:
            continue
        ct.create(tup, d, now=now)
        n += 1
    return n
