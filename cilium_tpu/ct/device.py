"""Device conntrack lookup over compiled snapshots.

The host CTMap stays authoritative (it mutates); batches evaluate
against a compiled snapshot, and the results (new flows, counters) are
applied back on host — the same split as the reference, where the BPF
map is written by the kernel and read/GC'd from userspace
asynchronously (pkg/maps/ctmap, bpf/lib/conntrack.h).

TPU-first layout: random element gathers cost ~7 ns/query on v5e but a
128-lane ROW gather costs about the same — so the table is BUCKETIZED:

  * buckets are [Cb, 128] u32 rows; each row holds up to 25 packed
    entries (stride 5);
  * the bucket hash is computed over the DIRECTION-NORMALIZED tuple
    (sorted (addr, port) pairs), so a flow's forward key, reverse key,
    and RELATED variants all land in the SAME bucket — one row gather
    answers ct_lookup4's reverse-then-forward probe order
    (bpf/lib/conntrack.h:349) that previously took four windowed
    probes;
  * entries that overflow their bucket go to a fixed-size stash that
    is broadcast-compared against every query (bounded, shape-stable);
  * every shape is pinned by the map's max-entries envelope, so churn
    rebuilds never change the jit cache key, and `apply_bucket_delta`
    updates individual bucket rows in place on device (donated) —
    sustained churn does not re-upload or re-jit anything.

Entry packing (5 × u32), PLANAR within the row — lanes [25k, 25k+25)
hold word k of entries 0..24, so the kernel extracts each word as a
contiguous [B, 25] slice of the fetched row (an interleaved layout
would force a [B, 25, 5] reshape that XLA materializes with 4×
tile padding — 16 GB at an 8M batch):
  w0  normalized lo address
  w1  normalized hi address
  w2  lo port << 16 | hi port
  w3  proto << 8 | swapped << 7 | key flags   (swapped: the original
      key's (daddr, dport) sorted above (saddr, sport))
  w4  rev_nat_index << 16 | slave
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.ct.table import (
    CT_ESTABLISHED,
    CT_INGRESS,
    CT_EGRESS,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CTMap,
    CTTuple,
    TUPLE_F_IN,
    TUPLE_F_OUT,
    TUPLE_F_RELATED,
    TUPLE_F_SERVICE,
)
from cilium_tpu.engine.hashtable import _fnv1a_host, fnv1a_device

ENTRY_WORDS = 5
BUCKET_LANES = 128
ENTRIES_PER_BUCKET = BUCKET_LANES // ENTRY_WORDS  # 25
STASH_ENTRIES = 128
# average entries per bucket at the max-entries envelope; 4 of 25
# keeps the Poisson tail of bucket overflow far below the stash size
BUCKET_LOAD = 4
_SWAPPED_BIT = 1 << 7
# an address word no real key produces (packed lo addr of an empty
# lane); lanes are zero-filled and flags=0 entries can't exist (every
# key carries at least one TUPLE_F bit or proto != 0 — but be exact:
# an all-zero w3 with zero addresses IS producible in theory, so empty
# lanes get an explicit invalid marker in w3 instead)
_EMPTY_W3 = np.uint32(0xFFFFFFFF)


def _normalize_host(
    daddr: int, saddr: int, dport: int, sport: int
) -> Tuple[int, int, int, int, int]:
    """(lo_addr, hi_addr, lo_port, hi_port, swapped) — swapped means
    (daddr, dport) sorts strictly above (saddr, sport)."""
    if (daddr, dport) > (saddr, sport):
        return saddr, daddr, sport, dport, 1
    return daddr, saddr, dport, sport, 0


def _bucket_hash_words(
    lo_addr, hi_addr, lo_port, hi_port, proto
) -> np.ndarray:
    return np.stack(
        [
            np.asarray(lo_addr, np.uint32),
            np.asarray(hi_addr, np.uint32),
            (np.asarray(lo_port, np.uint32) << 16)
            | np.asarray(hi_port, np.uint32),
            np.asarray(proto, np.uint32),
        ],
        axis=-1,
    )


def _pack_entry(key: CTTuple, entry) -> Tuple[int, int, int, int, int]:
    lo_a, hi_a, lo_p, hi_p, swapped = _normalize_host(
        key.daddr, key.saddr, key.dport, key.sport
    )
    w3 = (
        ((key.nexthdr & 0xFF) << 8)
        | (swapped * _SWAPPED_BIT)
        | (key.flags & 0x7F)
    )
    w4 = ((entry.rev_nat_index & 0xFFFF) << 16) | (entry.slave & 0xFFFF)
    return (
        lo_a & 0xFFFFFFFF,
        hi_a & 0xFFFFFFFF,
        ((lo_p & 0xFFFF) << 16) | (hi_p & 0xFFFF),
        w3,
        w4,
    )


@dataclass
class CTSnapshot:
    """Compiled CT: bucket rows + overflow stash (pytree; n_buckets
    and the entry layout are static aux so churn rebuilds share one
    jit cache entry and the probe branches at trace time).

    `entry_words` selects the row layout: 5 = the legacy planar
    5-word entries above; 4 = the SUB-WORD compact form
    (compact_ct_snapshot) whose state/flags lane is packed to a
    halfword beside the rev_nat/slave bytes:

      w3c = (proto << 8 | swapped << 7 | flags) << 16
            | rev_nat8 << 8 | slave8

    — 4 words/entry, so the same bucket load fits a 64-lane row
    (16 entries) instead of 128 lanes, halving the dominant CT
    gather.  Empty lanes hold w3c with the state halfword 0xFFFF
    (the packer verifies no real entry produces it)."""

    buckets: "np.ndarray"  # u32 [Cb, 128 (legacy) | lanes (compact)]
    # u32 [S, ENTRY_WORDS]: the occupied pow2 prefix of the
    # STASH_ENTRIES-capacity overflow stash (trim_ct_stash) — empty
    # at the default envelope, so S is 1 in the steady state.  The
    # stash keeps the legacy 5-word layout in BOTH forms (it is a
    # tiny broadcast compare, not a gather).
    stash: "np.ndarray"
    n_buckets: int
    entry_words: int = ENTRY_WORDS

    def tree_flatten(self):
        return (
            (self.buckets, self.stash),
            (self.n_buckets, self.entry_words),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        if isinstance(aux, tuple):
            nb, ew = aux
        else:  # pre-sub-word aux: bare bucket count
            nb, ew = aux, ENTRY_WORDS
        return cls(children[0], children[1], nb, ew)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            CTSnapshot,
            lambda t: t.tree_flatten(),
            lambda aux, ch: CTSnapshot.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def _envelope_buckets(max_entries: int) -> int:
    nb = 16
    while nb * BUCKET_LOAD < max(max_entries, 1):
        nb *= 2
    return nb


class CTBucketIndex:
    """Host mirror of the device bucket layout, for incremental churn
    updates: tracks which bucket each key lives in and rebuilds only
    the rows that changed (the agent-side analog of the kernel
    updating one hash bucket per CT event).

    DNATed flow entries are DUAL-HOMED: besides their natural bucket
    (hash of the post-DNAT normalized tuple, where ingress replies
    probe), a copy lives in the bucket of the flow's ORIGINAL
    pre-DNAT tuple — the bucket the merged egress probe fetches ONCE
    for both the service-scope lookup and the flow lookup
    (`ct_probe_rows`), mirroring how bpf_lxc looks up both per packet
    (bpf_lxc.c:486-509) without paying two row gathers here."""

    def __init__(self, ct: CTMap) -> None:
        self.n_buckets = _envelope_buckets(ct.max_entries)
        self.bucket_keys: List[List[CTTuple]] = [
            [] for _ in range(self.n_buckets)
        ]
        self.stash_keys: List[CTTuple] = []
        # key → list of homes (-1 = stash); DNATed entries have two
        self.key_home: Dict[CTTuple, List[int]] = {}
        self.ct = ct
        for key in ct.entries:
            self._place(key)

    def _bucket_of_tuple(
        self, daddr: int, saddr: int, dport: int, sport: int,
        proto: int,
    ) -> int:
        lo_a, hi_a, lo_p, hi_p, _ = _normalize_host(
            daddr, saddr, dport, sport
        )
        words = _bucket_hash_words(lo_a, hi_a, lo_p, hi_p, proto)
        return int(_fnv1a_host(words[None, :])[0]) & (self.n_buckets - 1)

    def _bucket_of(self, key: CTTuple) -> int:
        return self._bucket_of_tuple(
            key.daddr, key.saddr, key.dport, key.sport, key.nexthdr
        )

    def _homes_of(self, key: CTTuple) -> List[int]:
        homes = [self._bucket_of(key)]
        entry = self.ct.entries.get(key)
        orig_daddr = getattr(entry, "orig_daddr", 0) if entry else 0
        if orig_daddr:
            pre = self._bucket_of_tuple(
                orig_daddr, key.saddr,
                getattr(entry, "orig_dport", 0), key.sport,
                key.nexthdr,
            )
            if pre != homes[0]:
                homes.append(pre)
        return homes

    def _place(self, key: CTTuple) -> List[int]:
        """A key lives EITHER in its home rows (one copy per distinct
        bucket) OR exactly once in the stash — never both: the stash
        is broadcast-compared by every probe, so a row copy plus a
        stash copy would double-count in the masked value sum."""
        want = self._homes_of(key)
        if all(
            len(self.bucket_keys[b]) < ENTRIES_PER_BUCKET for b in want
        ):
            for b in want:
                self.bucket_keys[b].append(key)
            homes = list(want)
        else:
            if len(self.stash_keys) >= STASH_ENTRIES:
                raise ValueError(
                    "CT bucket and stash overflow — raise max_entries "
                    "(bucket envelope) or stash size"
                )
            self.stash_keys.append(key)
            homes = [-1]
        self.key_home[key] = homes
        return homes

    def _row(self, b: int) -> np.ndarray:
        row = np.zeros(BUCKET_LANES, dtype=np.uint32)
        # planar layout: word k of entry i sits at lane k*E + i
        row[3 * ENTRIES_PER_BUCKET : 4 * ENTRIES_PER_BUCKET] = _EMPTY_W3
        for i, key in enumerate(self.bucket_keys[b]):
            packed = _pack_entry(key, self.ct.entries[key])
            for k in range(ENTRY_WORDS):
                row[k * ENTRIES_PER_BUCKET + i] = packed[k]
        return row

    def _stash_rows(self) -> np.ndarray:
        stash = np.zeros((STASH_ENTRIES, ENTRY_WORDS), dtype=np.uint32)
        stash[:, 3] = _EMPTY_W3
        for i, key in enumerate(self.stash_keys):
            stash[i] = _pack_entry(key, self.ct.entries[key])
        return trim_ct_stash(stash)

    def full_snapshot(self) -> CTSnapshot:
        buckets = np.zeros((self.n_buckets, BUCKET_LANES), dtype=np.uint32)
        buckets[
            :, 3 * ENTRIES_PER_BUCKET : 4 * ENTRIES_PER_BUCKET
        ] = _EMPTY_W3
        for b in range(self.n_buckets):
            if self.bucket_keys[b]:
                buckets[b] = self._row(b)
        return CTSnapshot(
            buckets=buckets,
            stash=self._stash_rows(),
            n_buckets=self.n_buckets,
        )

    def apply(
        self, created: List[CTTuple], deleted: List[CTTuple]
    ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """Fold create/delete key sets into the mirror; returns
        (changed_bucket_indices i32 [M], changed_rows u32 [M, 128],
        new_stash or None) for `apply_bucket_delta`."""
        dirty = set()
        stash_dirty = False
        for key in deleted:
            homes = self.key_home.pop(key, None)
            if homes is None:
                continue
            for home in homes:
                if home < 0:
                    self.stash_keys.remove(key)
                    stash_dirty = True
                else:
                    self.bucket_keys[home].remove(key)
                    dirty.add(home)
        for key in created:
            homes = self.key_home.get(key)
            if homes is None:
                homes = self._place(key)
            for home in homes:  # value may have changed: re-pack
                if home < 0:
                    stash_dirty = True
                else:
                    dirty.add(home)
        idx = np.array(sorted(dirty), dtype=np.int32)
        rows = (
            np.stack([self._row(b) for b in idx])
            if len(idx)
            else np.zeros((0, BUCKET_LANES), dtype=np.uint32)
        )
        if len(idx):
            # pad the delta to a pow2 length by repeating the first
            # changed bucket (idempotent writes) so apply_bucket_delta
            # compiles once per size bucket instead of per batch
            m = 8
            while m < len(idx):
                m *= 2
            pad = m - len(idx)
            if pad:
                idx = np.concatenate(
                    [idx, np.full(pad, idx[0], np.int32)]
                )
                rows = np.concatenate(
                    [rows, np.repeat(rows[:1], pad, axis=0)]
                )
        return idx, rows, self._stash_rows() if stash_dirty else None


def trim_ct_stash(stash: np.ndarray) -> np.ndarray:
    """Trim the overflow stash to the pow2 prefix holding its
    occupied rows (front-filled; empty rows carry w3 = _EMPTY_W3).
    Every probe broadcast-compares EVERY stash lane against every
    tuple — at the default envelope the stash is empty, so shipping
    it at the 128-row capacity charges the fused pipeline ~10 wasted
    [B, 128] compares per probe.  Trimmed lanes can never match, so
    results are bit-identical; the stash shape only crosses a pow2
    class (one bounded recompile) when overflow actually grows."""
    from cilium_tpu.engine.hashtable import trim_pow2_prefix

    used = int((stash[:, 3] != _EMPTY_W3).sum())
    return trim_pow2_prefix(stash, used)


def compile_ct(ct: CTMap) -> CTSnapshot:
    """Snapshot the host CT into device bucket tables.  Bucket shapes
    are pinned by ct.max_entries (pkg/maps/ctmap/ctmap.go:71's
    envelope), identical across churn rebuilds; the stash ships at
    its occupied pow2 prefix (trim_ct_stash)."""
    return CTBucketIndex(ct).full_snapshot()


# compact (4-word) layout: empty-lane marker of the packed w3c word —
# the state halfword 0xFFFF, which compact_ct_snapshot PROVES no real
# entry produces before packing (exactness first, like _EMPTY_W3)
_EMPTY_W3C = np.uint32(0xFFFF0000)
CT_COMPACT_LANES = 64


def _decode_per_bucket(snapshot: CTSnapshot):
    """(per-bucket entry lists, stash entry list) of a snapshot,
    every entry as its 5 LEGACY words.  Bucket membership is
    preserved verbatim — crucial for the dual-homed DNAT copies,
    whose pre-DNAT home is NOT the hash of their stored tuple."""
    ew = snapshot.entry_words
    rows = np.asarray(snapshot.buckets)
    n_e = rows.shape[1] // ew
    per_bucket = []
    for b in range(snapshot.n_buckets):
        row = rows[b]
        entries = []
        for k in range(n_e):
            w3p = row[3 * n_e + k]
            if ew == ENTRY_WORDS:
                if w3p == _EMPTY_W3:
                    continue
                entries.append(
                    tuple(int(row[p * n_e + k]) for p in range(5))
                )
            else:
                if (w3p & np.uint32(0xFFFF0000)) == _EMPTY_W3C:
                    continue
                w3 = int(w3p) >> 16
                w4 = ((int(w3p) >> 8) & 0xFF) << 16 | (
                    int(w3p) & 0xFF
                )
                entries.append(
                    (
                        int(row[k]), int(row[n_e + k]),
                        int(row[2 * n_e + k]), w3, w4,
                    )
                )
        per_bucket.append(entries)
    stash = np.asarray(snapshot.stash)
    stash_entries = [
        tuple(int(v) for v in stash[i])
        for i in range(stash.shape[0])
        if stash[i, 3] != _EMPTY_W3
    ]
    return per_bucket, stash_entries


def _place_ct_layout(
    per_bucket, stash_entries, nb: int, lanes: int, entry_words: int
) -> CTSnapshot:
    """Bucket-preserving placement into either layout.  An entry
    whose bucket copy would overflow moves to the stash — and so do
    its OTHER bucket copies (dual-homed DNAT entries), because a row
    copy plus a stash copy would double-count in the masked value
    sums; the stash holds exactly one copy."""
    n_e = lanes // entry_words
    # first pass: find entries that overflow anywhere
    overflowed = set()
    for entries in per_bucket:
        if len(entries) > n_e:
            overflowed.update(entries[n_e:])
    buckets = np.zeros((nb, lanes), dtype=np.uint32)
    empty3 = _EMPTY_W3 if entry_words == ENTRY_WORDS else _EMPTY_W3C
    buckets[:, 3 * n_e : 4 * n_e] = empty3
    stash = np.zeros((STASH_ENTRIES, ENTRY_WORDS), dtype=np.uint32)
    stash[:, 3] = _EMPTY_W3
    sfill = 0
    stashed = set()
    for b, entries in enumerate(per_bucket):
        k = 0
        for ent in entries:
            if ent in overflowed:
                if ent not in stashed:
                    if sfill >= STASH_ENTRIES:
                        raise ValueError(
                            "CT bucket and stash overflow — keep "
                            "the wider layout"
                        )
                    stash[sfill] = ent
                    sfill += 1
                    stashed.add(ent)
                continue
            w0, w1, w2, w3, w4 = ent
            if entry_words == ENTRY_WORDS:
                for p, w in enumerate(ent):
                    buckets[b, p * n_e + k] = w
            else:
                buckets[b, k] = w0
                buckets[b, n_e + k] = w1
                buckets[b, 2 * n_e + k] = w2
                buckets[b, 3 * n_e + k] = (
                    (w3 << 16)
                    | (((w4 >> 16) & 0xFF) << 8)
                    | (w4 & 0xFF)
                )
            k += 1
    for ent in stash_entries:
        if ent in stashed:
            continue
        if sfill >= STASH_ENTRIES:
            raise ValueError(
                "CT bucket and stash overflow — keep the wider "
                "layout"
            )
        stash[sfill] = ent
        sfill += 1
    return CTSnapshot(
        buckets=buckets,
        stash=trim_ct_stash(stash),
        n_buckets=nb,
        entry_words=entry_words,
    )


def compact_ct_snapshot(
    snapshot: CTSnapshot, lanes: int = CT_COMPACT_LANES
) -> CTSnapshot:
    """Re-place a snapshot in the SUB-WORD compact layout: 4-word
    entries (state/flags halfword packed beside the rev_nat/slave
    bytes) in `lanes`-wide rows — same bucket count and the SAME
    bucket membership per entry (hashes and dual-homed DNAT copies
    unchanged, so churn deltas still touch only their bucket), row
    overflow spilling to the legacy stash.  Semantics must allow it:
    rev_nat and slave must fit a byte and no state halfword may
    equal the empty marker — verified, ValueError otherwise (the
    caller keeps the 5-word layout).  Lookups are bit-identical by
    construction (same keys, same hash, same combine)."""
    per_bucket, stash_entries = _decode_per_bucket(snapshot)
    for ent in (
        e for entries in per_bucket for e in entries
    ):
        w3, w4 = ent[3], ent[4]
        if ((w4 >> 16) & 0xFFFF) > 0xFF or (w4 & 0xFFFF) > 0xFF:
            raise ValueError(
                "rev_nat/slave exceed the compact byte fields — "
                "keeping the 5-word CT layout"
            )
        if w3 >= 0xFFFF:
            raise ValueError(
                "CT state halfword collides with the compact empty "
                "marker — keeping the 5-word CT layout"
            )
    return _place_ct_layout(
        per_bucket, stash_entries, snapshot.n_buckets, lanes, 4
    )


def expand_ct_snapshot(snapshot: CTSnapshot) -> CTSnapshot:
    """Compact -> legacy 5-word 128-lane layout (round-trip seam for
    the autotuner's width sweep)."""
    if snapshot.entry_words == ENTRY_WORDS:
        return snapshot
    per_bucket, stash_entries = _decode_per_bucket(snapshot)
    return _place_ct_layout(
        per_bucket, stash_entries, snapshot.n_buckets, BUCKET_LANES,
        ENTRY_WORDS,
    )


def apply_bucket_delta(snapshot, idx, rows, stash=None):
    """Scatter changed bucket rows (and optionally a new stash) into a
    device-resident snapshot.  Callers jit this with the snapshot
    donated so churn updates are in-place row writes, not re-uploads."""
    import jax.numpy as jnp

    buckets = snapshot.buckets.at[idx].set(rows)
    new_stash = snapshot.stash if stash is None else jnp.asarray(stash)
    return CTSnapshot(
        buckets=buckets, stash=new_stash,
        n_buckets=snapshot.n_buckets,
        entry_words=snapshot.entry_words,
    )


def _normalize_device(daddr, saddr, dport, sport):
    import jax.numpy as jnp

    daddr = daddr.astype(jnp.uint32)
    saddr = saddr.astype(jnp.uint32)
    dport = dport.astype(jnp.uint32) & 0xFFFF
    sport = sport.astype(jnp.uint32) & 0xFFFF
    swapped = (daddr > saddr) | ((daddr == saddr) & (dport > sport))
    lo_a = jnp.where(swapped, saddr, daddr)
    hi_a = jnp.where(swapped, daddr, saddr)
    lo_p = jnp.where(swapped, sport, dport)
    hi_p = jnp.where(swapped, dport, sport)
    return lo_a, hi_a, lo_p, hi_p, swapped


def ct_fetch_rows(snapshot: CTSnapshot, daddr, saddr, dport, sport, proto):
    """THE bucket row gather: fetch each flow's CT bucket row by the
    normalized-tuple hash.  Probes against the fetched rows are lane
    compares (`ct_probe_rows`) — the merged egress path fetches by the
    ORIGINAL tuple once and probes both the service-scope key and the
    (possibly DNATed) flow key against the same rows, relying on the
    dual-homed placement of CTBucketIndex."""
    import jax.numpy as jnp

    lo_a, hi_a, lo_p, hi_p, _ = _normalize_device(
        daddr, saddr, dport, sport
    )
    proto_u = proto.astype(jnp.uint32) & 0xFF
    h = fnv1a_device(
        jnp.stack([lo_a, hi_a, (lo_p << 16) | hi_p, proto_u], axis=1)
    )
    bucket = (h & jnp.uint32(snapshot.n_buckets - 1)).astype(jnp.int32)
    return jnp.asarray(snapshot.buckets)[bucket]  # [B, 128] — 1 gather


def ct_lookup_batch(
    snapshot: CTSnapshot,
    daddr,
    saddr,
    dport,
    sport,
    proto,
    direction,  # i32 [B]: 0=ingress 1=egress 2=service
    related_icmp=None,  # bool [B]: ICMP-error tuples (conntrack.h:349)
):
    """Returns (result u8 [B]: CT_NEW/ESTABLISHED/REPLY/RELATED,
    rev_nat u16-as-i32 [B], slave i32 [B]).

    ONE bucket row gather: the normalized hash puts the forward and
    reverse keys in the same bucket, and both direction probes are
    lane compares against the fetched row."""
    rows = ct_fetch_rows(snapshot, daddr, saddr, dport, sport, proto)
    return ct_probe_rows(
        snapshot, rows, daddr, saddr, dport, sport, proto, direction,
        related_icmp,
    )


def ct_probe_keys(
    daddr, saddr, dport, sport, proto, direction, related_icmp=None
):
    """Device probe-key computation shared by the single-chip and
    routed (mesh) CT probes: the normalized compare words and the
    forward/reverse w3 flag words.  Returns (lo_a, hi_a, ports_w,
    w3_fwd, w3_rev, probed_related)."""
    import jax.numpy as jnp

    base_flags = jnp.where(
        direction == CT_INGRESS,
        TUPLE_F_OUT,
        jnp.where(direction == CT_EGRESS, TUPLE_F_IN, TUPLE_F_SERVICE),
    ).astype(jnp.uint32)
    if related_icmp is not None:
        # ICMP errors probe the RELATED-flagged tuple, exactly as the
        # host lookup sets TUPLE_F_RELATED before probing
        base_flags = base_flags | jnp.where(
            jnp.asarray(related_icmp), jnp.uint32(TUPLE_F_RELATED), 0
        ).astype(jnp.uint32)
    rev_flags = base_flags ^ jnp.uint32(TUPLE_F_IN)

    lo_a, hi_a, lo_p, hi_p, swapped = _normalize_device(
        daddr, saddr, dport, sport
    )
    proto_u = proto.astype(jnp.uint32) & 0xFF

    # probe w3 values: the forward key's swapped bit is the flow's
    # own orientation; the reverse key's is the opposite (unless the
    # address/port pairs are identical, where both normalize the same)
    pairs_equal = (daddr.astype(jnp.uint32) == saddr.astype(jnp.uint32)) & (
        (dport.astype(jnp.uint32) & 0xFFFF)
        == (sport.astype(jnp.uint32) & 0xFFFF)
    )
    fwd_sw = swapped & ~pairs_equal
    rev_sw = ~swapped & ~pairs_equal
    w3_fwd = (
        (proto_u << 8)
        | (fwd_sw.astype(jnp.uint32) * _SWAPPED_BIT)
        | base_flags
    )
    w3_rev = (
        (proto_u << 8)
        | (rev_sw.astype(jnp.uint32) * _SWAPPED_BIT)
        | rev_flags
    )
    probed_related = (base_flags & jnp.uint32(TUPLE_F_RELATED)) != 0
    return lo_a, hi_a, (lo_p << 16) | hi_p, w3_fwd, w3_rev, probed_related


def ct_probe_row_parts(rows, lo_a, hi_a, ports_w, w3_fwd, w3_rev,
                       owns=None, entry_words: int = ENTRY_WORDS):
    """Bucket-ROW half of the CT probe: lane compares against
    pre-fetched rows, with an optional ownership mask (the routed
    mesh kernel gathers each row on its owning shard only and masks
    every other shard's contribution to zero, so an integer psum of
    these parts reconstructs the single-chip result exactly).
    Layout-generic: `entry_words` 5 = legacy, 4 = the sub-word
    compact form, whose state halfword and rev/slave bytes unpack
    in-jit back to the legacy compare/value encoding — results are
    bit-identical by construction.  Returns (fwd_found bool [B],
    rev_found bool [B], fwd_val u32 [B], rev_val u32 [B])."""
    import jax.numpy as jnp

    n_e = rows.shape[1] // entry_words
    # planar extraction: word k of all entries = one contiguous slice
    ew = [
        rows[:, k * n_e : (k + 1) * n_e]
        for k in range(entry_words)
    ]
    key_eq = (
        (ew[0] == lo_a[:, None])
        & (ew[1] == hi_a[:, None])
        & (ew[2] == ports_w[:, None])
    )
    if owns is not None:
        key_eq = key_eq & owns[:, None]
    if entry_words == ENTRY_WORDS:
        w3_plane = ew[3]
        val_plane = ew[4]
    else:
        # compact: w3c = state16 << 16 | rev8 << 8 | slave8 — the
        # in-jit unpack shim (the packed4 precedent applied to the
        # CT state/flags lane)
        w3_plane = ew[3] >> jnp.uint32(16)
        val_plane = (
            ((ew[3] >> jnp.uint32(8)) & jnp.uint32(0xFF))
            << jnp.uint32(16)
        ) | (ew[3] & jnp.uint32(0xFF))
    fwd_hit = key_eq & (w3_plane == w3_fwd[:, None])  # [B, E]
    rev_hit = key_eq & (w3_plane == w3_rev[:, None])
    fwd_val = jnp.sum(
        jnp.where(fwd_hit, val_plane, 0), axis=1, dtype=jnp.uint32
    )
    rev_val = jnp.sum(
        jnp.where(rev_hit, val_plane, 0), axis=1, dtype=jnp.uint32
    )
    return (
        jnp.any(fwd_hit, axis=1), jnp.any(rev_hit, axis=1),
        fwd_val, rev_val,
    )


def ct_probe_stash_parts(snapshot, lo_a, hi_a, ports_w, w3_fwd, w3_rev):
    """Overflow-stash half of the CT probe (broadcast compare; the
    stash replicates on a mesh, so these parts are computed once per
    shard and added AFTER the row-part psum — never summed across
    the table axis).  Same return contract as ct_probe_row_parts."""
    import jax.numpy as jnp

    stash = jnp.asarray(snapshot.stash)  # [S, 5]
    s_key_eq = (
        (stash[None, :, 0] == lo_a[:, None])
        & (stash[None, :, 1] == hi_a[:, None])
        & (stash[None, :, 2] == ports_w[:, None])
    )
    s_fwd = s_key_eq & (stash[None, :, 3] == w3_fwd[:, None])
    s_rev = s_key_eq & (stash[None, :, 3] == w3_rev[:, None])
    fwd_val = jnp.sum(
        jnp.where(s_fwd, stash[None, :, 4], 0), axis=1,
        dtype=jnp.uint32,
    )
    rev_val = jnp.sum(
        jnp.where(s_rev, stash[None, :, 4], 0), axis=1,
        dtype=jnp.uint32,
    )
    return (
        jnp.any(s_fwd, axis=1), jnp.any(s_rev, axis=1),
        fwd_val, rev_val,
    )


def ct_probe_combine(
    fwd_found, rev_found, fwd_val, rev_val, probed_related
):
    """Combine probe parts into the CT lookup result — the terminal
    shared step of both the single-chip and routed probes.  Returns
    (result u8 [B], rev_nat i32 [B], slave i32 [B])."""
    import jax.numpy as jnp

    # the probe itself carried the RELATED bit (exact key equality),
    # so a hit on a RELATED probe IS a RELATED entry
    result = jnp.where(
        rev_found,
        jnp.where(probed_related, CT_RELATED, CT_REPLY),
        jnp.where(
            fwd_found,
            jnp.where(probed_related, CT_RELATED, CT_ESTABLISHED),
            CT_NEW,
        ),
    ).astype(jnp.uint8)
    val = jnp.where(rev_found, rev_val, fwd_val)
    hit = rev_found | fwd_found
    rev_nat = jnp.where(hit, val >> 16, 0).astype(jnp.int32)
    slave = jnp.where(hit, val & 0xFFFF, 0).astype(jnp.int32)
    return result, rev_nat, slave


def ct_probe_rows(
    snapshot: CTSnapshot,
    rows,  # u32 [B, 128] from ct_fetch_rows
    daddr,
    saddr,
    dport,
    sport,
    proto,
    direction,
    related_icmp=None,
):
    """Probe pre-fetched bucket rows for the given tuple/direction —
    see ct_lookup_batch.  The rows need not have been fetched with
    THIS tuple's hash: the merged egress path probes the pre-DNAT
    row for the post-DNAT key (dual-homed entries)."""
    lo_a, hi_a, ports_w, w3_fwd, w3_rev, probed_related = (
        ct_probe_keys(
            daddr, saddr, dport, sport, proto, direction,
            related_icmp,
        )
    )
    rf, rr, rfv, rrv = ct_probe_row_parts(
        rows, lo_a, hi_a, ports_w, w3_fwd, w3_rev,
        entry_words=snapshot.entry_words,
    )
    sf, sr, sfv, srv = ct_probe_stash_parts(
        snapshot, lo_a, hi_a, ports_w, w3_fwd, w3_rev
    )
    return ct_probe_combine(
        rf | sf, rr | sr, rfv + sfv, rrv + srv, probed_related
    )


def apply_new_flows(
    ct: CTMap,
    results: np.ndarray,
    daddr,
    saddr,
    dport,
    sport,
    proto,
    direction,
    now: int = 0,
) -> int:
    """Create host CT entries for batch tuples that resolved CT_NEW
    and were allowed (caller pre-filters) — ct_create4 on CT_NEW
    (bpf_lxc.c:844).  Duplicates within the batch collapse."""
    n = 0
    for i in np.nonzero(results == CT_NEW)[0]:
        tup = CTTuple(
            int(daddr[i]), int(saddr[i]), int(dport[i]), int(sport[i]),
            int(proto[i]),
        )
        d = int(direction[i])
        key_flags = (
            TUPLE_F_OUT if d == CT_INGRESS
            else TUPLE_F_IN if d == CT_EGRESS else TUPLE_F_SERVICE
        )
        key = CTTuple(
            tup.daddr, tup.saddr, tup.dport, tup.sport, tup.nexthdr,
            key_flags,
        )
        if key in ct.entries:
            continue
        if ct.create_best_effort(tup, d, now=now):
            n += 1
    return n
