"""Connection tracking (the CT map of bpf/lib/conntrack.h).

`table` is the authoritative host-side CT state machine;
`device` compiles snapshots into open-addressed hash tensors for
batched device lookups, with new-flow/counter updates applied back on
host (the BPF map ↔ userspace async-handoff pattern of SURVEY §2.9).
"""

from cilium_tpu.ct.table import (
    CT_ESTABLISHED,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CTEntry,
    CTKey,
    CTMap,
    CTTuple,
)

__all__ = [
    "CTMap",
    "CTKey",
    "CTEntry",
    "CTTuple",
    "CT_NEW",
    "CT_ESTABLISHED",
    "CT_REPLY",
    "CT_RELATED",
]
