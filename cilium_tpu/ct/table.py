"""Host connection-tracking table.

Behavioral port of /root/reference/bpf/lib/conntrack.h and
pkg/maps/ctmap:
  - tuple layout (common.h:359): (daddr, saddr, dport, sport, nexthdr,
    flags) where flags carries direction (TUPLE_F_OUT/IN) and RELATED;
  - lookup order (ct_lookup4, conntrack.h:314-466): the REVERSE tuple
    is probed first because REPLY/RELATED take precedence over
    ESTABLISHED for policy purposes; then the forward tuple; else NEW;
  - timeouts (ct_update_timeout conntrack.h:190-207): TCP entries that
    have seen a non-SYN packet get CT_LIFETIME_TCP, SYN-only get
    CT_SYN_TIMEOUT, non-TCP get CT_LIFETIME_NONTCP; closing entries
    (FIN/RST, ACTION_CLOSE) get CT_CLOSE_TIMEOUT once dead;
  - accounting: rx on ingress, tx on egress (conntrack.h:247-255);
  - GC by expired lifetime (pkg/maps/ctmap GC).

Capacity envelope: 64k entries per endpoint-local map
(pkg/maps/ctmap/ctmap.go:71).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

# conntrack.h:55-66
TUPLE_F_OUT = 0
TUPLE_F_IN = 1
TUPLE_F_RELATED = 2
TUPLE_F_SERVICE = 4

# lookup results (conntrack.h CT_*)
CT_NEW = 0
CT_ESTABLISHED = 1
CT_REPLY = 2
CT_RELATED = 3

# directions (common.h CT_INGRESS/CT_EGRESS/CT_SERVICE)
CT_INGRESS = 0
CT_EGRESS = 1
CT_SERVICE = 2

# default lifetimes in seconds (bpf/lib/conntrack.h defaults)
CT_DEFAULT_LIFETIME_TCP = 21600
CT_DEFAULT_LIFETIME_NONTCP = 60
CT_SYN_TIMEOUT = 60
CT_CLOSE_TIMEOUT = 10

IPPROTO_TCP = 6

# pkg/maps/ctmap/ctmap.go:71
MAX_ENTRIES_LOCAL = 65536


@dataclass(frozen=True)
class CTTuple:
    """ipv4_ct_tuple (common.h:359), addresses as u32 host ints."""

    daddr: int
    saddr: int
    dport: int
    sport: int
    nexthdr: int
    flags: int = TUPLE_F_OUT

    def reverse(self) -> "CTTuple":
        """ipv4_ct_tuple_reverse (conntrack.h:286): swap addrs+ports,
        flip IN flag."""
        flags = self.flags
        if flags & TUPLE_F_IN:
            flags &= ~TUPLE_F_IN
        else:
            flags |= TUPLE_F_IN
        return CTTuple(
            daddr=self.saddr,
            saddr=self.daddr,
            dport=self.sport,
            sport=self.dport,
            nexthdr=self.nexthdr,
            flags=flags,
        )


CTKey = CTTuple


@dataclass
class CTEntry:
    """ct_entry (common.h:380)."""

    lifetime: int = 0
    rx_packets: int = 0
    rx_bytes: int = 0
    tx_packets: int = 0
    tx_bytes: int = 0
    rev_nat_index: int = 0
    slave: int = 0
    lb_loopback: bool = False
    seen_non_syn: bool = False
    rx_closing: bool = False
    tx_closing: bool = False
    # pre-DNAT frontend of a load-balanced flow (0 = not DNATed):
    # the device bucket index dual-homes such entries so the merged
    # egress probe finds them in the original tuple's bucket
    orig_daddr: int = 0
    orig_dport: int = 0

    def alive(self) -> bool:
        """ct_entry_alive: neither side closed."""
        return not (self.rx_closing or self.tx_closing)


@dataclass
class CTState:
    """ct_state handed back to the datapath."""

    rev_nat_index: int = 0
    loopback: bool = False
    slave: int = 0


class CTMap:
    def __init__(self, max_entries: int = MAX_ENTRIES_LOCAL) -> None:
        import time as _time

        self.entries: Dict[CTTuple, CTEntry] = {}
        self.max_entries = max_entries
        # the map's time base: callers pass `now` in seconds on
        # whatever monotonic scale they choose (tests use 0..N); the
        # daemon's GC uses now() — seconds since THIS map was created
        # — so wall-clock epochs can never mass-expire entries that
        # were stamped on a relative scale
        self._epoch = _time.monotonic()
        # ConntrackAccounting: per-flow packet/byte counters on probe
        # (flipped by the owning daemon's option hook)
        self.accounting = True
        # bumped on every mutation THROUGH this map (create, probe
        # side effects, gc) — replay's device-snapshot cache gates on
        # it plus the key set, so host-side lookups between replays
        # (which mutate lifetime/closing flags in place) invalidate
        # the cached snapshot.  Direct writes to `entries` values
        # bypass it; such callers must invalidate the cache
        # themselves (replay._ChurnDriver docstring).
        self.mutations = 0

    def now(self) -> int:
        """Seconds since this map's creation (the GC clock)."""
        import time as _time

        return int(_time.monotonic() - self._epoch)

    # -- timeout logic (conntrack.h:190-207) --------------------------------

    def _update_timeout(
        self, entry: CTEntry, is_tcp: bool, dir: int, syn: bool, now: int
    ) -> None:
        lifetime = CT_DEFAULT_LIFETIME_NONTCP
        if is_tcp:
            entry.seen_non_syn |= not syn
            lifetime = (
                CT_DEFAULT_LIFETIME_TCP
                if entry.seen_non_syn
                else CT_SYN_TIMEOUT
            )
        entry.lifetime = now + lifetime

    # -- __ct_lookup (conntrack.h:221) --------------------------------------

    def _probe(
        self,
        tup: CTTuple,
        action: str,
        dir: int,
        now: int,
        pkt_len: int,
        is_tcp: bool,
        syn: bool,
        ct_state: Optional[CTState],
    ) -> int:
        entry = self.entries.get(tup)
        if entry is None:
            return CT_NEW
        self.mutations += 1  # probe hits mutate in place (timeout,
        # counters, closing flags) — see __init__
        if entry.alive():
            self._update_timeout(entry, is_tcp, dir, syn, now)
        if ct_state is not None:
            ct_state.rev_nat_index = entry.rev_nat_index
            ct_state.loopback = entry.lb_loopback
            ct_state.slave = entry.slave
        if self.accounting:
            # per-flow statistics are compiled out when the
            # ConntrackAccounting option is off (the reference's
            # CONNTRACK_ACCOUNTING #define gates the counter bumps);
            # the owning daemon flips this flag on option change —
            # standalone maps account unconditionally
            if dir == CT_INGRESS:
                entry.rx_packets += 1
                entry.rx_bytes += pkt_len
            else:
                entry.tx_packets += 1
                entry.tx_bytes += pkt_len
        if action == "create":
            if entry.rx_closing or entry.tx_closing:
                # connection being reopened (conntrack.h:259-264)
                entry.rx_closing = False
                entry.tx_closing = False
                self._update_timeout(entry, is_tcp, dir, syn, now)
        elif action == "close":
            if dir == CT_INGRESS:
                entry.rx_closing = True
            else:
                entry.tx_closing = True
            if not entry.alive():
                entry.lifetime = now + CT_CLOSE_TIMEOUT
        return CT_ESTABLISHED

    # -- ct_lookup4 (conntrack.h:468) ---------------------------------------

    def lookup(
        self,
        tup: CTTuple,
        dir: int,
        now: int = 0,
        pkt_len: int = 0,
        tcp_syn: bool = False,
        tcp_fin_or_rst: bool = False,
        related_icmp: bool = False,
        ct_state: Optional[CTState] = None,
    ) -> int:
        """Returns CT_NEW / CT_ESTABLISHED / CT_REPLY / CT_RELATED.

        `tup` is the on-wire tuple; direction flags are derived from
        `dir` as the datapath does (conntrack.h:330-336)."""
        if dir == CT_INGRESS:
            flags = TUPLE_F_OUT
        elif dir == CT_EGRESS:
            flags = TUPLE_F_IN
        else:
            flags = TUPLE_F_SERVICE
        base = CTTuple(
            tup.daddr, tup.saddr, tup.dport, tup.sport, tup.nexthdr, flags
        )
        if related_icmp:
            base = CTTuple(
                base.daddr, base.saddr, base.dport, base.sport,
                base.nexthdr, base.flags | TUPLE_F_RELATED,
            )

        is_tcp = tup.nexthdr == IPPROTO_TCP
        action = "unspec"
        if is_tcp:
            if tcp_fin_or_rst:
                action = "close"
            elif tcp_syn:
                action = "create"

        # Reverse tuple first: REPLY/RELATED precedence
        # (conntrack.h:318-327).
        rev = base.reverse()
        ret = self._probe(
            rev, action, dir, now, pkt_len, is_tcp, tcp_syn, ct_state
        )
        if ret != CT_NEW:
            return (
                CT_RELATED if rev.flags & TUPLE_F_RELATED else CT_REPLY
            )
        ret = self._probe(
            base, action, dir, now, pkt_len, is_tcp, tcp_syn, ct_state
        )
        if ret != CT_NEW:
            return (
                CT_RELATED if base.flags & TUPLE_F_RELATED else
                CT_ESTABLISHED
            )
        return CT_NEW

    # -- ct_create4 (conntrack.h:500) ---------------------------------------

    def create(
        self,
        tup: CTTuple,
        dir: int,
        now: int = 0,
        rev_nat_index: int = 0,
        slave: int = 0,
        loopback: bool = False,
        tcp_syn: bool = False,
        orig_daddr: int = 0,
        orig_dport: int = 0,
    ) -> CTEntry:
        # chaos seam: an armed ct.insert site fails the map write the
        # way a full kernel map fails ct_create4.  Raises to THIS
        # caller; the datapath writeback paths go through
        # create_best_effort, which contains the failure (drop
        # accounting, stream continues)
        from cilium_tpu import faultinject

        faultinject.fire("ct.insert")
        if dir == CT_INGRESS:
            flags = TUPLE_F_OUT
        elif dir == CT_EGRESS:
            flags = TUPLE_F_IN
        else:
            flags = TUPLE_F_SERVICE
        key = CTTuple(
            tup.daddr, tup.saddr, tup.dport, tup.sport, tup.nexthdr, flags
        )
        if len(self.entries) >= self.max_entries and key not in self.entries:
            raise OverflowError("CT map full")
        entry = CTEntry(
            rev_nat_index=rev_nat_index, slave=slave, lb_loopback=loopback,
            orig_daddr=orig_daddr, orig_dport=orig_dport,
        )
        is_tcp = tup.nexthdr == IPPROTO_TCP
        self._update_timeout(entry, is_tcp, dir, tcp_syn, now)
        self.entries[key] = entry
        self.mutations += 1
        return entry

    # -- GC (pkg/maps/ctmap conntrack GC) -----------------------------------

    def gc(self, now: int) -> int:
        dead = [k for k, v in self.entries.items() if v.lifetime < now]
        for k in dead:
            del self.entries[k]
        if dead:
            self.mutations += 1
        return len(dead)

    def create_best_effort(self, tup: CTTuple, dir: int, **kw) -> bool:
        """CT creation is best-effort, like ct_create4 in the kernel
        datapath: a failed map write (full map — OverflowError — or
        an armed ct.insert fault) drops THIS entry under the
        canonical reason and the caller's stream continues; the
        flow's create retries on its next appearance.  Returns True
        when the entry landed."""
        try:
            self.create(tup, dir, **kw)
            return True
        except Exception as exc:
            from cilium_tpu.logging import get_logger
            from cilium_tpu.metrics import registry as _metrics
            from cilium_tpu.monitor.events import drop_reason_name

            _metrics.drop_count.inc(
                drop_reason_name(-155),  # "CT: Map insertion failed"
                # service-scope stickiness entries are neither
                # datapath direction — attribute them distinctly
                {CT_INGRESS: "INGRESS", CT_EGRESS: "EGRESS"}.get(
                    dir, "SERVICE"
                ),
            )
            get_logger("ct").warning(
                "CT create failed; entry dropped (best-effort)",
                extra={"fields": {"error": str(exc)}},
            )
            return False

    def evict_to(self, target_entries: int) -> int:
        """Emergency eviction (the watermark GC's last resort, the
        analog of ctmap's pressure-driven GC interval floor): drop
        soonest-to-expire entries until the map holds at most
        `target_entries`.  Returns the number evicted."""
        excess = len(self.entries) - max(0, target_entries)
        if excess <= 0:
            return 0
        victims = sorted(
            self.entries.items(), key=lambda kv: kv[1].lifetime
        )[:excess]
        for key, _ in victims:
            del self.entries[key]
        self.mutations += 1
        return len(victims)
