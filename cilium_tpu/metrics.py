"""Metrics registry.

Re-design of /root/reference/pkg/metrics/metrics.go: the same metric
names and label sets, over a minimal in-process registry with
Prometheus text exposition (an HTTP exporter can serve `expose()`
verbatim; no prometheus client dependency in the image).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

NAMESPACE = "cilium"


class Counter:
    def __init__(self, name: str, help_text: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = labels
        self._values: Dict[Tuple[str, ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, *label_values: str, value: float = 1.0) -> None:
        with self._lock:
            self._values[label_values] += value

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values[label_values]

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} counter",
        ]
        with self._lock:
            for labels, value in sorted(self._values.items()):
                sel = ",".join(
                    f'{k}="{v}"' for k, v in zip(self.label_names, labels)
                )
                suffix = f"{{{sel}}}" if sel else ""
                lines.append(f"{self.name}{suffix} {value}")
        return lines


class Gauge(Counter):
    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[label_values] = float(value)

    def dec(self, *label_values: str) -> None:
        self.inc(*label_values, value=-1.0)

    def expose(self) -> List[str]:
        lines = super().expose()
        lines[1] = f"# TYPE {self.name} gauge"
        return lines


class Histogram:
    """Fixed-bucket histogram (regeneration seconds etc.)."""

    DEFAULT_BUCKETS = (
        0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
    )

    def __init__(self, name: str, help_text: str, buckets=None):
        self.name = name
        self.help = help_text
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cumulative = 0
        with self._lock:
            for b, c in zip(self.buckets, self._counts):
                cumulative += c
                lines.append(
                    f'{self.name}_bucket{{le="{b}"}} {cumulative}'
                )
            lines.append(
                f'{self.name}_bucket{{le="+Inf"}} {self._n}'
            )
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._n}")
        return lines


class Registry:
    """pkg/metrics/metrics.go:120-278 metric set."""

    def __init__(self) -> None:
        ns = NAMESPACE
        self.endpoint_count_regenerating = Gauge(
            f"{ns}_endpoint_regenerating",
            "Number of endpoints currently regenerating",
        )
        self.endpoint_regenerations = Counter(
            f"{ns}_endpoint_regenerations",
            "Count of all endpoint regenerations that have completed",
            ("outcome",),
        )
        self.endpoint_regeneration_seconds = Histogram(
            f"{ns}_endpoint_regeneration_seconds",
            "Endpoint regeneration time",
        )
        self.endpoint_state_count = Gauge(
            f"{ns}_endpoint_state",
            "Count of all endpoints by state",
            ("endpoint_state",),
        )
        self.policy_count = Gauge(
            f"{ns}_policy_count", "Number of policies currently loaded"
        )
        self.policy_regeneration_count = Counter(
            f"{ns}_policy_regeneration_total",
            "Total number of policies regenerated successfully",
        )
        self.policy_revision = Gauge(
            f"{ns}_policy_max_revision",
            "Highest policy revision number in the agent",
        )
        self.policy_import_errors = Counter(
            f"{ns}_policy_import_errors",
            "Number of times a policy import has failed",
        )
        self.proxy_redirects = Gauge(
            f"{ns}_proxy_redirects",
            "Number of redirects installed for endpoints",
            ("protocol",),
        )
        self.policy_l7_total = Counter(
            f"{ns}_policy_l7_total",
            "Number of total L7 requests/responses",
            ("rule",),  # received|forwarded|denied|parse_errors
        )
        self.drop_count = Counter(
            f"{ns}_drop_count_total",
            "Total dropped packets by reason and direction",
            ("reason", "direction"),
        )
        self.forward_count = Counter(
            f"{ns}_forward_count_total",
            "Total forwarded packets by direction",
            ("direction",),
        )
        self.event_ts = Gauge(
            f"{ns}_event_ts",
            "Last timestamp when we received an event",
            ("source",),
        )
        self.verdict_throughput = Gauge(
            f"{ns}_verdicts_per_second",
            "Device verdict throughput (TPU-native metric)",
        )

    def expose(self) -> str:
        lines: List[str] = []
        for attr in vars(self).values():
            if isinstance(attr, (Counter, Gauge, Histogram)):
                lines.extend(attr.expose())
        return "\n".join(lines) + "\n"


# process-global registry, like pkg/metrics's default registry
registry = Registry()
