"""Metrics registry.

Re-design of /root/reference/pkg/metrics/metrics.go: the same metric
names and label sets, over a minimal in-process registry with
Prometheus text exposition (an HTTP exporter can serve `expose()`
verbatim; no prometheus client dependency in the image).
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Dict, List, Tuple

NAMESPACE = "cilium"


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped (exposition spec "Line format");
    raw interpolation corrupts the exposition for values like drop
    reasons containing quotes."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (not quotes)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_labels(
    label_names: Tuple[str, ...], label_values: Tuple[str, ...]
) -> str:
    """`{k="v",...}` selector with escaped values ('' when unlabeled)."""
    sel = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in zip(label_names, label_values)
    )
    return f"{{{sel}}}" if sel else ""


class Counter:
    def __init__(self, name: str, help_text: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = labels
        self._values: Dict[Tuple[str, ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, *label_values: str, value: float = 1.0) -> None:
        with self._lock:
            self._values[label_values] += value

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values[label_values]

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} counter",
        ]
        with self._lock:
            for labels, value in sorted(self._values.items()):
                suffix = format_labels(self.label_names, labels)
                lines.append(f"{self.name}{suffix} {value}")
        return lines


class Gauge(Counter):
    def set(self, *label_values: str, value: float) -> None:
        """Labels-first, keyword-only value — the same shape as
        Counter.inc(*labels, value=), so the two verbs can't be
        confused at a call site (the old value-first positional form
        silently read a label as the value and vice versa)."""
        with self._lock:
            self._values[label_values] = float(value)

    def dec(self, *label_values: str) -> None:
        self.inc(*label_values, value=-1.0)

    def expose(self) -> List[str]:
        lines = super().expose()
        lines[1] = f"# TYPE {self.name} gauge"
        return lines


class Histogram:
    """Fixed-bucket histogram (regeneration seconds etc.)."""

    DEFAULT_BUCKETS = (
        0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
    )

    def __init__(self, name: str, help_text: str, buckets=None):
        self.name = name
        self.help = help_text
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (linear within the
        landing bucket, the same estimator promql's histogram_quantile
        applies to the exposition)."""
        with self._lock:
            n = self._n
            if n == 0:
                return 0.0
            rank = q * n
            cumulative = 0
            lo = 0.0
            for b, c in zip(self.buckets, self._counts):
                if cumulative + c >= rank:
                    frac = (rank - cumulative) / c if c else 0.0
                    return lo + (b - lo) * frac
                cumulative += c
                lo = b
            return self.buckets[-1]

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        cumulative = 0
        with self._lock:
            for b, c in zip(self.buckets, self._counts):
                cumulative += c
                lines.append(
                    f'{self.name}_bucket{{le="{b}"}} {cumulative}'
                )
            lines.append(
                f'{self.name}_bucket{{le="+Inf"}} {self._n}'
            )
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._n}")
        return lines


class WindowedHistogram(Histogram):
    """Histogram plus a bounded window of recent raw observations for
    EXACT short-horizon quantiles (the p50/p99 batch-latency lines the
    bench and `cilium status` surface): the cumulative buckets feed
    Prometheus; the window answers "what is p99 right now" without
    bucket-resolution error."""

    def __init__(self, name, help_text, buckets=None, window: int = 512):
        super().__init__(name, help_text, buckets)
        self._window = deque(maxlen=window)

    def observe(self, value: float) -> None:
        super().observe(value)
        with self._lock:
            self._window.append(value)

    def window_quantile(self, q: float) -> float:
        """Exact quantile over the last `window` observations
        (nearest-rank); 0.0 when nothing has been observed."""
        with self._lock:
            if not self._window:
                return 0.0
            ordered = sorted(self._window)
            rank = min(
                len(ordered) - 1, max(0, int(q * len(ordered)))
            )
            return ordered[rank]


class Registry:
    """pkg/metrics/metrics.go:120-278 metric set."""

    def __init__(self) -> None:
        ns = NAMESPACE
        self.endpoint_count_regenerating = Gauge(
            f"{ns}_endpoint_regenerating",
            "Number of endpoints currently regenerating",
        )
        self.endpoint_regenerations = Counter(
            f"{ns}_endpoint_regenerations",
            "Count of all endpoint regenerations that have completed",
            ("outcome",),
        )
        self.endpoint_regeneration_seconds = Histogram(
            f"{ns}_endpoint_regeneration_seconds",
            "Endpoint regeneration time",
        )
        self.endpoint_state_count = Gauge(
            f"{ns}_endpoint_state",
            "Count of all endpoints by state",
            ("endpoint_state",),
        )
        self.policy_count = Gauge(
            f"{ns}_policy_count", "Number of policies currently loaded"
        )
        self.policy_regeneration_count = Counter(
            f"{ns}_policy_regeneration_total",
            "Total number of policies regenerated successfully",
        )
        self.policy_revision = Gauge(
            f"{ns}_policy_max_revision",
            "Highest policy revision number in the agent",
        )
        self.policy_import_errors = Counter(
            f"{ns}_policy_import_errors",
            "Number of times a policy import has failed",
        )
        self.proxy_redirects = Gauge(
            f"{ns}_proxy_redirects",
            "Number of redirects installed for endpoints",
            ("protocol",),
        )
        self.policy_l7_total = Counter(
            f"{ns}_policy_l7_total",
            "Number of total L7 requests/responses",
            ("rule",),  # received|forwarded|denied|parse_errors
        )
        self.drop_count = Counter(
            f"{ns}_drop_count_total",
            "Total dropped packets by reason and direction",
            ("reason", "direction"),
        )
        self.forward_count = Counter(
            f"{ns}_forward_count_total",
            "Total forwarded packets by direction",
            ("direction",),
        )
        self.event_ts = Gauge(
            f"{ns}_event_ts",
            "Last timestamp when we received an event",
            ("source",),
        )
        self.verdict_throughput = Gauge(
            f"{ns}_verdicts_per_second",
            "Device verdict throughput (TPU-native metric)",
        )
        self.policy_verdict_total = Counter(
            f"{ns}_policy_verdict_total",
            "Policy verdicts by direction, match type and action",
            ("direction", "match", "action"),
        )
        self.datapath_stage_total = Counter(
            f"{ns}_datapath_stage_total",
            "Datapath stage outcomes by stage and direction "
            "(LB DNAT, CT states, ipcache world fallback, proxy "
            "redirects) folded from the on-device accumulator",
            ("stage", "direction"),
        )
        self.batch_duration = WindowedHistogram(
            f"{ns}_datapath_batch_duration_seconds",
            "Wall time of one datapath batch (dispatch to drained)",
            buckets=(
                0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5,
            ),
        )
        # -- resilience plane (circuit breaker / degraded mode /
        # overload shedding / fault injection) --------------------------
        self.breaker_state = Gauge(
            f"{ns}_circuit_breaker_state",
            "Circuit breaker state (0=closed, 1=open, 2=half-open)",
            ("breaker",),
        )
        self.dispatch_retries_total = Counter(
            f"{ns}_dispatch_retries_total",
            "Device dispatch attempts retried after a failure",
        )
        self.degraded_batches_total = Counter(
            f"{ns}_degraded_batches_total",
            "Batches served by the host-path fallback while the "
            "device dispatch breaker was open or failing",
        )
        self.shed_flows_total = Counter(
            f"{ns}_shed_flows_total",
            "Flows shed by bounded admission under overload",
        )
        self.ct_occupancy = Gauge(
            f"{ns}_ct_occupancy_ratio",
            "Conntrack map occupancy as a fraction of capacity",
        )
        self.ct_emergency_gc_total = Counter(
            f"{ns}_ct_emergency_gc_total",
            "Emergency CT garbage collections triggered by the "
            "occupancy high watermark",
        )
        self.fault_injections_total = Counter(
            f"{ns}_fault_injections_total",
            "Injected faults fired, by site and mode",
            ("site", "mode"),
        )
        self.publish_fallback_total = Counter(
            f"{ns}_publish_fallback_total",
            "Delta publishes that fell back to a full upload "
            "because an armed publish.scatter fault poisoned the "
            "device scatter (real scatter errors de-register the "
            "spare and propagate instead)",
        )
        self.memo_insert_faults_total = Counter(
            f"{ns}_memo_insert_faults_total",
            "Verdict-cache commits dropped by a memo.insert fault; "
            "each such batch re-dispatched through the uncached "
            "program (bit-identity unconditional)",
        )
        # -- per-chip failover plane (engine/failover.py) ----------------
        self.chip_breaker_state = Gauge(
            f"{ns}_chip_breaker_state",
            "Per-chip dispatch breaker state keyed by device ordinal "
            "(0=closed, 1=open, 2=half-open) — the mesh refinement "
            "of cilium_circuit_breaker_state",
            ("chip",),
        )
        self.rerouted_batches_total = Counter(
            f"{ns}_rerouted_batches_total",
            "Batches whose tuple stream was re-split across "
            "surviving chips because at least one chip's breaker "
            "was open",
        )
        self.replica_gather_total = Counter(
            f"{ns}_replica_gather_total",
            "Tuples whose routed table gather was served from a "
            "backup (N+1 replica) shard region because the primary "
            "owner's breaker was open",
        )
        self.rebalance_bytes_h2d_total = Counter(
            f"{ns}_rebalance_bytes_h2d_total",
            "Bytes scattered host->device by chip re-admission "
            "rebalances (replaying the rows a chip missed while its "
            "breaker was open, through the delta-scatter path)",
        )
        # -- verdict memoization plane (engine/memo.py) ------------------
        self.verdict_cache_hits_total = Counter(
            f"{ns}_verdict_cache_hits_total",
            "Tuples whose policy verdict was served from the "
            "device-resident verdict cache (lattice gathers skipped)",
        )
        self.verdict_cache_misses_total = Counter(
            f"{ns}_verdict_cache_misses_total",
            "Tuples whose policy key missed the verdict cache and "
            "was evaluated through the lattice",
        )
        self.verdict_cache_insertions_total = Counter(
            f"{ns}_verdict_cache_insertions_total",
            "Entries inserted into the verdict cache (missed "
            "representatives after intra-batch dedup)",
        )
        self.verdict_cache_flushes_total = Counter(
            f"{ns}_verdict_cache_flushes_total",
            "Verdict-cache flushes (epoch-stamp change on a delta "
            "publish / repack / partition change, or a chip "
            "kill/readmission)",
        )
        # -- continuous serving plane (cilium_tpu.serve) -----------------
        self.serve_queue_depth = Gauge(
            f"{ns}_serve_queue_depth",
            "Flows queued in the serving plane's ingest queue, per "
            "tenant (the dynamic-batching backlog)",
            ("tenant",),
        )
        self.serve_queue_delay_seconds = WindowedHistogram(
            f"{ns}_serve_queue_delay_seconds",
            "Per-flow time from submission to device dispatch in "
            "the serving plane (the batching wait the SLO bounds)",
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5,
            ),
        )
        self.serve_latency_seconds = WindowedHistogram(
            f"{ns}_serve_latency_seconds",
            "Per-submission time from submission to completed reply "
            "in the serving plane (what serving_p99_ms summarizes)",
            buckets=(
                0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0,
            ),
        )
        self.serving_p99_ms = Gauge(
            f"{ns}_serving_p99_ms",
            "p99 submission-to-reply latency over the serving "
            "plane's rolling window, milliseconds",
        )
        self.serve_batch_fill_pct = Gauge(
            f"{ns}_serve_batch_fill_pct",
            "Valid-tuple fill of the most recent coalesced device "
            "batch (100 = the jit class dispatched full)",
        )
        self.serve_batches_total = Counter(
            f"{ns}_serve_batches_total",
            "Coalesced device batches dispatched by the serving "
            "plane",
        )
        self.serve_deadline_dispatch_total = Counter(
            f"{ns}_serve_deadline_dispatch_total",
            "Serving-plane batches dispatched EARLY (below the "
            "target fill) because the oldest queued flow's deadline "
            "no longer allowed waiting, by the SLO class of the "
            "flow that forced it (\"default\" = no named class)",
            ("slo_class",),
        )
        self.serve_admitted_flows_total = Counter(
            f"{ns}_serve_admitted_flows_total",
            "Flows admitted into the serving plane's ingest queue, "
            "per tenant",
            ("tenant",),
        )
        self.serve_shed_flows_total = Counter(
            f"{ns}_serve_shed_flows_total",
            "Flows shed by the serving plane under the canonical "
            "Overload drop reason, per tenant (backlog bound or "
            "admission gate)",
            ("tenant",),
        )
        # -- shadow policy rollout / verdict-diff canarying
        # (cilium_tpu.shadow) --------------------------------------------
        self.policy_diff_sampled_total = Counter(
            f"{ns}_policy_diff_sampled_total",
            "Flows sampled into the armed shadow window and "
            "dual-epoch evaluated (folded exactly once each; "
            "refused in-flight samples count in "
            "policy_diff_refused_total instead)",
        )
        self.policy_diff_changed_total = Counter(
            f"{ns}_policy_diff_changed_total",
            "Sampled flows whose verdict column differs between the "
            "live and shadow policy worlds, by column and direction",
            ("column", "direction"),
        )
        self.policy_diff_flows_allow_to_deny_total = Counter(
            f"{ns}_policy_diff_flows_allow_to_deny_total",
            "Sampled flows the live world allows that the shadow "
            "world would deny (the blast-radius line of a pending "
            "policy change)",
        )
        self.policy_diff_flows_deny_to_allow_total = Counter(
            f"{ns}_policy_diff_flows_deny_to_allow_total",
            "Sampled flows the live world denies that the shadow "
            "world would allow (the exposure line of a pending "
            "policy change)",
        )
        self.policy_diff_stale_total = Counter(
            f"{ns}_policy_diff_stale_total",
            "Shadow diff windows closed with an explicit stale "
            "status because a publish moved the live world past the "
            "pinned epoch stamp (a diff never silently spans a "
            "third world)",
        )
        self.policy_diff_refused_total = Counter(
            f"{ns}_policy_diff_refused_total",
            "Sampled shadow dispatches refused instead of folded "
            "(window closed while the sample was in flight, shadow "
            "evaluation failure, or a drain-side failover dropped "
            "the shadow columns) — exactly-once accounting's "
            "complement to policy_diff_sampled_total",
        )
        # -- flow observability plane (cilium_tpu.flow) ------------------
        self.flow_records_captured_total = Counter(
            f"{ns}_flow_records_captured_total",
            "Flow records accounted by the capture fold, by verdict "
            "(every drop counts here even when a drop storm exceeds "
            "ring capacity — the excess shows in flow_store_evicted)",
            ("verdict",),
        )
        self.flow_store_evicted = Gauge(
            f"{ns}_flow_store_evicted",
            "Flow records lost to the bounded FlowStore ring "
            "(overflow eviction + drop-storm truncation): what a "
            "late reader can no longer see",
        )
        # -- delta table publication (engine/publish.py) -----------------
        self.table_publish_total = Counter(
            f"{ns}_table_publish_total",
            "Device table-epoch publications by mode (delta = "
            "in-place scatter of the changed rows, full = whole "
            "upload)",
            ("mode",),
        )
        self.table_publish_bytes = Counter(
            f"{ns}_table_publish_bytes_total",
            "Bytes shipped host->device by table publications, "
            "by mode",
            ("mode",),
        )
        self.table_publish_seconds = Gauge(
            f"{ns}_table_publish_last_seconds",
            "Wall seconds of the most recent device table "
            "publication",
        )
        # -- device-resource accounting (publish layer + jitted entry
        # points): HBM growth and recompile storms in one scrape ---------
        self.device_table_bytes = Gauge(
            f"{ns}_device_table_bytes",
            "Device-resident policy-table bytes per epoch slot "
            "(live = the serving epoch, standby = the double-buffered "
            "spare awaiting the next delta scatter)",
            ("epoch",),
        )
        self.device_table_bytes_per_chip = Gauge(
            f"{ns}_device_table_bytes_per_chip",
            "Device-resident policy-table bytes per mesh chip "
            "(live + standby epochs), sampled at publish — the "
            "per-shard HBM line behind the universe_max_identities "
            "headroom model (identity-sharded tables divide across "
            "chips; replicated leaves repeat on every chip)",
            ("chip",),
        )
        self.device_table_retired_bytes = Counter(
            f"{ns}_device_table_donation_retired_bytes_total",
            "Bytes of standby-epoch buffers consumed (donated in "
            "place) by delta publications — HBM reused, not "
            "reallocated",
        )
        self.jit_cache_hits = Counter(
            f"{ns}_jit_cache_hits",
            "Calls into an instrumented jitted entry point served "
            "from the executable cache, by site",
            ("site",),
        )
        self.jit_cache_misses = Counter(
            f"{ns}_jit_cache_misses",
            "Calls into an instrumented jitted entry point that "
            "grew the executable cache (fresh XLA trace+compile), "
            "by site",
            ("site",),
        )
        self.jit_compile_seconds = Counter(
            f"{ns}_jit_cache_compile_seconds",
            "Wall seconds spent in cache-growing calls (compile + "
            "first execution), by site — the recompile-storm signal",
            ("site",),
        )
        # -- trace plane (cilium_tpu.tracing) -----------------------------
        self.trace_spans_total = Gauge(
            f"{ns}_trace_spans_total",
            "Spans exported to the trace ring since process start "
            "(sampled from the tracer at span-export points)",
        )
        self.trace_spans_dropped = Gauge(
            f"{ns}_trace_spans_dropped",
            "Spans lost to the bounded trace ring (oldest-first "
            "eviction): what a late /debug/traces reader can no "
            "longer see",
        )
        # -- phase spans + mesh telemetry --------------------------------
        self.spanstat_seconds = Gauge(
            f"{ns}_spanstat_seconds",
            "Accumulated wall seconds per SpanStat phase "
            "(success + failure), mirroring /debug/profile",
            ("scope", "phase"),
        )
        self.telemetry_per_chip = Counter(
            f"{ns}_datapath_telemetry_per_chip_total",
            "Per-chip datapath stage histogram on a sharded mesh "
            "(TELEM_* column names); summing a column over `chip` "
            "equals the mesh-total counters",
            ("chip", "column", "direction"),
        )
        # -- live performance plane (cilium_tpu.perfplane) ----------------
        self.serve_phase_seconds = Gauge(
            f"{ns}_serve_phase_seconds",
            "Decaying-window quantiles of per-batch serve-loop phase "
            "durations (pack = host staging, dispatch = jit enqueue, "
            "drain = blocked on device readback, device = enqueue + "
            "drain, fold = drain-side event/flow/metric fold, wall = "
            "plan-to-reply), stat in p50|p99|max",
            ("phase", "stat"),
        )
        self.serve_batch_fill_window_pct = Gauge(
            f"{ns}_serve_batch_fill_window_pct",
            "Decaying-window quantiles of coalesced-batch fill "
            "(serve_batch_fill_pct promoted from last-value to the "
            "perf plane's window), stat in p50|p99|max",
            ("stat",),
        )
        self.serve_queue_delay_window_seconds = Gauge(
            f"{ns}_serve_queue_delay_window_seconds",
            "Decaying-window quantiles of per-span queue delay "
            "(serve_queue_delay_seconds promoted to the perf "
            "plane's window), stat in p50|p99|max",
            ("stat",),
        )
        self.serve_ingest_stall_seconds = Counter(
            f"{ns}_serve_ingest_stall_seconds_total",
            "Wall seconds the serve loop spent waiting with a "
            "NONEMPTY ingest queue while nothing was in flight on "
            "the device (the ingest-starvation accumulator: the "
            "device idles because the host trickle-feeds it)",
        )
        self.serve_slo_deadline_total = Counter(
            f"{ns}_serve_slo_deadline_total",
            "Completed serving-plane submissions by deadline "
            "outcome (hit = replied within the submission's "
            "deadline, miss = reply landed late or flows shed), "
            "per tenant and SLO class",
            ("tenant", "slo_class", "outcome"),
        )
        self.serve_slo_error_budget_burn = Gauge(
            f"{ns}_serve_slo_error_budget_burn",
            "Per-tenant error-budget burn rate: windowed deadline "
            "miss fraction over the SLO class's allowed miss "
            "fraction (1 - objective); > 1 burns budget faster "
            "than the class allows",
            ("tenant",),
        )
        self.perf_model_bytes_per_tuple = Gauge(
            f"{ns}_perf_model_bytes_per_tuple",
            "The gatherprof byte model evaluated LIVE against the "
            "published layout stamp: hot = modeled hot-plane gather "
            "bytes, cold = dense-fallback bytes, effective = hot "
            "under the observed dedup/cache-hit factors",
            ("plane",),
        )
        self.perf_model_gbps = Gauge(
            f"{ns}_perf_model_gbps",
            "Modeled sustained gather bandwidth: effective "
            "bytes-per-tuple x the serving plane's measured "
            "verdicts/s EWMA (model x measurement, not a "
            "measurement)",
        )
        self.retune_total = Counter(
            f"{ns}_retune_total",
            "Online re-tune layout swaps applied by "
            "engine.autotune.online_retune, by drift trigger "
            "(p99_drift | fill_low | stall | forced)",
            ("trigger",),
        )
        self.datapath_persistent_launches = Counter(
            f"{ns}_datapath_persistent_launches_total",
            "Fused persistent-program launches (each covers K "
            "staged batch pairs in one device program)",
        )
        self.datapath_persistent_pairs = Counter(
            f"{ns}_datapath_persistent_pairs_total",
            "Batch pairs staged into the persistent fused program "
            "(pairs/launches = realized staging depth)",
        )
        self.reshard_total = Counter(
            f"{ns}_reshard_total",
            "Live elastic reshard migrations by outcome (cutover | "
            "rollback | restart_full)",
            ("outcome",),
        )
        self.reshard_bytes_h2d_total = Counter(
            f"{ns}_reshard_bytes_h2d_total",
            "Bytes streamed host->device by reshard migration "
            "steps (moved-owner rows only; the stop-the-world "
            "comparator would ship the whole world)",
        )
        self.reshard_steps_total = Counter(
            f"{ns}_reshard_steps_total",
            "Bounded-byte migration steps executed by reshard "
            "plans (each step scatters at most step_bytes into the "
            "staged target epoch)",
        )
        self.reshard_seconds = Histogram(
            f"{ns}_reshard_seconds",
            "End-to-end reshard migration duration, plan begin "
            "through cutover or rollback",
        )

    def expose(self) -> str:
        lines: List[str] = []
        for attr in vars(self).values():
            if isinstance(attr, (Counter, Gauge, Histogram)):
                lines.extend(attr.expose())
        return "\n".join(lines) + "\n"


# process-global registry, like pkg/metrics's default registry
registry = Registry()
