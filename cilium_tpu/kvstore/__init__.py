"""Distributed state: watchable kvstore, cluster-wide ID allocator,
ipcache sync, clustermesh.

Re-design of /root/reference/pkg/kvstore (+allocator/, store/),
pkg/clustermesh and the kvstore halves of pkg/identity / pkg/ipcache.
The reference's inter-node "communication backend" is etcd/consul
watch — no NCCL/MPI (SURVEY §2.7).  Here the same versioned-watch
semantics run over an in-process backend (`KVStore`) that mirrors
BackendOperations (backend.go:92); an etcd adapter can implement the
same five primitives when real multi-host deployment needs it.  Device
table replication across hosts rides this control plane (tables are
recompiled per host from watched state), while batch evaluation within
a pod slice uses XLA collectives (engine.sharded).
"""

from cilium_tpu.kvstore.store import KVStore, KVEvent
from cilium_tpu.kvstore.allocator import Allocator
from cilium_tpu.kvstore.ipsync import (
    IPIdentityWatcher,
    delete_ip_mapping,
    upsert_ip_mapping,
)
from cilium_tpu.kvstore.clustermesh import ClusterMesh, RemoteCluster

__all__ = [
    "KVStore",
    "KVEvent",
    "Allocator",
    "IPIdentityWatcher",
    "upsert_ip_mapping",
    "delete_ip_mapping",
    "ClusterMesh",
    "RemoteCluster",
]

from cilium_tpu.kvstore.paths import (  # noqa: E402
    BASE_KEY_PREFIX,
    CLUSTER_ID_SHIFT,
    IDENTITIES_PATH,
    IP_IDENTITIES_PATH,
    NODES_PATH,
)
