"""Socket transport server for the kvstore: the etcd-stand-in.

The reference's consensus layer is a network client against etcd
(/root/reference/pkg/kvstore/etcd.go); this is the matching server
side for this framework's BackendOperations (store.py), so multiple
agent PROCESSES share one store the way cilium agents share one etcd:

  * newline-delimited JSON frames over TCP (localhost); requests carry
    an `id` and are answered in order;
  * lease sessions are NAMED by the client (the node name, as in the
    in-process store) and die with the connection that owns them
    (etcd lease expiry ≙ dead-agent state cleanup,
    pkg/kvstore/keepalive.go);
  * watches are server-side subscriptions; events are pushed as
    un-id'd frames tagged with the client's watch id, following the
    ListAndWatch contract (replay-then-stream); `unwatch` removes the
    server-side watcher;
  * distributed locks are lease-scoped CAS keys under `lock/`
    (etcd.go LockPath's concurrency.Mutex reduced to its observable
    contract: mutual exclusion with liveness under client death);
  * an optional snapshot file (debounced, plus on connection close
    and SIGTERM) makes restarts durable for the reconnect story —
    etcd's raft log reduced to a JSON dump; the semantics under test
    are CLIENT re-list/re-watch, not server replication.

Run standalone:  python -m cilium_tpu.kvstore.server --port 4321
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socketserver
import threading
from typing import Optional

from cilium_tpu.kvstore.store import (
    KVEvent,
    KVStore,
    wire_decode as _dec,
    wire_encode as _enc,
)

_SNAPSHOT_DEBOUNCE_S = 0.2


class _Conn(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: KVStoreServer = self.server.kv_server  # type: ignore
        conn_session = f"conn-{id(self)}"
        send_lock = threading.Lock()
        unsubscribes = {}
        owned_sessions = set()

        def push(frame: dict) -> None:
            data = (json.dumps(frame) + "\n").encode()
            with send_lock:
                try:
                    self.wfile.write(data)
                    self.wfile.flush()
                except OSError:
                    pass

        try:
            for line in self.rfile:
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    break
                op = req.get("op")
                rid = req.get("id")
                try:
                    result = self._dispatch(
                        server,
                        conn_session,
                        owned_sessions,
                        op,
                        req,
                        push,
                        unsubscribes,
                    )
                except Exception as exc:  # surfaced to the client
                    push({"id": rid, "error": str(exc)})
                    continue
                push({"id": rid, "result": result})
        finally:
            for unsub in unsubscribes.values():
                unsub()
            # connection death = lease expiry for every session this
            # connection wrote through (named by the client, so
            # expire_session-by-name keeps working remotely)
            for session in owned_sessions | {conn_session}:
                server.store.expire_session(session)
            server.mark_dirty()
            server.save_snapshot()

    def _dispatch(
        self,
        server,
        conn_session,
        owned_sessions,
        op,
        req,
        push,
        unsubscribes,
    ):
        store = server.store
        key = req.get("key", "")
        value = _dec(req.get("value"))
        session = req.get("session")
        if session is not None:
            owned_sessions.add(session)
        mutated = False
        try:
            if op == "get":
                return _enc(store.get(key))
            if op == "get_prefix":
                got = store.get_prefix(key)
                return None if got is None else [got[0], _enc(got[1])]
            if op == "list_prefix":
                return {
                    k: _enc(v)
                    for k, v in store.list_prefix(key).items()
                }
            if op == "set":
                mutated = True
                return store.set(key, value, session=session)
            if op == "create_only":
                mutated = True
                return store.create_only(key, value, session=session)
            if op == "create_if_exists":
                mutated = True
                return store.create_if_exists(
                    req["cond_key"], key, value, session=session
                )
            if op == "delete":
                mutated = True
                return store.delete(key)
            if op == "delete_prefix":
                mutated = True
                return store.delete_prefix(key)
            if op == "lock_acquire":
                # lease-scoped CAS key: mutual exclusion with
                # liveness under client death
                return store.create_only(
                    f"lock/{key}",
                    conn_session.encode(),
                    session=conn_session,
                )
            if op == "lock_release":
                holder = store.get(f"lock/{key}")
                if holder == conn_session.encode():
                    store.delete(f"lock/{key}")
                    return True
                return False
            if op == "watch":
                wid = req["wid"]

                def watcher(event: KVEvent) -> None:
                    push(
                        {
                            "watch": wid,
                            "event": {
                                "kind": event.kind,
                                "key": event.key,
                                "value": _enc(event.value),
                                "revision": event.revision,
                            },
                        }
                    )

                unsubscribes[wid] = store.watch_prefix(key, watcher)
                return True
            if op == "unwatch":
                unsub = unsubscribes.pop(req["wid"], None)
                if unsub is not None:
                    unsub()
                return True
            if op == "revision":
                return store.revision
            if op == "expire_session":
                mutated = True
                return store.expire_session(req["session"])
            if op == "ping":
                return "pong"
            raise ValueError(f"unknown op {op!r}")
        finally:
            if mutated:
                server.mark_dirty()


class _ThreadedTCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class KVStoreServer:
    """Wraps a KVStore in the socket protocol; one thread per client;
    debounced snapshotting to an optional state file."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        state_file: Optional[str] = None,
    ) -> None:
        self.store = KVStore()
        self.state_file = state_file
        self._snap_lock = threading.Lock()
        self._dirty = threading.Event()
        self._stopping = threading.Event()
        if state_file and os.path.exists(state_file):
            with open(state_file) as f:
                for k, v in json.load(f).items():
                    self.store.set(k, _dec(v))
        self._tcp = _ThreadedTCP((host, port), _Conn)
        self._tcp.kv_server = self  # type: ignore
        self.port = self._tcp.server_address[1]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True
        )
        self._snapshotter = threading.Thread(
            target=self._snapshot_loop, daemon=True
        )

    def mark_dirty(self) -> None:
        self._dirty.set()

    def _snapshot_loop(self) -> None:
        while not self._stopping.is_set():
            if self._dirty.wait(timeout=0.5):
                self._stopping.wait(_SNAPSHOT_DEBOUNCE_S)
                self._dirty.clear()
                self.save_snapshot()

    def save_snapshot(self) -> None:
        if not self.state_file:
            return
        with self._snap_lock:
            # durable_items captures the lease exclusion atomically
            # under the store lock — a key expiring concurrently can
            # never be persisted
            data = {
                k: _enc(v) for k, v in self.store.durable_items().items()
            }
            tmp = self.state_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self.state_file)

    def start(self) -> "KVStoreServer":
        self._thread.start()
        self._snapshotter.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self._tcp.shutdown()
        self._tcp.server_close()
        self.save_snapshot()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--state-file", default=None)
    args = ap.parse_args()
    server = KVStoreServer(args.host, args.port, args.state_file)
    server.start()

    stop = threading.Event()

    def _term(signum, frame):
        server.stop()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    print(
        f"kvstore-server listening on {args.host}:{server.port}",
        flush=True,
    )
    try:
        stop.wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
