"""ipcache ↔ kvstore synchronisation.

Behavioral port of /root/reference/pkg/ipcache/kvstore.go: each agent
publishes its local endpoint IP → identity mappings under
`cilium/state/ip/v1/<address space>/<ip>` (UpsertIPToKVStore
kvstore.go:159, lease-scoped so dead nodes' IPs expire), and every
agent watches the whole prefix (InitIPIdentityWatcher kvstore.go:393)
to feed its IPCache with source=kvstore — which then fans out to the
device LPM builder.
"""

from __future__ import annotations

import json
from typing import Callable, Optional

from cilium_tpu.ipcache.ipcache import FROM_KVSTORE, IPCache, IPIdentity
from cilium_tpu.kvstore.paths import IP_IDENTITIES_PATH
from cilium_tpu.kvstore.store import KVEvent, KVStore

DEFAULT_ADDRESS_SPACE = "default"  # kvstore.go AddressSpace


def _ip_path(base: str, address_space: str, ip: str) -> str:
    return f"{base}/{address_space}/{ip}"


def upsert_ip_mapping(
    store: KVStore,
    ip: str,
    identity: int,
    host_ip: Optional[str] = None,
    node: Optional[str] = None,
    base: str = IP_IDENTITIES_PATH,
    address_space: str = DEFAULT_ADDRESS_SPACE,
) -> None:
    """UpsertIPToKVStore (kvstore.go:159): JSON payload {IP, ID, Host}
    under a node lease."""
    payload = json.dumps(
        {"IP": ip, "ID": identity, "Host": host_ip}
    ).encode()
    store.set(
        _ip_path(base, address_space, ip), payload, session=node
    )


def delete_ip_mapping(
    store: KVStore,
    ip: str,
    base: str = IP_IDENTITIES_PATH,
    address_space: str = DEFAULT_ADDRESS_SPACE,
) -> None:
    store.delete(_ip_path(base, address_space, ip))


class IPIdentityWatcher:
    """InitIPIdentityWatcher (kvstore.go:393): replay + stream kvstore
    IP mappings into the local IPCache with source=kvstore (so local
    agent entries keep precedence, ipcache.go:183)."""

    def __init__(
        self,
        store: KVStore,
        ipcache: IPCache,
        base: str = IP_IDENTITIES_PATH,
        address_space: str = DEFAULT_ADDRESS_SPACE,
    ) -> None:
        self.ipcache = ipcache
        prefix = f"{base}/{address_space}/"
        self._unsubscribe = store.watch_prefix(prefix, self._on_event)

    def _on_event(self, event: KVEvent) -> None:
        ip = event.key.rsplit("/", 1)[1]
        if event.kind == "delete":
            cached, ok = self.ipcache.lookup_by_prefix(ip)
            # only remove kvstore-owned entries (never agent-local)
            if ok and cached.source == FROM_KVSTORE:
                self.ipcache.delete(ip)
            return
        try:
            payload = json.loads(event.value.decode())
        except (ValueError, UnicodeDecodeError):
            return
        self.ipcache.upsert(
            ip,
            IPIdentity(int(payload["ID"]), FROM_KVSTORE),
            host_ip=payload.get("Host"),
        )

    def close(self) -> None:
        self._unsubscribe()
