"""Socket transport client: BackendOperations over the wire.

The process-local face of the shared store (the analog of
/root/reference/pkg/kvstore/etcd.go's etcdClient): implements the
same method surface as the in-process KVStore, so the Daemon, the
identity Allocator, ipcache sync, node discovery, and clustermesh run
unchanged against a REMOTE store — multiple agent processes converge
the way cilium agents converge through one etcd.

Reconnect semantics (etcd.go's session/watcher re-establishment):
on connection loss a background thread redials with backoff, then
  * re-registers every live watch — the server replays the prefix as
    `create` events (ListAndWatch), which downstream consumers treat
    idempotently, exactly like an etcd watch restarted from a
    compacted revision;
  * re-publishes this client's lease-scoped keys — the old session
    died with the old connection (lease expiry), and re-creating them
    is the keepalive re-acquisition of pkg/kvstore/keepalive.go.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from cilium_tpu.logging import get_logger

log = get_logger("kvstore")

from cilium_tpu.kvstore.store import (
    KVEvent,
    Watcher,
    wire_decode as _dec,
    wire_encode as _enc,
)


# how long a lock acquisition spins before giving up: a holder that
# never releases (wedged peer whose lease hasn't expired yet) must
# surface as a TimeoutError the caller can handle, not an eternal
# busy-wait on a background thread (etcd.go's ctx-scoped Lock)
DEFAULT_LOCK_TIMEOUT = 30.0


class RemoteLock:
    """Distributed lock: lease-scoped CAS key on the server (mutual
    exclusion across processes; liveness by lease expiry on client
    death).  Context-manager like the in-process RLock."""

    def __init__(
        self,
        backend: "RemoteBackend",
        path: str,
        timeout: Optional[float] = DEFAULT_LOCK_TIMEOUT,
    ) -> None:
        self._backend = backend
        self._path = path
        self._timeout = timeout

    def __enter__(self) -> "RemoteLock":
        deadline = (
            None
            if self._timeout is None
            else time.monotonic() + self._timeout
        )
        backoff = 0.005
        while not self._backend._call("lock_acquire", key=self._path):
            if (
                deadline is not None
                and time.monotonic() >= deadline
            ):
                raise TimeoutError(
                    f"lock {self._path!r} not acquired within "
                    f"{self._timeout}s"
                )
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.25)
        return self

    def __exit__(self, *exc) -> None:
        self._backend._call("lock_release", key=self._path)

    # RLock-compat aliases
    acquire = __enter__

    def release(self) -> None:
        self.__exit__()


class RemoteBackend:
    """KVStore-compatible client for a KVStoreServer."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        reconnect: bool = True,
        dial_timeout: float = 5.0,
    ) -> None:
        self._host = host
        self._port = port
        self._reconnect = reconnect
        self._dial_timeout = dial_timeout
        self._io_lock = threading.Lock()
        self._pending: Dict[int, "queue.Queue"] = {}
        self._next_id = 0
        self._next_wid = 0
        self._watches: Dict[int, Tuple[str, Watcher]] = {}
        self._lease_keys: Dict[str, bytes] = {}
        self._closed = False
        self._sock = None
        self._connected = threading.Event()
        # watch callbacks run on a dedicated dispatcher thread, NOT
        # the reader: a callback that itself issues kvstore calls
        # would otherwise deadlock waiting for the reader it blocks
        import queue as _queue

        self._event_q: "_queue.Queue" = _queue.Queue()
        self._dial()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True
        )
        self._dispatcher.start()

    # -- wire ----------------------------------------------------------------

    def _dial(self) -> None:
        deadline = time.monotonic() + self._dial_timeout
        backoff = 0.02
        while True:
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=5.0
                )
                sock.settimeout(None)
                self._sock = sock
                self._rfile = sock.makefile("rb")
                self._connected.set()
                return
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)

    def _send(self, frame: dict) -> None:
        # chaos seam: an armed kvstore.conn site severs THIS client's
        # connection (the mid-watch socket drop the reconnect tests
        # inject) — the read loop sees EOF, redials and re-establishes
        # watches + lease keys exactly as for a real network fault
        from cilium_tpu import faultinject

        if faultinject.should_fire("kvstore.conn"):
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise ConnectionError("kvstore connection lost (injected)")
        data = (json.dumps(frame) + "\n").encode()
        self._sock.sendall(data)

    def _call(self, op: str, **kw):
        import queue

        if self._closed:
            raise ConnectionError("backend closed")
        self._connected.wait(self._dial_timeout)
        q: "queue.Queue" = queue.Queue(maxsize=1)
        with self._io_lock:
            self._next_id += 1
            rid = self._next_id
            self._pending[rid] = q
            try:
                self._send({"id": rid, "op": op, **kw})
            except OSError:
                self._pending.pop(rid, None)
                raise ConnectionError("kvstore connection lost")
        import queue as _queue

        try:
            got = q.get(timeout=30.0)
        except _queue.Empty:
            raise ConnectionError(
                f"kvstore call {op!r} timed out"
            ) from None
        finally:
            self._pending.pop(rid, None)
        if "error" in got:
            raise RuntimeError(got["error"])
        return got.get("result")

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                line = self._rfile.readline()
            except OSError:
                line = b""
            if not line:
                self._connected.clear()
                # fail outstanding requests
                with self._io_lock:
                    pending, self._pending = self._pending, {}
                for q in pending.values():
                    q.put({"error": "kvstore connection lost"})
                if self._closed or not self._reconnect:
                    return
                try:
                    self._dial()
                except OSError:
                    return
                # re-establishment issues normal calls, whose
                # responses THIS thread must keep reading — run it on
                # its own thread
                log.info(
                    "kvstore connection lost; redialed, "
                    "re-establishing watches and leases",
                    extra={"fields": {
                        "watches": len(self._watches),
                        "leaseKeys": len(self._lease_keys),
                    }},
                )
                threading.Thread(
                    target=self._reestablish, daemon=True
                ).start()
                continue
            try:
                frame = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "watch" in frame:
                ev = frame["event"]
                self._event_q.put(
                    (
                        "ev",
                        frame["watch"],
                        KVEvent(
                            ev["kind"],
                            ev["key"],
                            _dec(ev["value"]) or b"",
                            ev["revision"],
                        ),
                    )
                )
                continue
            q = self._pending.pop(frame.get("id"), None)
            if q is not None:
                q.put(frame)

    def _dispatch_loop(self) -> None:
        while not self._closed:
            item = self._event_q.get()
            if item is None:
                return
            if item[0] == "sync":
                item[1].set()
                continue
            _, wid, event = item
            entry = self._watches.get(wid)
            if entry is not None:
                try:
                    entry[1](event)
                except Exception:
                    pass  # a broken watcher must not kill dispatch

    def _reestablish(self) -> None:
        """Post-reconnect: re-publish lease keys (the old lease died
        with the old connection) and re-register watches (the server
        replays the prefix — idempotent for consumers)."""
        for key, (value, session) in list(self._lease_keys.items()):
            try:
                self._call(
                    "set", key=key, value=_enc(value), session=session
                )
            except (ConnectionError, RuntimeError):
                return
        for wid, (prefix, _) in list(self._watches.items()):
            try:
                self._call("watch", key=prefix, wid=wid)
            except (ConnectionError, RuntimeError):
                return

    # -- BackendOperations surface -------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        return _dec(self._call("get", key=key))

    def get_prefix(self, prefix: str):
        got = self._call("get_prefix", key=prefix)
        return None if got is None else (got[0], _dec(got[1]))

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        return {
            k: _dec(v)
            for k, v in self._call("list_prefix", key=prefix).items()
        }

    def set(
        self, key: str, value: bytes, session: Optional[str] = None
    ) -> int:
        if session is not None:
            self._lease_keys[key] = (value, session)
        else:
            # an unleased overwrite detaches any lease this client
            # tracked (mirrors KVStore._attach_session)
            self._lease_keys.pop(key, None)
        return self._call(
            "set", key=key, value=_enc(value), session=session
        )

    def create_only(
        self, key: str, value: bytes, session: Optional[str] = None
    ) -> bool:
        ok = self._call(
            "create_only", key=key, value=_enc(value), session=session
        )
        if ok and session is not None:
            self._lease_keys[key] = (value, session)
        return ok

    def create_if_exists(
        self,
        cond_key: str,
        key: str,
        value: bytes,
        session: Optional[str] = None,
    ) -> bool:
        ok = self._call(
            "create_if_exists",
            cond_key=cond_key,
            key=key,
            value=_enc(value),
            session=session,
        )
        if ok and session is not None:
            self._lease_keys[key] = (value, session)
        return ok

    def delete(self, key: str) -> bool:
        self._lease_keys.pop(key, None)
        return self._call("delete", key=key)

    def delete_prefix(self, prefix: str) -> int:
        for k in list(self._lease_keys):
            if k.startswith(prefix):
                del self._lease_keys[k]
        return self._call("delete_prefix", key=prefix)

    def lock_path(
        self,
        path: str,
        timeout: Optional[float] = DEFAULT_LOCK_TIMEOUT,
    ) -> RemoteLock:
        return RemoteLock(self, path, timeout=timeout)

    def expire_session(self, session: str) -> int:
        return self._call("expire_session", session=session)

    def watch_prefix(
        self, prefix: str, watcher: Watcher
    ) -> Callable[[], None]:
        with self._io_lock:
            self._next_wid += 1
            wid = self._next_wid
        self._watches[wid] = (prefix, watcher)
        self._call("watch", key=prefix, wid=wid)
        # the server pushed the ListAndWatch replay BEFORE the watch
        # response; drain the dispatcher up to here so callers see the
        # in-process contract ("current contents replayed on return")
        marker = threading.Event()
        self._event_q.put(("sync", marker))
        marker.wait(timeout=10.0)

        def unsubscribe() -> None:
            self._watches.pop(wid, None)
            try:
                self._call("unwatch", wid=wid)
            except (ConnectionError, RuntimeError):
                pass  # a dead connection has no watcher to remove

        return unsubscribe

    @property
    def revision(self) -> int:
        return self._call("revision")

    def close(self) -> None:
        self._closed = True
        self._event_q.put(None)
        # shutdown + close BOTH handles: the makefile() reader holds
        # its own reference to the fd, so sock.close() alone never
        # sends FIN and the server would keep the lease session alive
        try:
            if self._sock is not None:
                try:
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self._sock.close()
            if getattr(self, "_rfile", None) is not None:
                self._rfile.close()
        except OSError:
            pass
