"""Cluster-wide ID allocator over the kvstore.

Behavioral port of /root/reference/pkg/kvstore/allocator/allocator.go:
  key layout:
    <prefix>/id/<id>            master key: id → key string (CAS)
    <prefix>/value/<key>/<node> slave key: refcount + lease holder
  protocol (lockedAllocate, allocator.go:423):
    1. GetPrefix(/value/<key>/) — an existing master mapping wins;
       create our slave key and reuse the id.
    2. Else pick a free id from the local pool, lock the key path,
       CAS-create the master key; on CAS failure (another node won)
       retry; then create the slave key.
  release (allocator.go Release): refcounted locally; the last local
  ref deletes the slave key.  Master keys are garbage collected when
  no slave keys remain (RunGC in the reference; `gc()` here).

The same numeric id is therefore agreed upon by every node for the
same label-set key — the consensus that makes identities meaningful
cluster-wide.  Events from watching <prefix>/id/ feed remote caches
(cache.go) and clustermesh.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu.kvstore.paths import CLUSTER_ID_SHIFT
from cilium_tpu.kvstore.store import KVEvent, KVStore


class Allocator:
    def __init__(
        self,
        store: KVStore,
        prefix: str,
        node: str,
        id_min: int = 256,
        id_max: int = (1 << 24) - 1,
        cluster_id: int = 0,
    ) -> None:
        self.store = store
        self.prefix = prefix.rstrip("/")
        self.node = node
        self.id_min = id_min
        self.id_max = id_max
        # ClusterID partitioning (numericidentity.go:162): ids carry
        # the cluster id in bits 16-23.
        self.cluster_id = cluster_id
        self._lock = threading.RLock()
        # local refcounts per key (localKeys, allocator.go)
        self._refs: Dict[str, int] = {}
        self._ids: Dict[str, int] = {}
        self._next_probe = id_min

    # -- paths ---------------------------------------------------------------

    def _id_path(self, num_id: int) -> str:
        return f"{self.prefix}/id/{num_id}"

    def _value_prefix(self, key: str) -> str:
        return f"{self.prefix}/value/{key}/"

    def _slave_path(self, key: str) -> str:
        return f"{self.prefix}/value/{key}/{self.node}"

    def _mask_id(self, num_id: int) -> int:
        return num_id | (self.cluster_id << CLUSTER_ID_SHIFT)

    # -- protocol ------------------------------------------------------------

    def get(self, key: str) -> int:
        """Existing cluster-wide id for key, or 0."""
        got = self.store.get_prefix(self._value_prefix(key))
        return int(got[1]) if got else 0

    def _select_available_id(self) -> int:
        for _ in range(self.id_max - self.id_min + 1):
            candidate = self._mask_id(self._next_probe)
            self._next_probe += 1
            if self._next_probe > self.id_max:
                self._next_probe = self.id_min
            if self.store.get(self._id_path(candidate)) is None:
                return candidate
        return 0

    def allocate(self, key: str) -> int:
        """Idempotent, refcounted, cluster-consistent (allocator.go:534
        Allocate → lockedAllocate)."""
        with self._lock:
            if key in self._ids:
                self._refs[key] += 1
                return self._ids[key]

        for _ in range(16):  # kvstore CAS retry budget
            existing = self.get(key)
            if existing:
                self.store.set(
                    self._slave_path(key),
                    str(existing).encode(),
                    session=self.node,
                )
                with self._lock:
                    self._ids[key] = existing
                    self._refs[key] = self._refs.get(key, 0) + 1
                return existing

            with self._lock:
                candidate = self._select_available_id()
            if candidate == 0:
                raise RuntimeError("no more available IDs")

            path_lock = self.store.lock_path(key)
            with path_lock:
                # Re-check under the key lock: another writer may have
                # won the race since the unlocked Get above
                # (lockedAllocate re-runs Get inside the lock,
                # allocator.go:427-452) — without this, two nodes can
                # mint DIFFERENT master ids for the same key.
                existing = self.get(key)
                if existing:
                    continue  # outer loop reuses it via the fast path
                if not self.store.create_only(
                    self._id_path(candidate), key.encode()
                ):
                    continue  # another writer took the id: retry
                self.store.set(
                    self._slave_path(key),
                    str(candidate).encode(),
                    session=self.node,
                )
            with self._lock:
                self._ids[key] = candidate
                self._refs[key] = self._refs.get(key, 0) + 1
            return candidate
        raise RuntimeError(f"allocation of key {key!r} keeps failing")

    def release(self, key: str) -> bool:
        """True when this node's last reference is gone."""
        with self._lock:
            if key not in self._refs:
                return False
            self._refs[key] -= 1
            if self._refs[key] > 0:
                return False
            del self._refs[key]
            del self._ids[key]
        self.store.delete(self._slave_path(key))
        return True

    def gc(self) -> int:
        """Master keys with no remaining slave keys are collected
        (allocator RunGC)."""
        removed = 0
        for path, value in self.store.list_prefix(f"{self.prefix}/id/").items():
            key = value.decode()
            if not self.store.list_prefix(self._value_prefix(key)):
                if self.store.delete(path):
                    removed += 1
        return removed

    # -- events (cache.go) ---------------------------------------------------

    def watch(
        self, handler: Callable[[str, int, str], None]
    ) -> Callable[[], None]:
        """Watch master keys: handler(kind, id, key)."""

        def on_event(event: KVEvent) -> None:
            num_id = int(event.key.rsplit("/", 1)[1])
            handler(event.kind, num_id, event.value.decode())

        return self.store.watch_prefix(f"{self.prefix}/id/", on_event)


class IdentityBackendAdapter:
    """Adapter wiring this allocator as the `backend` of
    cilium_tpu.identity.IdentityAllocator (sorted-label-bytes key)."""

    def __init__(self, allocator: Allocator) -> None:
        self.allocator = allocator

    def allocate(self, key: bytes) -> int:
        return self.allocator.allocate(key.decode("utf-8", "replace"))

    def release(self, key: bytes) -> None:
        self.allocator.release(key.decode("utf-8", "replace"))
