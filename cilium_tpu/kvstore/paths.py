"""kvstore key layout — single source of truth.

pkg/kvstore BaseKeyPrefix + the per-subsystem prefixes
(pkg/identity/allocator.go:57, pkg/ipcache/kvstore.go:43,
pkg/node store paths).  A layout bump here reaches every writer and
watcher at once.
"""

BASE_KEY_PREFIX = "cilium"
IDENTITIES_PATH = f"{BASE_KEY_PREFIX}/state/identities/v1"
IP_IDENTITIES_PATH = f"{BASE_KEY_PREFIX}/state/ip/v1"
NODES_PATH = f"{BASE_KEY_PREFIX}/state/nodes/v1"

# NumericIdentity.ClusterID partitioning (numericidentity.go:162).
CLUSTER_ID_SHIFT = 16
CLUSTER_ID_MAX = 255
