"""Node registry + discovery over the kvstore.

Behavioral port of /root/reference/pkg/node (+ pkg/kvstore/store's
shared-store sync): each agent publishes its own Node object under
`cilium/state/nodes/v1/<cluster>/<name>` with a lease (dead nodes
disappear on expiry); every agent watches the prefix to learn the
cluster topology — node IPs, per-node pod CIDRs (feeding tunnel/route
decisions) and health targets.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cilium_tpu.kvstore.paths import NODES_PATH
from cilium_tpu.kvstore.store import KVEvent, KVStore


@dataclass
class Node:
    """pkg/node/node.go Node: identity + addressing."""

    name: str
    cluster: str = "default"
    internal_ip: Optional[str] = None
    ipv4_alloc_cidr: Optional[str] = None  # per-node pod CIDR
    ipv6_alloc_cidr: Optional[str] = None

    def path(self) -> str:
        return f"{NODES_PATH}/{self.cluster}/{self.name}"

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "Name": self.name,
                "Cluster": self.cluster,
                "IP": self.internal_ip,
                "IPv4AllocCIDR": self.ipv4_alloc_cidr,
                "IPv6AllocCIDR": self.ipv6_alloc_cidr,
            }
        ).encode()

    @staticmethod
    def from_json(data: bytes) -> "Node":
        doc = json.loads(data.decode())
        return Node(
            name=doc["Name"],
            cluster=doc.get("Cluster", "default"),
            internal_ip=doc.get("IP"),
            ipv4_alloc_cidr=doc.get("IPv4AllocCIDR"),
            ipv6_alloc_cidr=doc.get("IPv6AllocCIDR"),
        )


def register_node(store: KVStore, node: Node) -> None:
    """Publish under the node's own lease (store.go key ownership)."""
    store.set(node.path(), node.to_json(), session=node.name)


def unregister_node(store: KVStore, node: Node) -> None:
    store.delete(node.path())


class NodeWatcher:
    """Discovery: maintains the cluster's node set from the kvstore,
    invoking on_change(kind, node) per event."""

    def __init__(
        self,
        store: KVStore,
        cluster: str = "default",
        on_change: Optional[Callable[[str, Node], None]] = None,
    ) -> None:
        self.nodes: Dict[str, Node] = {}
        self._on_change = on_change
        self._unsubscribe = store.watch_prefix(
            f"{NODES_PATH}/{cluster}/", self._on_event
        )

    def _on_event(self, event: KVEvent) -> None:
        name = event.key.rsplit("/", 1)[1]
        if event.kind == "delete":
            node = self.nodes.pop(name, None)
            if node is not None and self._on_change:
                self._on_change("delete", node)
            return
        try:
            node = Node.from_json(event.value)
        except (ValueError, KeyError):
            return
        self.nodes[name] = node
        if self._on_change:
            self._on_change(event.kind, node)

    def close(self) -> None:
        self._unsubscribe()
