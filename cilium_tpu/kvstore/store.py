"""In-process watchable kvstore with etcd-like semantics.

Implements the BackendOperations surface of
/root/reference/pkg/kvstore/backend.go:92 — Get/GetPrefix/Set/Delete/
CreateOnly/CreateIfExists/ListPrefix/DeletePrefix/LockPath/Watch —
plus lease semantics: keys created with a `session` are removed en
masse when that session expires (etcd lease expiry ≙ dead node state
cleanup, pkg/kvstore/keepalive.go).

Watchers follow the reference's ListAndWatch contract (etcd.go):
subscribing replays the current prefix contents as `create` events
then streams subsequent modifications in order.  Every mutation gets a
monotonically increasing mod-revision.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class KVEvent:
    """kvstore.KeyValueEvent: create | modify | delete."""

    kind: str
    key: str
    value: bytes
    revision: int


Watcher = Callable[[KVEvent], None]


class KVStore:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: Dict[str, bytes] = {}
        self._sessions: Dict[str, set] = {}  # session → keys
        self._key_session: Dict[str, str] = {}
        self._revision = 0
        self._watchers: List[Tuple[str, Watcher]] = []
        self._locks: Dict[str, threading.RLock] = {}

    # -- primitives ----------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def get_prefix(self, prefix: str) -> Optional[Tuple[str, bytes]]:
        """First key matching the prefix (backend.go GetPrefix)."""
        with self._lock:
            for k in sorted(self._data):
                if k.startswith(prefix):
                    return k, self._data[k]
            return None

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        with self._lock:
            return {
                k: v for k, v in self._data.items() if k.startswith(prefix)
            }

    def set(self, key: str, value: bytes, session: Optional[str] = None) -> int:
        with self._lock:
            kind = "modify" if key in self._data else "create"
            self._data[key] = value
            self._attach_session(key, session)
            return self._emit(kind, key, value)

    def create_only(
        self, key: str, value: bytes, session: Optional[str] = None
    ) -> bool:
        """CAS create: False when the key already exists."""
        with self._lock:
            if key in self._data:
                return False
            self._data[key] = value
            self._attach_session(key, session)
            self._emit("create", key, value)
            return True

    def create_if_exists(
        self, cond_key: str, key: str, value: bytes,
        session: Optional[str] = None,
    ) -> bool:
        with self._lock:
            if cond_key not in self._data:
                return False
            self._data[key] = value
            self._attach_session(key, session)
            self._emit("create", key, value)
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            value = self._data.pop(key)
            self._detach_session(key)
            self._emit("delete", key, value)
            return True

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                self.delete(k)
            return len(keys)

    # -- locks (backend.go LockPath) ----------------------------------------

    def lock_path(self, path: str) -> threading.RLock:
        with self._lock:
            return self._locks.setdefault(path, threading.RLock())

    # -- sessions / leases ---------------------------------------------------

    def _attach_session(self, key: str, session: Optional[str]) -> None:
        old = self._key_session.pop(key, None)
        if old is not None:
            self._sessions.get(old, set()).discard(key)
        if session is not None:
            self._sessions.setdefault(session, set()).add(key)
            self._key_session[key] = session

    def _detach_session(self, key: str) -> None:
        old = self._key_session.pop(key, None)
        if old is not None:
            self._sessions.get(old, set()).discard(key)

    def expire_session(self, session: str) -> int:
        """Lease expiry: all keys of the session vanish (with delete
        events) — how a dead node's state leaves the cluster."""
        with self._lock:
            keys = sorted(self._sessions.pop(session, set()))
            for key in keys:
                self._key_session.pop(key, None)
                if key in self._data:
                    value = self._data.pop(key)
                    self._emit("delete", key, value)
            return len(keys)

    # -- watch (ListAndWatch) ------------------------------------------------

    def watch_prefix(self, prefix: str, watcher: Watcher) -> Callable[[], None]:
        """Replay current contents as `create` events, then stream.
        Returns an unsubscribe function."""
        with self._lock:
            for k in sorted(self._data):
                if k.startswith(prefix):
                    watcher(
                        KVEvent("create", k, self._data[k], self._revision)
                    )
            entry = (prefix, watcher)
            self._watchers.append(entry)

        def unsubscribe() -> None:
            with self._lock:
                if entry in self._watchers:
                    self._watchers.remove(entry)

        return unsubscribe

    def _emit(self, kind: str, key: str, value: bytes) -> int:
        self._revision += 1
        event = KVEvent(kind, key, value, self._revision)
        for prefix, watcher in list(self._watchers):
            if key.startswith(prefix):
                watcher(event)
        return self._revision

    @property
    def revision(self) -> int:
        with self._lock:
            return self._revision

    def durable_items(self) -> Dict[str, bytes]:
        """Non-lease-scoped contents, captured atomically — what a
        snapshot may persist (lease keys die with their session and
        must not resurrect across a restart)."""
        with self._lock:
            return {
                k: v
                for k, v in self._data.items()
                if k not in self._key_session
            }


def wire_encode(value: Optional[bytes]) -> Optional[str]:
    """Shared wire codec for the socket transport (server + client)."""
    import base64

    if value is None:
        return None
    return base64.b64encode(value).decode()


def wire_decode(value: Optional[str]) -> Optional[bytes]:
    import base64

    if value is None:
        return None
    return base64.b64decode(value)
