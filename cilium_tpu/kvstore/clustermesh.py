"""ClusterMesh: multi-cluster state fan-in.

Behavioral port of /root/reference/pkg/clustermesh: each remote
cluster is a kvstore endpoint (config per remote, clustermesh.go /
remote_cluster.go); the agent watches the remote cluster's identities
(identity.WatchRemoteIdentities, pkg/identity/allocator.go:191) and
ipcache prefix, merging them into the local caches.  ClusterID
partitions the identity space (NumericIdentity.ClusterID,
numericidentity.go:162: bits 16-23) so ids never collide across
clusters.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from cilium_tpu.ipcache.ipcache import IPCache
from cilium_tpu.kvstore.ipsync import IPIdentityWatcher
from cilium_tpu.kvstore.paths import (
    CLUSTER_ID_MAX,
    CLUSTER_ID_SHIFT,
    IDENTITIES_PATH,
)
from cilium_tpu.kvstore.store import KVStore

def cluster_id_of(num_id: int) -> int:
    """numericidentity.go:162."""
    return (num_id >> CLUSTER_ID_SHIFT) & CLUSTER_ID_MAX


class RemoteCluster:
    """pkg/clustermesh/remote_cluster.go: one connected remote."""

    def __init__(
        self,
        name: str,
        store: KVStore,
        local_ipcache: IPCache,
        identities_path: str = IDENTITIES_PATH,
        on_identity: Optional[Callable[[str, int, str], None]] = None,
    ) -> None:
        self.name = name
        self.store = store
        # remote identities → local identity event stream
        self._remote_ids: Dict[int, str] = {}
        self._on_identity = on_identity

        def handler(event) -> None:
            num_id = int(event.key.rsplit("/", 1)[1])
            key = event.value.decode()
            if event.kind == "delete":
                self._remote_ids.pop(num_id, None)
            else:
                self._remote_ids[num_id] = key
            if self._on_identity is not None:
                self._on_identity(event.kind, num_id, key)

        self._unsub_ids = store.watch_prefix(
            f"{identities_path}/id/", handler
        )
        # remote ipcache → local IPCache (source=kvstore)
        self._ip_watcher = IPIdentityWatcher(store, local_ipcache)

    def remote_identities(self) -> Dict[int, str]:
        return dict(self._remote_ids)

    def close(self) -> None:
        self._unsub_ids()
        self._ip_watcher.close()


class ClusterMesh:
    """pkg/clustermesh/clustermesh.go: the set of connected remotes,
    keyed by cluster name (config-dir watching replaced by explicit
    add/remove — the config watcher belongs to the daemon shell)."""

    def __init__(self, local_ipcache: IPCache) -> None:
        self.local_ipcache = local_ipcache
        self.clusters: Dict[str, RemoteCluster] = {}

    def add_cluster(
        self,
        name: str,
        store: KVStore,
        on_identity: Optional[Callable[[str, int, str], None]] = None,
    ) -> RemoteCluster:
        if name in self.clusters:
            self.remove_cluster(name)
        remote = RemoteCluster(
            name, store, self.local_ipcache, on_identity=on_identity
        )
        self.clusters[name] = remote
        return remote

    def remove_cluster(self, name: str) -> None:
        remote = self.clusters.pop(name, None)
        if remote is not None:
            remote.close()

    def num_connected(self) -> int:
        return len(self.clusters)
