"""Orchestrator plugins: the CNI shim (plugins/cilium-cni analog)."""
