"""Docker libnetwork remote driver shim.

Behavioral analog of /root/reference/plugins/cilium-docker: a unix-
socket HTTP server speaking the libnetwork remote-driver protocol
(driver.go:173-181 route set — Plugin.Activate handshake,
NetworkDriver.* lifecycle — plus the IpamDriver.* surface of ipam.go),
delegating to a RUNNING agent over its REST API the way the reference
driver calls the agent through pkg/client.  The veth/route plumbing of
the reference's Join belongs to the host networking layer; the shim
answers the protocol with the interface naming contract and keeps the
CONTROL-PLANE state (endpoint registration, IPAM) authoritative in
the agent.

libnetwork contract notes:
  * every call is POST with a JSON body; errors are {"Err": "..."};
  * CreateEndpoint receives Interface.Address when docker's IPAM (us,
    via IpamDriver) already assigned one — the driver must then NOT
    return an address (EndpointInterface conflict, driver.go
    createEndpoint);
  * DeleteEndpoint/Leave must be idempotent.
"""

from __future__ import annotations

import json
import os
import socketserver
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional

from cilium_tpu.logging import get_logger
from cilium_tpu.plugins.cni import ALLOCATE_EP_ID

log = get_logger("docker-plugin")

CONTAINER_IF_PREFIX = "cilium"


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _reply(self, body: dict) -> None:
        data = json.dumps(body).encode()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client hung up / server stopping mid-reply

    def do_POST(self) -> None:  # noqa: N802
        plugin: "DockerPlugin" = self.server.plugin  # type: ignore
        n = int(self.headers.get("Content-Length") or 0)
        try:
            body = json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError:
            body = {}
        try:
            handler = plugin.routes.get(self.path)
            if handler is None:
                return self._reply(
                    {"Err": f"unknown method {self.path}"}
                )
            return self._reply(handler(body))
        except Exception as exc:
            return self._reply({"Err": str(exc)})


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class DockerPlugin:
    """Serve the libnetwork remote-driver protocol on a unix socket,
    delegating to the agent REST API (`client` = api.client.APIClient
    or compatible)."""

    def __init__(self, client, socket_path: str) -> None:
        self.client = client
        self.socket_path = socket_path
        # libnetwork endpoint id → (agent endpoint id, allocated ip)
        self._endpoints: Dict[str, tuple] = {}
        self._server: Optional[_UnixHTTPServer] = None
        self.routes = {
            "/Plugin.Activate": self._activate,
            "/NetworkDriver.GetCapabilities": self._capabilities,
            "/NetworkDriver.CreateNetwork": self._ok,
            "/NetworkDriver.DeleteNetwork": self._ok,
            "/NetworkDriver.CreateEndpoint": self._create_endpoint,
            "/NetworkDriver.DeleteEndpoint": self._delete_endpoint,
            "/NetworkDriver.EndpointOperInfo": self._oper_info,
            "/NetworkDriver.Join": self._join,
            "/NetworkDriver.Leave": self._ok,
            "/IpamDriver.GetCapabilities": self._ok,
            "/IpamDriver.GetDefaultAddressSpaces": self._address_spaces,
            "/IpamDriver.RequestPool": self._request_pool,
            "/IpamDriver.ReleasePool": self._ok,
            "/IpamDriver.RequestAddress": self._request_address,
            "/IpamDriver.ReleaseAddress": self._release_address,
        }

    # -- handshake ---------------------------------------------------------

    def _activate(self, body: dict) -> dict:
        return {"Implements": ["NetworkDriver", "IpamDriver"]}

    def _capabilities(self, body: dict) -> dict:
        return {"Scope": "local"}

    def _ok(self, body: dict) -> dict:
        return {}

    # -- NetworkDriver -----------------------------------------------------

    def _create_endpoint(self, body: dict) -> dict:
        eid = body.get("EndpointID", "")
        if not eid:
            return {"Err": "EndpointID missing"}
        iface = body.get("Interface") or {}
        given = (iface.get("Address") or "").split("/")[0] or None
        # the agent allocates the endpoint id (see plugins/cni.py)
        created = self.client.endpoint_create(
            ALLOCATE_EP_ID,
            {
                "labels": [
                    {
                        "key": "container",
                        "value": eid[:12],
                        "source": "container",
                    }
                ],
                "name": eid[:12],
                # an Interface.Address came from docker, which got it
                # from OUR IpamDriver — it is already reserved in the
                # agent pool
                **(
                    {"ipv4": given, "ip_reserved": True}
                    if given
                    else {}
                ),
            },
        )
        self._endpoints[eid] = (created.get("id"), created.get("ipv4"))
        if given:
            # docker already assigned the address through our
            # IpamDriver — returning one again is a protocol error
            return {"Interface": {}}
        return {
            "Interface": {"Address": f"{created.get('ipv4')}/32"}
        }

    def _delete_endpoint(self, body: dict) -> dict:
        eid = body.get("EndpointID", "")
        entry = self._endpoints.pop(eid, None)
        ep_id = entry[0] if entry else ALLOCATE_EP_ID
        try:
            # id 0 + name resolves by the endpoint name (restart case)
            self.client.endpoint_delete(ep_id, name=eid[:12])
        except Exception:
            pass  # idempotent per the protocol
        return {}

    def _oper_info(self, body: dict) -> dict:
        eid = body.get("EndpointID", "")
        entry = self._endpoints.get(eid)
        return {
            "Value": {"ip": entry[1] if entry else None}
        }

    def _join(self, body: dict) -> dict:
        eid = body.get("EndpointID", "")
        return {
            "InterfaceName": {
                "SrcName": f"{CONTAINER_IF_PREFIX}{eid[:5]}",
                "DstPrefix": CONTAINER_IF_PREFIX,
            },
            # gateway handling mirrors the reference: traffic routes
            # through the host; no per-endpoint gateway address
            "Gateway": "",
        }

    # -- IpamDriver --------------------------------------------------------

    def _address_spaces(self, body: dict) -> dict:
        return {
            "LocalDefaultAddressSpace": "CiliumLocal",
            "GlobalDefaultAddressSpace": "CiliumGlobal",
        }

    def _request_pool(self, body: dict) -> dict:
        # the agent owns the pool; docker gets an opaque pool id and
        # the agent's CIDR via the config surface
        cidr = self.client.config_get().get(
            "ipam_cidr", "10.200.0.0/16"
        )
        return {"PoolID": "cilium-tpu-pool", "Pool": cidr}

    def _request_address(self, body: dict) -> dict:
        preferred = (body.get("Address") or "") or None
        got = self.client.ipam_allocate(preferred)
        return {"Address": f"{got['ip']}/32"}

    def _release_address(self, body: dict) -> dict:
        addr = (body.get("Address") or "").split("/")[0]
        if addr:
            self.client.ipam_release(addr)
        return {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DockerPlugin":
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._server = _UnixHTTPServer(self.socket_path, _Handler)
        self._server.plugin = self  # type: ignore
        import threading

        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)
