"""CNI plugin shim: CNI ADD/DEL → the agent's REST API.

Behavioral analog of /root/reference/plugins/cilium-cni: the runtime
invokes the plugin with the CNI contract (CNI_COMMAND/CNI_CONTAINERID
env + network config JSON on stdin); the reference plugin creates the
veth pair and PUTs /endpoint to the agent.  This framework has no
kernel datapath to plumb a veth into, so the shim performs the
CONTROL-PLANE half — register/deregister the workload as an endpoint
over the unix-socket REST API (IP from the agent's IPAM) — and
returns a spec-shaped CNI result; interface plumbing belongs to the
host networking layer that embeds the framework.

Endpoint numbering: the AGENT allocates the endpoint id (PUT
/endpoint/0); DEL resolves the endpoint by its container-derived
name, so ADD and DEL agree without plugin-side state and without
hash collisions.

Usage (CNI conformance): `python -m cilium_tpu.plugins.cni` with the
standard env + stdin; VERSION/ADD/DEL supported, errors returned as
CNI error JSON on stdout with a non-zero exit.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

CNI_VERSIONS = ["0.3.0", "0.3.1", "0.4.0"]
DEFAULT_SOCKET = "/var/run/cilium_tpu.sock"

# The shim does NOT derive endpoint ids from container ids: a
# hash-derived id collides at birthday rates (~7% at 100 concurrent
# workloads) and a collision is a permanent ADD failure.  Instead the
# AGENT allocates the id (PUT /endpoint/0, like the reference's
# endpointmanager); ADD reads the allocated id from the reply and DEL
# resolves by the container-derived endpoint name.
ALLOCATE_EP_ID = 0


def _labels_from_args(cni_args: str) -> list:
    """CNI_ARGS K8S_POD_NAMESPACE/K8S_POD_NAME → k8s labels (the
    reference resolves pod labels via the apiserver; the shim carries
    the identifying pair so the k8s watcher can refine later)."""
    kv: Dict[str, str] = {}
    for part in (cni_args or "").split(";"):
        if "=" in part:
            k, _, v = part.partition("=")
            kv[k] = v
    labels = []
    if kv.get("K8S_POD_NAMESPACE"):
        labels.append(
            {
                "key": "io.kubernetes.pod.namespace",
                "value": kv["K8S_POD_NAMESPACE"],
                "source": "k8s",
            }
        )
    if kv.get("K8S_POD_NAME"):
        labels.append(
            {
                "key": "io.kubernetes.pod.name",
                "value": kv["K8S_POD_NAME"],
                "source": "k8s",
            }
        )
    if not labels:
        labels.append(
            {"key": "unmanaged", "value": "", "source": "container"}
        )
    return labels


def run(
    env: Optional[Dict[str, str]] = None,
    stdin: Optional[str] = None,
    client=None,
) -> tuple:
    """Execute one CNI invocation; returns (exit_code, result_dict).
    `client` injects an APIClient (tests); default connects to the
    socket named in the network config ("socket_path") or
    DEFAULT_SOCKET."""
    env = dict(os.environ if env is None else env)
    command = env.get("CNI_COMMAND", "")
    if command == "VERSION":
        return 0, {
            "cniVersion": CNI_VERSIONS[-1],
            "supportedVersions": CNI_VERSIONS,
        }

    try:
        conf = json.loads(stdin or "{}")
    except json.JSONDecodeError as exc:
        return 1, _error(2, f"bad network config: {exc}")
    container_id = env.get("CNI_CONTAINERID", "")
    if not container_id:
        return 1, _error(2, "CNI_CONTAINERID missing")
    if client is None:
        from cilium_tpu.api.client import APIClient

        client = APIClient(
            conf.get("socket_path", DEFAULT_SOCKET)
        )
    if command == "ADD":
        try:
            created = client.endpoint_create(
                ALLOCATE_EP_ID,
                {
                    "labels": _labels_from_args(
                        env.get("CNI_ARGS", "")
                    ),
                    "name": container_id[:12],
                },
            )
        except Exception as exc:
            status = getattr(exc, "status", None)
            if status == 409:
                return 1, _error(7, f"endpoint conflict: {exc}")
            if status is not None:
                return 1, _error(11, f"agent error {status}: {exc}")
            return 1, _error(11, f"agent unreachable: {exc}")
        ipv4 = created.get("ipv4")
        return 0, {
            "cniVersion": conf.get("cniVersion", CNI_VERSIONS[-1]),
            "interfaces": [
                {"name": env.get("CNI_IFNAME", "eth0")}
            ],
            "ips": (
                [
                    {
                        "version": "4",
                        "address": f"{ipv4}/32",
                        "interface": 0,
                    }
                ]
                if ipv4
                else []
            ),
        }

    if command == "DEL":
        # CNI DEL must be idempotent and succeed for unknown
        # containers (the runtime retries DELs).  id 0 + name =
        # delete-by-name: the shim never learns the allocated id, and
        # the name guard keeps a DEL from tearing down another
        # workload's endpoint.
        try:
            client.endpoint_delete(
                ALLOCATE_EP_ID, name=container_id[:12]
            )
        except Exception:
            pass
        return 0, {}

    return 1, _error(4, f"unsupported CNI_COMMAND {command!r}")


def _error(code: int, msg: str) -> dict:
    return {
        "cniVersion": CNI_VERSIONS[-1],
        "code": code,
        "msg": msg,
    }


def main() -> int:
    rc, result = run(stdin=sys.stdin.read())
    print(json.dumps(result))
    return rc


if __name__ == "__main__":
    sys.exit(main())
