"""CIDR arithmetic helpers.

Re-implements the semantics of /root/reference/pkg/ip/ip.go
(RemoveCIDRs) and the Go-stdlib-specific parsing quirks that the policy
layer depends on (classful default masks in CIDRPolicyMap.Insert,
pkg/policy/l3.go:66-103).
"""

from __future__ import annotations

import ipaddress
from typing import List, Optional, Tuple, Union

IPNetwork = Union[ipaddress.IPv4Network, ipaddress.IPv6Network]


def parse_cidr(s: str) -> IPNetwork:
    """Like Go net.ParseCIDR: returns the *masked* network."""
    return ipaddress.ip_network(s, strict=False)


def go_default_mask_v4(ip: ipaddress.IPv4Address) -> Optional[int]:
    """Go net.IP.DefaultMask: classful A/8, B/16, C/24; else None."""
    first = int(ip) >> 24
    if first < 0x80:
        return 8
    if first < 0xC0:
        return 16
    if first < 0xE0:
        return 24
    return None


def parse_cidr_or_ip_classful(s: str) -> IPNetwork:
    """The exact parse performed by CIDRPolicyMap.Insert (l3.go:66-85).

    Try CIDR parse; else parse as bare IP.  Bare IPv6 gets /128.  Bare
    IPv4 gets its *classful default mask* if the host bits under that
    mask are zero, else /32.  This Go-stdlib behavior is load-bearing
    for key construction in the CIDR policy map.
    """
    # Go net.ParseCIDR only accepts "ip/len" strings; Python's
    # ip_network also accepts bare IPs (as /32), which would shadow the
    # classful path — so branch on the slash explicitly.
    if "/" in s:
        return ipaddress.ip_network(s, strict=False)
    ip = ipaddress.ip_address(s)
    if ip.version == 6:
        return ipaddress.ip_network((ip, 128))
    plen = go_default_mask_v4(ip)
    if plen is not None:
        masked = int(ip) & (((1 << plen) - 1) << (32 - plen))
        if masked == int(ip):
            return ipaddress.ip_network((ip, plen))
    return ipaddress.ip_network((ip, 32))


def remove_cidrs(allow: List[IPNetwork],
                 remove: List[IPNetwork]) -> List[IPNetwork]:
    """pkg/ip RemoveCIDRs: subtract 'remove' nets from 'allow' nets,
    splitting the allowed prefixes minimally.

    Result ordering: for each allowed CIDR (input order), the surviving
    fragments sorted ascending — a deterministic canonical order (the
    reference's ordering is an implementation detail of its splitting
    recursion; only set-equality is observable in verdicts).
    """
    out: List[IPNetwork] = []
    for a in allow:
        fragments = [a]
        for r in remove:
            if r.version != a.version:
                continue
            next_fragments: List[IPNetwork] = []
            for f in fragments:
                if r.overlaps(f):
                    if r.prefixlen <= f.prefixlen:
                        # fully removed
                        continue
                    next_fragments.extend(f.address_exclude(r))
                else:
                    next_fragments.append(f)
            fragments = next_fragments
        out.extend(sorted(fragments))
    return out


def ip_to_u32(ip: str) -> int:
    return int(ipaddress.IPv4Address(ip))


def ip6_to_ints(ip: str) -> Tuple[int, int]:
    v = int(ipaddress.IPv6Address(ip))
    return (v >> 64) & ((1 << 64) - 1), v & ((1 << 64) - 1)
