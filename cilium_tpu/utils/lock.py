"""Lock wrappers with opt-in deadlock detection.

Behavioral analog of /root/reference/pkg/lock: thin wrappers over the
platform mutexes that the whole agent uses, with a debug build-tag
variant (pkg/lock/lock_debug.go) that detects deadlocks.  Here the
debug variant is runtime-switchable (`enable_lock_debug()`), and
detects the two bug classes the reference's deadlock-detecting
mutexes catch:

  * **lock-order inversion**: acquiring B while holding A records the
    edge A→B in a global order graph; a later acquisition that would
    close a cycle (any path B⤳A already recorded) raises
    `LockOrderViolation` at acquire time — the deadlock is reported
    deterministically on the FIRST inverted acquisition, not only on
    the unlucky interleaving that actually wedges;
  * **long-held locks**: a lock held longer than `hold_warning_s`
    logs the holder's acquisition stack (go-deadlock's
    DeadlockTimeout analog), through the `lock` subsys logger.

`Mutex` and `RWLock` (sync.Mutex / sync.RWMutex) are context
managers; RWLock exposes `.read()` / `.write()` scopes.  With debug
off they add one attribute read over the raw primitives.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

from cilium_tpu.logging import get_logger

log = get_logger("lock")

_DEBUG = False
_HOLD_WARNING_S = 10.0

# global lock-order graph: edge a → b means "b acquired while a held"
_order_lock = threading.Lock()
_order_edges: Dict[int, Set[int]] = {}
_names: Dict[int, str] = {}

_tls = threading.local()


class LockOrderViolation(RuntimeError):
    """Acquire would close a cycle in the global lock-order graph."""


def enable_lock_debug(hold_warning_s: float = 10.0) -> None:
    """Turn on detection (the reference's `lockdebug` build tag)."""
    global _DEBUG, _HOLD_WARNING_S
    _DEBUG = True
    _HOLD_WARNING_S = hold_warning_s


def disable_lock_debug() -> None:
    global _DEBUG
    _DEBUG = False
    with _order_lock:
        _order_edges.clear()
        _names.clear()


def _held_stack() -> List[Tuple[int, float]]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _reaches(src: int, dst: int) -> bool:
    """Path src ⤳ dst in the order graph (held under _order_lock)."""
    seen = set()
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        frontier.extend(_order_edges.get(node, ()))
    return False


def _debug_acquired(lock_id: int, name: str) -> None:
    held = _held_stack()
    with _order_lock:
        _names[lock_id] = name
        for prior_id, *_ in held:
            if prior_id == lock_id:
                continue
            # would edge prior→lock_id close a cycle?
            if _reaches(lock_id, prior_id):
                prior = _names.get(prior_id, hex(prior_id))
                raise LockOrderViolation(
                    f"lock-order inversion: acquiring {name!r} while "
                    f"holding {prior!r}, but {name!r} ⤳ {prior!r} "
                    "was recorded on another path"
                )
            _order_edges.setdefault(prior_id, set()).add(lock_id)
    held.append(
        (
            lock_id,
            time.monotonic(),
            # the holder's stack, captured AT ACQUIRE — the long-hold
            # warning must point at where the lock was taken, not the
            # release-site frame
            "".join(traceback.format_stack(limit=8)[:-2]),
        )
    )


def _debug_released(lock_id: int) -> None:
    """ALWAYS runs on release (not only when debug is on): a lock
    acquired while debug was enabled must leave the per-thread held
    stack even if debug was toggled off in between — a stale entry
    would fabricate order edges and spurious violations after a
    re-enable."""
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == lock_id:
            _, t0, acquire_stack = held.pop(i)
            dur = time.monotonic() - t0
            if _DEBUG and dur > _HOLD_WARNING_S:
                log.warning(
                    "lock held past the warning threshold",
                    extra={"fields": {
                        "lock": _names.get(lock_id, hex(lock_id)),
                        "heldSeconds": round(dur, 3),
                        "stack": acquire_stack,
                    }},
                )
            return


class Mutex:
    """sync.Mutex analog (context manager)."""

    def __init__(self, name: Optional[str] = None) -> None:
        self._lock = threading.Lock()
        self.name = name or f"mutex@{id(self):x}"

    def acquire(self) -> None:
        self._lock.acquire()
        if _DEBUG:
            try:
                _debug_acquired(id(self), self.name)
            except LockOrderViolation:
                self._lock.release()
                raise

    def release(self) -> None:
        _debug_released(id(self))
        self._lock.release()

    def __enter__(self) -> "Mutex":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RWLock:
    """sync.RWMutex analog: many readers or one writer.

    Writer-preferring: a waiting writer blocks NEW readers, so a
    steady reader stream cannot starve regeneration (the reference
    relies on Go's sync.RWMutex doing the same)."""

    def __init__(self, name: Optional[str] = None) -> None:
        self.name = name or f"rwlock@{id(self):x}"
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    # -- write side --------------------------------------------------------

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        if _DEBUG:
            try:
                _debug_acquired(id(self), self.name)
            except LockOrderViolation:
                with self._cond:
                    self._writer = False
                    self._cond.notify_all()
                raise

    def release_write(self) -> None:
        _debug_released(id(self))
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    # -- read side ---------------------------------------------------------

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        if _DEBUG:
            try:
                _debug_acquired(id(self), self.name)
            except LockOrderViolation:
                with self._cond:
                    self._readers -= 1
                    self._cond.notify_all()
                raise

    def release_read(self) -> None:
        _debug_released(id(self))
        with self._cond:
            self._readers -= 1
            if not self._readers:
                self._cond.notify_all()

    class _Scope:
        def __init__(self, enter, leave) -> None:
            self._enter, self._leave = enter, leave

        def __enter__(self):
            self._enter()
            return self

        def __exit__(self, *exc):
            self._leave()

    def read(self) -> "_Scope":
        return self._Scope(self.acquire_read, self.release_read)

    def write(self) -> "_Scope":
        return self._Scope(self.acquire_write, self.release_write)
