"""Debounced trigger with MinInterval batching.

Port of /root/reference/pkg/trigger/trigger.go:151: N rapid
TriggerWithReason calls collapse into one invocation no more often
than min_interval, with the collected reasons passed through —
how policy updates batch endpoint regenerations.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class Trigger:
    def __init__(
        self,
        trigger_func: Callable[[List[str]], None],
        min_interval: float = 0.0,
        name: str = "",
    ) -> None:
        self.trigger_func = trigger_func
        self.min_interval = min_interval
        self.name = name
        self._lock = threading.Lock()
        self._reasons: List[str] = []
        self._pending = False
        self._last_run = 0.0
        self._timer: Optional[threading.Timer] = None
        self._closed = False

    def trigger_with_reason(self, reason: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._reasons.append(reason)
            if self._pending:
                return
            wait = max(
                0.0, self.min_interval - (time.time() - self._last_run)
            )
            self._pending = True
            self._timer = threading.Timer(wait, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def trigger(self) -> None:
        self.trigger_with_reason("")

    def _fire(self) -> None:
        with self._lock:
            reasons = [r for r in self._reasons if r]
            self._reasons = []
            self._pending = False
            self._last_run = time.time()
        self.trigger_func(reasons)

    def close(self, wait: bool = True) -> None:
        with self._lock:
            self._closed = True
            timer = self._timer
        if timer is not None:
            if wait:
                # let a scheduled run finish deterministically
                timer.join(timeout=5)
            else:
                timer.cancel()
