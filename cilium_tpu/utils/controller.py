"""Named periodic reconciliation loops with backoff.

Port of /root/reference/pkg/controller/controller.go:127,175: every
resilient background task is a named controller with RunInterval,
exponential error backoff, success/failure bookkeeping surfaced by
`cilium status` — the framework's failure-detection backbone.
"""

from __future__ import annotations

import threading
import time

from cilium_tpu.logging import get_logger

log = get_logger("controller")
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class ControllerStatus:
    success_count: int = 0
    failure_count: int = 0
    consecutive_failures: int = 0
    last_error: Optional[str] = None
    last_success: Optional[float] = None
    last_failure: Optional[float] = None


class Controller:
    def __init__(
        self,
        name: str,
        do_func: Callable[[], None],
        run_interval: float = 0.0,
        error_retry_base: float = 0.05,
        max_backoff: float = 30.0,
    ) -> None:
        self.name = name
        self.do_func = do_func
        self.run_interval = run_interval
        self.error_retry_base = error_retry_base
        self.max_backoff = max_backoff
        self.status = ControllerStatus()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.do_func()
                self.status.success_count += 1
                self.status.consecutive_failures = 0
                self.status.last_error = None
                self.status.last_success = time.time()
                delay = self.run_interval
                if delay <= 0:
                    break  # one-shot controller
            except Exception as exc:  # controller.go:175 retry w/ backoff
                self.status.failure_count += 1
                self.status.consecutive_failures += 1
                self.status.last_error = str(exc)
                self.status.last_failure = time.time()
                log.warning(
                    "controller run failed, retrying with backoff",
                    extra={"fields": {
                        "name": self.name,
                        "consecutiveFailures":
                            self.status.consecutive_failures,
                        "error": str(exc),
                    }},
                )
                delay = min(
                    self.error_retry_base
                    * (2 ** (self.status.consecutive_failures - 1)),
                    self.max_backoff,
                )
            self._wake.wait(timeout=delay)
            self._wake.clear()

    def start(self) -> "Controller":
        self._thread = threading.Thread(
            target=self._loop, name=f"ctrl-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def trigger(self) -> None:
        self._wake.set()

    def stop(self, wait: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if wait and self._thread is not None:
            self._thread.join(timeout=5)


class ControllerManager:
    """pkg/controller Manager: UpdateController replaces by name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.controllers: Dict[str, Controller] = {}

    def update_controller(self, controller: Controller) -> Controller:
        with self._lock:
            old = self.controllers.get(controller.name)
            if old is not None:
                old.stop(wait=False)
            self.controllers[controller.name] = controller
        return controller.start()

    def remove_controller(self, name: str) -> None:
        with self._lock:
            controller = self.controllers.pop(name, None)
        if controller is not None:
            controller.stop(wait=False)

    def statuses(self) -> Dict[str, ControllerStatus]:
        with self._lock:
            return {
                name: c.status for name, c in self.controllers.items()
            }

    def stop_all(self) -> None:
        with self._lock:
            controllers = list(self.controllers.values())
            self.controllers.clear()
        for c in controllers:
            c.stop(wait=False)
