"""WaitGroup-with-deadline for ACK-gated publication.

Port of /root/reference/pkg/completion: policy regeneration blocks on
proxy ACKs (pkg/envoy/xds/ack.go) with a timeout
(EndpointGenerationTimeout, pkg/endpoint/bpf.go:442); the same
pattern gates device table flips on consumer acknowledgment.
"""

from __future__ import annotations

import threading
from typing import List, Optional


class Completion:
    def __init__(self) -> None:
        self._event = threading.Event()
        self._failed = False

    def complete(self) -> None:
        self._event.set()

    def fail(self) -> None:
        """NACK (xds/ack.go's NACK path): the waiter returns False
        immediately instead of blocking out the timeout."""
        self._failed = True
        self._event.set()

    @property
    def completed(self) -> bool:
        return self._event.is_set() and not self._failed

    @property
    def failed(self) -> bool:
        return self._failed


class WaitGroup:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._completions: List[Completion] = []

    def add_completion(self) -> Completion:
        c = Completion()
        with self._lock:
            self._completions.append(c)
        return c

    def wait(self, timeout: Optional[float] = None) -> bool:
        """True when every completion finished in time; False on
        timeout (the caller keeps old state and retries, like failed
        regenerations, pkg/endpoint/policy.go:770-775)."""
        import time

        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            completions = list(self._completions)
        for c in completions:
            remaining = (
                None if deadline is None else max(0.0, deadline - time.time())
            )
            if not c._event.wait(timeout=remaining):
                return False
            if c.failed:
                return False
        return True

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._completions)
