"""Small host-side utilities (CIDR math, timing, counters)."""
