"""Duration accumulators for regeneration phases.

Port of /root/reference/pkg/spanstat: Start/End accumulate success and
failure totals separately; pkg/endpoint/policy.go:689-699 logs one
SpanStat per regeneration phase (policy calculation, map sync, table
compile, total).
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class SpanStat:
    def __init__(self) -> None:
        self.success_total = 0.0
        self.failure_total = 0.0
        self.num_success = 0
        self.num_failure = 0
        self._start: Optional[float] = None

    def start(self) -> "SpanStat":
        """Begin a measurement window.  A start() while a span is
        already running folds the in-flight elapsed time as a
        SUCCESS first (the old behavior silently discarded it —
        wrong once spans wrap re-entrant regen phases): no wall time
        observed by a start/start/end sequence is ever lost."""
        if self._start is not None:
            self.end(success=True)
        self._start = time.perf_counter()
        return self

    def end(self, success: bool = True) -> "SpanStat":
        if self._start is None:
            return self
        d = time.perf_counter() - self._start
        self._start = None
        return self.observe(d, success=success)

    def observe(self, duration: float, success: bool = True) -> "SpanStat":
        """Fold an externally measured duration — the ONE fold
        implementation shared by end() and tracing.StatSpan, so the
        /debug/profile and /debug/traces planes can't drift."""
        if success:
            self.success_total += duration
            self.num_success += 1
        else:
            self.failure_total += duration
            self.num_failure += 1
        return self

    def total(self) -> float:
        return self.success_total + self.failure_total

    def seconds(self) -> float:
        return self.total()


class SpanStats(dict):
    """Named phase map (regenerationStatistics, pkg/endpoint/policy.go)."""

    def span(self, name: str) -> SpanStat:
        if name not in self:
            self[name] = SpanStat()
        return self[name]

    def report(self) -> Dict[str, float]:
        return {name: s.total() for name, s in self.items()}
