"""Desired policy-map-state computation (the compiler frontend).

Behavioral port of /root/reference/pkg/endpoint/policy.go:
  - resolveL4Policy (policy.go:222)
  - convertL4FilterToPolicyMapKeys (policy.go:110)
  - computeDesiredL4PolicyMapEntries (policy.go:143)
  - determineAllowLocalhost / determineAllowFromWorld (policy.go:285,306)
  - computeDesiredL3PolicyMapEntries (policy.go:318)

This host-side pass is the *semantic spec* of the verdict tables: the
engine's device output must be bit-identical to evaluating the map
state returned here with the 3-probe lattice (engine.oracle).
"""

from __future__ import annotations

from typing import Dict, Optional

from cilium_tpu import option
from cilium_tpu.identity import (
    RESERVED_HOST,
    RESERVED_WORLD,
    IdentityCache,
)
from cilium_tpu.labels import LabelArray
from cilium_tpu.maps.policymap import (
    EGRESS,
    INGRESS,
    PolicyKey,
    PolicyMapState,
    PolicyMapStateEntry,
)
from cilium_tpu.policy.l4 import L4Filter, L4Policy, proxy_id
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.search import Decision, SearchContext

# policy.go:49-60: unconditional ingress L3 allows.
LOCALHOST_KEY = PolicyKey(identity=RESERVED_HOST, traffic_direction=INGRESS)
WORLD_KEY = PolicyKey(identity=RESERVED_WORLD, traffic_direction=INGRESS)


def _get_security_identities(labels_map: IdentityCache, selector) -> list:
    """policy.go:92: all identity ids whose labels the selector selects."""
    return [
        num_id
        for num_id, labels in labels_map.items()
        if selector.matches(labels)
    ]


def _convert_l4_filter_to_keys(
    labels_map: IdentityCache, f: L4Filter, direction: int
) -> list:
    """policy.go:110: one PolicyKey per (selected identity, port, proto)."""
    keys = []
    for sel in f.endpoints:
        for num_id in _get_security_identities(labels_map, sel):
            keys.append(
                PolicyKey(
                    identity=num_id,
                    dest_port=f.port,
                    nexthdr=f.u8proto,
                    traffic_direction=direction,
                )
            )
    return keys


def resolve_l4_policy(
    repo: Repository,
    ep_labels: LabelArray,
    ingress_enabled: bool = True,
    egress_enabled: bool = True,
) -> L4Policy:
    """policy.go:222 resolveL4Policy."""
    from cilium_tpu.policy.l4 import L4PolicyMap

    ingress = (
        repo.resolve_l4_ingress_policy(SearchContext(to_labels=ep_labels))
        if ingress_enabled
        else L4PolicyMap()
    )
    egress = (
        repo.resolve_l4_egress_policy(SearchContext(from_labels=ep_labels))
        if egress_enabled
        else L4PolicyMap()
    )
    return L4Policy(ingress=ingress, egress=egress)


def compute_desired_policy_map_state(
    repo: Repository,
    identity_cache: IdentityCache,
    ep_labels: LabelArray,
    *,
    endpoint_id: int = 0,
    ingress_enabled: bool = True,
    egress_enabled: bool = True,
    realized_redirects: Optional[Dict[str, int]] = None,
    l4_policy: Optional[L4Policy] = None,
) -> PolicyMapState:
    """computeDesiredPolicyMapState (policy.go:273), phase-ordered as the
    reference: L4 entries, then localhost/world overrides, then the
    identity × label-verdict L3 loop.

    `realized_redirects` maps proxy-id strings to allocated proxy ports;
    redirect filters with no allocated port are skipped
    (policy.go:157-166), exactly as the reference defers them to
    addNewRedirectsFromMap.
    """
    desired: PolicyMapState = {}
    if l4_policy is None:
        l4_policy = resolve_l4_policy(
            repo, ep_labels, ingress_enabled, egress_enabled
        )
    redirects = realized_redirects or {}

    # --- computeDesiredL4PolicyMapEntries (policy.go:143) -------------------
    for direction, l4map in (
        (INGRESS, l4_policy.ingress),
        (EGRESS, l4_policy.egress),
    ):
        for f in l4map.values():
            proxy_port = 0
            if f.is_redirect():
                pid = proxy_id(endpoint_id, f.ingress, f.protocol, f.port)
                proxy_port = redirects.get(pid, 0)
                if proxy_port == 0:
                    continue
            for key in _convert_l4_filter_to_keys(identity_cache, f, direction):
                desired[key] = PolicyMapStateEntry(proxy_port=proxy_port)

    # --- determineAllowLocalhost (policy.go:285) ----------------------------
    if option.Config.always_allow_localhost() or l4_policy.has_redirect():
        desired[LOCALHOST_KEY] = PolicyMapStateEntry()

    # --- determineAllowFromWorld (policy.go:306) ----------------------------
    if option.Config.host_allows_world and LOCALHOST_KEY in desired:
        desired[WORLD_KEY] = PolicyMapStateEntry()

    # --- computeDesiredL3PolicyMapEntries (policy.go:318) -------------------
    for num_id, labels in identity_cache.items():
        if ingress_enabled:
            ctx = SearchContext(from_labels=labels, to_labels=ep_labels)
            ingress_access = repo.allows_ingress_label_access(ctx)
        else:
            ingress_access = Decision.ALLOWED
        if ingress_access == Decision.ALLOWED:
            desired[
                PolicyKey(identity=num_id, traffic_direction=INGRESS)
            ] = PolicyMapStateEntry()

        if egress_enabled:
            ctx = SearchContext(from_labels=ep_labels, to_labels=labels)
            egress_access = repo.allows_egress_label_access(ctx)
        else:
            egress_access = Decision.ALLOWED
        if egress_access == Decision.ALLOWED:
            desired[
                PolicyKey(identity=num_id, traffic_direction=EGRESS)
            ] = PolicyMapStateEntry()

    return desired
