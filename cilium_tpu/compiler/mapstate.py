"""Desired policy-map-state computation (the compiler frontend).

Behavioral port of /root/reference/pkg/endpoint/policy.go:
  - resolveL4Policy (policy.go:222)
  - convertL4FilterToPolicyMapKeys (policy.go:110)
  - computeDesiredL4PolicyMapEntries (policy.go:143)
  - determineAllowLocalhost / determineAllowFromWorld (policy.go:285,306)
  - computeDesiredL3PolicyMapEntries (policy.go:318)

This host-side pass is the *semantic spec* of the verdict tables: the
engine's device output must be bit-identical to evaluating the map
state returned here with the 3-probe lattice (engine.oracle).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from cilium_tpu import option
from cilium_tpu.identity import (
    RESERVED_HOST,
    RESERVED_WORLD,
    IdentityCache,
)
from cilium_tpu.labels import LabelArray
from cilium_tpu.maps.policymap import (
    EGRESS,
    INGRESS,
    MapStateArrays,
    PolicyKey,
    PolicyMapState,
    PolicyMapStateEntry,
)
from cilium_tpu.policy.l4 import L4Filter, L4Policy, proxy_id
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.search import Decision, SearchContext

# policy.go:49-60: unconditional ingress L3 allows.
LOCALHOST_KEY = PolicyKey(identity=RESERVED_HOST, traffic_direction=INGRESS)
WORLD_KEY = PolicyKey(identity=RESERVED_WORLD, traffic_direction=INGRESS)


def _get_security_identities(labels_map: IdentityCache, selector) -> list:
    """policy.go:92: all identity ids whose labels the selector selects."""
    return [
        num_id
        for num_id, labels in labels_map.items()
        if selector.matches(labels)
    ]


def _convert_l4_filter_to_keys(
    labels_map: IdentityCache, f: L4Filter, direction: int
) -> list:
    """policy.go:110: one PolicyKey per (selected identity, port, proto)."""
    keys = []
    for sel in f.endpoints:
        for num_id in _get_security_identities(labels_map, sel):
            keys.append(
                PolicyKey(
                    identity=num_id,
                    dest_port=f.port,
                    nexthdr=f.u8proto,
                    traffic_direction=direction,
                )
            )
    return keys


def resolve_l4_policy(
    repo: Repository,
    ep_labels: LabelArray,
    ingress_enabled: bool = True,
    egress_enabled: bool = True,
    rules=None,
) -> L4Policy:
    """policy.go:222 resolveL4Policy.  `rules` restricts the walk to
    the endpoint's relevant sublist (RuleIndex invariant)."""
    from cilium_tpu.policy.l4 import L4PolicyMap

    ingress = (
        repo.resolve_l4_ingress_policy(
            SearchContext(to_labels=ep_labels), rules
        )
        if ingress_enabled
        else L4PolicyMap()
    )
    egress = (
        repo.resolve_l4_egress_policy(
            SearchContext(from_labels=ep_labels), rules
        )
        if egress_enabled
        else L4PolicyMap()
    )
    return L4Policy(ingress=ingress, egress=egress)


def _l3_allowed_identities(
    repo: Repository,
    selector_cache,
    ep_labels: LabelArray,
    ingress: bool,
    rules=None,
) -> frozenset:
    """The set of identities whose label-only verdict is ALLOWED,
    computed with set algebra over the SelectorCache instead of the
    per-identity can_reach walk.

    Derivation from the reference lattice (repository.go:80 +
    rule.go:352-391): iterating rules, the first DENIED (an unmet
    FromRequires of any rule selecting the endpoint) terminates with
    Denied, and ALLOWED (an L3-only allow match) is remembered
    otherwise — so the final label verdict for an identity is ALLOWED
    iff (a) no relevant rule's requires reject it, and (b) some
    relevant rule's L3-only (no ToPorts) block selects it.  Both are
    unions/intersections of selector match sets.
    """
    universe = selector_cache.identities()
    allowed: set = set()
    denied: set = set()
    for r in repo.rules if rules is None else rules:
        if not r.endpoint_selector.matches(ep_labels):
            continue
        blocks = r.rule.ingress if ingress else r.rule.egress
        for b in blocks:
            requires = b.from_requires if ingress else b.to_requires
            for sel in requires:
                denied |= universe - selector_cache.matches(sel)
        for b in blocks:
            if len(b.to_ports) != 0:
                continue
            sels = (
                b.get_source_endpoint_selectors()
                if ingress
                else b.get_destination_endpoint_selectors()
            )
            for sel in sels:
                allowed |= selector_cache.matches(sel)
    return frozenset(allowed - denied)


def compute_desired_policy_map_state(
    repo: Repository,
    identity_cache: IdentityCache,
    ep_labels: LabelArray,
    *,
    endpoint_id: int = 0,
    ingress_enabled: bool = True,
    egress_enabled: bool = True,
    realized_redirects: Optional[Dict[str, int]] = None,
    l4_policy: Optional[L4Policy] = None,
    selector_cache=None,
    rules=None,
) -> PolicyMapState:
    """computeDesiredPolicyMapState (policy.go:273), phase-ordered as the
    reference: L4 entries, then localhost/world overrides, then the
    identity × label-verdict L3 loop.

    `realized_redirects` maps proxy-id strings to allocated proxy ports;
    redirect filters with no allocated port are skipped
    (policy.go:157-166), exactly as the reference defers them to
    addNewRedirectsFromMap.

    `selector_cache` (a synced compiler.selectorcache.SelectorCache)
    switches selector→identity resolution and the L3 loop to indexed
    set algebra — same results, O(selectors) instead of
    O(identities × selectors).
    """
    if l4_policy is None:
        l4_policy = resolve_l4_policy(
            repo, ep_labels, ingress_enabled, egress_enabled, rules
        )
    redirects = realized_redirects or {}
    if selector_cache is not None:
        if len(selector_cache.identities()) != len(identity_cache):
            # cheap guard only — full sync is the caller's contract
            raise ValueError(
                "selector_cache universe is out of sync with "
                "identity_cache; call selector_cache.sync(identity_cache) "
                "first"
            )
        return _compute_desired_arrays(
            repo,
            identity_cache,
            ep_labels,
            endpoint_id,
            ingress_enabled,
            egress_enabled,
            redirects,
            l4_policy,
            selector_cache,
            rules,
        )

    desired: PolicyMapState = {}
    # --- computeDesiredL4PolicyMapEntries (policy.go:143) -------------------
    for direction, l4map in (
        (INGRESS, l4_policy.ingress),
        (EGRESS, l4_policy.egress),
    ):
        for f in l4map.values():
            proxy_port = 0
            if f.is_redirect():
                pid = proxy_id(endpoint_id, f.ingress, f.protocol, f.port)
                proxy_port = redirects.get(pid, 0)
                if proxy_port == 0:
                    continue
            for key in _convert_l4_filter_to_keys(
                identity_cache, f, direction
            ):
                desired[key] = PolicyMapStateEntry(
                    proxy_port=proxy_port
                )

    # --- determineAllowLocalhost (policy.go:285) ----------------------------
    if option.Config.always_allow_localhost() or l4_policy.has_redirect():
        desired[LOCALHOST_KEY] = PolicyMapStateEntry()

    # --- determineAllowFromWorld (policy.go:306) ----------------------------
    if option.Config.host_allows_world and LOCALHOST_KEY in desired:
        desired[WORLD_KEY] = PolicyMapStateEntry()

    # --- computeDesiredL3PolicyMapEntries (policy.go:318) -------------------
    for num_id, labels in identity_cache.items():
        if ingress_enabled:
            ctx = SearchContext(from_labels=labels, to_labels=ep_labels)
            ingress_access = repo.allows_ingress_label_access(ctx)
        else:
            ingress_access = Decision.ALLOWED
        if ingress_access == Decision.ALLOWED:
            desired[
                PolicyKey(identity=num_id, traffic_direction=INGRESS)
            ] = PolicyMapStateEntry()

        if egress_enabled:
            ctx = SearchContext(from_labels=ep_labels, to_labels=labels)
            egress_access = repo.allows_egress_label_access(ctx)
        else:
            egress_access = Decision.ALLOWED
        if egress_access == Decision.ALLOWED:
            desired[
                PolicyKey(identity=num_id, traffic_direction=EGRESS)
            ] = PolicyMapStateEntry()

    return desired


def _ids_to_keys(
    ids, dest_port: int, nexthdr: int, direction: int
) -> np.ndarray:
    """identity set → packed u64 PolicyKeys (one np op per filter
    instead of one PolicyKey object per identity)."""
    from cilium_tpu.maps.policymap import pack_keys

    return pack_keys(
        np.fromiter(ids, np.uint64, count=len(ids)),
        dest_port,
        nexthdr,
        direction,
    )


def _compute_desired_arrays(
    repo,
    identity_cache,
    ep_labels,
    endpoint_id,
    ingress_enabled,
    egress_enabled,
    redirects,
    l4_policy,
    selector_cache,
    rules,
) -> MapStateArrays:
    """The vectorized computeDesiredPolicyMapState (policy.go:273):
    selector match sets come from the SelectorCache postings and the
    per-(identity, filter) key expansion is array math — O(selectors +
    output entries) with no per-entry Python objects.  Entry order
    (and therefore duplicate-key overwrite) mirrors the dict path:
    L4, localhost, world, then L3; MapStateArrays.build keeps the
    last occurrence."""
    key_chunks = []
    proxy_chunks = []

    # --- computeDesiredL4PolicyMapEntries (policy.go:143) -------------------
    for direction, l4map in (
        (INGRESS, l4_policy.ingress),
        (EGRESS, l4_policy.egress),
    ):
        for f in l4map.values():
            proxy_port = 0
            if f.is_redirect():
                pid = proxy_id(endpoint_id, f.ingress, f.protocol, f.port)
                proxy_port = redirects.get(pid, 0)
                if proxy_port == 0:
                    continue
            ids: set = set()
            for sel in f.endpoints:
                ids |= selector_cache.matches(sel)
            if not ids:
                continue
            keys = _ids_to_keys(ids, f.port, f.u8proto, direction)
            key_chunks.append(keys)
            proxy_chunks.append(
                np.full(len(keys), proxy_port, np.uint32)
            )

    # --- determineAllowLocalhost / AllowFromWorld (policy.go:285,306) -------
    allow_localhost = (
        option.Config.always_allow_localhost() or l4_policy.has_redirect()
    )
    if allow_localhost:
        key_chunks.append(
            _ids_to_keys([RESERVED_HOST], 0, 0, INGRESS)
        )
        proxy_chunks.append(np.zeros(1, np.uint32))
        if option.Config.host_allows_world:
            key_chunks.append(
                _ids_to_keys([RESERVED_WORLD], 0, 0, INGRESS)
            )
            proxy_chunks.append(np.zeros(1, np.uint32))

    # --- computeDesiredL3PolicyMapEntries (policy.go:318) -------------------
    ing_allowed = (
        _l3_allowed_identities(repo, selector_cache, ep_labels, True, rules)
        if ingress_enabled
        else frozenset(identity_cache)
    )
    eg_allowed = (
        _l3_allowed_identities(repo, selector_cache, ep_labels, False, rules)
        if egress_enabled
        else frozenset(identity_cache)
    )
    if ing_allowed:
        key_chunks.append(_ids_to_keys(ing_allowed, 0, 0, INGRESS))
        proxy_chunks.append(np.zeros(len(ing_allowed), np.uint32))
    if eg_allowed:
        key_chunks.append(_ids_to_keys(eg_allowed, 0, 0, EGRESS))
        proxy_chunks.append(np.zeros(len(eg_allowed), np.uint32))

    if not key_chunks:
        return MapStateArrays(
            np.zeros(0, np.uint64), np.zeros(0, np.uint32)
        )
    return MapStateArrays.build(
        np.concatenate(key_chunks), np.concatenate(proxy_chunks)
    )
