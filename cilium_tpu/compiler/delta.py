"""Delta table publication: host-side diffing of compiled tables.

The FleetCompiler already re-lowers only endpoints whose map state
moved (token-gated rows).  This module closes the remaining O(world)
gaps so that one rule added to a 50k-rule fleet costs O(change), not
O(fleet):

  * IncrementalHashPair — maintains the hashed L4 entry tables
    (build_l4_hash_pair layout) across compiles.  A dirty endpoint's
    entry section is diffed against its previous lowering; only hash
    BUCKETS whose ordered content changed are re-placed.  The result
    is bit-identical to a from-scratch build_l4_hash_pair over the
    same concatenated entries (the property the churn tests pin):
    lane order inside a bucket is the global concatenation order, and
    an unaffected bucket's subsequence is unchanged by construction.

  * PendingBuffer — the double-buffered publish pair for a mutable
    master array (the same realized/backup shuffle the stacked rows
    use): each publish flips to the standby buffer and copies only
    the rows dirtied since that buffer was last handed out, so
    consumers may hold the previously published array for one flip.

  * TableDelta — a per-leaf scatter description (indices + fresh
    values, or whole-leaf replacement when the shape class moved)
    that the device store (engine/publish.py) applies to a resident
    epoch with `.at[idx].set(rows)` instead of re-uploading every
    table (reference Cilium updates individual policymap entries in
    place; it never rewrites the whole BPF map on a rule add).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Set, Tuple

import numpy as np

# -- per-leaf scatter update -------------------------------------------------


@dataclass
class LeafUpdate:
    """Scatter payload for one PolicyTables leaf: write `values` at
    `idx` (a tuple of index arrays, one per indexed leading axis)."""

    idx: Tuple[np.ndarray, ...]
    values: np.ndarray

    @property
    def nbytes(self) -> int:
        return (
            sum(i.nbytes for i in self.idx) + self.values.nbytes
        )


@dataclass
class TableDelta:
    """Everything that changed between two publish generations.

    `updates` leaves scatter in place; `replace` leaves ship whole
    (their shape class moved, or they are cheap scalars).  Leaves in
    neither dict are byte-identical between the generations.

    `layout` stamps the hot/cold + pack-width layout
    (compiler.tables.tables_layout_version) the delta's leaf set was
    recorded against: the device store refuses to scatter it into an
    epoch holding a different layout (and falls back to a full
    upload) — indices recorded against one lane width or leaf split
    are meaningless against another."""

    base_stamp: int
    new_stamp: int
    updates: Dict[str, LeafUpdate] = field(default_factory=dict)
    replace: Dict[str, np.ndarray] = field(default_factory=dict)
    layout: int = 0

    @property
    def bytes_h2d(self) -> int:
        """Bytes this delta ships host→device (the full-upload
        comparator is PolicyTables' total nbytes)."""
        n = sum(u.nbytes for u in self.updates.values())
        n += sum(np.asarray(a).nbytes for a in self.replace.values())
        return n


def tables_nbytes(tables) -> int:
    """Total payload of a full PolicyTables upload."""
    total = 0
    for leaf in tables.tree_flatten()[0]:
        if leaf is not None:
            total += np.asarray(leaf).nbytes
    return total


# -- double-buffered publish pair over a mutable master ----------------------


class PendingBuffer:
    """Two publish buffers ping-ponging over a master array that is
    mutated in place between publishes.  `publish()` flips to the
    standby buffer and copies only the rows dirtied since that buffer
    was last returned — the caller may keep the previously returned
    array untouched for exactly one flip (the FleetCompiler's
    documented staleness window)."""

    def __init__(self) -> None:
        self._bufs = [
            {"arr": None, "pending": set()} for _ in range(2)
        ]
        self._flip = 0

    def mark(self, rows) -> None:
        """Record master rows changed since the last publish (row
        indices along axis 0)."""
        for buf in self._bufs:
            buf["pending"].update(rows)

    def mark_all(self) -> None:
        for buf in self._bufs:
            buf["arr"] = None
            buf["pending"].clear()

    def publish(self, master: np.ndarray) -> np.ndarray:
        self._flip ^= 1
        buf = self._bufs[self._flip]
        arr = buf["arr"]
        if arr is None or arr.shape != master.shape:
            buf["arr"] = master.copy()
        elif buf["pending"]:
            idx = np.fromiter(
                buf["pending"], dtype=np.int64, count=len(buf["pending"])
            )
            buf["arr"][idx] = master[idx]
        buf["pending"].clear()
        # pre-warm the standby: paying its first full copy NOW (at
        # build/full-publish time) keeps the first incremental
        # publish delta-priced instead of charging it the warm-up
        other = self._bufs[self._flip ^ 1]
        if other["arr"] is None or other["arr"].shape != master.shape:
            other["arr"] = master.copy()
            other["pending"].clear()
        return buf["arr"]


# -- incremental hashed L4 entry tables --------------------------------------

# mirrored from compiler.tables (imported lazily to avoid the cycle)


def _hash_cols(ep_idx: int, ent: dict):
    """Per-endpoint key/value columns of the hashed probe, split into
    the exact and wildcard partitions (concat order preserved)."""
    from cilium_tpu.compiler.tables import (
        L4H_WILD_IDX,
        _fnv1a_host_2,
        l4h_key0,
        l4h_key1,
    )

    d = ent["d"]
    idx = ent["idx"]
    if len(idx) and int(idx.max()) > int(L4H_WILD_IDX):
        raise ValueError("identity index exceeds 22-bit hash key space")
    ep = np.full(len(d), ep_idx, np.uint32)
    w0 = l4h_key0(idx, d, ep)
    w1 = l4h_key1(ent["dport"], ent["proto"], ep)
    h = _fnv1a_host_2(w0, w1)
    wild = idx == L4H_WILD_IDX
    keep = ~wild
    out = {}
    for name, sel in (("exact", keep), ("wild", wild)):
        out[name] = {
            "w0": w0[sel],
            "w1": w1[sel],
            "val": ent["val"][sel],
            "h": h[sel],
        }
    return out


def _key64(sec: dict) -> np.ndarray:
    return (sec["w0"].astype(np.uint64) << np.uint64(32)) | sec[
        "w1"
    ].astype(np.uint64)


def _window_buckets(old: dict, new: dict, mask: int) -> np.ndarray:
    """Conservative fallback: buckets touched by the difference
    window (common prefix/suffix stripped).  Correct for ANY section
    reordering — entries outside the window are identical in content
    and relative order."""
    lo, ln = len(old["w0"]), len(new["w0"])
    m = min(lo, ln)
    if m:
        eq = (
            (old["w0"][:m] == new["w0"][:m])
            & (old["w1"][:m] == new["w1"][:m])
            & (old["val"][:m] == new["val"][:m])
        )
        prefix = int(m) if eq.all() else int(np.argmin(eq))
    else:
        prefix = 0
    rm = min(lo, ln) - prefix  # suffix must not overlap the prefix
    if rm:
        eq = (
            (old["w0"][lo - rm :] == new["w0"][ln - rm :])
            & (old["w1"][lo - rm :] == new["w1"][ln - rm :])
            & (old["val"][lo - rm :] == new["val"][ln - rm :])
        )
        rev = eq[::-1]
        suffix = int(rm) if rev.all() else int(np.argmin(rev))
    else:
        suffix = 0
    win = np.concatenate(
        [
            old["h"][prefix : lo - suffix],
            new["h"][prefix : ln - suffix],
        ]
    )
    return np.unique(win & np.uint32(mask))


def _section_changed_buckets(
    old: dict, new: dict, mask: int
) -> Optional[np.ndarray]:
    """Buckets whose ordered subsequence of THIS section's entries
    differs between `old` and `new`.  Returns None when nothing
    changed.

    Fast path: entries are keyed by their unique (w0, w1) words and
    diffed as sets (one rule add touches the handful of buckets its
    entries hash to, even though the entries interleave across the
    sorted section).  This is only sound when the COMMON entries keep
    their relative order — sections lowered from sorted MapStateArrays
    always do; if they don't (dict-ordered states), the conservative
    window diff takes over."""
    lo, ln = len(old["w0"]), len(new["w0"])
    if lo == ln and (
        np.array_equal(old["w0"], new["w0"])
        and np.array_equal(old["w1"], new["w1"])
        and np.array_equal(old["val"], new["val"])
    ):
        return None
    ko, kn = _key64(old), _key64(new)
    sn = np.sort(kn)
    pos = np.searchsorted(sn, ko)
    pos_c = np.minimum(pos, max(len(sn) - 1, 0))
    old_in_new = (
        sn[pos_c] == ko if len(sn) else np.zeros(lo, bool)
    )
    so = np.sort(ko)
    pos = np.searchsorted(so, kn)
    pos_c = np.minimum(pos, max(len(so) - 1, 0))
    new_in_old = (
        so[pos_c] == kn if len(so) else np.zeros(ln, bool)
    )
    if not np.array_equal(ko[old_in_new], kn[new_in_old]):
        # common entries reordered → key-diff unsound
        return _window_buckets(old, new, mask)
    # values of the matched pairs (aligned by the order check above)
    val_changed = old["val"][old_in_new] != new["val"][new_in_old]
    win = np.concatenate(
        [
            old["h"][~old_in_new],
            new["h"][~new_in_old],
            old["h"][old_in_new][val_changed],
        ]
    )
    if not len(win):
        return None
    return np.unique(win & np.uint32(mask))


class _IncrementalTable:
    """One hashed entry table (exact or wild) maintained across
    compiles.  The master `rows` array mutates in place; publishes go
    through a PendingBuffer pair.  `stash` is rebuilt per publish
    (64×3 — cheaper to rebuild than to track)."""

    def __init__(self, min_rows: int, lanes: Optional[int] = None) -> None:
        from cilium_tpu.compiler.tables import L4H_LANES

        self.min_rows = min_rows
        self.lanes = L4H_LANES if lanes is None else lanes
        self.rows: Optional[np.ndarray] = None
        self.stash: Optional[np.ndarray] = None
        self.n_rows = 0
        # bucket -> [k, 3] u32 overflow triples in global order
        self.overflow: Dict[int, np.ndarray] = {}
        self.pub = PendingBuffer()
        self.stash_dirty = True

    @property
    def entries(self) -> int:
        from cilium_tpu.compiler.tables import l4h_entries

        return l4h_entries(self.lanes)

    def _sized_rows(self, t: int) -> int:
        from cilium_tpu.compiler.tables import _pow2_at_least, l4h_load

        return _pow2_at_least(
            max(t // l4h_load(self.lanes), 1), self.min_rows
        )

    def full_build(self, cols: dict) -> Set[int]:
        """From-scratch placement — delegates to the ONE shared
        layout implementation (tables.place_l4_hash) and keeps its
        overflow positions as the per-bucket state the delta path
        maintains.  Returns the changed-row set (= all rows) for the
        records."""
        from cilium_tpu.compiler.tables import place_l4_hash

        rows, stash, so, b = place_l4_hash(
            cols["w0"], cols["w1"], cols["val"], cols["h"],
            self.min_rows, lanes=self.lanes,
        )
        self.overflow = {}
        for pos in so.tolist():  # already (bucket, order)-sorted
            bb = int(b[pos])
            triple = np.asarray(
                [cols["w0"][pos], cols["w1"][pos], cols["val"][pos]],
                np.uint32,
            )[None]
            prev = self.overflow.get(bb)
            self.overflow[bb] = (
                triple if prev is None else np.concatenate([prev, triple])
            )
        self.rows = rows
        self.n_rows = rows.shape[0]
        self.stash = stash
        self.stash_dirty = True
        self.pub.mark_all()
        return set(range(self.n_rows))

    def _rebuild_stash(self) -> None:
        from cilium_tpu.compiler.tables import L4H_STASH

        stash = np.zeros((L4H_STASH, 3), dtype=np.uint32)
        stash[:, 1] = np.uint32(0xFFFFFFFF)
        k = 0
        for bb in sorted(self.overflow):
            tri = self.overflow[bb]
            stash[k : k + len(tri)] = tri
            k += len(tri)
        self.stash = stash
        self.stash_dirty = True

    def delta_build(
        self,
        t_new: int,
        affected: np.ndarray,
        dirty_stack: Set[int],
        new_by_bucket: Dict[int, list],
    ) -> Optional[Set[int]]:
        """Re-place only `affected` buckets — O(changed), never
        touching the untouched entries.  A bucket's CURRENT ordered
        content is read back from the master rows (lanes are in
        global concatenation order; overflow triples follow), dirty
        endpoints' entries are dropped and replaced by
        `new_by_bucket[b]` (each tagged with its stack index), and a
        stable merge by stack index reproduces exactly the
        concatenation order a full rebuild would place.  Returns the
        changed-row set, or None when the delta preconditions fail
        (size class moved / stash overflow) and the caller must
        full_build."""
        from cilium_tpu.compiler.tables import L4H_STASH

        if self.rows is None or self._sized_rows(t_new) != self.n_rows:
            return None
        if len(affected) == 0:
            return set()
        e = self.entries
        placed: Dict[int, list] = {}
        over_total = sum(len(v) for v in self.overflow.values())
        for bb in affected.tolist():
            bb = int(bb)
            row = self.rows[bb]
            w1s = row[e : 2 * e]
            kept = []
            for lane in range(e):
                w1 = int(w1s[lane])
                if w1 == 0xFFFFFFFF:
                    break  # lanes fill front-to-back
                w0 = int(row[lane])
                stack = ((w0 >> 23) & 0x1FF) | ((w1 & 0x7F) << 9)
                if stack not in dirty_stack:
                    kept.append(
                        (stack, (w0, w1, int(row[2 * e + lane])))
                    )
            for tri in self.overflow.get(bb, ()):
                w0, w1 = int(tri[0]), int(tri[1])
                stack = ((w0 >> 23) & 0x1FF) | ((w1 & 0x7F) << 9)
                if stack not in dirty_stack:
                    kept.append((stack, (w0, w1, int(tri[2]))))
            fresh = new_by_bucket.get(bb, ())
            # stable by stack index: kept and fresh are each already
            # ordered, and one stack index never appears in both
            merged = sorted(kept + list(fresh), key=lambda x: x[0])
            placed[bb] = [tri for _, tri in merged]
        # stash capacity check before mutating anything
        removed = sum(
            len(self.overflow.get(int(bb), ()))
            for bb in affected.tolist()
        )
        added = sum(max(len(v) - e, 0) for v in placed.values())
        if over_total - removed + added > L4H_STASH:
            return None
        stash_changed = removed > 0 or added > 0
        for bb, content in placed.items():
            row = self.rows[bb]
            row[:] = 0
            row[e : 2 * e] = 0xFFFFFFFF
            lanes = content[:e]
            if lanes:
                arr = np.asarray(lanes, np.uint32)
                k = len(lanes)
                row[:k] = arr[:, 0]
                row[e : e + k] = arr[:, 1]
                row[2 * e : 2 * e + k] = arr[:, 2]
            spill = content[e:]
            self.overflow.pop(bb, None)
            if spill:
                self.overflow[bb] = np.asarray(spill, np.uint32)
        changed = set(placed)
        self.pub.mark(changed)
        if stash_changed:
            self._rebuild_stash()
        else:
            self.stash_dirty = False
        return changed

    def published(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, stash) safe to hand out: rows through the publish
        pair, stash freshly owned by this generation and trimmed to
        its occupied pow2 prefix (tables.trim_stash) — the published
        layout the probes broadcast-compare."""
        from cilium_tpu.compiler.tables import trim_stash

        return self.pub.publish(self.rows), trim_stash(self.stash)


class IncrementalHashPair:
    """The (exact, wild) hashed L4 table pair, maintained across
    compiles (see module docstring).  `build` is the FleetCompiler's
    replacement for the from-scratch _build_hash."""

    def __init__(self, lanes: Optional[int] = None) -> None:
        self._sections: Dict[int, dict] = {}  # ep_id -> cols per table
        self._order: Optional[Tuple[int, ...]] = None
        self.exact = _IncrementalTable(min_rows=64, lanes=lanes)
        self.wild = _IncrementalTable(min_rows=16, lanes=lanes)
        self.lanes = self.exact.lanes

    def reset(self) -> None:
        self.__init__(self.lanes)

    def _concat(self, order: Sequence[int], table: str) -> dict:
        secs = [self._sections[ep][table] for ep in order]
        if not secs:
            return {
                k: np.zeros(0, np.uint32)
                for k in ("w0", "w1", "val", "h")
            }
        return {
            k: np.concatenate([s[k] for s in secs])
            for k in ("w0", "w1", "val", "h")
        }

    def build(
        self,
        order: Sequence[int],
        rows_by_ep: Dict[int, dict],
        dirty_ep_ids: Sequence[int],
    ) -> Tuple[tuple, dict]:
        """Update the pair for this compile.  `rows_by_ep[ep]["ent"]`
        holds each endpoint's (possibly fresh) entry columns; only
        `dirty_ep_ids` have changed since the previous call.

        Returns ((rows, stash, wild_rows, wild_stash), delta_info)
        where delta_info maps table name → set of changed row indices
        (None = the table was fully rebuilt)."""
        order_t = tuple(order)
        if len(order_t) > 65536:
            # the empty-lane marker relies on ep >> 9 < 128 (see
            # build_l4_hash); the reference caps endpoint ids too
            raise ValueError(
                "endpoint axis exceeds the 16-bit key space"
            )
        ep_index = {ep: i for i, ep in enumerate(order_t)}
        full = self._order != order_t or self.exact.rows is None
        if full:
            self._sections = {
                ep: _hash_cols(ep_index[ep], rows_by_ep[ep]["ent"])
                for ep in order_t
            }
        else:
            dirty = [ep for ep in dirty_ep_ids if ep in ep_index]
            new_secs = {
                ep: _hash_cols(ep_index[ep], rows_by_ep[ep]["ent"])
                for ep in dirty
            }
        self._order = order_t

        delta_info = {}
        for name, table in (("exact", self.exact), ("wild", self.wild)):
            if full:
                changed = table.full_build(self._concat(order_t, name))
                delta_info[name] = None
                delta_info[name + "_stash"] = True
                continue
            mask = table.n_rows - 1
            parts = []
            for ep in dirty:
                got = _section_changed_buckets(
                    self._sections[ep][name], new_secs[ep][name], mask
                )
                if got is not None:
                    parts.append(got)
            if not parts:
                delta_info[name] = set()
                delta_info[name + "_stash"] = False
                table.stash_dirty = False
                continue
            affected = np.unique(np.concatenate(parts))
            # splice the fresh sections in before the re-place
            for ep in dirty:
                self._sections[ep][name] = new_secs[ep][name]
            # dirty endpoints' contributions to the affected buckets,
            # tagged with their stack index, in (stack, section)
            # order — what the per-bucket merge interleaves with the
            # kept entries
            dirty_set = set(dirty)
            dirty_stack = {ep_index[ep] for ep in dirty}
            new_by_bucket: Dict[int, list] = {}
            for ep in order_t:
                if ep not in dirty_set:
                    continue
                sec = self._sections[ep][name]
                b = (sec["h"] & np.uint32(mask)).astype(np.int64)
                stack = ep_index[ep]
                for pos in np.nonzero(np.isin(b, affected))[0].tolist():
                    new_by_bucket.setdefault(int(b[pos]), []).append(
                        (
                            stack,
                            (
                                int(sec["w0"][pos]),
                                int(sec["w1"][pos]),
                                int(sec["val"][pos]),
                            ),
                        )
                    )
            t_new = sum(
                len(self._sections[ep][name]["w0"]) for ep in order_t
            )
            changed = table.delta_build(
                t_new, affected, dirty_stack, new_by_bucket
            )
            if changed is None:
                changed = table.full_build(self._concat(order_t, name))
                delta_info[name] = None
                delta_info[name + "_stash"] = True
            else:
                delta_info[name] = changed
                delta_info[name + "_stash"] = table.stash_dirty
        if not full and dirty:
            for ep in dirty:
                self._sections[ep] = new_secs[ep]
        rows, stash = self.exact.published()
        wrows, wstash = self.wild.published()
        return (rows, stash, wrows, wstash), delta_info
