"""SelectorCache: bulk selector → identity-set resolution.

The reference's computeDesiredPolicyMapState walks every known
identity per endpoint per selector (pkg/endpoint/policy.go:92,318 —
O(identities × selectors) calls into k8s LabelSelector matching).
That is fine in Go at small scale; at the 50k-rule / 64k-identity
envelope it dominates control-plane latency.

TPU-first control plane treats selector resolution as set algebra over
inverted indexes instead of per-pair predicate calls:

  * per identity, the *effective* label view is two first-occurrence
    maps (LabelArray.get returns the first matching label in array
    order, labels.py has/get):
      - ``any.<key>``    → value of the first label with that key
      - ``<src>.<key>``  → value of the first label with that exact
                           extended key
  * the cache maintains postings  (key_form, value) → {ids}  and
    key_form → {ids}  (exists), so a selector's match set is exactly:
      - match_labels:      ∩ val_index[(k, v)]
      - In(k, vs):         ∩ ⋃ val_index[(k, v) for v in vs]
      - NotIn(k, vs):      − ⋃ val_index[(k, v) for v in vs]
      - Exists(k):         ∩ exists_index[k]
      - DoesNotExist(k):   − exists_index[k]
    which reproduces Requirement.matches / EndpointSelector.matches
    (policy/api/selector.py) exactly, because those are defined purely
    in terms of has/get.

Results are memoized per selector object (selectors hash by identity,
matching the reference's pointer-keyed L7DataMap) and invalidated by a
universe version bump.  Incremental identity add/remove updates the
postings in O(labels of that identity) and re-validates memoized
selectors lazily.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, FrozenSet, List, Set, Tuple

from cilium_tpu import labels as lbl
from cilium_tpu.identity import IdentityCache
from cilium_tpu.labels import PATH_DELIMITER, SOURCE_ANY, LabelArray
from cilium_tpu.policy.api.selector import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    EndpointSelector,
)


def _effective_views(labels: LabelArray) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(any_first, ext_first): first-occurrence value maps mirroring
    LabelArray.get's array-order semantics."""
    any_first: Dict[str, str] = {}
    ext_first: Dict[str, str] = {}
    for l in labels:
        if l.key not in any_first:
            any_first[l.key] = l.value
        ek = l.get_extended_key()
        if ek not in ext_first:
            ext_first[ek] = l.value
    return any_first, ext_first


def _split_key_form(ext_key: str) -> Tuple[bool, str]:
    """ext_key → (is_any_source, canonical form).  Mirrors
    labels.get_cilium_key_from + parse_label: a missing source prefix
    means the any source."""
    parts = ext_key.split(PATH_DELIMITER, 1)
    if len(parts) == 2:
        return parts[0] == SOURCE_ANY, ext_key
    return True, SOURCE_ANY + PATH_DELIMITER + parts[0]


class SelectorCache:
    """Identity-universe index + memoized selector match sets."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._universe: Dict[int, LabelArray] = {}
        self._val_index: Dict[Tuple[str, str], Set[int]] = {}
        self._exists_index: Dict[str, Set[int]] = {}
        # per-id undo lists: the index keys this id was posted under
        self._postings: Dict[int, List[Tuple[str, str]]] = {}
        self._all: Set[int] = set()
        self.version = 0
        # (allocator cache version, own version) of the last full sync
        self._synced: Tuple[int, int] = (-1, -1)
        self._memo: "weakref.WeakKeyDictionary[EndpointSelector, Tuple[int, FrozenSet[int]]]" = (
            weakref.WeakKeyDictionary()
        )

    # -- universe maintenance ------------------------------------------------

    def _index_identity(self, num_id: int, labels: LabelArray) -> None:
        any_first, ext_first = _effective_views(labels)
        posted: List[Tuple[str, str]] = []
        for k, v in any_first.items():
            form = SOURCE_ANY + PATH_DELIMITER + k
            self._val_index.setdefault((form, v), set()).add(num_id)
            self._exists_index.setdefault(form, set()).add(num_id)
            posted.append((form, v))
        for ek, v in ext_first.items():
            # The 'any.<key>' index form is fed ONLY by the bare-key
            # first-occurrence map above: LabelArray.get('any.<key>')
            # returns the first bare-key value in array order, so an
            # any-source label shadowed by an earlier same-key label of
            # another source must not post under 'any.<key>'.
            if ek.split(PATH_DELIMITER, 1)[0] == SOURCE_ANY:
                continue
            self._val_index.setdefault((ek, v), set()).add(num_id)
            self._exists_index.setdefault(ek, set()).add(num_id)
            posted.append((ek, v))
        self._postings[num_id] = posted
        self._all.add(num_id)

    def _unindex_identity(self, num_id: int) -> None:
        for form, v in self._postings.pop(num_id, []):
            s = self._val_index.get((form, v))
            if s is not None:
                s.discard(num_id)
                if not s:
                    del self._val_index[(form, v)]
            e = self._exists_index.get(form)
            if e is not None:
                e.discard(num_id)
                if not e:
                    del self._exists_index[form]
        self._all.discard(num_id)

    def upsert_identity(self, num_id: int, labels: LabelArray) -> None:
        with self._lock:
            old = self._universe.get(num_id)
            if old is not None:
                if old == labels:
                    return
                self._unindex_identity(num_id)
            self._universe[num_id] = labels
            self._index_identity(num_id, labels)
            self.version += 1

    def remove_identity(self, num_id: int) -> None:
        with self._lock:
            if self._universe.pop(num_id, None) is not None:
                self._unindex_identity(num_id)
                self.version += 1

    def sync(
        self, identity_cache: IdentityCache, cache_version=None
    ) -> int:
        """Diff the universe against a full identity-cache snapshot
        (getLabelsMap, policy.go:194) and apply adds/changes/removes
        incrementally.  Returns the resulting version.

        `cache_version` is the allocator's version stamp for this
        snapshot: when it matches the previously synced stamp (and no
        out-of-band upsert/remove moved the cache since), the
        O(universe) diff is skipped entirely — the hot path for
        rule-only churn, where the identity universe is untouched."""
        with self._lock:
            if (
                cache_version is not None
                and self._synced == (cache_version, self.version)
            ):
                return self.version
            for num_id in list(self._universe):
                if num_id not in identity_cache:
                    self.remove_identity(num_id)
            for num_id, labels in identity_cache.items():
                self.upsert_identity(num_id, labels)
            if cache_version is not None:
                self._synced = (cache_version, self.version)
            return self.version

    def identities(self) -> FrozenSet[int]:
        with self._lock:
            return frozenset(self._all)

    # -- selector resolution -------------------------------------------------

    def _resolve(self, selector: EndpointSelector) -> FrozenSet[int]:
        # reserved.all short-circuit (selector.go:277 via matches())
        for k in selector.match_labels:
            if k == lbl.SOURCE_RESERVED_KEY_PREFIX + lbl.ID_NAME_ALL:
                return frozenset(self._all)
        # Gather positive constraint sets first and seed from the
        # smallest, so resolving a narrow selector (the common case:
        # one match_labels pair selecting a handful of ids) never
        # copies the whole universe — intersection and subtraction
        # commute, so positives-first is order-equivalent to the
        # requirement walk.
        positive: List[Set[int]] = []
        negative: List[Set[int]] = []
        fallback_reqs = []
        for ext_key, value in selector.match_labels.items():
            _, form = _split_key_form(ext_key)
            positive.append(self._val_index.get((form, value), set()))
        for req in selector.match_expressions:
            _, form = _split_key_form(req.key)
            if req.operator == OP_IN:
                hit: Set[int] = set()
                for v in req.values:
                    hit |= self._val_index.get((form, v), set())
                positive.append(hit)
            elif req.operator == OP_NOT_IN:
                miss: Set[int] = set()
                for v in req.values:
                    miss |= self._val_index.get((form, v), set())
                negative.append(miss)
            elif req.operator == OP_EXISTS:
                positive.append(self._exists_index.get(form, set()))
            elif req.operator == OP_DOES_NOT_EXIST:
                negative.append(self._exists_index.get(form, set()))
            else:  # pragma: no cover - sanitize rejects unknown ops
                fallback_reqs.append(req)
        if positive:
            seed = min(positive, key=len)
            candidates = set(seed)
            for s in positive:
                if s is seed:
                    continue
                candidates &= s
                if not candidates:
                    return frozenset()
        else:
            candidates = set(self._all)
        for s in negative:
            candidates -= s
            if not candidates:
                return frozenset()
        for req in fallback_reqs:  # pragma: no cover
            candidates = {
                i for i in candidates if req.matches(self._universe[i])
            }
        return frozenset(candidates)

    def matches(self, selector: EndpointSelector) -> FrozenSet[int]:
        """All identity ids the selector selects, memoized."""
        with self._lock:
            hit = self._memo.get(selector)
            if hit is not None and hit[0] == self.version:
                return hit[1]
            result = self._resolve(selector)
            self._memo[selector] = (self.version, result)
            return result


class RuleIndex:
    """identity id → the ordered sublist of repo rules whose
    endpoint_selector selects that identity's labels.

    Every per-endpoint resolution walk (resolve_l4_*, resolve_cidr,
    the L3 label loop) is a no-op for rules not selecting the
    endpoint, so restricting the walk to this sublist is semantics-
    preserving and turns O(rules) per endpoint into O(relevant rules)
    — the control-plane analog of the per-endpoint PROG_ARRAY
    dispatch.  Rebuilt lazily when the repo revision or the selector-
    cache universe version moves.
    """

    def __init__(self) -> None:
        self._key: Tuple[int, int] = (-1, -1)
        self._map: Dict[int, List] = {}
        self._seen: List = []  # rule refs in repo order, for delta builds
        self._lock = threading.Lock()

    def build(self, repo, selector_cache: SelectorCache) -> None:
        key = (repo.get_revision(), selector_cache.version)
        with self._lock:
            if key == self._key:
                return
            rules = list(repo.rules)
            # append-only fast path: same universe, previous rules an
            # identical prefix of the new list → index only the suffix
            append_only = (
                self._key[1] == key[1]
                and len(rules) >= len(self._seen)
                and all(
                    a is b for a, b in zip(self._seen, rules)
                )
            )
            if append_only:
                new_rules = rules[len(self._seen):]
                m = self._map
            else:
                new_rules = rules
                m = {}
            for r in new_rules:
                for num_id in selector_cache.matches(r.endpoint_selector):
                    m.setdefault(num_id, []).append(r)
            self._map = m
            self._seen = rules
            self._key = key

    def relevant(self, identity_id: int) -> List:
        with self._lock:
            return self._map.get(identity_id, [])
