"""The policy compiler: rule repository → dense device tensors.

This is the TPU-first replacement for the reference's per-endpoint
table generation (pkg/endpoint/policy.go computeDesiredPolicyMapState)
plus the clang/llc datapath build (pkg/datapath/loader): instead of
compiling C to BPF bytecode per endpoint, we lower the desired policy
map state into padded integer tensors consumed by the jitted verdict
engine (cilium_tpu.engine).
"""

from cilium_tpu.compiler.mapstate import compute_desired_policy_map_state
from cilium_tpu.compiler.tables import (
    PolicyTables,
    compile_map_states,
    lower_map_state,
)

__all__ = [
    "compute_desired_policy_map_state",
    "PolicyTables",
    "compile_map_states",
    "lower_map_state",
]
