"""Lowering PolicyMapState → dense, padded device tensors.

TPU-first design replacing the per-endpoint BPF hash map
(pkg/maps/policymap) with integer tensors:

  * identity axis: raw u32 security identities are mapped to dense
    indices through a sorted `id_table` (device-side searchsorted —
    the analog of the hash-map key probe, O(log n) but fully
    vectorized over the batch and MXU/VPU friendly);
  * L4 axis: the distinct (dport, proto) keys of the endpoint's
    filters, packed into u32 `dport << 8 | proto` (at most a few
    hundred per endpoint; the reference caps total map entries at
    16,384, policymap.go:37);
  * allow sets: bit-packed u32 words over the identity axis, one row
    per (direction, l4-key) plus an L3-only row pair — 32× smaller
    than bool tensors, so a 64k-identity × 1k-filter endpoint table is
    ~8 MB instead of 256 MB of HBM.

All axes are padded to configurable buckets so that XLA compilation
caches across table updates (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from cilium_tpu.maps.policymap import (
    EGRESS,
    INGRESS,
    PolicyKey,
    PolicyMapState,
)

# Sentinel for padded slots of the sorted identity table: sorts above
# every real identity, so searchsorted never aliases a real id.
PAD_ID = np.uint32(0xFFFFFFFF)
# Sentinel for padded / absent L4 key slots (a real packed key is at
# most 0xFFFF << 8 | 0xFF < 0x01000000).
PAD_PORTKEY = np.uint32(0xFFFFFFFF)

NUM_DIRECTIONS = 2  # INGRESS, EGRESS


def _round_up(n: int, mult: int) -> int:
    return max(mult, ((n + mult - 1) // mult) * mult)


def pack_port_proto(dport: int, proto: int) -> int:
    """u32 key: dport<<8 | proto (both host byte order)."""
    return (dport << 8) | proto


@dataclass
class PolicyTables:
    """Stacked verdict tables for E endpoints — the device-resident
    equivalent of E pinned policy maps plus the tail-call PROG_ARRAY
    dispatch (bpf/bpf_lxc.c:1039: per-tuple gather along the endpoint
    axis replaces the per-endpoint program jump).

    Shapes (E endpoints, K padded L4 keys, N padded identities,
    W = N // 32 words):
      id_table       u32 [N]           sorted identity universe (shared)
      l4_ports       u32 [E, 2, K]     packed (dport<<8|proto), PAD empty
      l4_proxy       u16 [E, 2, K]     proxy port per L4 key
      l4_allow_bits  u32 [E, 2, K, W]  per-identity allow bits (exact probe)
      l4_wild        u8  [E, 2, K]     identity-0 wildcard slot (3rd probe)
      l3_allow_bits  u32 [E, 2, W]     L3-only allow bits (2nd probe)
    """

    id_table: np.ndarray
    l4_ports: np.ndarray
    l4_proxy: np.ndarray
    l4_allow_bits: np.ndarray
    l4_wild: np.ndarray
    l3_allow_bits: np.ndarray

    @property
    def num_endpoints(self) -> int:
        return self.l4_ports.shape[0]

    @property
    def num_identities(self) -> int:
        return self.id_table.shape[0]

    @property
    def num_l4_keys(self) -> int:
        return self.l4_ports.shape[2]

    def tree_flatten(self):
        return (
            (
                self.id_table,
                self.l4_ports,
                self.l4_proxy,
                self.l4_allow_bits,
                self.l4_wild,
                self.l3_allow_bits,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            PolicyTables,
            lambda t: t.tree_flatten(),
            lambda aux, ch: PolicyTables.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover - jax always present in CI
        pass


_register_pytree()


def build_id_table(
    identity_ids: Sequence[int], identity_pad: int = 1024
) -> np.ndarray:
    """Sorted, padded identity universe (the shape-defining snapshot,
    reference getLabelsMap pkg/endpoint/policy.go:194)."""
    ids = sorted(set(int(i) for i in identity_ids))
    n = _round_up(len(ids), identity_pad)
    # Identity axis must stay a multiple of 32 for bit packing.
    n = _round_up(n, 32)
    table = np.full((n,), PAD_ID, dtype=np.uint32)
    table[: len(ids)] = np.asarray(ids, dtype=np.uint32)
    return table


def lower_map_state(
    states: Sequence[PolicyMapState],
    id_table: np.ndarray,
    filter_pad: int = 64,
) -> PolicyTables:
    """Lower E desired map states onto a shared identity universe.

    Any state entry whose identity is absent from `id_table` would be
    unreachable in the reference too (the BPF map key could never be
    probed with that source identity derived from ipcache); we assert
    against it to surface compiler/universe skew early — the moral
    equivalent of pkg/alignchecker.
    """
    id_list = id_table.tolist()
    n = id_table.shape[0]
    w = n // 32
    id_index: Dict[int, int] = {}
    for i, v in enumerate(id_list):
        if v == int(PAD_ID):
            break
        id_index[v] = i

    e_count = len(states)

    # Collect per-endpoint distinct (dport, proto) key sets per direction.
    per_ep_l4: List[Dict[Tuple[int, int, int], Dict]] = []
    max_k = 1
    for state in states:
        l4: Dict[Tuple[int, int, int], Dict] = {}
        for key, entry in state.items():
            if key.is_l3_only():
                continue
            kk = (key.traffic_direction, key.dest_port, key.nexthdr)
            slot = l4.setdefault(
                kk, {"proxy": entry.proxy_port, "ids": [], "wild": False}
            )
            # proxy port is constant per (port,proto,dir): one L4Filter
            # per L4PolicyMap key (pkg/policy/l4.go:276).  A state that
            # violates this cannot be lowered without diverging from
            # the per-entry oracle — refuse it.
            if slot["proxy"] != entry.proxy_port:
                raise ValueError(
                    f"conflicting proxy ports for {kk}: "
                    f"{slot['proxy']} vs {entry.proxy_port}"
                )
            if key.identity == 0:
                slot["wild"] = True
            else:
                slot["ids"].append(key.identity)
        per_ep_l4.append(l4)
        for d in (INGRESS, EGRESS):
            kcount = sum(1 for kk in l4 if kk[0] == d)
            max_k = max(max_k, kcount)

    k = _round_up(max_k, filter_pad)

    l4_ports = np.full((e_count, 2, k), PAD_PORTKEY, dtype=np.uint32)
    l4_proxy = np.zeros((e_count, 2, k), dtype=np.uint16)
    l4_wild = np.zeros((e_count, 2, k), dtype=np.uint8)
    # Bits are set directly into the packed words — never materialize
    # the dense [E, 2, K, N] bool tensor (it would be 32× the size the
    # packing exists to avoid).
    l4_allow_bits = np.zeros((e_count, 2, k, w), dtype=np.uint32)
    l3_allow_bits = np.zeros((e_count, 2, w), dtype=np.uint32)

    def _id_idx(num_id: int) -> int:
        idx = id_index.get(num_id)
        if idx is None:
            raise ValueError(
                f"identity {num_id} in map state but not in the "
                f"identity universe (universe/table skew)"
            )
        return idx

    for e, (state, l4) in enumerate(zip(states, per_ep_l4)):
        slot_idx = {INGRESS: 0, EGRESS: 0}
        for (d, dport, proto), slot in sorted(l4.items()):
            j = slot_idx[d]
            slot_idx[d] += 1
            l4_ports[e, d, j] = pack_port_proto(dport, proto)
            l4_proxy[e, d, j] = slot["proxy"]
            l4_wild[e, d, j] = 1 if slot["wild"] else 0
            for num_id in slot["ids"]:
                idx = _id_idx(num_id)
                l4_allow_bits[e, d, j, idx >> 5] |= np.uint32(
                    1 << (idx & 31)
                )
        for key in state:
            if not key.is_l3_only():
                continue
            idx = _id_idx(key.identity)
            l3_allow_bits[e, key.traffic_direction, idx >> 5] |= np.uint32(
                1 << (idx & 31)
            )

    return PolicyTables(
        id_table=id_table,
        l4_ports=l4_ports,
        l4_proxy=l4_proxy,
        l4_allow_bits=l4_allow_bits,
        l4_wild=l4_wild,
        l3_allow_bits=l3_allow_bits,
    )


def compile_map_states(
    states: Sequence[PolicyMapState],
    identity_ids: Sequence[int],
    identity_pad: int = 1024,
    filter_pad: int = 64,
) -> PolicyTables:
    """One-shot: build the shared identity table and lower E states."""
    return lower_map_state(
        states, build_id_table(identity_ids, identity_pad), filter_pad
    )
