"""Lowering PolicyMapState → dense, padded device tensors.

TPU-first design replacing the per-endpoint BPF hash maps
(pkg/maps/policymap) with direct-indexed integer tensors.  The guiding
constraint is that XLA-TPU executes random HBM gathers at ~100M/s per
chip, so every probe must be O(1) gathers — no device-side binary
search, no per-tuple scans:

  * identity probe — raw u32 security identity → dense index through
    two direct tables: `id_lo` for cluster-scope ids (dense from 0)
    and `id_local` for local CIDR identities (dense from
    LOCAL_ID_BASE).  One 4-byte gather each, both from tables that fit
    VMEM for realistic universes (512k ids = 2 MB; the reference's
    ipcache cap, ipcache.go:36).
  * L4 key probe — (proto, dport) → global filter slot through a
    256-entry proto remap plus a [8, 65536] u16 slot table (1 MB).
    This replaces the reference's per-endpoint hash-map key probe
    (policy.h:54) with two gathers shared by all endpoints.
  * allow sets — bit-packed u32 words over the identity axis, one row
    per (endpoint, direction, slot) plus an L3-only row pair; 32×
    smaller than bool tensors (64k ids × 256 slots × 16 endpoints
    ≈ 64 MB instead of 2 GB).

All axes are padded to configurable buckets so XLA compilation caches
across table updates (SURVEY.md §7 hard part 3).  Identities, ports
and verdict bits are integers end-to-end — no floats (hard part 5).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.identity import IdentityAllocator
from cilium_tpu.maps.policymap import (
    EGRESS,
    INGRESS,
    MapStateArrays,
    PolicyKey,
    PolicyMapState,
    unpack_keys,
)

# Sentinel for padded slots of the sorted identity table: sorts above
# every real identity, so searchsorted never aliases a real id.
PAD_ID = np.uint32(0xFFFFFFFF)
# Absent-entry marker in the direct identity index tables.
NO_INDEX = np.uint32(0xFFFFFFFF)
# Absent-slot marker in the (proto, dport) → L4 slot table.
NO_SLOT = np.uint16(0xFFFF)
# Cap on direct-table sizes (2^22 u32 = 16 MB).  Identity universes
# with non-local ids above this would need a hash-probe fallback; the
# reference caps at 512k ipcache entries (ipcache.go:36), well below.
MAX_DIRECT = 1 << 22

LOCAL_ID_BASE = IdentityAllocator.LOCAL_IDENTITY_BASE

# FleetCompiler instance nonces for generation-stamp scoping.
import itertools as _itertools

_COMPILER_NONCE = _itertools.count(1)

NUM_DIRECTIONS = 2  # INGRESS, EGRESS

# -- hashed L4 entry table ---------------------------------------------------
# The exact and wildcard L4 probes gather from ONE bucketized entry
# table instead of the dense [E, 2, Kg, W] bitmap: measured on v5e,
# element gathers from >=128 MB tables run ~17 ns/flow while 128-lane
# ROW gathers run ~7 ns regardless of row width, and the entry table
# is proportional to the REALIZED map entries (the reference's
# per-endpoint BPF hash maps are entry-proportional too,
# pkg/maps/policymap) rather than E×Kg×identities.
#
# Row = one bucket of `lanes // 3` planar 3-word entries (E below):
#   lanes [0, E)    key0 = idx | dir << 22 | (ep & 0x1FF) << 23
#   lanes [E, 2E)   key1 = dport << 16 | proto << 8 | ep >> 9
#   lanes [2E, 3E)  value = j << 16 | proxy_port
# Wildcard (identity 0) entries store idx = L4H_WILD_IDX.  Empty lanes
# hold key1 = 0xFFFFFFFF, unreachable because ep >> 9 < 128 for any
# endpoint index < 2^16 (the reference's endpoint-id cap).
#
# The lane width is the HOT-PLANE PACK WIDTH: the per-tuple probe
# gathers exactly one `lanes`-wide row and lane-compares E entries, so
# bytes-moved-per-tuple and compare work both scale linearly with it.
# The default is 64 lanes (21 entries, ~8 average load): halving the
# legacy 128-lane rows halves the dominant gather of the fused
# pipeline while the overflow tail (Poisson beyond 21 at lambda=8)
# stays far below the stash.  Build and probe both derive E from the
# row shape — the array IS the layout contract.
L4H_LANES = 64
L4H_WILD_IDX = np.uint32((1 << 22) - 1)
L4H_STASH = 64


def l4h_entries(lanes: int) -> int:
    """Entries per bucket row at a given lane width (3 words each)."""
    return lanes // 3


def l4h_load(lanes: int) -> int:
    """Target average entries per bucket when sizing the row count —
    lanes/8 keeps the overflow tail roughly constant across widths."""
    return max(lanes // 8, 2)


def trim_stash(stash: np.ndarray) -> np.ndarray:
    """Trim a [L4H_STASH, 3 or 2] stash to the pow2 prefix that holds
    its occupied rows (front-filled; empty rows carry w1 = 0xFFFFFFFF
    in the 3-word layout, cw1 = L4C_EMPTY_W1 in the compact one).
    The probe broadcast-compares EVERY stash lane against every tuple,
    so an empty stash shipped at capacity charges the hot path 64
    never-matching compares per table per tuple; verdicts are
    unchanged by construction (trimmed lanes can never match)."""
    from cilium_tpu.engine.hashtable import trim_pow2_prefix

    if stash.shape[-1] == 2:
        used = int((stash[:, 1] != L4C_EMPTY_W1).sum())
    else:
        used = int((stash[:, 1] != np.uint32(0xFFFFFFFF)).sum())
    return trim_pow2_prefix(stash, used)


# -- sub-word (compact, 2-word) L4 entries -----------------------------------
# The 3-word entry spends a full u32 on `value = j << 16 | proxy`, but
# the proxy port is ALREADY resident in the hot l4_meta plane at
# [ep, d, j] (lower_map_state writes it there for every entry, and the
# proxy-consistency check guarantees the two copies agree) — so the
# sub-word form stores only the 12-bit slot index, folded into the
# spare bits of key word 1 ("row metadata" packed beside the key):
#
#   cw0 = idx18            | (dport & 0x3FFF) << 18
#   cw1 = dport >> 14      bits 0-1
#         | proto << 2     bits 2-9
#         | ep << 10       bits 10-17
#         | dir << 18      bit  18
#         | j << 19        bits 19-30   (VALUE, masked out of compares)
#         bit 31 = 0; empty lanes hold cw1 = 0x80000000 (bit 31 set —
#         unreachable for any real entry, the exact-marker discipline
#         of the 3-word layout's key1 trick)
#
# 2 words/entry instead of 3 → the same bucket load fits a 32-lane row
# (16 entries) where the 3-word layout needs 64 lanes: the dominant
# lattice gathers halve again.  The probe reconstructs proxy with ONE
# l4_meta element gather at the combined j (+4 B/tuple, priced by
# gatherprof).  Semantics allow it only when idx < 2^18-1 (universe
# ≤ 262142 padded identities), ep < 2^8, j < 2^12 — repack_l4_subword
# verifies and refuses otherwise.  The stash ships 2-word entries too:
# its width (2 vs 3) is the LAYOUT MARKER the kernels branch on
# (l4_entry_words: a static jit-cache axis that travels with the
# pytree, no aux-structure change).
L4C_LANES = 32
L4C_WILD_IDX18 = np.uint32((1 << 18) - 1)
L4C_KEY_MASK = np.uint32((1 << 19) - 1)
L4C_EMPTY_W1 = np.uint32(1 << 31)
L4C_CMP_MASK = np.uint32(L4C_KEY_MASK | L4C_EMPTY_W1)


def l4_entry_words(tables_or_stash) -> int:
    """Entry word count of the hashed L4 layout (3 legacy, 2 compact)
    read from the stash width — the shape-borne layout marker shared
    by build, probe and the layout stamp."""
    stash = getattr(tables_or_stash, "l4_hash_stash", tables_or_stash)
    if stash is None:
        return 3
    return 2 if int(stash.shape[-1]) == 2 else 3


def l4c_key0(idx, dport):
    """Compact key word 0 (dtype-generic; build and probe share)."""
    return (
        (idx.astype(np.uint32) & np.uint32(0x3FFFF))
        | ((dport.astype(np.uint32) & np.uint32(0x3FFF)) << np.uint32(18))
    )


def l4c_key1(dport, proto, ep, d):
    """Compact key word 1, KEY BITS ONLY (j is ORed in at build)."""
    return (
        (dport.astype(np.uint32) >> np.uint32(14))
        | ((proto.astype(np.uint32) & np.uint32(0xFF)) << np.uint32(2))
        | ((ep.astype(np.uint32) & np.uint32(0xFF)) << np.uint32(10))
        | ((d.astype(np.uint32) & np.uint32(1)) << np.uint32(18))
    )


def l4h_key0(idx, d, ep):
    """Key word 0 of the hashed L4 probe.  Dtype-generic (np or jnp
    arrays): build side and device probe MUST share this packing."""
    return (
        idx.astype(np.uint32)
        | (d.astype(np.uint32) << np.uint32(22))
        | ((ep.astype(np.uint32) & np.uint32(0x1FF)) << np.uint32(23))
    )


def l4h_key1(dport, proto, ep):
    """Key word 1 (see l4h_key0)."""
    return (
        (dport.astype(np.uint32) << np.uint32(16))
        | (proto.astype(np.uint32) << np.uint32(8))
        | (ep.astype(np.uint32) >> np.uint32(9))
    )


def place_l4_hash(
    w0: np.ndarray,
    w1: np.ndarray,
    value: np.ndarray,
    h: np.ndarray,
    min_rows: int,
    lanes: int = L4H_LANES,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Sizing + bucket placement over precomputed key/hash columns —
    THE layout implementation, shared by build_l4_hash and the
    incremental delta builder (compiler/delta.py), whose bit-identity
    contract depends on there being exactly one copy of this logic.
    Returns (rows, stash, overflow_positions, buckets): the last two
    let the delta builder reconstruct its per-bucket overflow state
    without re-deriving the placement."""
    t = len(w0)
    entries = l4h_entries(lanes)
    n_rows = _pow2_at_least(max(t // l4h_load(lanes), 1), min_rows)
    while True:
        b = (h & np.uint32(n_rows - 1)).astype(np.int64)
        order = np.argsort(b, kind="stable")
        sb = b[order]
        first = np.searchsorted(sb, sb)
        rank = np.arange(t, dtype=np.int64) - first
        main = rank < entries
        if int((~main).sum()) <= L4H_STASH:
            break
        n_rows <<= 1
    rows = np.zeros((n_rows, lanes), dtype=np.uint32)
    rows[:, entries : 2 * entries] = np.uint32(0xFFFFFFFF)
    flat = rows.reshape(-1)
    # `main`/`rank` index SORTED positions; `order` maps them back
    mo = order[main]
    base = sb[main] * lanes + rank[main]
    flat[base] = w0[mo]
    flat[base + entries] = w1[mo]
    flat[base + 2 * entries] = value[mo]
    stash = np.zeros((L4H_STASH, 3), dtype=np.uint32)
    stash[:, 1] = np.uint32(0xFFFFFFFF)
    so = order[~main]
    stash[: len(so), 0] = w0[so]
    stash[: len(so), 1] = w1[so]
    stash[: len(so), 2] = value[so]
    return rows, stash, so, b


def build_l4_hash(
    ep: np.ndarray,
    d: np.ndarray,
    idx: np.ndarray,
    dport: np.ndarray,
    proto: np.ndarray,
    value: np.ndarray,
    min_rows: int = 64,
    lanes: int = L4H_LANES,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized bucket placement of T entries → (rows u32
    [R, lanes], stash u32 [pow2 used, 3]).  R is a power of two sized
    for ~lanes/8 entries per lanes//3-capacity row; rows double until
    the overflow fits the stash (never in practice — the tail is
    Poisson)."""
    t = len(ep)
    if np.any((idx >= L4H_WILD_IDX) & (idx != L4H_WILD_IDX)):
        raise ValueError("identity index exceeds 22-bit hash key space")
    if t and int(ep.max()) >= 65536:
        # the empty-lane marker relies on ep >> 9 < 128; the reference
        # caps endpoint ids at 65535 too (pkg/endpoint/endpoint.go)
        raise ValueError("endpoint axis exceeds the 16-bit key space")
    w0 = l4h_key0(idx, d, ep)
    w1 = l4h_key1(dport, proto, ep)
    h = _fnv1a_host_2(w0, w1)
    rows, stash, _, _ = place_l4_hash(
        w0, w1, value, h, min_rows, lanes=lanes
    )
    return rows, trim_stash(stash)


def build_l4_hash_pair(
    ep: np.ndarray,
    d: np.ndarray,
    idx: np.ndarray,
    dport: np.ndarray,
    proto: np.ndarray,
    value: np.ndarray,
    lanes: int = L4H_LANES,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Partition entries into the main (exact) and wildcard tables:
    (rows, stash, wild_rows, wild_stash)."""
    wild = idx == L4H_WILD_IDX
    keep = ~wild
    rows, stash = build_l4_hash(
        ep[keep], d[keep], idx[keep], dport[keep], proto[keep],
        value[keep], lanes=lanes,
    )
    wrows, wstash = build_l4_hash(
        ep[wild], d[wild], idx[wild], dport[wild], proto[wild],
        value[wild], min_rows=16, lanes=lanes,
    )
    return rows, stash, wrows, wstash


def _fnv1a_host_2(w0: np.ndarray, w1: np.ndarray) -> np.ndarray:
    """FNV-1a over two u32 word columns (avoids the [T, 2] stack)."""
    from cilium_tpu.engine.hashtable import FNV_OFFSET, FNV_PRIME

    h = np.full(len(w0), FNV_OFFSET, dtype=np.uint64)
    prime = np.uint64(int(FNV_PRIME))
    for col in (w0, w1):
        c = col.astype(np.uint64)
        for shift in (0, 8, 16, 24):
            h = ((h ^ ((c >> shift) & 0xFF)) * prime) & 0xFFFFFFFF
    return h.astype(np.uint32)


def _round_up(n: int, mult: int) -> int:
    return max(mult, ((n + mult - 1) // mult) * mult)


def _pow2_at_least(n: int, floor: int) -> int:
    size = floor
    while size < n:
        size <<= 1
    return size


@dataclass
class PolicyTables:
    """Stacked verdict tables for E endpoints — the device-resident
    equivalent of E pinned policy maps plus the tail-call PROG_ARRAY
    dispatch (bpf/bpf_lxc.c:1039: per-tuple gather along the endpoint
    axis replaces the per-endpoint program jump).

    Gather budget: the kernel spends ~20-30 ms per 1M-tuple random
    gather on TPU regardless of table size, so fused layouts matter
    more than compactness — identity index is ONE table (`id_direct` =
    cluster-scope ids dense from 0, then local CIDR ids dense from
    `id_lo_len`), and proxy-port + wildcard-bit are ONE u32 word
    (`l4_meta` = proxy << 1 | wild).

    Shapes (E endpoints, Kg padded global L4 slots, N padded
    identities, W = N // 32 words):
      id_table       u32 [N]            sorted identity universe
      id_direct      u32 [LO+LL]        id → index (two dense regions)
      id_lo_len      i32 scalar         split point of id_direct
      port_slot      u16 [256, 65536]   (proto, dport) → L4 slot; one
                                        row per raw IP proto byte — 32
                                        MB buys one fewer gather/tuple
      l4_meta        u32 [E, 2, Kg]     proxy_port << 1 | wildcard
      l4_allow_bits  u32 [E, 2, Kg, W]  per-identity allow (exact probe)
      l3_allow_bits  u32 [E, 2, W]      L3-only allow (2nd probe)
    """

    id_table: np.ndarray
    id_direct: np.ndarray
    id_lo_len: np.ndarray
    port_slot: np.ndarray
    l4_meta: np.ndarray
    l4_allow_bits: np.ndarray
    l3_allow_bits: np.ndarray
    # publish-generation stamp (FleetCompiler): a pytree CHILD (scalar
    # u64: compiler-instance nonce << 32 | publish counter) so it
    # survives device_put/flatten round trips without becoming a jit
    # cache key; 0 = unstamped (hand-built tables)
    generation: np.ndarray = np.uint64(0)
    # hashed L4 entry tables (see build_l4_hash): the exact and
    # wildcard probes are each ONE 128-lane row gather from an
    # entry-proportional table instead of element gathers from the
    # dense bitmap — on v5e row gathers run ~2x faster than big-table
    # element gathers.  Wildcard (identity 0) entries live in their
    # own SMALL table: they are per-(ep, dir, port, proto), so the
    # table stays a few KB and the second gather per flow hits a hot
    # region instead of paying the big-table random-access cost
    # again.  None → the kernel falls back to the dense
    # l4_allow_bits/l4_meta path (the layout the table-axis-sharded
    # mesh evaluator uses).
    l4_hash_rows: "np.ndarray | None" = None
    l4_hash_stash: "np.ndarray | None" = None
    l4_wild_rows: "np.ndarray | None" = None
    l4_wild_stash: "np.ndarray | None" = None

    @property
    def num_endpoints(self) -> int:
        return self.l4_meta.shape[0]

    @property
    def num_identities(self) -> int:
        return self.id_table.shape[0]

    @property
    def num_l4_slots(self) -> int:
        return self.l4_meta.shape[2]

    def tree_flatten(self):
        return (
            (
                self.id_table,
                self.id_direct,
                self.id_lo_len,
                self.port_slot,
                self.l4_meta,
                self.l4_allow_bits,
                self.l3_allow_bits,
                self.generation,
                self.l4_hash_rows,
                self.l4_hash_stash,
                self.l4_wild_rows,
                self.l4_wild_stash,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            PolicyTables,
            lambda t: t.tree_flatten(),
            lambda aux, ch: PolicyTables.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover - jax always present in CI
        pass


_register_pytree()


# -- hot/cold leaf planes ----------------------------------------------------
# The fused single-chip kernels (engine/verdict._probes with the
# hashed entry tables, engine/datapath) touch only the HOT leaves:
# everything the per-tuple verdict gathers read.  The COLD leaves are
# the dense-bitmap fallback layout — the 32 MB (proto, dport) slot
# table and the [E, 2, Kg, W] allow bitmap, by far the largest leaves
# — consumed only by the table-axis-sharded mesh evaluator and
# hand-built tables without the hash pair.  A hot-only publication
# (engine/publish.DeviceTableStore(hot_only=True)) keeps the cold
# plane host-resident: HBM holds and delta publishes ship only the
# words the verdict path can ever gather.
HOT_LEAVES = (
    "id_table",
    "id_direct",
    "id_lo_len",
    "l4_meta",
    "l3_allow_bits",
    "generation",
    "l4_hash_rows",
    "l4_hash_stash",
    "l4_wild_rows",
    "l4_wild_stash",
)
COLD_LEAVES = ("port_slot", "l4_allow_bits")


def split_hot(tables: "PolicyTables") -> "PolicyTables":
    """The hot plane of `tables`: cold leaves dropped (None).  Only
    valid for tables carrying the hashed entry pair — without it the
    kernel's fallback path needs the cold dense layout."""
    if tables.l4_hash_rows is None:
        raise ValueError(
            "hot/cold split requires the hashed L4 entry tables "
            "(dense-fallback tables gather the cold plane)"
        )
    import dataclasses

    return dataclasses.replace(
        tables, **{leaf: None for leaf in COLD_LEAVES}
    )


def is_hot_only(tables) -> bool:
    return any(getattr(tables, leaf) is None for leaf in COLD_LEAVES)


def tables_layout_version(tables) -> int:
    """Layout stamp of a PolicyTables instance: hashed-table pack
    widths + hot/cold coldness bits.  Two tables with different
    stamps have structurally different leaf sets or lane widths, so a
    TableDelta recorded against one cannot scatter into an epoch
    holding the other — DeviceTableStore falls back to a full upload
    on mismatch (the layout guard beside the reset-counter guard)."""
    if tables is None:
        return 0
    rows = getattr(tables, "l4_hash_rows", None)
    wrows = getattr(tables, "l4_wild_rows", None)
    lanes = 0 if rows is None else int(rows.shape[1])
    wlanes = 0 if wrows is None else int(wrows.shape[1])
    cold_bits = 0
    for i, leaf in enumerate(COLD_LEAVES):
        if getattr(tables, leaf, None) is None:
            cold_bits |= 1 << i
    # sub-word marker: the compact 2-word entry form at the same lane
    # count is a DIFFERENT layout (a delta recorded against one can
    # never scatter into the other)
    compact_bit = (
        1 if l4_entry_words(tables) == 2 else 0
    ) if getattr(tables, "l4_hash_stash", None) is not None else 0
    return (
        lanes | (wlanes << 11) | (cold_bits << 22)
        | (compact_bit << 24)
    )


def repack_hash_lanes(
    tables: "PolicyTables", lanes: int
) -> "PolicyTables":
    """Re-place both hashed entry tables at a different hot-plane
    pack width IN THE 3-WORD LAYOUT — the autotuner's layout knob.
    Entry fields are read back from the existing rows (either layout
    — a compact input is expanded through l4_entry_records), so no
    compiler state is needed; verdicts are identical by construction
    (probe hits are keyed, not positional).  The result's layout
    stamp differs from the source compiler's, so delta publication
    refuses it (full upload) — the repacked layout is a
    dispatch-side choice, not a new compile."""
    import dataclasses

    if tables.l4_hash_rows is None:
        raise ValueError("no hashed entry tables to repack")
    recs = l4_entry_records(tables)
    out = {}
    for key, rows_leaf, stash_leaf, min_rows in (
        ("exact", "l4_hash_rows", "l4_hash_stash", 64),
        ("wild", "l4_wild_rows", "l4_wild_stash", 16),
    ):
        r = recs[key]
        w0 = l4h_key0(r["idx"], r["d"], r["ep"])
        w1 = l4h_key1(r["dport"], r["proto"], r["ep"])
        val = (r["j"] << np.uint32(16)) | r["proxy"]
        h = _fnv1a_host_2(w0, w1)
        rows, stash, _, _ = place_l4_hash(
            w0, w1, val, h, min_rows, lanes=lanes
        )
        out[rows_leaf] = rows
        out[stash_leaf] = trim_stash(stash)
    return dataclasses.replace(tables, **out)


def place_l4_hash_compact(
    cw0: np.ndarray,
    cw1_key: np.ndarray,
    j: np.ndarray,
    h: np.ndarray,
    min_rows: int,
    lanes: int = L4C_LANES,
) -> Tuple[np.ndarray, np.ndarray]:
    """Compact sibling of place_l4_hash: 2-word planar entries (cw0
    plane then cw1 plane, the slot index ORed into cw1's value bits).
    Same sizing rule (lanes/8 target load, rows double until the
    overflow fits the stash); returns (rows, stash untrimmed)."""
    t = len(cw0)
    entries = lanes // 2
    cw1 = cw1_key | (j.astype(np.uint32) << np.uint32(19))
    n_rows = _pow2_at_least(max(t // l4h_load(lanes), 1), min_rows)
    while True:
        b = (h & np.uint32(n_rows - 1)).astype(np.int64)
        order = np.argsort(b, kind="stable")
        sb = b[order]
        first = np.searchsorted(sb, sb)
        rank = np.arange(t, dtype=np.int64) - first
        main = rank < entries
        if int((~main).sum()) <= L4H_STASH:
            break
        n_rows <<= 1
    rows = np.zeros((n_rows, lanes), dtype=np.uint32)
    rows[:, entries : 2 * entries] = L4C_EMPTY_W1
    flat = rows.reshape(-1)
    mo = order[main]
    base = sb[main] * lanes + rank[main]
    flat[base] = cw0[mo]
    flat[base + entries] = cw1[mo]
    stash = np.zeros((L4H_STASH, 2), dtype=np.uint32)
    stash[:, 1] = L4C_EMPTY_W1
    so = order[~main]
    stash[: len(so), 0] = cw0[so]
    stash[: len(so), 1] = cw1[so]
    return rows, stash


def l4_entry_records(tables: "PolicyTables") -> Dict[str, dict]:
    """Decode every realized hashed-pair entry — either layout — back
    to its field columns: {"exact"/"wild": {ep, d, idx, dport, proto,
    j, proxy}} (idx carries L4H_WILD_IDX for wildcard entries).  The
    repack helpers rebuild ANY layout from these, so the autotuner can
    sweep widths and forms without recompiling policy."""
    if tables.l4_hash_rows is None:
        raise ValueError("no hashed entry tables to decode")
    ew = l4_entry_words(tables)
    meta = np.asarray(tables.l4_meta)
    out = {}
    for key, rows_leaf, stash_leaf in (
        ("exact", "l4_hash_rows", "l4_hash_stash"),
        ("wild", "l4_wild_rows", "l4_wild_stash"),
    ):
        rows = np.asarray(getattr(tables, rows_leaf))
        stash = np.asarray(getattr(tables, stash_leaf))
        e = rows.shape[1] // ew
        if ew == 3:
            w0 = np.concatenate(
                [rows[:, :e].reshape(-1), stash[:, 0]]
            )
            w1 = np.concatenate(
                [rows[:, e : 2 * e].reshape(-1), stash[:, 1]]
            )
            val = np.concatenate(
                [rows[:, 2 * e : 3 * e].reshape(-1), stash[:, 2]]
            )
            keep = w1 != np.uint32(0xFFFFFFFF)
            w0, w1, val = w0[keep], w1[keep], val[keep]
            # key1's low byte holds ep >> 9 (8 bits — build guards
            # ep < 2^16, but decode the encoder's full field width)
            ep = ((w0 >> 23) & 0x1FF) | ((w1 & 0xFF) << 9)
            rec = {
                "ep": ep.astype(np.uint32),
                "d": ((w0 >> 22) & 1).astype(np.uint32),
                "idx": (w0 & np.uint32(0x3FFFFF)).astype(np.uint32),
                "dport": (w1 >> 16).astype(np.uint32),
                "proto": ((w1 >> 8) & 0xFF).astype(np.uint32),
                "j": (val >> 16).astype(np.uint32),
                "proxy": (val & 0xFFFF).astype(np.uint32),
            }
        else:
            cw0 = np.concatenate(
                [rows[:, :e].reshape(-1), stash[:, 0]]
            )
            cw1 = np.concatenate(
                [rows[:, e : 2 * e].reshape(-1), stash[:, 1]]
            )
            keep = (cw1 & L4C_EMPTY_W1) == 0
            cw0, cw1 = cw0[keep], cw1[keep]
            idx18 = cw0 & np.uint32(0x3FFFF)
            idx = np.where(
                idx18 == L4C_WILD_IDX18, L4H_WILD_IDX, idx18
            ).astype(np.uint32)
            ep = ((cw1 >> 10) & 0xFF).astype(np.uint32)
            d = ((cw1 >> 18) & 1).astype(np.uint32)
            j = ((cw1 >> 19) & 0xFFF).astype(np.uint32)
            rec = {
                "ep": ep,
                "d": d,
                "idx": idx,
                "dport": (
                    (cw0 >> 18) | ((cw1 & 3) << 14)
                ).astype(np.uint32),
                "proto": ((cw1 >> 2) & 0xFF).astype(np.uint32),
                "j": j,
                # proxy rides the l4_meta plane in the compact form
                "proxy": (
                    meta[
                        ep.astype(np.int64), d.astype(np.int64),
                        j.astype(np.int64),
                    ]
                    >> 1
                ).astype(np.uint32),
            }
        out[key] = rec
    return out


def repack_l4_subword(
    tables: "PolicyTables", lanes: int = L4C_LANES
) -> "PolicyTables":
    """Re-place both hashed entry tables in the SUB-WORD (2-word)
    layout — nibble/byte-packed verdict lanes for the lattice probe.
    Verdicts are identical by construction (keys compare exactly; the
    proxy port is reconstructed from the l4_meta plane, which the
    lowering keeps bit-equal to the entry's copy).  Raises ValueError
    when the world's ranges don't fit the compact fields (universe
    > 2^18-2 padded identities, > 256 endpoints, > 4096 L4 slots) —
    semantics first, the caller keeps the 3-word layout then.  The
    result's layout stamp differs, so delta publication refuses it
    (full upload), exactly like repack_hash_lanes."""
    import dataclasses

    n = int(tables.id_table.shape[0])
    e_count, _, kg = tables.l4_meta.shape
    if n > (1 << 18) - 2:
        raise ValueError(
            f"identity axis {n} exceeds the compact 18-bit idx field"
        )
    if e_count > 256:
        raise ValueError(
            f"endpoint axis {e_count} exceeds the compact 8-bit field"
        )
    if kg > (1 << 12):
        raise ValueError(
            f"L4 slot axis {kg} exceeds the compact 12-bit field"
        )
    recs = l4_entry_records(tables)
    meta = np.asarray(tables.l4_meta)
    out = {}
    for key, rows_leaf, stash_leaf, min_rows in (
        ("exact", "l4_hash_rows", "l4_hash_stash", 64),
        ("wild", "l4_wild_rows", "l4_wild_stash", 16),
    ):
        r = recs[key]
        # the compact form DROPS the per-entry proxy copy: verify the
        # l4_meta plane agrees (the lowering invariant) so the probe's
        # reconstruction is provably exact
        meta_proxy = (
            meta[
                r["ep"].astype(np.int64), r["d"].astype(np.int64),
                r["j"].astype(np.int64),
            ]
            >> 1
        )
        if not np.array_equal(meta_proxy, r["proxy"]):
            raise ValueError(
                "entry proxy diverges from the l4_meta plane — "
                "compact layout would change verdicts"
            )
        idx18 = np.where(
            r["idx"] == L4H_WILD_IDX, L4C_WILD_IDX18, r["idx"]
        ).astype(np.uint32)
        cw0 = l4c_key0(idx18, r["dport"])
        cw1k = l4c_key1(r["dport"], r["proto"], r["ep"], r["d"])
        h = _fnv1a_host_2(cw0, cw1k)
        rows, stash = place_l4_hash_compact(
            cw0, cw1k, r["j"], h, min_rows, lanes=lanes
        )
        out[rows_leaf] = rows
        out[stash_leaf] = trim_stash(stash)
    return dataclasses.replace(tables, **out)


def build_id_table(
    identity_ids: Sequence[int], identity_pad: int = 1024
) -> np.ndarray:
    """Sorted, padded identity universe (the shape-defining snapshot,
    reference getLabelsMap pkg/endpoint/policy.go:194)."""
    ids = sorted(set(int(i) for i in identity_ids))
    n = _round_up(len(ids), identity_pad)
    # Identity axis must stay a multiple of 32 for bit packing.
    n = _round_up(n, 32)
    table = np.full((n,), PAD_ID, dtype=np.uint32)
    table[: len(ids)] = np.asarray(ids, dtype=np.uint32)
    return table


def _build_direct_index(id_table: np.ndarray) -> Tuple[np.ndarray, int]:
    """One fused direct id→index table for the O(1) identity probe:
    [0, lo_len) maps cluster-scope ids, [lo_len, end) maps local CIDR
    ids offset by LOCAL_ID_BASE.  Returns (id_direct, lo_len)."""
    ids = id_table[id_table != PAD_ID].astype(np.int64)
    index = np.arange(len(ids), dtype=np.uint32)

    local_mask = ids >= LOCAL_ID_BASE
    lo_ids, lo_idx = ids[~local_mask], index[~local_mask]
    local_ids, local_idx = ids[local_mask] - LOCAL_ID_BASE, index[local_mask]

    lo_max = int(lo_ids.max()) + 1 if len(lo_ids) else 1
    ll_max = int(local_ids.max()) + 1 if len(local_ids) else 1
    if lo_max > MAX_DIRECT or ll_max > MAX_DIRECT:
        raise ValueError(
            f"identity id range too large for direct indexing "
            f"(lo={lo_max}, local={ll_max}, cap={MAX_DIRECT})"
        )
    lo_len = _pow2_at_least(lo_max, 1024)
    ll_len = _pow2_at_least(ll_max, 32)
    id_direct = np.full(lo_len + ll_len, NO_INDEX, dtype=np.uint32)
    id_direct[lo_ids] = lo_idx
    id_direct[lo_len + local_ids] = local_idx
    return id_direct, lo_len


def lower_map_state(
    states: Sequence[PolicyMapState],
    id_table: np.ndarray,
    filter_pad: int = 64,
    hash_lanes: int = L4H_LANES,
) -> PolicyTables:
    """Lower E desired map states onto a shared identity universe.

    Any state entry whose identity is absent from `id_table` would be
    unreachable in the reference too (the BPF map key could never be
    probed with that source identity derived from ipcache); we raise
    on it to surface compiler/universe skew early — the moral
    equivalent of pkg/alignchecker.
    """
    n = id_table.shape[0]
    if n >= int(L4H_WILD_IDX):
        raise ValueError(
            "identity axis too large for the hashed L4 probe "
            f"(n={n}, cap={int(L4H_WILD_IDX)})"
        )
    w = n // 32
    id_index: Dict[int, int] = {}
    for i, v in enumerate(id_table.tolist()):
        if v == int(PAD_ID):
            break
        id_index[v] = i
    id_direct, id_lo_len = _build_direct_index(id_table)

    e_count = len(states)

    # Global slot space: distinct (dport, proto) over all endpoints.
    all_keys = sorted(
        {
            (k.dest_port, k.nexthdr)
            for state in states
            for k in state
            if not k.is_l3_only()
        }
    )
    kg = _round_up(max(len(all_keys), 1), filter_pad)
    slot_of = {key: j for j, key in enumerate(all_keys)}

    port_slot = np.full((256, 65536), NO_SLOT, dtype=np.uint16)
    for (dport, proto), j in slot_of.items():
        port_slot[proto & 0xFF, dport] = j

    l4_meta = np.zeros((e_count, 2, kg), dtype=np.uint32)
    # Bits are set directly into the packed words — never materialize
    # the dense [E, 2, Kg, N] bool tensor.
    l4_allow_bits = np.zeros((e_count, 2, kg, w), dtype=np.uint32)
    l3_allow_bits = np.zeros((e_count, 2, w), dtype=np.uint32)

    def _id_idx(num_id: int) -> int:
        idx = id_index.get(num_id)
        if idx is None:
            raise ValueError(
                f"identity {num_id} in map state but not in the "
                f"identity universe (universe/table skew)"
            )
        return idx

    # Track per-(e,d,slot) proxy consistency: one L4Filter per
    # port/proto key in an L4PolicyMap (pkg/policy/l4.go:276), so one
    # proxy port; conflicting states can't be lowered without
    # diverging from the per-entry oracle.
    proxy_seen: Dict[Tuple[int, int, int], int] = {}

    # hashed entry-table columns (one row per non-L3 map entry)
    h_ep: List[int] = []
    h_d: List[int] = []
    h_idx: List[int] = []
    h_dport: List[int] = []
    h_proto: List[int] = []
    h_val: List[int] = []

    for e, state in enumerate(states):
        for key, entry in state.items():
            d = key.traffic_direction
            if key.is_l3_only():
                idx = _id_idx(key.identity)
                l3_allow_bits[e, d, idx >> 5] |= np.uint32(1 << (idx & 31))
                continue
            j = slot_of[(key.dest_port, key.nexthdr)]
            prev = proxy_seen.setdefault((e, d, j), entry.proxy_port)
            if prev != entry.proxy_port:
                raise ValueError(
                    f"conflicting proxy ports for endpoint {e} slot "
                    f"{(key.dest_port, key.nexthdr, d)}: "
                    f"{prev} vs {entry.proxy_port}"
                )
            l4_meta[e, d, j] |= np.uint32(entry.proxy_port << 1)
            if key.identity == 0:
                l4_meta[e, d, j] |= np.uint32(1)
                idx = int(L4H_WILD_IDX)
            else:
                idx = _id_idx(key.identity)
                l4_allow_bits[e, d, j, idx >> 5] |= np.uint32(
                    1 << (idx & 31)
                )
            h_ep.append(e)
            h_d.append(d)
            h_idx.append(idx)
            h_dport.append(key.dest_port)
            h_proto.append(key.nexthdr)
            h_val.append((j << 16) | entry.proxy_port)

    rows, stash, wrows, wstash = build_l4_hash_pair(
        np.asarray(h_ep, np.uint32),
        np.asarray(h_d, np.uint32),
        np.asarray(h_idx, np.uint32),
        np.asarray(h_dport, np.uint32),
        np.asarray(h_proto, np.uint32),
        np.asarray(h_val, np.uint32),
        lanes=hash_lanes,
    )
    return PolicyTables(
        id_table=id_table,
        id_direct=id_direct,
        id_lo_len=np.int32(id_lo_len),
        port_slot=port_slot,
        l4_meta=l4_meta,
        l4_allow_bits=l4_allow_bits,
        l3_allow_bits=l3_allow_bits,
        l4_hash_rows=rows,
        l4_hash_stash=stash,
        l4_wild_rows=wrows,
        l4_wild_stash=wstash,
    )


def compile_map_states(
    states: Sequence[PolicyMapState],
    identity_ids: Sequence[int],
    identity_pad: int = 1024,
    filter_pad: int = 64,
    hash_lanes: int = L4H_LANES,
) -> PolicyTables:
    """One-shot: build the shared identity table and lower E states."""
    return lower_map_state(
        states,
        build_id_table(identity_ids, identity_pad),
        filter_pad,
        hash_lanes=hash_lanes,
    )


class FleetCompiler:
    """Incremental fleet lowering — the delta-compilation seam.

    The one-shot path rebuilds everything per policy event: the 32 MB
    `port_slot`, the direct identity index, and every endpoint's bit
    rows — O(fleet) per event (SURVEY §7 hard part 4; the reference
    gates this per-endpoint with revision checks,
    pkg/endpoint/policy.go:540-552).  This compiler caches each piece
    keyed on what actually invalidates it:

      * identity universe — arrival-ordered, append-only: adding an
        identity appends an index instead of re-sorting, so existing
        bit rows stay valid.  Removing one forces a full reset (rare:
        identity GC).
      * L4 slot space — monotonic: new (dport, proto) keys append new
        slots; `port_slot` is copied-on-write only when keys appear.
      * per-endpoint rows — relowered only when the endpoint's
        `state_token` changes (the endpoint bumps it in
        sync_policy_map); stacked rows are padded up lazily when the
        identity/slot buckets grow.

    The produced PolicyTables are bit-compatible with the engine but
    NOT byte-identical to compile_map_states (slot and identity order
    differ); verdicts are identical — tests compare through the
    engine/oracle, never raw tables.
    """

    def __init__(
        self,
        identity_pad: int = 1024,
        filter_pad: int = 64,
        hash_lanes: int = L4H_LANES,
    ) -> None:
        self.identity_pad = identity_pad
        self.filter_pad = filter_pad
        # hot-plane pack width of the hashed entry tables; fixed for
        # the compiler's lifetime (the delta machinery's row/stash
        # state is lane-width-specific)
        self.hash_lanes = hash_lanes
        # publish generation: tables one generation old are intact
        # (double buffering); older ones may have been mutated in
        # place.  Survives _reset() — it counts publishes, not state.
        # The instance nonce scopes stamps to THIS compiler: stamps
        # from another FleetCompiler are not comparable and the check
        # must not apply its arithmetic to them.
        self._generation = 0
        self._instance_nonce = next(_COMPILER_NONCE)
        # compile() mutates every cache (slot table, universe, row
        # cache, stack buffers); callers run from both the daemon's
        # trigger thread and test/bench main threads, so serialize —
        # a concurrent _reset() mid-_lower_rows otherwise drops slots
        # out from under the lowering loop.
        self._compile_lock = threading.Lock()
        self._reset()

    def set_hash_lanes(self, lanes: int) -> None:
        """Online pack-width change (the autotuner's re-tune knob,
        applied WITHOUT a compiler reset): swap in a fresh
        IncrementalHashPair at the new width.  The fresh pair's
        empty row state forces build()'s full-rebuild branch on the
        next compile, so the produced tables carry a different
        layout stamp (tables_layout_version folds the lane counts)
        — the device store's layout guard then refuses the delta,
        full-uploads, and deltas resume on the publishes after.
        Everything else (identity universe, slot space, cached
        endpoint rows, the generation counter) is lane-agnostic and
        survives, so verdicts are identical by construction."""
        from cilium_tpu.compiler.delta import IncrementalHashPair

        with self._compile_lock:
            if int(lanes) == self.hash_lanes:
                return
            self.hash_lanes = int(lanes)
            self._hash_pair = IncrementalHashPair(
                lanes=self.hash_lanes
            )

    def _reset(self) -> None:
        from cilium_tpu.compiler.delta import IncrementalHashPair

        # monotone reset marker: a reset mid-compile (identity
        # removal) invalidates every delta precondition captured
        # before it
        self._reset_count = getattr(self, "_reset_count", 0) + 1
        self._id_list: List[int] = []
        self._id_index: Dict[int, int] = {}
        self._slot_of: Dict[Tuple[int, int], int] = {}
        self._slot_list: List[Tuple[int, int]] = []  # arrival order
        # delta-publication state: the incremental hashed-table pair,
        # the last compile's shape class, the per-publish change
        # records delta_for merges, and the caller-provided universe
        # version that short-circuits _sync_universe
        self._hash_pair = IncrementalHashPair(lanes=self.hash_lanes)
        self._shape_state: Optional[dict] = None
        self._pub_records = deque(maxlen=8)
        self._universe_token = None
        # (len, sorted_pairs, order) cache for _slot_pair_lut
        self._slot_lut_cache = None
        # double-buffered port_slot: each buffer tracks how many slots
        # it has applied; updates write only the new cells
        self._port_slot_bufs = [
            {
                "arr": np.full((256, 65536), NO_SLOT, dtype=np.uint16),
                "applied": 0,
            }
            for _ in range(2)
        ]
        self._port_slot_flip = 0
        # cached per-endpoint rows: ep_id → dict(token, kg, w, meta,
        # l4, l3)
        self._rows: Dict[int, dict] = {}
        # double-buffered stacked tensors (the realized/backup map
        # shuffle of pkg/datapath/ipcache/listener.go:167): each
        # buffer records the token its copy of every endpoint's rows
        # reflects, so a delta compile copies only rows that moved
        # since THIS buffer was last published.  Consumers may hold
        # the previously-published tables safely for one flip.
        self._stack_bufs: List[Optional[dict]] = [None, None]
        self._stack_flip = 0
        self._id_table: np.ndarray = None  # rebuilt lazily
        self._id_direct: np.ndarray = None
        self._id_lo_len: int = 0
        self._id_tables_dirty = True

    # -- identity universe ---------------------------------------------------

    def _sync_universe(self, identity_ids: Sequence[int]) -> None:
        want = set(int(i) for i in identity_ids)
        have = self._id_index.keys()
        if not want >= have:
            # removal: indices would shift — full reset
            self._reset()
            want = set(int(i) for i in identity_ids)
        new = want - self._id_index.keys()
        if new:
            for num_id in sorted(new):
                self._id_index[num_id] = len(self._id_list)
                self._id_list.append(num_id)
            self._id_tables_dirty = True

    def _padded_n(self) -> int:
        n = _round_up(
            max(len(self._id_list), 1), self.identity_pad
        )
        return _round_up(n, 32)

    def identity_index(self) -> Tuple[Dict[int, int], int]:
        """(identity id → dense index, padded identity count) for the
        CURRENT universe — the same index space as the produced
        tables' id_direct.  Consumers compiling parallel per-identity
        tensors (the L7 ident_rules) MUST use this, not a sorted
        rebuild, or their identity axes diverge from the engine's."""
        return dict(self._id_index), self._padded_n()

    def _ensure_id_tables(self) -> None:
        if not self._id_tables_dirty and self._id_table is not None:
            return
        n = self._padded_n()
        if n >= int(L4H_WILD_IDX):
            raise ValueError(
                "identity axis too large for the hashed L4 probe "
                f"(n={n}, cap={int(L4H_WILD_IDX)})"
            )
        table = np.full((n,), PAD_ID, dtype=np.uint32)
        table[: len(self._id_list)] = np.asarray(
            self._id_list, dtype=np.uint32
        )
        # arrival order ≠ sorted: build the direct index from the
        # arrival-ordered table (never via build_id_table, which sorts)
        ids = np.asarray(self._id_list, dtype=np.int64)
        index = np.arange(len(ids), dtype=np.uint32)
        local_mask = ids >= LOCAL_ID_BASE
        lo_ids, lo_idx = ids[~local_mask], index[~local_mask]
        local_ids = ids[local_mask] - LOCAL_ID_BASE
        local_idx = index[local_mask]
        lo_max = int(lo_ids.max()) + 1 if len(lo_ids) else 1
        ll_max = int(local_ids.max()) + 1 if len(local_ids) else 1
        if lo_max > MAX_DIRECT or ll_max > MAX_DIRECT:
            raise ValueError(
                f"identity id range too large for direct indexing "
                f"(lo={lo_max}, local={ll_max}, cap={MAX_DIRECT})"
            )
        lo_len = _pow2_at_least(lo_max, 1024)
        ll_len = _pow2_at_least(ll_max, 32)
        direct = np.full(lo_len + ll_len, NO_INDEX, dtype=np.uint32)
        direct[lo_ids] = lo_idx
        direct[lo_len + local_ids] = local_idx
        self._id_table = table
        self._id_direct = direct
        self._id_lo_len = lo_len
        self._id_tables_dirty = False

    # -- slot space ----------------------------------------------------------

    def _ensure_slots(self, state: PolicyMapState) -> bool:
        """Append slots for unseen (dport, proto) keys.  Returns True
        if the slot space grew."""
        grew = False
        if isinstance(state, MapStateArrays):
            _, dport, proto, _ = unpack_keys(state.keys_packed)
            nonl3 = (dport != 0) | (proto != 0)
            pairs = np.unique(
                (dport[nonl3].astype(np.int64) << 8) | proto[nonl3]
            )
            for p in pairs.tolist():
                key = (p >> 8, p & 0xFF)
                if key not in self._slot_of:
                    self._slot_of[key] = len(self._slot_list)
                    self._slot_list.append(key)
                    grew = True
            return grew
        for k in state:
            if k.is_l3_only():
                continue
            key = (k.dest_port, k.nexthdr)
            if key not in self._slot_of:
                self._slot_of[key] = len(self._slot_list)
                self._slot_list.append(key)
                grew = True
        return grew

    def _current_port_slot(self) -> np.ndarray:
        """Flip to the standby port_slot buffer and catch it up with
        the slots appended since it was last published (cells are
        written exactly once, so catching up is O(new slots))."""
        buf = self._port_slot_bufs[self._port_slot_flip]
        if buf["applied"] == len(self._slot_list):
            return buf["arr"]
        self._port_slot_flip ^= 1
        buf = self._port_slot_bufs[self._port_slot_flip]
        for j in range(buf["applied"], len(self._slot_list)):
            dport, proto = self._slot_list[j]
            buf["arr"][proto & 0xFF, dport] = j
        buf["applied"] = len(self._slot_list)
        return buf["arr"]

    def _padded_kg(self) -> int:
        return _round_up(
            max(len(self._slot_of), 1), self.filter_pad
        )

    def _slot_pair_lut(self) -> Tuple[np.ndarray, np.ndarray]:
        """(sorted packed (dport<<8|proto) pairs, slot index by sorted
        position) for the vectorized slot lookup — rebuilt only when
        the append-only slot list grows."""
        cached = self._slot_lut_cache
        if cached is not None and cached[0] == len(self._slot_list):
            return cached[1], cached[2]
        pairs = np.asarray(
            [(dp << 8) | pr for dp, pr in self._slot_list], np.int64
        )
        order = np.argsort(pairs, kind="stable")
        self._slot_lut_cache = (len(self._slot_list), pairs[order], order)
        return self._slot_lut_cache[1], self._slot_lut_cache[2]

    # -- per-endpoint rows ---------------------------------------------------

    def _lower_rows_arrays(self, state: MapStateArrays) -> dict:
        """Vectorized row lowering: the per-entry bit-set loop of
        _lower_rows as array scatters (np.bitwise_or.at) — no Python
        per-entry work.  Bit-identical rows to the dict path."""
        n = self._padded_n()
        w = n // 32
        kg = self._padded_kg()
        meta = np.zeros((2, kg), dtype=np.uint32)
        l4 = np.zeros((2, kg, w), dtype=np.uint32)
        l3 = np.zeros((2, w), dtype=np.uint32)
        m = len(state.keys_packed)
        empty_ent = {
            "d": np.zeros(0, np.uint32),
            "idx": np.zeros(0, np.uint32),
            "dport": np.zeros(0, np.uint32),
            "proto": np.zeros(0, np.uint32),
            "val": np.zeros(0, np.uint32),
        }
        if m == 0:
            return {
                "kg": kg, "w": w, "meta": meta, "l4": l4, "l3": l3,
                "ent": empty_ent,
            }

        ident, dport, proto, d = unpack_keys(state.keys_packed)
        l3_mask = (dport == 0) & (proto == 0)
        wild_mask = (ident == 0) & ~l3_mask

        # identity → dense index through the arrival-ordered direct
        # table (same derivation as the device _index kernel)
        ident64 = ident.astype(np.int64)
        is_local = ident64 >= LOCAL_ID_BASE
        pos = np.where(
            is_local,
            self._id_lo_len + ident64 - LOCAL_ID_BASE,
            ident64,
        )
        idx = np.full(m, NO_INDEX, dtype=np.uint32)
        in_range = (pos >= 0) & (pos < len(self._id_direct))
        idx[in_range] = self._id_direct[pos[in_range]]
        need_idx = ~wild_mask
        bad = need_idx & (idx == NO_INDEX)
        if bad.any():
            missing = int(ident[np.argmax(bad)])
            raise ValueError(
                f"identity {missing} in map state but not in the "
                f"identity universe (universe/table skew)"
            )
        word = (idx >> 5).astype(np.int64)
        bit = (np.uint32(1) << (idx & np.uint32(31))).astype(np.uint32)

        # -- L3-only rows -------------------------------------------------
        sel3 = l3_mask
        np.bitwise_or.at(
            l3.reshape(-1),
            d[sel3].astype(np.int64) * w + word[sel3],
            bit[sel3],
        )

        # -- L4 slots -----------------------------------------------------
        sel4 = ~l3_mask
        ent = empty_ent
        if sel4.any():
            sorted_pairs, order = self._slot_pair_lut()
            pair = (dport[sel4].astype(np.int64) << 8) | proto[sel4]
            j = order[np.searchsorted(sorted_pairs, pair)].astype(
                np.int64
            )
            dj = d[sel4].astype(np.int64) * kg + j
            proxy4 = state.proxy[sel4].astype(np.int64)
            # proxy-consistency check (one L4Filter per slot): any slot
            # carrying two distinct proxy values is a lowering error
            uniq_pairs = np.unique(
                np.stack([dj, proxy4], axis=1), axis=0
            )
            if len(np.unique(uniq_pairs[:, 0])) != len(uniq_pairs):
                dup = uniq_pairs[:, 0][
                    np.nonzero(np.diff(uniq_pairs[:, 0]) == 0)[0][0]
                ]
                jj = int(dup) % kg
                dd = int(dup) // kg
                dpp, prr = self._slot_list[jj]
                raise ValueError(
                    f"conflicting proxy ports for slot "
                    f"{(dpp, prr, dd)}"
                )
            np.bitwise_or.at(
                meta.reshape(-1),
                dj,
                (proxy4.astype(np.uint32) << np.uint32(1))
                | wild_mask[sel4].astype(np.uint32),
            )
            setbits = sel4 & ~wild_mask
            if setbits.any():
                dj_all = np.full(m, -1, np.int64)
                dj_all[sel4] = dj
                np.bitwise_or.at(
                    l4.reshape(-1),
                    (dj_all[setbits]) * w + word[setbits],
                    bit[setbits],
                )
            # hashed-probe entry columns (ep bits added at stack time
            # — the endpoint's stack position is not known here)
            ent_idx = idx[sel4].copy()
            ent_idx[wild_mask[sel4]] = L4H_WILD_IDX
            ent = {
                "d": d[sel4].astype(np.uint32),
                "idx": ent_idx.astype(np.uint32),
                "dport": dport[sel4].astype(np.uint32),
                "proto": proto[sel4].astype(np.uint32),
                "val": (
                    (j.astype(np.uint32) << np.uint32(16))
                    | proxy4.astype(np.uint32)
                ),
            }
        return {
            "kg": kg, "w": w, "meta": meta, "l4": l4, "l3": l3,
            "ent": ent,
        }

    def _lower_rows(self, state: PolicyMapState) -> dict:
        if isinstance(state, MapStateArrays):
            return self._lower_rows_arrays(state)
        n = self._padded_n()
        w = n // 32
        kg = self._padded_kg()
        meta = np.zeros((2, kg), dtype=np.uint32)
        l4 = np.zeros((2, kg, w), dtype=np.uint32)
        l3 = np.zeros((2, w), dtype=np.uint32)
        proxy_seen: Dict[Tuple[int, int], int] = {}
        h_d: List[int] = []
        h_idx: List[int] = []
        h_dport: List[int] = []
        h_proto: List[int] = []
        h_val: List[int] = []
        for key, entry in state.items():
            d = key.traffic_direction
            if key.is_l3_only():
                idx = self._id_index.get(key.identity)
                if idx is None:
                    raise ValueError(
                        f"identity {key.identity} in map state but not "
                        f"in the identity universe (universe/table skew)"
                    )
                l3[d, idx >> 5] |= np.uint32(1 << (idx & 31))
                continue
            j = self._slot_of[(key.dest_port, key.nexthdr)]
            prev = proxy_seen.setdefault((d, j), entry.proxy_port)
            if prev != entry.proxy_port:
                raise ValueError(
                    f"conflicting proxy ports for slot "
                    f"{(key.dest_port, key.nexthdr, d)}: "
                    f"{prev} vs {entry.proxy_port}"
                )
            meta[d, j] |= np.uint32(entry.proxy_port << 1)
            if key.identity == 0:
                meta[d, j] |= np.uint32(1)
                idx = int(L4H_WILD_IDX)
            else:
                idx = self._id_index.get(key.identity)
                if idx is None:
                    raise ValueError(
                        f"identity {key.identity} in map state but not "
                        f"in the identity universe (universe/table skew)"
                    )
                l4[d, j, idx >> 5] |= np.uint32(1 << (idx & 31))
            h_d.append(d)
            h_idx.append(idx)
            h_dport.append(key.dest_port)
            h_proto.append(key.nexthdr)
            h_val.append((j << 16) | entry.proxy_port)
        ent = {
            "d": np.asarray(h_d, np.uint32),
            "idx": np.asarray(h_idx, np.uint32),
            "dport": np.asarray(h_dport, np.uint32),
            "proto": np.asarray(h_proto, np.uint32),
            "val": np.asarray(h_val, np.uint32),
        }
        return {
            "kg": kg, "w": w, "meta": meta, "l4": l4, "l3": l3,
            "ent": ent,
        }

    @staticmethod
    def _pad_rows(rows: dict, kg: int, w: int) -> dict:
        """Grow cached rows to the current buckets (zero columns for
        new slots / identity words keep old bits valid)."""
        if rows["kg"] == kg and rows["w"] == w:
            return rows
        dk, dw = kg - rows["kg"], w - rows["w"]
        rows = dict(
            rows,
            kg=kg,
            w=w,
            meta=np.pad(rows["meta"], ((0, 0), (0, dk))),
            l4=np.pad(rows["l4"], ((0, 0), (0, dk), (0, dw))),
            l3=np.pad(rows["l3"], ((0, 0), (0, dw))),
        )
        return rows

    # -- compile -------------------------------------------------------------

    def compile(
        self,
        endpoints: Sequence[Tuple[int, PolicyMapState, int]],
        identity_ids: Sequence[int],
        universe_token=None,
    ) -> Tuple[PolicyTables, Dict[int, int]]:
        """Lower the fleet incrementally.

        `endpoints` is [(ep_id, realized_map_state, state_token)];
        rows are relowered only when the token differs from the cached
        one.  `universe_token`, when provided, is the caller's version
        stamp of `identity_ids` (the identity-allocator version): a
        compile whose token matches the previous one skips the
        O(universe) identity-set diff entirely — the caller warrants
        the id set is unchanged.  Returns (tables, ep_id →
        endpoint-axis index).
        """
        from cilium_tpu import tracing

        with self._compile_lock, tracing.tracer.span(
            "compiler.compile", site="compiler",
            attrs={"endpoints": len(endpoints)},
        ) as sp:
            tables, index = self._compile_locked(
                endpoints, identity_ids, universe_token
            )
            sp.attrs["identities"] = len(self._id_list)
            sp.attrs["slots"] = len(self._slot_list)
            return tables, index

    def _compile_locked(
        self,
        endpoints: Sequence[Tuple[int, PolicyMapState, int]],
        identity_ids: Sequence[int],
        universe_token=None,
    ) -> Tuple[PolicyTables, Dict[int, int]]:
        prev_id_len = len(self._id_list)
        prev_slots = len(self._slot_list)
        shape_prev = self._shape_state
        reset_before = self._reset_count
        if (
            universe_token is None
            or self._universe_token is None
            or universe_token != self._universe_token
        ):
            self._sync_universe(identity_ids)
            if self._reset_count != reset_before:  # _reset() ran
                prev_id_len = 0
                prev_slots = 0
                shape_prev = None
            self._universe_token = universe_token

        live = {ep_id for ep_id, _, _ in endpoints}
        for gone in set(self._rows) - live:
            del self._rows[gone]

        dirty = []
        for ep_id, state, token in endpoints:
            cached = self._rows.get(ep_id)
            if cached is None or cached["token"] != token:
                dirty.append((ep_id, state, token))
                self._ensure_slots(state)

        self._ensure_id_tables()
        n = self._padded_n()
        w = n // 32
        kg = self._padded_kg()

        for ep_id, state, token in dirty:
            rows = self._lower_rows(state)
            rows["token"] = token
            self._rows[ep_id] = rows

        order = sorted(live)
        index = {ep_id: i for i, ep_id in enumerate(order)}
        if order:
            for ep_id in order:
                self._rows[ep_id] = self._pad_rows(
                    self._rows[ep_id], kg, w
                )
            l4_meta, l4_bits, l3_bits = self._stacked(order, kg, w)
        else:
            l4_meta = np.zeros((1, 2, kg), dtype=np.uint32)
            l4_bits = np.zeros((1, 2, kg, w), dtype=np.uint32)
            l3_bits = np.zeros((1, 2, w), dtype=np.uint32)

        (hash_rows, hash_stash, wild_rows, wild_stash), hash_info = (
            self._hash_pair.build(
                order, self._rows, [ep_id for ep_id, _, _ in dirty]
            )
        )
        tables = PolicyTables(
            id_table=self._id_table,
            id_direct=self._id_direct,
            id_lo_len=np.int32(self._id_lo_len),
            port_slot=self._current_port_slot(),
            l4_meta=l4_meta,
            l4_allow_bits=l4_bits,
            l3_allow_bits=l3_bits,
            l4_hash_rows=hash_rows,
            l4_hash_stash=hash_stash,
            l4_wild_rows=wild_rows,
            l4_wild_stash=wild_stash,
        )
        self._generation += 1
        tables.generation = np.uint64(
            (self._instance_nonce << 32) | self._generation
        )
        self._record_publish(
            shape_prev, order, index, dirty, n, w, kg,
            prev_id_len, prev_slots, hash_info,
        )
        return tables, index

    # -- delta publication records -------------------------------------------

    def _record_publish(
        self,
        shape_prev: Optional[dict],
        order: List[int],
        index: Dict[int, int],
        dirty: list,
        n: int,
        w: int,
        kg: int,
        prev_id_len: int,
        prev_slots: int,
        hash_info: dict,
    ) -> None:
        """Append the per-publish change record delta_for merges: per
        leaf, either the indices that changed since the previous
        publish or None (= the leaf's shape class moved and it must
        ship whole)."""
        order_t = tuple(order)
        direct_len = (
            len(self._id_direct) if self._id_direct is not None else 0
        )
        shape_now = {
            "order": order_t, "kg": kg, "w": w, "n": n,
            "direct_len": direct_len, "lo_len": self._id_lo_len,
        }
        stack_full = (
            shape_prev is None
            or shape_prev["order"] != order_t
            or shape_prev["kg"] != kg
            or shape_prev["w"] != w
        )
        id_full = shape_prev is None or shape_prev["n"] != n
        direct_full = (
            shape_prev is None
            or shape_prev["direct_len"] != direct_len
            or shape_prev["lo_len"] != self._id_lo_len
        )
        new_ids = self._id_list[prev_id_len:]
        direct_pos = None
        if not direct_full:
            direct_pos = np.asarray(
                [
                    i if i < LOCAL_ID_BASE
                    else self._id_lo_len + i - LOCAL_ID_BASE
                    for i in new_ids
                ],
                np.int64,
            )
        rec = {
            "gen": self._generation,
            "stack": (
                None if stack_full
                else sorted({index[ep_id] for ep_id, _, _ in dirty})
            ),
            "id_table": (
                None if id_full else (prev_id_len, len(self._id_list))
            ),
            "id_direct": direct_pos,
            "slots": (prev_slots, len(self._slot_list)),
            "hash_exact": hash_info.get("exact"),
            "hash_exact_stash": hash_info.get("exact_stash", True),
            "hash_wild": hash_info.get("wild"),
            "hash_wild_stash": hash_info.get("wild_stash", True),
        }
        self._pub_records.append(rec)
        self._shape_state = shape_now

    def delta_for(
        self, base_stamp: Optional[int], tables: PolicyTables
    ):
        """TableDelta describing every change from the publish stamped
        `base_stamp` to `tables` (which must be THIS compiler's most
        recent compile), or None when no delta can be derived (unknown
        base, record gap, different compiler instance) and the caller
        must full-upload.  Scatter values are fresh copies taken from
        `tables` — safe to ship asynchronously."""
        from cilium_tpu import tracing
        from cilium_tpu.compiler.delta import LeafUpdate, TableDelta

        with self._compile_lock, tracing.tracer.span(
            "compiler.delta_for", site="compiler"
        ):
            if not base_stamp:
                return None
            if (base_stamp >> 32) != self._instance_nonce:
                return None
            cur_stamp = int(np.asarray(tables.generation))
            if cur_stamp != (
                (self._instance_nonce << 32) | self._generation
            ):
                return None
            layout = tables_layout_version(tables)
            base_gen = base_stamp & 0xFFFFFFFF
            if base_gen == self._generation:
                return TableDelta(base_stamp, cur_stamp, layout=layout)
            recs = [
                r for r in self._pub_records
                if base_gen < r["gen"] <= self._generation
            ]
            if len(recs) != self._generation - base_gen:
                return None  # record gap (reset or deque overflow)
            delta = TableDelta(base_stamp, cur_stamp, layout=layout)
            delta.replace["generation"] = np.uint64(cur_stamp)

            def scatter1(name, arr, idx_list):
                idx = np.asarray(sorted(idx_list), np.int64)
                if len(idx):
                    delta.updates[name] = LeafUpdate(
                        (idx,), arr[idx]
                    )

            # stacked per-endpoint rows
            if any(r["stack"] is None for r in recs):
                delta.replace["l4_meta"] = tables.l4_meta
                delta.replace["l4_allow_bits"] = tables.l4_allow_bits
                delta.replace["l3_allow_bits"] = tables.l3_allow_bits
            else:
                rows = set()
                for r in recs:
                    rows.update(r["stack"])
                scatter1("l4_meta", tables.l4_meta, rows)
                scatter1("l4_allow_bits", tables.l4_allow_bits, rows)
                scatter1("l3_allow_bits", tables.l3_allow_bits, rows)

            # identity universe
            if any(r["id_table"] is None for r in recs):
                delta.replace["id_table"] = tables.id_table
            else:
                lo = min(r["id_table"][0] for r in recs)
                hi = max(r["id_table"][1] for r in recs)
                if hi > lo:
                    delta.updates["id_table"] = LeafUpdate(
                        (np.arange(lo, hi, dtype=np.int64),),
                        tables.id_table[lo:hi].copy(),
                    )
            if any(r["id_direct"] is None for r in recs):
                delta.replace["id_direct"] = tables.id_direct
                delta.replace["id_lo_len"] = np.int32(
                    self._id_lo_len
                )
            else:
                pos = np.unique(
                    np.concatenate(
                        [r["id_direct"] for r in recs]
                        + [np.zeros(0, np.int64)]
                    )
                )
                if len(pos):
                    delta.updates["id_direct"] = LeafUpdate(
                        (pos,), tables.id_direct[pos]
                    )

            # (proto, dport) → slot cells: append-only, write-once
            slot_lo = min(r["slots"][0] for r in recs)
            slot_hi = max(r["slots"][1] for r in recs)
            if slot_hi > slot_lo:
                cells = self._slot_list[slot_lo:slot_hi]
                delta.updates["port_slot"] = LeafUpdate(
                    (
                        np.asarray(
                            [pr & 0xFF for _, pr in cells], np.int64
                        ),
                        np.asarray([dp for dp, _ in cells], np.int64),
                    ),
                    np.arange(slot_lo, slot_hi, dtype=np.uint16),
                )

            # hashed entry tables
            for leaf, stash_leaf, key in (
                ("l4_hash_rows", "l4_hash_stash", "hash_exact"),
                ("l4_wild_rows", "l4_wild_stash", "hash_wild"),
            ):
                arr = getattr(tables, leaf)
                if any(r[key] is None for r in recs) or (
                    arr.shape[0]
                    != getattr(
                        self._hash_pair,
                        "exact" if key == "hash_exact" else "wild",
                    ).n_rows
                ):
                    delta.replace[leaf] = arr
                    delta.replace[stash_leaf] = getattr(
                        tables, stash_leaf
                    )
                    continue
                rows = set()
                for r in recs:
                    rows.update(r[key])
                scatter1(leaf, arr, rows)
                if any(r[key + "_stash"] for r in recs):
                    delta.replace[stash_leaf] = getattr(
                        tables, stash_leaf
                    )
            return delta

    def check_tables_current(self, tables) -> None:
        """Enforce the documented one-flip staleness window on the
        STACKED tensors (l4_meta/l4_allow_bits/l3_allow_bits): tables
        produced two or more compiles ago share stack buffers that
        have been rewritten in place — evaluating flows against them
        returns wrong verdicts silently.  (id_table/id_direct are
        freshly allocated per rebuild and port_slot cells are
        write-once, so *reading the index tables* of a stale snapshot
        stays safe; the hazard is the per-endpoint rows.)

        Raises ValueError on violation; tables without a generation
        stamp (hand-built via lower_map_state, generation=0) or
        stamped by a different FleetCompiler instance are accepted —
        the stamp is instance-scoped.  It is a pytree child, so it
        survives device_put / flatten round trips."""
        raw = getattr(tables, "generation", None)
        stamp = int(np.asarray(raw)) if raw is not None else 0
        if stamp == 0 or (stamp >> 32) != self._instance_nonce:
            return
        gen = stamp & 0xFFFFFFFF
        if self._generation - gen > 1:
            raise ValueError(
                f"stale PolicyTables: generation {gen} is "
                f"{self._generation - gen} publishes old (max 1 — "
                f"double-buffered rows have been overwritten)"
            )

    def _stacked(self, order: List[int], kg: int, w: int):
        """Write rows into the standby stacked buffer, copying only
        endpoints whose token differs from what this buffer already
        holds.  A full np.stack happens only when the endpoint set or
        the padded shapes change."""
        self._stack_flip ^= 1
        buf = self._stack_bufs[self._stack_flip]
        shape_key = (tuple(order), kg, w)
        if buf is None or buf["shape_key"] != shape_key:
            e = len(order)
            buf = {
                "shape_key": shape_key,
                "meta": np.empty((e, 2, kg), dtype=np.uint32),
                "l4": np.empty((e, 2, kg, w), dtype=np.uint32),
                "l3": np.empty((e, 2, w), dtype=np.uint32),
                "tokens": {},
            }
            self._stack_bufs[self._stack_flip] = buf
        tokens = buf["tokens"]
        for i, ep_id in enumerate(order):
            rows = self._rows[ep_id]
            if tokens.get(ep_id) == rows["token"]:
                continue
            buf["meta"][i] = rows["meta"]
            buf["l4"][i] = rows["l4"]
            buf["l3"][i] = rows["l3"]
            tokens[ep_id] = rows["token"]
        # pre-warm the standby buffer: its first full clone happens
        # at full-(re)stack time, so the next publish copies only the
        # endpoints dirtied in between instead of the whole fleet
        other_i = self._stack_flip ^ 1
        other = self._stack_bufs[other_i]
        if other is None or other["shape_key"] != shape_key:
            self._stack_bufs[other_i] = {
                "shape_key": shape_key,
                "meta": buf["meta"].copy(),
                "l4": buf["l4"].copy(),
                "l3": buf["l3"].copy(),
                "tokens": dict(tokens),
            }
        return buf["meta"], buf["l4"], buf["l3"]
