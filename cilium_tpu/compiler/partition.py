"""Declarative per-leaf partition rules for the device table pytrees.

The replicated layout (engine/sharded.replicated_table_shardings) caps
the identity universe at ONE chip's HBM: every chip holds every leaf,
so a 50k-rule/65k-identity world costs ~442 MB on each chip and a
mesh buys zero capacity.  This module is the t5x-style answer
(PAPERS.md [1], arXiv:2203.17189): a REGEX RULE TABLE matched over
the named pytree — `match_partition_rules` + `named_tree_map`, the
SNIPPETS.md [2]/[3] pattern — instead of hand-placed shardings, with
`replicated` as the explicit fallback so small leaves (stashes, the
identity index tables, DFA transition tables) stay replicated while
the identity-major leaves shard:

  * `l4_hash_rows`     — the hashed L4 entry plane, sharded along the
                         bucket-row axis (each chip owns a contiguous
                         row slice; the probe routes each tuple's
                         bucket to its owning shard);
  * `l3_allow_bits`    — the L3-only lattice rows, sharded along the
                         identity WORD axis (the layout the 2D mesh
                         evaluator already combines with a psum);
  * `l4_allow_bits`    — the dense allow bitmap (the cold fallback
                         plane), same word axis;
  * ipcache `buckets`  — the /32 prefix-row plane, bucket-row axis.

Everything else — `id_table`/`id_direct` (a few MB even at 512k ids),
`port_slot`, stashes, `l4_wild_rows` (per-(ep,port) — identity-free
and tiny), scalars — matches the fallback rule and replicates.

The rule table is DATA: `partition_digest` hashes it into a stamp the
device store folds into its epoch layout, so a delta recorded against
one partitioning can never scatter into an epoch laid out under
another.
"""

from __future__ import annotations

import re
import zlib
from typing import List, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The mesh axis the identity-major leaves shard along (the existing
# 2D (batch × table) mesh of engine/sharded.py).
TABLE_AXIS = "table"


def tree_path_to_string(path, sep: str = "/") -> str:
    """jax key-path → 'a/b/c' (SNIPPETS.md [3] tree_path_to_string)."""
    keys = []
    for key in path:
        if isinstance(key, jax.tree_util.SequenceKey):
            keys.append(str(key.idx))
        elif isinstance(key, jax.tree_util.DictKey):
            keys.append(str(key.key))
        elif isinstance(key, jax.tree_util.GetAttrKey):
            keys.append(str(key.name))
        elif isinstance(key, jax.tree_util.FlattenedIndexKey):
            keys.append(str(key.key))
        else:
            keys.append(str(key))
    return sep.join(keys)


def named_tree_map(f, tree, *rest, is_leaf=None, sep: str = "/"):
    """tree_map where `f` receives (path-string, leaf, *rest-leaves) —
    the extended tree_map of SNIPPETS.md [2]/[3].  For dict/list
    pytrees the names are real key paths; the registered table
    dataclasses flatten positionally, so the helpers below pair their
    children with the explicit *_LEAF_NAMES tables instead."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x, *r: f(tree_path_to_string(path, sep=sep), x, *r),
        tree,
        *rest,
        is_leaf=is_leaf,
    )


# Child order of the registered table pytrees (== tree_flatten order;
# the pytrees flatten positionally, so the names live here, beside
# the rules that consume them).
POLICY_LEAF_NAMES = (
    "id_table", "id_direct", "id_lo_len", "port_slot", "l4_meta",
    "l4_allow_bits", "l3_allow_bits", "generation", "l4_hash_rows",
    "l4_hash_stash", "l4_wild_rows", "l4_wild_stash",
)
IPCACHE_LEAF_NAMES = (
    "buckets", "stash", "range_base", "range_mask", "range_plen",
    "range_value", "range_l3_in", "range_l3_out", "range_rows",
)


# -- the rule tables ---------------------------------------------------------
# (regex, PartitionSpec) pairs, first match wins; the final catch-all
# IS the replicated fallback — explicit, so a new leaf added to
# PolicyTables replicates by default instead of failing to place.


def default_table_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """The PolicyTables rule table (identity-major leaves sharded)."""
    return [
        # dense allow bitmap [E, 2, Kg, W]: identity WORD axis
        (r"^l4_allow_bits$", P(None, None, None, table_axis)),
        # L3-only rows [E, 2, W]: identity WORD axis
        (r"^l3_allow_bits$", P(None, None, table_axis)),
        # hashed L4 entry plane [R, lanes]: bucket-row axis (the row
        # count is pow2 and identities spread uniformly by hash, so
        # equal row slices carry near-equal entry loads)
        (r"^l4_hash_rows$", P(table_axis)),
        # wild rows are per-(ep, dir, port, proto) — identity-free and
        # a few KB; stashes are ≤64 rows: replicated (the fallback
        # would catch them too, but the intent is worth spelling out)
        (r"^l4_(wild_rows|hash_stash|wild_stash)$", P()),
        # replicated fallback: id tables, port_slot, generation, ...
        (r".*", P()),
    ]


def default_ipcache_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """IPCacheDevice rule table: the /32 bucket-row plane shards; the
    small range-class plane and stash replicate."""
    return [
        (r"^buckets$", P(table_axis)),
        (r".*", P()),
    ]


def match_partition_rules(
    rules: Sequence[tuple], names: Sequence[str], leaves: Sequence
) -> list:
    """PartitionSpec per leaf: each `names[i]` is matched against
    `rules` in order; scalars/0-d/None leaves never partition.
    Unmatched leaves raise — the catch-all fallback rule makes that
    unreachable for the default tables."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = []
    for name, leaf in zip(names, leaves):
        if leaf is None:
            out.append(P())
            continue
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            out.append(P())  # never partition scalars
            continue
        for rx, spec in compiled:
            if rx.search(name) is not None:
                out.append(spec)
                break
        else:
            raise ValueError(
                f"partition rule not found for leaf: {name}"
            )
    return out


def policy_partition_specs(tables, table_axis: str = TABLE_AXIS):
    """PartitionSpecs for a PolicyTables pytree under the default
    rule table, as a PolicyTables of specs (shape-aware: scalars
    replicate regardless of rules)."""
    children, _ = tables.tree_flatten()
    specs = match_partition_rules(
        default_table_rules(table_axis), POLICY_LEAF_NAMES, children
    )
    return type(tables).tree_unflatten(None, tuple(specs))


def ipcache_partition_specs(dev, table_axis: str = TABLE_AXIS):
    """PartitionSpecs for an IPCacheDevice (or replicated specs for
    the DIR-24-8 fallback form)."""
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    if not isinstance(dev, IPCacheDevice):
        children, aux = dev.tree_flatten()
        return type(dev).tree_unflatten(
            aux, tuple(P() for _ in children)
        )
    children, aux = dev.tree_flatten()
    specs = match_partition_rules(
        default_ipcache_rules(table_axis), IPCACHE_LEAF_NAMES, children
    )
    return type(dev).tree_unflatten(aux, tuple(specs))


def partition_digest(rules: Sequence[tuple]) -> int:
    """Stable 32-bit digest of a rule table — folded into the device
    store's epoch layout stamp so cross-partitioning deltas are
    refused (engine/publish.DeviceTableStore)."""
    text = ";".join(
        f"{pat}->{tuple(spec)}" for pat, spec in rules
    ).encode()
    return zlib.crc32(text) & 0xFFFFFFFF


def _divisible(spec: P, shape, ntp: int, table_axis: str) -> bool:
    for axis, name in enumerate(spec):
        if name == table_axis and (
            axis >= len(shape) or shape[axis] % ntp != 0
        ):
            return False
    return True


def divisible_partition_specs(
    tables, ntp: int, table_axis: str = TABLE_AXIS
):
    """policy_partition_specs with the shard-axis divisibility check
    applied: leaves whose sharded axis does not split evenly over
    `ntp` shards fall back to replicated (the shard_map evaluator and
    the store must agree on this, so it lives in the rule layer)."""
    specs = policy_partition_specs(tables, table_axis)
    spec_children, _ = specs.tree_flatten()
    leaf_children, _ = tables.tree_flatten()
    out = []
    for spec, leaf in zip(spec_children, leaf_children):
        if leaf is None or not _divisible(
            spec, getattr(leaf, "shape", ()), ntp, table_axis
        ):
            spec = P()
        out.append(spec)
    return type(tables).tree_unflatten(None, tuple(out))


def table_shardings(mesh: Mesh, tables, table_axis: str = TABLE_AXIS):
    """NamedShardings pytree for device_put / DeviceTableStore: the
    default rule table resolved against `mesh`.  Leaves whose sharded
    axis does not divide by the mesh's table-axis size fall back to
    replicated (correctness first; tools/shardprof.py reports it)."""
    specs = divisible_partition_specs(
        tables, int(mesh.shape[table_axis]), table_axis
    )
    spec_children, _ = specs.tree_flatten()
    out = tuple(NamedSharding(mesh, s) for s in spec_children)
    return type(tables).tree_unflatten(None, out)


# -- N+1 shard replicas (per-chip failover placement) ------------------------
#
# The t5x lesson — placement rules are DATA — applied to failure
# domains: each identity-sharded leaf's rows also live on a BACKUP
# owner, the next shard over (slice i's replica sits on shard
# (i+1) % ntp), so a chip whose breaker opens takes down one COPY of
# its rows, not the rows themselves.  The failover evaluator
# (engine/sharded.make_failover_evaluator) routes a tuple's gather to
# the backup region when the primary owner is dead; the host lattice
# fold remains the terminal fallback only when primary AND backup are
# both gone.
#
# Replication applies to the leaves the ROUTED evaluator gathers —
# the hashed L4 entry rows and the L3 bit-words.  The dense
# l4_allow_bits fallback plane stays single-sharded: nothing on the
# hashed hot path reads it, and doubling the largest leaf would spend
# the replica HBM budget on rows no routed gather can reach.

REPLICA_LEAVES = ("l4_hash_rows", "l3_allow_bits")
# slice i's backup owner is shard (i + REPLICA_BACKUP_OFFSET) % ntp
REPLICA_BACKUP_OFFSET = 1


def replica_axes(tables, ntp: int, table_axis: str = TABLE_AXIS):
    """{leaf name: sharded-axis position} for the leaves the replica
    layout augments: REPLICA_LEAVES that the divisibility-checked
    rule layer actually shards at `ntp` (an indivisible leaf falls
    back to replicated and needs no backup copy)."""
    specs = divisible_partition_specs(tables, ntp, table_axis)
    out = {}
    for name in REPLICA_LEAVES:
        spec = getattr(specs, name)
        for axis, ax_name in enumerate(spec):
            if ax_name == table_axis:
                out[name] = axis
                break
    return out


def replicate_shard_axis(arr, ntp: int, axis: int):
    """Augment one sharded leaf with its backup copies: the sharded
    axis [S] becomes [2S], laid out per shard q as
    [primary slice q ; copy of slice (q - 1) % ntp] — so a
    NamedSharding along the same axis gives every chip its own rows
    plus its left neighbour's, and the in-kernel backup gather is
    `n + (i mod n)` on shard (owner + 1) % ntp."""
    arr = np.asarray(arr)
    n = arr.shape[axis] // ntp
    slices = [
        np.take(arr, np.arange(q * n, (q + 1) * n), axis=axis)
        for q in range(ntp)
    ]
    parts = []
    for q in range(ntp):
        parts.append(slices[q])
        parts.append(slices[(q - REPLICA_BACKUP_OFFSET) % ntp])
    return np.concatenate(parts, axis=axis)


def replicate_table_leaves(tables, ntp: int,
                           table_axis: str = TABLE_AXIS):
    """PolicyTables with every replica-rule leaf augmented (the
    device layout the replica store publishes); non-replica leaves
    pass through untouched."""
    import dataclasses

    axes = replica_axes(tables, ntp, table_axis)
    return dataclasses.replace(
        tables,
        **{
            name: replicate_shard_axis(
                getattr(tables, name), ntp, axis
            )
            for name, axis in axes.items()
        },
    )


def replica_positions(idx, n: int, ntp: int):
    """Map original global sharded-axis indices to their two
    positions in the augmented layout: (primary, backup)."""
    idx = np.asarray(idx)
    shard = idx // n
    within = idx % n
    primary = shard * (2 * n) + within
    backup = (
        ((shard + REPLICA_BACKUP_OFFSET) % ntp) * (2 * n)
        + n
        + within
    )
    return primary, backup


def replica_delta(delta, tables, ntp: int,
                  table_axis: str = TABLE_AXIS):
    """Rewrite a TableDelta recorded against the un-augmented layout
    into augmented coordinates, so one delta publish keeps primary
    and backup copies bit-identical.  Two shapes of update exist:

      * the scatter INDEXES the sharded axis (l4_hash_rows: idx[0]
        is the bucket row) — every row lands twice, at its primary
        and backup augmented positions, values repeated;
      * the scatter indexes LEADING axes only and its values SPAN
        the sharded axis (l3_allow_bits: idx is the endpoint, values
        are whole [2, W] slabs) — the values augment along the
        corresponding value axis, exactly as the resident leaf did.

    Whole-leaf replacements of replica leaves ship in augmented
    form; leaves outside the replica set pass through untouched."""
    from cilium_tpu.compiler.delta import LeafUpdate, TableDelta

    axes = replica_axes(tables, ntp, table_axis)
    updates = {}
    for name, up in delta.updates.items():
        axis = axes.get(name)
        if axis is None:
            updates[name] = up
            continue
        n = getattr(tables, name).shape[axis] // ntp
        if axis < len(up.idx):
            primary, backup = replica_positions(
                up.idx[axis], n, ntp
            )
            idx = tuple(
                np.concatenate([primary, backup])
                if i == axis
                else np.concatenate([comp, comp])
                for i, comp in enumerate(up.idx)
            )
            values = np.concatenate([up.values, up.values], axis=0)
        else:
            # leaf axis `axis` sits inside the values: idx consumes
            # the first len(idx) leaf axes, the values' axis 0 is
            # the scatter row, so leaf axis a maps to values axis
            # a - len(idx) + 1
            idx = up.idx
            values = replicate_shard_axis(
                up.values, ntp, axis - len(up.idx) + 1
            )
        updates[name] = LeafUpdate(idx=idx, values=values)
    replace = {
        name: (
            replicate_shard_axis(arr, ntp, axes[name])
            if name in axes
            else arr
        )
        for name, arr in delta.replace.items()
    }
    return TableDelta(
        base_stamp=delta.base_stamp,
        new_stamp=delta.new_stamp,
        updates=updates,
        replace=replace,
        layout=delta.layout,
    )


def replica_partition_digest(table_axis: str = TABLE_AXIS) -> int:
    """Digest of the replica placement (rule table + replica set +
    backup offset): a replica-layout epoch can never accept a delta
    recorded under plain sharding, and vice versa."""
    text = ";".join(
        f"{pat}->{tuple(spec)}"
        for pat, spec in default_table_rules(table_axis)
    )
    text += (
        f";replicas={','.join(REPLICA_LEAVES)}"
        f";backup_offset={REPLICA_BACKUP_OFFSET}"
    )
    return zlib.crc32(text.encode()) & 0xFFFFFFFF


def replica_bytes_model(tables, num_shards: int,
                        table_axis: str = TABLE_AXIS):
    """shard_bytes_model under the N+1 replica layout: replica leaves
    cost 2/num_shards per chip (their own slice + the neighbour's
    backup copy), everything else as the plain sharded model.
    Returns (rows, per_chip_total, replica_overhead_per_chip) where
    the overhead is exactly the backup copies' bytes — the quantity
    tools/shardprof.py bounds by sharded_bytes / num_shards."""
    axes = replica_axes(tables, num_shards, table_axis)
    rows, per_chip, _replicated = shard_bytes_model(
        tables, num_shards, table_axis
    )
    overhead = 0
    for r in rows:
        if r["leaf"] in axes and r["sharded"]:
            r["replicated_n_plus_1"] = True
            overhead += r["bytes_per_chip"]
            r["bytes_per_chip"] *= 2
        else:
            r["replicated_n_plus_1"] = False
    return rows, per_chip + overhead, overhead


# -- bytes / headroom models -------------------------------------------------


def shard_bytes_model(tables, num_shards: int,
                      table_axis: str = TABLE_AXIS):
    """Per-leaf per-chip bytes under the default rule table.  Returns
    (rows, per_chip_total, replicated_total): rows are dicts with
    leaf/sharded/bytes; replicated_total is the per-chip overhead the
    acceptance bound allows on top of sharded_bytes / num_shards.
    Applies the same divisibility fallback as table_shardings, so the
    model classifies each leaf exactly as the store will place it."""
    specs_tree = divisible_partition_specs(
        tables, num_shards, table_axis
    )
    children, _ = tables.tree_flatten()
    specs, _ = specs_tree.tree_flatten()
    rows = []
    per_chip = 0
    replicated = 0
    for name, leaf, spec in zip(POLICY_LEAF_NAMES, children, specs):
        if leaf is None:
            continue
        nbytes = int(getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes)
        sharded = any(ax == table_axis for ax in spec)
        chip = (
            (nbytes + num_shards - 1) // num_shards
            if sharded
            else nbytes
        )
        if not sharded:
            replicated += nbytes
        per_chip += chip
        rows.append(
            {"leaf": name, "sharded": sharded,
             "bytes_total": nbytes, "bytes_per_chip": chip}
        )
    return rows, per_chip, replicated


def universe_max_identities(
    tables,
    num_shards: int,
    hbm_bytes: int = 16 << 30,
    table_axis: str = TABLE_AXIS,
) -> int:
    """Headroom model: the identity-universe size one mesh can hold.

    Identity-major leaf bytes scale linearly with the padded identity
    count N and divide across `num_shards`; replicated leaves are a
    per-chip constant.  Solving
        replicated + identity_bytes_per_id * N / num_shards ≤ hbm
    for N gives the `universe_max_identities` line bench emits — the
    capacity the sharding refactor actually buys (the replicated
    layout is the num_shards=1 row).

    Classification is by RULE INTENT, not current-shape divisibility:
    at the universe being solved for, the identity axis is padded to
    a shard multiple, so a leaf the rules shard contributes to the
    per-id slope even if today's word count happens not to divide by
    `num_shards` (shard_bytes_model, which accounts the CURRENT
    shapes, applies the divisibility fallback instead)."""
    children, _ = tables.tree_flatten()
    specs = match_partition_rules(
        default_table_rules(table_axis), POLICY_LEAF_NAMES, children
    )
    n = int(tables.id_table.shape[0])
    id_bytes = 0
    replicated = 0
    for leaf, spec in zip(children, specs):
        if leaf is None:
            continue
        nbytes = int(getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes)
        if any(ax == table_axis for ax in spec):
            id_bytes += nbytes
        else:
            replicated += nbytes
    per_id = id_bytes / max(n, 1)
    budget = hbm_bytes - replicated
    if per_id <= 0 or budget <= 0:
        return 0
    return int(budget * num_shards / per_id)


def alltoall_bytes_per_tuple(num_shards: int) -> float:
    """Collective bytes the routed-gather evaluator moves per tuple
    along the identity axis: each routed probe returns its verdict
    column to the originating shard through one integer psum —
    exact-probe found+value (8 B) plus the L3 word-probe bit (4 B).
    A 1-shard mesh moves nothing (the psum folds away)."""
    if num_shards <= 1:
        return 0.0
    return 12.0
