"""Declarative per-leaf partition rules for the device table pytrees.

The replicated layout (engine/sharded.replicated_table_shardings) caps
the identity universe at ONE chip's HBM: every chip holds every leaf,
so a 50k-rule/65k-identity world costs ~442 MB on each chip and a
mesh buys zero capacity.  This module is the t5x-style answer
(PAPERS.md [1], arXiv:2203.17189): a REGEX RULE TABLE matched over
the named pytree — `match_partition_rules` + `named_tree_map`, the
SNIPPETS.md [2]/[3] pattern — instead of hand-placed shardings, with
`replicated` as the explicit fallback so small leaves (stashes, the
identity index tables, DFA transition tables) stay replicated while
the identity-major leaves shard:

  * `l4_hash_rows`     — the hashed L4 entry plane, sharded along the
                         bucket-row axis (each chip owns a contiguous
                         row slice; the probe routes each tuple's
                         bucket to its owning shard);
  * `l3_allow_bits`    — the L3-only lattice rows, sharded along the
                         identity WORD axis (the layout the 2D mesh
                         evaluator already combines with a psum);
  * `l4_allow_bits`    — the dense allow bitmap (the cold fallback
                         plane), same word axis;
  * ipcache `buckets`  — the /32 prefix-row plane, bucket-row axis.

Everything else — `id_table`/`id_direct` (a few MB even at 512k ids),
`port_slot`, stashes, `l4_wild_rows` (per-(ep,port) — identity-free
and tiny), scalars — matches the fallback rule and replicates.

The rule table is DATA: `partition_digest` hashes it into a stamp the
device store folds into its epoch layout, so a delta recorded against
one partitioning can never scatter into an epoch laid out under
another.
"""

from __future__ import annotations

import re
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The mesh axis the identity-major leaves shard along (the existing
# 2D (batch × table) mesh of engine/sharded.py).
TABLE_AXIS = "table"


def tree_path_to_string(path, sep: str = "/") -> str:
    """jax key-path → 'a/b/c' (SNIPPETS.md [3] tree_path_to_string)."""
    keys = []
    for key in path:
        if isinstance(key, jax.tree_util.SequenceKey):
            keys.append(str(key.idx))
        elif isinstance(key, jax.tree_util.DictKey):
            keys.append(str(key.key))
        elif isinstance(key, jax.tree_util.GetAttrKey):
            keys.append(str(key.name))
        elif isinstance(key, jax.tree_util.FlattenedIndexKey):
            keys.append(str(key.key))
        else:
            keys.append(str(key))
    return sep.join(keys)


def named_tree_map(f, tree, *rest, is_leaf=None, sep: str = "/"):
    """tree_map where `f` receives (path-string, leaf, *rest-leaves) —
    the extended tree_map of SNIPPETS.md [2]/[3].  For dict/list
    pytrees the names are real key paths; the registered table
    dataclasses flatten positionally, so the helpers below pair their
    children with the explicit *_LEAF_NAMES tables instead."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x, *r: f(tree_path_to_string(path, sep=sep), x, *r),
        tree,
        *rest,
        is_leaf=is_leaf,
    )


# Child order of the registered table pytrees (== tree_flatten order;
# the pytrees flatten positionally, so the names live here, beside
# the rules that consume them).
POLICY_LEAF_NAMES = (
    "id_table", "id_direct", "id_lo_len", "port_slot", "l4_meta",
    "l4_allow_bits", "l3_allow_bits", "generation", "l4_hash_rows",
    "l4_hash_stash", "l4_wild_rows", "l4_wild_stash",
)
IPCACHE_LEAF_NAMES = (
    "buckets", "stash", "range_base", "range_mask", "range_plen",
    "range_value", "range_l3_in", "range_l3_out", "range_rows",
)
# remaining fused-datapath leaf families (tree_flatten child order)
CT_LEAF_NAMES = ("buckets", "stash")
LB_INLINE_LEAF_NAMES = ("rows", "stash")
LB_CLASSIC_LEAF_NAMES = ("buckets", "stash", "backend_rows")


# -- the rule tables ---------------------------------------------------------
# (regex, PartitionSpec) pairs, first match wins; the final catch-all
# IS the replicated fallback — explicit, so a new leaf added to
# PolicyTables replicates by default instead of failing to place.


def default_table_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """The PolicyTables rule table (identity-major leaves sharded)."""
    return [
        # dense allow bitmap [E, 2, Kg, W]: identity WORD axis
        (r"^l4_allow_bits$", P(None, None, None, table_axis)),
        # L3-only rows [E, 2, W]: identity WORD axis
        (r"^l3_allow_bits$", P(None, None, table_axis)),
        # hashed L4 entry plane [R, lanes]: bucket-row axis (the row
        # count is pow2 and identities spread uniformly by hash, so
        # equal row slices carry near-equal entry loads)
        (r"^l4_hash_rows$", P(table_axis)),
        # wild rows are per-(ep, dir, port, proto) — identity-free and
        # a few KB; stashes are ≤64 rows: replicated (the fallback
        # would catch them too, but the intent is worth spelling out)
        (r"^l4_(wild_rows|hash_stash|wild_stash)$", P()),
        # replicated fallback: id tables, port_slot, generation, ...
        (r".*", P()),
    ]


def default_ipcache_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """IPCacheDevice rule table: the /32 bucket-row plane AND the
    hashed range-class rows shard along their bucket-row axis; the
    (base, mask, plen, value) broadcast-fallback arrays and the
    stash replicate (they are small and every shard compares them)."""
    return [
        (r"^(buckets|range_rows)$", P(table_axis)),
        (r".*", P()),
    ]


def default_ct_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """CTSnapshot rule table: the [Cb, 128] bucket-row plane shards
    along the bucket-row axis (rows spread uniformly by the
    direction-normalized tuple hash); the overflow stash replicates
    (broadcast-compared by every probe)."""
    return [
        (r"^buckets$", P(table_axis)),
        (r".*", P()),
    ]


def default_lb_rules(table_axis: str = TABLE_AXIS) -> List[tuple]:
    """LB rule table: the INLINE layout's service rows (service key +
    backends in one 128-lane row) shard along the bucket-row axis.
    The classic two-gather layout replicates wholesale — its backend
    rows are indexed by the service entry's stored row index, not a
    hash, so a split would need a second routing hop for the rare
    >40-backend fallback; the stash replicates like every stash."""
    return [
        (r"^rows$", P(table_axis)),
        (r".*", P()),
    ]


def match_partition_rules(
    rules: Sequence[tuple], names: Sequence[str], leaves: Sequence
) -> list:
    """PartitionSpec per leaf: each `names[i]` is matched against
    `rules` in order; scalars/0-d/None leaves never partition.
    Unmatched leaves raise — the catch-all fallback rule makes that
    unreachable for the default tables."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = []
    for name, leaf in zip(names, leaves):
        if leaf is None:
            out.append(P())
            continue
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            out.append(P())  # never partition scalars
            continue
        for rx, spec in compiled:
            if rx.search(name) is not None:
                out.append(spec)
                break
        else:
            raise ValueError(
                f"partition rule not found for leaf: {name}"
            )
    return out


def policy_partition_specs(tables, table_axis: str = TABLE_AXIS):
    """PartitionSpecs for a PolicyTables pytree under the default
    rule table, as a PolicyTables of specs (shape-aware: scalars
    replicate regardless of rules)."""
    children, _ = tables.tree_flatten()
    specs = match_partition_rules(
        default_table_rules(table_axis), POLICY_LEAF_NAMES, children
    )
    return type(tables).tree_unflatten(None, tuple(specs))


def ipcache_partition_specs(dev, table_axis: str = TABLE_AXIS):
    """PartitionSpecs for an IPCacheDevice (or replicated specs for
    the DIR-24-8 fallback form)."""
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    if not isinstance(dev, IPCacheDevice):
        children, aux = dev.tree_flatten()
        return type(dev).tree_unflatten(
            aux, tuple(P() for _ in children)
        )
    children, aux = dev.tree_flatten()
    specs = match_partition_rules(
        default_ipcache_rules(table_axis), IPCACHE_LEAF_NAMES, children
    )
    return type(dev).tree_unflatten(aux, tuple(specs))


def partition_digest(rules: Sequence[tuple]) -> int:
    """Stable 32-bit digest of a rule table — folded into the device
    store's epoch layout stamp so cross-partitioning deltas are
    refused (engine/publish.DeviceTableStore)."""
    text = ";".join(
        f"{pat}->{tuple(spec)}" for pat, spec in rules
    ).encode()
    return zlib.crc32(text) & 0xFFFFFFFF


def _divisible(spec: P, shape, ntp: int, table_axis: str) -> bool:
    for axis, name in enumerate(spec):
        if name == table_axis and (
            axis >= len(shape) or shape[axis] % ntp != 0
        ):
            return False
    return True


def divisible_partition_specs(
    tables, ntp: int, table_axis: str = TABLE_AXIS
):
    """policy_partition_specs with the shard-axis divisibility check
    applied: leaves whose sharded axis does not split evenly over
    `ntp` shards fall back to replicated (the shard_map evaluator and
    the store must agree on this, so it lives in the rule layer)."""
    specs = policy_partition_specs(tables, table_axis)
    spec_children, _ = specs.tree_flatten()
    leaf_children, _ = tables.tree_flatten()
    out = []
    for spec, leaf in zip(spec_children, leaf_children):
        if leaf is None or not _divisible(
            spec, getattr(leaf, "shape", ()), ntp, table_axis
        ):
            spec = P()
        out.append(spec)
    return type(tables).tree_unflatten(None, tuple(out))


def table_shardings(mesh: Mesh, tables, table_axis: str = TABLE_AXIS):
    """NamedShardings pytree for device_put / DeviceTableStore: the
    default rule table resolved against `mesh`.  Leaves whose sharded
    axis does not divide by the mesh's table-axis size fall back to
    replicated (correctness first; tools/shardprof.py reports it)."""
    specs = divisible_partition_specs(
        tables, int(mesh.shape[table_axis]), table_axis
    )
    spec_children, _ = specs.tree_flatten()
    out = tuple(NamedSharding(mesh, s) for s in spec_children)
    return type(tables).tree_unflatten(None, out)


# -- N+1 shard replicas (per-chip failover placement) ------------------------
#
# The t5x lesson — placement rules are DATA — applied to failure
# domains: each identity-sharded leaf's rows also live on a BACKUP
# owner, the next shard over (slice i's replica sits on shard
# (i+1) % ntp), so a chip whose breaker opens takes down one COPY of
# its rows, not the rows themselves.  The failover evaluator
# (engine/sharded.make_failover_evaluator) routes a tuple's gather to
# the backup region when the primary owner is dead; the host lattice
# fold remains the terminal fallback only when primary AND backup are
# both gone.
#
# Replication applies to the leaves the ROUTED evaluator gathers —
# the hashed L4 entry rows and the L3 bit-words.  The dense
# l4_allow_bits fallback plane stays single-sharded: nothing on the
# hashed hot path reads it, and doubling the largest leaf would spend
# the replica HBM budget on rows no routed gather can reach.

REPLICA_LEAVES = ("l4_hash_rows", "l3_allow_bits")
# slice i's backup owner is shard (i + REPLICA_BACKUP_OFFSET) % ntp
REPLICA_BACKUP_OFFSET = 1


def replica_axes(tables, ntp: int, table_axis: str = TABLE_AXIS):
    """{leaf name: sharded-axis position} for the leaves the replica
    layout augments: REPLICA_LEAVES that the divisibility-checked
    rule layer actually shards at `ntp` (an indivisible leaf falls
    back to replicated and needs no backup copy)."""
    specs = divisible_partition_specs(tables, ntp, table_axis)
    out = {}
    for name in REPLICA_LEAVES:
        spec = getattr(specs, name)
        for axis, ax_name in enumerate(spec):
            if ax_name == table_axis:
                out[name] = axis
                break
    return out


def replicate_shard_axis(arr, ntp: int, axis: int):
    """Augment one sharded leaf with its backup copies: the sharded
    axis [S] becomes [2S], laid out per shard q as
    [primary slice q ; copy of slice (q - 1) % ntp] — so a
    NamedSharding along the same axis gives every chip its own rows
    plus its left neighbour's, and the in-kernel backup gather is
    `n + (i mod n)` on shard (owner + 1) % ntp."""
    arr = np.asarray(arr)
    n = arr.shape[axis] // ntp
    slices = [
        np.take(arr, np.arange(q * n, (q + 1) * n), axis=axis)
        for q in range(ntp)
    ]
    parts = []
    for q in range(ntp):
        parts.append(slices[q])
        parts.append(slices[(q - REPLICA_BACKUP_OFFSET) % ntp])
    return np.concatenate(parts, axis=axis)


def replicate_table_leaves(tables, ntp: int,
                           table_axis: str = TABLE_AXIS):
    """PolicyTables with every replica-rule leaf augmented (the
    device layout the replica store publishes); non-replica leaves
    pass through untouched."""
    import dataclasses

    axes = replica_axes(tables, ntp, table_axis)
    return dataclasses.replace(
        tables,
        **{
            name: replicate_shard_axis(
                getattr(tables, name), ntp, axis
            )
            for name, axis in axes.items()
        },
    )


def replica_positions(idx, n: int, ntp: int):
    """Map original global sharded-axis indices to their two
    positions in the augmented layout: (primary, backup)."""
    idx = np.asarray(idx)
    shard = idx // n
    within = idx % n
    primary = shard * (2 * n) + within
    backup = (
        ((shard + REPLICA_BACKUP_OFFSET) % ntp) * (2 * n)
        + n
        + within
    )
    return primary, backup


def replica_delta(delta, tables, ntp: int,
                  table_axis: str = TABLE_AXIS):
    """Rewrite a TableDelta recorded against the un-augmented layout
    into augmented coordinates, so one delta publish keeps primary
    and backup copies bit-identical.  Two shapes of update exist:

      * the scatter INDEXES the sharded axis (l4_hash_rows: idx[0]
        is the bucket row) — every row lands twice, at its primary
        and backup augmented positions, values repeated;
      * the scatter indexes LEADING axes only and its values SPAN
        the sharded axis (l3_allow_bits: idx is the endpoint, values
        are whole [2, W] slabs) — the values augment along the
        corresponding value axis, exactly as the resident leaf did.

    Whole-leaf replacements of replica leaves ship in augmented
    form; leaves outside the replica set pass through untouched."""
    from cilium_tpu.compiler.delta import LeafUpdate, TableDelta

    axes = replica_axes(tables, ntp, table_axis)
    updates = {}
    for name, up in delta.updates.items():
        axis = axes.get(name)
        if axis is None:
            updates[name] = up
            continue
        n = getattr(tables, name).shape[axis] // ntp
        if axis < len(up.idx):
            primary, backup = replica_positions(
                up.idx[axis], n, ntp
            )
            idx = tuple(
                np.concatenate([primary, backup])
                if i == axis
                else np.concatenate([comp, comp])
                for i, comp in enumerate(up.idx)
            )
            values = np.concatenate([up.values, up.values], axis=0)
        else:
            # leaf axis `axis` sits inside the values: idx consumes
            # the first len(idx) leaf axes, the values' axis 0 is
            # the scatter row, so leaf axis a maps to values axis
            # a - len(idx) + 1
            idx = up.idx
            values = replicate_shard_axis(
                up.values, ntp, axis - len(up.idx) + 1
            )
        updates[name] = LeafUpdate(idx=idx, values=values)
    replace = {
        name: (
            replicate_shard_axis(arr, ntp, axes[name])
            if name in axes
            else arr
        )
        for name, arr in delta.replace.items()
    }
    return TableDelta(
        base_stamp=delta.base_stamp,
        new_stamp=delta.new_stamp,
        updates=updates,
        replace=replace,
        layout=delta.layout,
    )


def replica_partition_digest(
    table_axis: str = TABLE_AXIS, ntp: Optional[int] = None
) -> int:
    """Digest of the replica placement (rule table + replica set +
    backup offset): a replica-layout epoch can never accept a delta
    recorded under plain sharding, and vice versa.  With `ntp` the
    SHARD COUNT folds in too: the augmented leaves have the same
    total shape [2S] at every ntp (a reshard is a pure permutation of
    the augmented layout), so without the count in the digest a
    source-layout delta or repair could scatter bit-compatibly — but
    row-incorrectly — into a target-layout epoch.  The reshard
    engine's refusal seam depends on the two layouts stamping
    differently."""
    text = ";".join(
        f"{pat}->{tuple(spec)}"
        for pat, spec in default_table_rules(table_axis)
    )
    text += (
        f";replicas={','.join(REPLICA_LEAVES)}"
        f";backup_offset={REPLICA_BACKUP_OFFSET}"
    )
    if ntp is not None:
        text += f";ntp={int(ntp)}"
    return zlib.crc32(text.encode()) & 0xFFFFFFFF


def replica_bytes_model(tables, num_shards: int,
                        table_axis: str = TABLE_AXIS):
    """shard_bytes_model under the N+1 replica layout: replica leaves
    cost 2/num_shards per chip (their own slice + the neighbour's
    backup copy), everything else as the plain sharded model.
    Returns (rows, per_chip_total, replica_overhead_per_chip) where
    the overhead is exactly the backup copies' bytes — the quantity
    tools/shardprof.py bounds by sharded_bytes / num_shards."""
    axes = replica_axes(tables, num_shards, table_axis)
    rows, per_chip, _replicated = shard_bytes_model(
        tables, num_shards, table_axis
    )
    overhead = 0
    for r in rows:
        if r["leaf"] in axes and r["sharded"]:
            r["replicated_n_plus_1"] = True
            overhead += r["bytes_per_chip"]
            r["bytes_per_chip"] *= 2
        else:
            r["replicated_n_plus_1"] = False
    return rows, per_chip + overhead, overhead


# -- bytes / headroom models -------------------------------------------------


def shard_bytes_model(tables, num_shards: int,
                      table_axis: str = TABLE_AXIS):
    """Per-leaf per-chip bytes under the default rule table.  Returns
    (rows, per_chip_total, replicated_total): rows are dicts with
    leaf/sharded/bytes; replicated_total is the per-chip overhead the
    acceptance bound allows on top of sharded_bytes / num_shards.
    Applies the same divisibility fallback as table_shardings, so the
    model classifies each leaf exactly as the store will place it."""
    specs_tree = divisible_partition_specs(
        tables, num_shards, table_axis
    )
    children, _ = tables.tree_flatten()
    specs, _ = specs_tree.tree_flatten()
    rows = []
    per_chip = 0
    replicated = 0
    for name, leaf, spec in zip(POLICY_LEAF_NAMES, children, specs):
        if leaf is None:
            continue
        nbytes = int(getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes)
        sharded = any(ax == table_axis for ax in spec)
        chip = (
            (nbytes + num_shards - 1) // num_shards
            if sharded
            else nbytes
        )
        if not sharded:
            replicated += nbytes
        per_chip += chip
        rows.append(
            {"leaf": name, "sharded": sharded,
             "bytes_total": nbytes, "bytes_per_chip": chip}
        )
    return rows, per_chip, replicated


def universe_max_identities(
    tables,
    num_shards: int,
    hbm_bytes: int = 16 << 30,
    table_axis: str = TABLE_AXIS,
) -> int:
    """Headroom model: the identity-universe size one mesh can hold.

    Identity-major leaf bytes scale linearly with the padded identity
    count N and divide across `num_shards`; replicated leaves are a
    per-chip constant.  Solving
        replicated + identity_bytes_per_id * N / num_shards ≤ hbm
    for N gives the `universe_max_identities` line bench emits — the
    capacity the sharding refactor actually buys (the replicated
    layout is the num_shards=1 row).

    Classification is by RULE INTENT, not current-shape divisibility:
    at the universe being solved for, the identity axis is padded to
    a shard multiple, so a leaf the rules shard contributes to the
    per-id slope even if today's word count happens not to divide by
    `num_shards` (shard_bytes_model, which accounts the CURRENT
    shapes, applies the divisibility fallback instead)."""
    children, _ = tables.tree_flatten()
    specs = match_partition_rules(
        default_table_rules(table_axis), POLICY_LEAF_NAMES, children
    )
    n = int(tables.id_table.shape[0])
    id_bytes = 0
    replicated = 0
    for leaf, spec in zip(children, specs):
        if leaf is None:
            continue
        nbytes = int(getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes)
        if any(ax == table_axis for ax in spec):
            id_bytes += nbytes
        else:
            replicated += nbytes
    per_id = id_bytes / max(n, 1)
    budget = hbm_bytes - replicated
    if per_id <= 0 or budget <= 0:
        return 0
    return int(budget * num_shards / per_id)


# -- fused-datapath leaf families (ipcache / CT / LB planes) -----------------
#
# The same declarative layer extended to the REMAINING DatapathTables
# families: every hashed bucket-row plane the fused pipeline gathers
# (CT buckets, ipcache /32 buckets + range-class rows, LB service
# rows) shards along the same table axis as l4_hash_rows, and the hot
# ones join the N+1 replica placement so a dead chip's CT/ipcache/LB
# rows serve from their backup owner exactly like the policy rows.
# Everything else — stashes, broadcast-fallback range arrays, the
# classic LB backend-row table, prefilter, tunnel — replicates.

# the datapath leaves the N+1 failover layout augments, as
# (family, leaf) pairs; entries whose family lacks the leaf (lb.rows
# on the classic layout, lb.buckets on the inline one) are skipped
DATAPATH_REPLICA_LEAVES = (
    ("ct", "buckets"),
    ("ipcache", "buckets"),
    ("ipcache", "range_rows"),
    ("lb", "rows"),
)


def _family_spec_children(children, names, rules, ntp, table_axis):
    """Per-child PartitionSpecs for one table family with the
    shard-axis divisibility fallback applied; None children keep a
    None spec (empty subtrees must stay empty subtrees so the spec
    tree's structure matches the value tree's)."""
    specs = match_partition_rules(rules, names, children)
    out = []
    for leaf, spec in zip(children, specs):
        if leaf is None:
            out.append(None)
            continue
        if not _divisible(spec, np.shape(leaf), ntp, table_axis):
            spec = P()
        out.append(spec)
    return tuple(out)


def _replicated_specs(tree):
    """All-replicated spec tree matching `tree`'s structure."""
    return jax.tree.map(lambda _: P(), tree)


def ct_family_specs(ct, ntp: int, table_axis: str = TABLE_AXIS):
    """CTSnapshot of PartitionSpecs under default_ct_rules."""
    children, aux = ct.tree_flatten()
    return type(ct).tree_unflatten(
        aux,
        _family_spec_children(
            children, CT_LEAF_NAMES, default_ct_rules(table_axis),
            ntp, table_axis,
        ),
    )


def lb_family_specs(lb, ntp: int, table_axis: str = TABLE_AXIS):
    """LBInline/LBTables of PartitionSpecs under default_lb_rules."""
    from cilium_tpu.lb.device import LBInline

    children, aux = lb.tree_flatten()
    names = (
        LB_INLINE_LEAF_NAMES
        if isinstance(lb, LBInline)
        else LB_CLASSIC_LEAF_NAMES
    )
    return type(lb).tree_unflatten(
        aux,
        _family_spec_children(
            children, names, default_lb_rules(table_axis), ntp,
            table_axis,
        ),
    )


def ipcache_family_specs(dev, ntp: int, table_axis: str = TABLE_AXIS):
    """IPCacheDevice of PartitionSpecs under default_ipcache_rules
    (divisibility-checked); the DIR-24-8 fallback form replicates."""
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    children, aux = dev.tree_flatten()
    if not isinstance(dev, IPCacheDevice):
        return type(dev).tree_unflatten(
            aux, tuple(None if c is None else P() for c in children)
        )
    return type(dev).tree_unflatten(
        aux,
        _family_spec_children(
            children, IPCACHE_LEAF_NAMES,
            default_ipcache_rules(table_axis), ntp, table_axis,
        ),
    )


def datapath_partition_specs(
    dtables, ntp: int, table_axis: str = TABLE_AXIS
):
    """PartitionSpecs for a full DatapathTables pytree: every family
    resolved under its own rule table, prefilter/tunnel replicated,
    the policy sub-tree under the existing policy rules."""
    from cilium_tpu.engine.datapath import DatapathTables

    return DatapathTables(
        prefilter=_replicated_specs(dtables.prefilter),
        ipcache=ipcache_family_specs(
            dtables.ipcache, ntp, table_axis
        ),
        ct=ct_family_specs(dtables.ct, ntp, table_axis),
        lb=lb_family_specs(dtables.lb, ntp, table_axis),
        policy=divisible_partition_specs(
            dtables.policy, ntp, table_axis
        ),
        tunnel=(
            None
            if dtables.tunnel is None
            else _replicated_specs(dtables.tunnel)
        ),
    )


def datapath_table_shardings(
    mesh: Mesh, dtables, table_axis: str = TABLE_AXIS
):
    """NamedShardings for a DatapathTables pytree under the family
    rule tables (the datapath store's placement resolver)."""
    specs = datapath_partition_specs(
        dtables, int(mesh.shape[table_axis]), table_axis
    )
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def datapath_replica_axes(
    dtables, ntp: int, table_axis: str = TABLE_AXIS
):
    """{(family, leaf): sharded-axis position} for the datapath
    leaves the N+1 layout augments: DATAPATH_REPLICA_LEAVES that the
    divisibility-checked rule layer actually shards at `ntp`."""
    specs = datapath_partition_specs(dtables, ntp, table_axis)
    out = {}
    for fam, leaf in DATAPATH_REPLICA_LEAVES:
        fobj = getattr(dtables, fam)
        if fobj is None or not hasattr(fobj, leaf):
            continue
        if getattr(fobj, leaf, None) is None:
            continue
        spec = getattr(getattr(specs, fam), leaf, None)
        if spec is None:
            continue
        for axis, ax in enumerate(spec):
            if ax == table_axis:
                out[(fam, leaf)] = axis
                break
    return out


def datapath_all_replica_axes(
    dtables, ntp: int, table_axis: str = TABLE_AXIS
):
    """{(family, leaf): sharded-axis} over the WHOLE datapath tree —
    the datapath families (datapath_replica_axes) merged with the
    policy replica leaves keyed as ("policy", name).  THE augmented-
    leaf enumeration the delta publish, the chip repair and the
    residency assertions all share, so they can never disagree about
    which leaves carry N+1 copies."""
    out = dict(datapath_replica_axes(dtables, ntp, table_axis))
    out.update(
        {
            ("policy", name): axis
            for name, axis in replica_axes(
                dtables.policy, ntp, table_axis
            ).items()
        }
    )
    return out


def replicate_datapath_leaves(
    dtables, ntp: int, table_axis: str = TABLE_AXIS
):
    """DatapathTables with every datapath replica-rule leaf augmented
    along its sharded axis (replicate_shard_axis: each shard's slice
    plus its left neighbour's backup copy) and the policy sub-tree
    augmented by replicate_table_leaves — the device layout the fused
    failover evaluator consumes."""
    import dataclasses

    axes = datapath_replica_axes(dtables, ntp, table_axis)
    fam_updates = {}
    for (fam, leaf), axis in axes.items():
        fam_updates.setdefault(fam, {})[leaf] = replicate_shard_axis(
            getattr(getattr(dtables, fam), leaf), ntp, axis
        )
    new_fams = {
        fam: dataclasses.replace(getattr(dtables, fam), **ups)
        for fam, ups in fam_updates.items()
    }
    return dataclasses.replace(
        dtables,
        policy=replicate_table_leaves(
            dtables.policy, ntp, table_axis
        ),
        **new_fams,
    )


def datapath_partition_digest(
    table_axis: str = TABLE_AXIS, ntp: Optional[int] = None
) -> int:
    """Digest of the WHOLE fused-datapath placement — every family's
    rule table plus both replica sets and the backup offset — folded
    into the datapath store's epoch layout, so a delta recorded under
    one partitioning can never scatter into an epoch laid out under
    another (the cross-layout refusal the policy store already has,
    extended to the CT/ipcache/LB planes)."""
    parts = []
    for fam, rules in (
        ("policy", default_table_rules(table_axis)),
        ("ipcache", default_ipcache_rules(table_axis)),
        ("ct", default_ct_rules(table_axis)),
        ("lb", default_lb_rules(table_axis)),
    ):
        parts.append(
            fam + ":" + ";".join(
                f"{pat}->{tuple(spec)}" for pat, spec in rules
            )
        )
    parts.append("replicas=" + ",".join(REPLICA_LEAVES))
    parts.append(
        "dp_replicas="
        + ",".join(f"{f}.{l}" for f, l in DATAPATH_REPLICA_LEAVES)
    )
    parts.append(f"backup_offset={REPLICA_BACKUP_OFFSET}")
    if ntp is not None:
        # the reshard refusal seam: same reason as
        # replica_partition_digest(ntp=...) — augmented shapes are
        # ntp-invariant, so only the digest separates the layouts
        parts.append(f"ntp={int(ntp)}")
    return zlib.crc32("|".join(parts).encode()) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Elastic resharding: the owned-row delta between two shard counts
# ---------------------------------------------------------------------------
#
# The augmented replica layout is ntp-INVARIANT in total shape: a
# sharded axis [S] becomes [2S] under ANY shard count (each shard
# holds its primary slice plus one backup copy), so migrating a leaf
# from ntp_src to ntp_dst is a pure index permutation of the
# augmented axis.  The owned-row delta below says which target
# augmented positions a migration must actually MOVE.
#
# Byte-accounting model (the simulation boundary the reshard engine
# documents): a target augmented row j — holding un-augmented row u,
# owned by target LOGICAL column c — is RETAINED (a device-local
# copy, zero H2D bytes) iff column c also existed in the source
# layout (c < ntp_src) and the source chip at the same logical
# column already held u in its primary or backup region.  Every
# other row is MOVED: streamed host→device in bounded-byte steps and
# counted into reshard_bytes_h2d.  Growth 2→4 therefore moves
# exactly the new columns' contents — the rows whose owner changed —
# never O(world).


def reshard_row_map(
    n_rows: int, ntp_src: int, ntp_dst: int
) -> Tuple[np.ndarray, np.ndarray]:
    """For one sharded leaf axis of un-augmented length `n_rows`:
    (src_unaug, moved) over the TARGET augmented axis [2 * n_rows].

      * src_unaug[j] — the un-augmented row index target augmented
        position j holds under ntp_dst (primary region [0, n) of
        each column block holds the column's own slice, backup
        region [n, 2n) its left neighbour's);
      * moved[j]     — True when position j must be streamed under
        the column-identity retention model above.

    Both shard counts must divide `n_rows` (the divisibility-checked
    rule layer guarantees it for every sharded leaf)."""
    S = int(n_rows)
    if S % ntp_src or S % ntp_dst:
        raise ValueError(
            f"shard counts {ntp_src}->{ntp_dst} must divide the "
            f"sharded axis ({S} rows)"
        )
    n_t = S // ntp_dst
    n_s = S // ntp_src
    j = np.arange(2 * S)
    col = j // (2 * n_t)
    within = j - col * 2 * n_t
    primary = within < n_t
    src_unaug = np.where(
        primary,
        col * n_t + within,
        ((col - REPLICA_BACKUP_OFFSET) % ntp_dst) * n_t
        + (within - n_t),
    )
    src_shard = src_unaug // n_s
    resident = (col < ntp_src) & (
        (src_shard == col)
        | (src_shard == (col - REPLICA_BACKUP_OFFSET) % ntp_src)
    )
    return src_unaug, ~resident


def reshard_moved_rows(
    tables, ntp_src: int, ntp_dst: int,
    table_axis: str = TABLE_AXIS,
) -> Dict[str, Tuple[int, np.ndarray]]:
    """{leaf: (axis, moved target-augmented indices)} for the policy
    replica leaves — the owned-row delta a ReshardPlan streams.  The
    replica leaf SET must agree between the two shard counts (a leaf
    sharded at one count but replicated at the other is a geometry
    change, not a permutation): the plan refuses and full-uploads
    into the target instead."""
    src_axes = replica_axes(tables, ntp_src, table_axis)
    dst_axes = replica_axes(tables, ntp_dst, table_axis)
    if set(src_axes) != set(dst_axes):
        raise ValueError(
            "replica leaf sets differ between shard counts "
            f"{ntp_src} ({sorted(src_axes)}) and {ntp_dst} "
            f"({sorted(dst_axes)}): not a permutation reshard"
        )
    out: Dict[str, Tuple[int, np.ndarray]] = {}
    for name, axis in dst_axes.items():
        n = int(
            np.asarray(getattr(tables, name)).shape[axis]
        )
        _, moved = reshard_row_map(n, ntp_src, ntp_dst)
        out[name] = (axis, np.flatnonzero(moved))
    return out


def datapath_reshard_moved_rows(
    dtables, ntp_src: int, ntp_dst: int,
    table_axis: str = TABLE_AXIS,
) -> Dict[Tuple[str, str], Tuple[int, np.ndarray]]:
    """reshard_moved_rows over the WHOLE datapath tree: {(family,
    leaf): (axis, moved target-augmented indices)} for every
    N+1-augmented leaf — policy + CT + ipcache + LB, the same
    enumeration the delta publish and chip repair share
    (datapath_all_replica_axes)."""
    src_axes = datapath_all_replica_axes(
        dtables, ntp_src, table_axis
    )
    dst_axes = datapath_all_replica_axes(
        dtables, ntp_dst, table_axis
    )
    if set(src_axes) != set(dst_axes):
        raise ValueError(
            "datapath replica leaf sets differ between shard "
            f"counts {ntp_src} and {ntp_dst}: not a permutation "
            "reshard"
        )
    out: Dict[Tuple[str, str], Tuple[int, np.ndarray]] = {}
    for (fam, leaf), axis in dst_axes.items():
        n = int(
            np.asarray(
                getattr(getattr(dtables, fam), leaf)
            ).shape[axis]
        )
        _, moved = reshard_row_map(n, ntp_src, ntp_dst)
        out[(fam, leaf)] = (axis, np.flatnonzero(moved))
    return out


def _family_byte_rows(
    fam, obj, names, rules, ntp, table_axis, rep_axes
):
    children, _ = obj.tree_flatten()
    specs = _family_spec_children(
        children, names, rules, ntp, table_axis
    )
    rows = []
    for name, leaf, spec in zip(names, children, specs):
        if leaf is None:
            continue
        # leaf.nbytes avoids a D2H copy when the model runs over a
        # device-resident tree (bench does)
        nbytes = int(
            getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes
        )
        sharded = spec is not None and any(
            ax == table_axis for ax in spec
        )
        chip = (nbytes + ntp - 1) // ntp if sharded else nbytes
        rep = (fam, name) in rep_axes
        if rep:
            chip *= 2
        rows.append(
            {
                "leaf": f"{fam}.{name}",
                "sharded": sharded,
                "replicated_n_plus_1": rep,
                "bytes_total": nbytes,
                "bytes_per_chip": chip,
            }
        )
    return rows


def datapath_bytes_model(
    dtables, num_shards: int, table_axis: str = TABLE_AXIS
):
    """Per-leaf per-chip bytes of the WHOLE fused datapath under the
    family rule tables + the N+1 replica placement (policy leaves via
    replica_bytes_model, CT/ipcache/LB via their family rules).
    Returns (rows, per_chip_total, replicated_total, overhead):
    `replicated_total` is the per-chip constant the acceptance bound
    allows on top of replicated-bytes / num_shards; `overhead` is
    exactly the backup copies' bytes — bounded by replicated/N."""
    from cilium_tpu.lb.device import LBInline

    rep_axes = datapath_replica_axes(dtables, num_shards, table_axis)
    pol_rows, pol_per_chip, pol_overhead = replica_bytes_model(
        dtables.policy, num_shards, table_axis
    )
    rows = [
        {**r, "leaf": f"policy.{r['leaf']}"} for r in pol_rows
    ]
    per_chip = pol_per_chip
    overhead = pol_overhead
    replicated = sum(
        r["bytes_per_chip"] for r in rows if not r["sharded"]
    )
    fam_args = [
        ("ct", dtables.ct, CT_LEAF_NAMES,
         default_ct_rules(table_axis)),
        (
            "lb", dtables.lb,
            LB_INLINE_LEAF_NAMES
            if isinstance(dtables.lb, LBInline)
            else LB_CLASSIC_LEAF_NAMES,
            default_lb_rules(table_axis),
        ),
    ]
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    if isinstance(dtables.ipcache, IPCacheDevice):
        fam_args.append(
            ("ipcache", dtables.ipcache, IPCACHE_LEAF_NAMES,
             default_ipcache_rules(table_axis))
        )
    for fam, obj, names, rules in fam_args:
        frows = _family_byte_rows(
            fam, obj, names, rules, num_shards, table_axis, rep_axes
        )
        rows.extend(frows)
        for r in frows:
            per_chip += r["bytes_per_chip"]
            if not r["sharded"]:
                replicated += r["bytes_per_chip"]
            elif r["replicated_n_plus_1"]:
                overhead += r["bytes_per_chip"] // 2
    # prefilter / tunnel / a DIR-24-8 ipcache: replicated constants
    extra = [dtables.prefilter, dtables.tunnel]
    if not isinstance(dtables.ipcache, IPCacheDevice):
        extra.append(dtables.ipcache)
    for tree in extra:
        if tree is None:
            continue
        nbytes = sum(
            int(getattr(l, "nbytes", None) or np.asarray(l).nbytes)
            for l in jax.tree.leaves(tree)
        )
        if nbytes:
            per_chip += nbytes
            replicated += nbytes
    return rows, per_chip, replicated, overhead


def datapath_universe_max_identities(
    dtables,
    num_shards: int,
    hbm_bytes: int = 16 << 30,
    table_axis: str = TABLE_AXIS,
) -> int:
    """universe_max_identities extended to the WHOLE datapath
    footprint.  Identity-scaling bytes = the policy identity-major
    leaves (by rule intent, as universe_max_identities classifies)
    PLUS the ipcache /32 bucket plane (every identity is reachable
    at ≥ 1 /32 entry, so the bucket table grows linearly with the
    universe) — N+1 replica leaves count twice.  The CT/LB planes
    scale with flows and services, not identities: their sharded
    leaves divide by num_shards (×2 where replicated N+1) and join
    the per-chip constant alongside the replicated leaves."""
    children, _ = dtables.policy.tree_flatten()
    specs = match_partition_rules(
        default_table_rules(table_axis), POLICY_LEAF_NAMES, children
    )
    n = int(dtables.policy.id_table.shape[0])
    id_bytes = 0.0
    constant = 0.0
    for name, leaf, spec in zip(POLICY_LEAF_NAMES, children, specs):
        if leaf is None:
            continue
        nbytes = int(
            getattr(leaf, "nbytes", None) or np.asarray(leaf).nbytes
        )
        if any(ax == table_axis for ax in spec):
            id_bytes += nbytes * (2 if name in REPLICA_LEAVES else 1)
        else:
            constant += nbytes
    rows, per_chip, _replicated, _overhead = datapath_bytes_model(
        dtables, num_shards, table_axis
    )
    for r in rows:
        if r["leaf"].startswith("policy."):
            continue  # accounted above (slope or constant)
        if r["leaf"] == "ipcache.buckets" and r["sharded"]:
            id_bytes += r["bytes_total"] * (
                2 if r["replicated_n_plus_1"] else 1
            )
        else:
            constant += r["bytes_per_chip"]
    # prefilter / tunnel constants datapath_bytes_model folded into
    # per_chip but not into rows: recover them from the totals
    row_chip = sum(r["bytes_per_chip"] for r in rows)
    constant += max(per_chip - row_chip, 0)
    per_id = id_bytes / max(n, 1)
    budget = hbm_bytes - constant
    if per_id <= 0 or budget <= 0:
        return 0
    return int(budget * num_shards / per_id)


def datapath_alltoall_bytes_per_tuple(
    num_shards: int, range_classes: int = 2
) -> float:
    """Collective bytes the fused routed-gather pipeline moves per
    tuple along the table axis: the lattice's exact+L3 psum pair
    (12 B, alltoall_bytes_per_tuple) plus the CT service probe
    (found + value, 8 B), the CT flow probe (fwd/rev found + values,
    16 B), the LB service resolution (found/slave/daddr/dport/rev_nat,
    20 B), the ipcache exact probe (found + value, 8 B) and one
    (found + value) pair per hashed range-length class.  A 1-shard
    mesh moves nothing."""
    if num_shards <= 1:
        return 0.0
    return 12.0 + 8.0 + 16.0 + 20.0 + 8.0 + 8.0 * range_classes


def alltoall_bytes_per_tuple(num_shards: int) -> float:
    """Collective bytes the routed-gather evaluator moves per tuple
    along the identity axis: each routed probe returns its verdict
    column to the originating shard through one integer psum —
    exact-probe found+value (8 B) plus the L3 word-probe bit (4 B).
    A 1-shard mesh moves nothing (the psum folds away)."""
    if num_shards <= 1:
        return 0.0
    return 12.0
