"""Host half of the datapath telemetry plane.

The instrumented device kernels (engine/datapath.py
``datapath_step_*_telem``) carry a [2, TELEM_COLS] u32 stage/drop
accumulator alongside the per-entry counter buffer — one masked-sum
reduction set fused into the verdict dispatch, no extra launches.
This module folds that accumulator (or, equivalently, per-tuple
DatapathVerdicts columns host-side) into:

  * ``metrics.Registry`` — cilium_drop_count_total{reason,direction},
    cilium_forward_count_total, cilium_policy_verdict_total and
    cilium_datapath_stage_total, the same metric surface
    pkg/metrics exposes for the kernel datapath;
  * summary dicts for bench/status output.

Both folds derive from the ONE mask definition set
(engine.verdict.telemetry_masks), so the on-device histogram and the
host per-tuple fold are bit-identical by construction — the property
the bench's telemetry gate asserts on a ≥1M-tuple batch.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from cilium_tpu.engine.verdict import (
    TELEM_COLS,
    TELEM_CT_BYPASS_ALLOW,
    TELEM_CT_DELETE,
    TELEM_CT_ESTABLISHED,
    TELEM_CT_NEW,
    TELEM_CT_RELATED,
    TELEM_CT_REPLY,
    TELEM_DENIED,
    TELEM_DROP_FRAG,
    TELEM_DROP_POLICY,
    TELEM_DROP_PREFILTER,
    TELEM_FORWARDED,
    TELEM_IPCACHE_WORLD,
    TELEM_LB_DNAT,
    TELEM_MATCH_FRAG,
    TELEM_MATCH_L3,
    TELEM_MATCH_L4,
    TELEM_MATCH_L4_WILD,
    TELEM_MATCH_NONE,
    TELEM_NAMES,
    TELEM_PROXY_REDIRECT,
    TELEM_TOTAL,
    telemetry_masks,
)
from cilium_tpu.monitor.events import drop_reason_name

DIRECTION_NAMES = ("INGRESS", "EGRESS")

# drop-column → canonical bpf/lib/common.h reason string (the same
# names `cilium monitor` prints, via monitor.events.DROP_REASONS)
DROP_COLUMN_REASONS = {
    TELEM_DROP_PREFILTER: drop_reason_name(-162),  # Policy denied (CIDR)
    TELEM_DROP_POLICY: drop_reason_name(-133),  # Policy denied (L3)
    TELEM_DROP_FRAG: drop_reason_name(-157),  # Fragmentation needed
}

# match-column → (match label, action label) of
# cilium_policy_verdict_total; the lattice verdict is implied by the
# match kind (hits allow, none/frag deny)
MATCH_COLUMN_LABELS = {
    TELEM_MATCH_L4: ("l4", "allowed"),
    TELEM_MATCH_L3: ("l3", "allowed"),
    TELEM_MATCH_L4_WILD: ("l4_wild", "allowed"),
    TELEM_MATCH_NONE: ("none", "denied"),
    TELEM_MATCH_FRAG: ("frag", "denied"),
}

# stage-column → cilium_datapath_stage_total{stage} label
STAGE_COLUMN_LABELS = {
    TELEM_LB_DNAT: "lb_dnat",
    TELEM_CT_NEW: "ct_new",
    TELEM_CT_ESTABLISHED: "ct_established",
    TELEM_CT_REPLY: "ct_reply",
    TELEM_CT_RELATED: "ct_related",
    TELEM_CT_BYPASS_ALLOW: "ct_bypass_allow",
    TELEM_CT_DELETE: "ct_delete",
    TELEM_IPCACHE_WORLD: "ipcache_world",
    TELEM_PROXY_REDIRECT: "proxy_redirect",
}


def telemetry_from_outputs(
    out, directions, valid: Optional[int] = None
) -> np.ndarray:
    """Fold per-tuple DatapathVerdicts columns into the same
    [2, TELEM_COLS] u64 stage histogram the device accumulator
    carries — the host side of the bit-identity gate, and the fold
    non-instrumented callers (replay audit paths, tests) use.

    ``directions``: per-tuple direction array (required —
    DatapathVerdicts does not carry the direction column).  ``valid``
    truncates padded batches to their live prefix."""
    if directions is None:
        raise ValueError(
            "telemetry_from_outputs needs the per-tuple direction "
            "array (DatapathVerdicts does not carry it)"
        )
    cols = {
        name: np.asarray(getattr(out, name))
        for name in (
            "pre_dropped", "ct_result", "match_kind", "allowed",
            "ct_delete", "proxy_port", "lb_slave", "ipcache_miss",
        )
    }
    directions = np.asarray(directions)
    if valid is not None:
        cols = {k: a[:valid] for k, a in cols.items()}
        directions = directions[:valid]
    masks = telemetry_masks(
        cols["pre_dropped"], cols["ct_result"], cols["match_kind"],
        cols["allowed"], cols["ct_delete"], cols["proxy_port"],
        cols["lb_slave"], cols["ipcache_miss"], xp=np,
    )
    telem = np.zeros((2, TELEM_COLS), np.uint64)
    for d in (0, 1):
        in_dir = directions == d
        for c, mask in enumerate(masks):
            telem[d, c] = int(np.sum(mask & in_dir))
    return telem


def fold_telemetry(telem, registry=None) -> None:
    """Fold a [2, TELEM_COLS] stage histogram DELTA into the metrics
    registry (process-global by default).  Callers pass the amount
    accumulated since their last fold — the counters are cumulative,
    so refolding the same buffer double-counts."""
    if registry is None:
        from cilium_tpu.metrics import registry as registry_
        registry = registry_
    telem = np.asarray(telem)
    for d, dname in enumerate(DIRECTION_NAMES):
        row = telem[d]
        if int(row[TELEM_FORWARDED]):
            registry.forward_count.inc(
                dname, value=int(row[TELEM_FORWARDED])
            )
        for col, reason in DROP_COLUMN_REASONS.items():
            if int(row[col]):
                registry.drop_count.inc(
                    reason, dname, value=int(row[col])
                )
        for col, (match, action) in MATCH_COLUMN_LABELS.items():
            if int(row[col]):
                registry.policy_verdict_total.inc(
                    dname, match, action, value=int(row[col])
                )
        for col, stage in STAGE_COLUMN_LABELS.items():
            if int(row[col]):
                registry.datapath_stage_total.inc(
                    stage, dname, value=int(row[col])
                )


def fold_telemetry_per_chip(per_chip, registry=None) -> np.ndarray:
    """Fold an all-gathered [n_chips, 2, TELEM_COLS] per-chip stage
    histogram DELTA (engine.sharded.make_mesh_evaluator with
    collect_telemetry) into the registry: the chip-summed mesh total
    goes through fold_telemetry — so ONE /metrics/prometheus scrape
    covers the whole mesh — and each chip's rows land under the
    `chip` label in cilium_datapath_telemetry_per_chip_total for
    imbalance debugging.  Summing a column over `chip` equals the
    mesh-total counters by construction.  Returns the mesh-total
    [2, TELEM_COLS] u64 histogram."""
    if registry is None:
        from cilium_tpu.metrics import registry as registry_
        registry = registry_
    per_chip = np.asarray(per_chip).astype(np.uint64)
    total = per_chip.sum(axis=0)
    fold_telemetry(total, registry=registry)
    for chip in range(per_chip.shape[0]):
        for d, dname in enumerate(DIRECTION_NAMES):
            row = per_chip[chip, d]
            for col, name in enumerate(TELEM_NAMES):
                if int(row[col]):
                    registry.telemetry_per_chip.inc(
                        str(chip), name, dname,
                        value=int(row[col]),
                    )
    return total


def telemetry_summary(telem) -> Dict[str, Dict[str, int]]:
    """{direction: {column name: count}} rendering of a stage
    histogram, for bench JSON lines and `cilium status`-style dumps
    (zero columns omitted)."""
    telem = np.asarray(telem)
    out: Dict[str, Dict[str, int]] = {}
    for d, dname in enumerate(DIRECTION_NAMES):
        row = {
            name: int(v)
            for name, v in zip(TELEM_NAMES, telem[d])
            if int(v)
        }
        out[dname.lower()] = row
    return out


def telemetry_consistent(telem) -> bool:
    """Internal-consistency invariants of one histogram: the final
    outcomes partition the batch, and the drop columns partition the
    denials.  The bench gate asserts this on the device buffer before
    comparing against the host fold."""
    telem = np.asarray(telem)
    ok = True
    for d in (0, 1):
        row = telem[d]
        ok &= int(row[TELEM_TOTAL]) == int(row[TELEM_FORWARDED]) + int(
            row[TELEM_DENIED]
        )
        ok &= int(row[TELEM_DENIED]) == (
            int(row[TELEM_DROP_PREFILTER])
            + int(row[TELEM_DROP_POLICY])
            + int(row[TELEM_DROP_FRAG])
        )
        ok &= int(row[TELEM_TOTAL]) == (
            int(row[TELEM_CT_NEW])
            + int(row[TELEM_CT_ESTABLISHED])
            + int(row[TELEM_CT_REPLY])
            + int(row[TELEM_CT_RELATED])
        )
    return bool(ok)
