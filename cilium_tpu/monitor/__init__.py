"""Monitor: the datapath event bus (perf-ring analog).

Re-design of /root/reference/monitor + pkg/monitor: the datapath emits
DropNotify (bpf/lib/drop.h:40), TraceNotify (bpf/lib/trace.h:84) and
debug events into a perf ring read by cilium-node-monitor and fanned
out to `cilium monitor` clients.  Here the verdict engine's batched
outputs are folded into events on an in-process bus with subscriber
fan-out; a remote-socket transport can wrap the same bus.
"""

from cilium_tpu.monitor.events import (
    AgentNotify,
    DropNotify,
    LogRecordNotify,
    PolicyVerdictNotify,
    TraceNotify,
    drop_reason_name,
)
from cilium_tpu.monitor.bus import MonitorBus, verdicts_to_events

__all__ = [
    "MonitorBus",
    "DropNotify",
    "TraceNotify",
    "PolicyVerdictNotify",
    "AgentNotify",
    "LogRecordNotify",
    "drop_reason_name",
    "verdicts_to_events",
]
