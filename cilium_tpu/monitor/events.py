"""Monitor event payload types.

Port of /root/reference/pkg/monitor/{datapath_drop.go,datapath_trace.go,
agent.go} payloads and the bpf-side structs they decode
(bpf/lib/drop.h:40 drop_notify, bpf/lib/trace.h trace_notify).
Message type ids follow bpf/lib/common.h:209-215.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

# bpf/lib/common.h:209-215
NOTIFY_UNSPEC = 0
NOTIFY_DROP = 1
NOTIFY_DBG_MSG = 2
NOTIFY_DBG_CAPTURE = 3
NOTIFY_TRACE = 4
# agent-level messages (pkg/monitor/messages.go)
NOTIFY_AGENT = 5
NOTIFY_LOG_RECORD = 6
NOTIFY_POLICY_VERDICT = 7

# bpf/lib/common.h:237-269 drop reasons (negative datapath returns).
DROP_REASONS: Dict[int, str] = {
    -130: "Invalid source mac",
    -131: "Invalid destination mac",
    -132: "Invalid source ip",
    -133: "Policy denied (L3)",
    -134: "Invalid packet",
    -135: "CT: Truncated or invalid header",
    -136: "CT: Missing ACK in known connection",
    -137: "CT: Unknown L4 protocol",
    -138: "CT: Can't create entry from packet",
    -139: "Unsupported L3 protocol",
    -140: "Missed tail call",
    -141: "Error writing to packet",
    -142: "Unknown L4 protocol",
    -143: "Unknown ICMPv4 code",
    -144: "Unknown ICMPv4 type",
    -145: "Unknown ICMPv6 code",
    -146: "Unknown ICMPv6 type",
    -147: "Error retrieving tunnel key",
    -148: "Error retrieving tunnel options",
    -149: "Invalid Geneve option",
    -150: "Unknown L3 target address",
    -151: "Not a local target address",
    -152: "No matching local container found",
    -153: "Error while correcting L3 checksum",
    -154: "Error while correcting L4 checksum",
    -155: "CT: Map insertion failed",
    -156: "Invalid IPv6 extension header",
    -157: "Fragmentation needed",
    -158: "No matching service",
    -159: "Policy denied (L4)",
    -160: "No tunnel/encapsulation endpoint",
    -161: "Failed to insert into proxymap",
    -162: "Policy denied (CIDR)",
    # framework extension: bounded-admission overload shedding (the
    # serving plane drops with attribution instead of queueing
    # unboundedly; no bpf/lib/common.h analog in the snapshot ported)
    -163: "Overload",
}

DROP_OVERLOAD = -163


def drop_reason_name(code: int) -> str:
    """pkg/monitor/datapath_drop.go dropReason."""
    return DROP_REASONS.get(code, f"unknown ({code})")


@dataclass
class DropNotify:
    """drop_notify (bpf/lib/drop.h:40)."""

    source: int  # endpoint id
    hash: int = 0
    orig_len: int = 0
    cap_len: int = 0
    src_label: int = 0
    dst_label: int = 0
    dst_id: int = 0
    reason: int = 0  # positive DROP_* magnitude (common.h sign flip)
    ifindex: int = 0

    type: int = NOTIFY_DROP


# trace observation points (bpf/lib/trace.h:30-47)
TRACE_TO_LXC = 0
TRACE_TO_PROXY = 1
TRACE_TO_HOST = 2
TRACE_TO_STACK = 3
TRACE_TO_OVERLAY = 4
TRACE_FROM_LXC = 5
TRACE_FROM_PROXY = 6
TRACE_FROM_HOST = 7
TRACE_FROM_STACK = 8
TRACE_FROM_OVERLAY = 9
TRACE_FROM_NETWORK = 10


@dataclass
class TraceNotify:
    """trace_notify (bpf/lib/trace.h:84 send_trace_notify)."""

    source: int
    obs_point: int = TRACE_TO_LXC
    hash: int = 0
    orig_len: int = 0
    cap_len: int = 0
    src_label: int = 0
    dst_label: int = 0
    dst_id: int = 0
    reason: int = 0
    ifindex: int = 0

    type: int = NOTIFY_TRACE


@dataclass
class PolicyVerdictNotify:
    """Per-tuple verdict record (the PolicyVerdictNotification option,
    pkg/option; payload shaped after the drop/trace structs)."""

    source: int
    src_label: int
    dst_label: int
    dport: int
    proto: int
    ingress: bool
    allowed: bool
    proxy_port: int = 0
    match_kind: int = 0

    type: int = NOTIFY_POLICY_VERDICT


@dataclass
class AgentNotify:
    """pkg/monitor/agent.go:27: agent-level event (policy updated,
    endpoint created/deleted, ...)."""

    kind: str
    text: str = ""

    type: int = NOTIFY_AGENT


@dataclass
class LogRecordNotify:
    """L7 access-log record reference (pkg/proxy/accesslog)."""

    endpoint_id: int
    l7_proto: str
    verdict: str
    info: str = ""

    type: int = NOTIFY_LOG_RECORD
