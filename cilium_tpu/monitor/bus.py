"""The monitor event bus + verdict-batch folding.

Fan-out mirrors monitor/monitor.go: the node monitor reads the perf
ring and multiplexes to subscribed listeners; slow listeners in the
reference get disconnected — here `lost_events` counts what a bounded
subscriber queue dropped (the perf ring's lost-samples counter,
pkg/bpf/perf.go).

`verdicts_to_events` folds a batched engine output into DropNotify /
PolicyVerdictNotify events host-side.  The datapath stays batched; the
event bus is a control-plane consumer, so the per-event Python cost
only applies to the (sampled or denied) slice that gets folded.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

from cilium_tpu.engine.oracle import MATCH_NONE, MATCH_FRAG_DROP
from cilium_tpu.monitor.events import (
    DropNotify,
    PolicyVerdictNotify,
)

DROP_POLICY_CODE = 133  # magnitude of DROP_POLICY (common.h:240)
DROP_FRAG_CODE = 157  # magnitude of DROP_FRAG_NOSUPPORT (common.h:264)


class MonitorBus:
    def __init__(self, queue_size: int = 65536) -> None:
        self._lock = threading.Lock()
        self._subscribers: List[Deque] = []
        self._callbacks: List[Callable] = []
        self.queue_size = queue_size
        self.lost_events = 0

    def subscribe_queue(self) -> Deque:
        """Bounded queue subscriber; overflow counts lost events."""
        q: Deque = deque(maxlen=self.queue_size)
        with self._lock:
            self._subscribers.append(q)
        return q

    def subscribe(self, fn: Callable) -> None:
        with self._lock:
            self._callbacks.append(fn)

    def publish(self, event) -> None:
        with self._lock:
            for q in self._subscribers:
                if len(q) == q.maxlen:
                    self.lost_events += 1
                q.append(event)
            callbacks = list(self._callbacks)
        for fn in callbacks:
            fn(event)


def verdicts_to_events(
    bus: MonitorBus,
    verdicts,
    ep_ids: np.ndarray,
    identities: np.ndarray,
    dports: np.ndarray,
    protos: np.ndarray,
    directions: np.ndarray,
    emit_allowed: bool = False,
) -> int:
    """Fold a batch: denied tuples → DropNotify (+ verdict events when
    PolicyVerdictNotification is on / emit_allowed).  Returns the
    number of events published."""
    allowed = np.asarray(verdicts.allowed)
    kind = np.asarray(verdicts.match_kind)
    proxy = np.asarray(verdicts.proxy_port)
    n = 0
    idx = (
        np.arange(len(allowed))
        if emit_allowed
        else np.nonzero(allowed == 0)[0]
    )
    for i in idx:
        if allowed[i]:
            bus.publish(
                PolicyVerdictNotify(
                    source=int(ep_ids[i]),
                    src_label=int(identities[i]),
                    dst_label=0,
                    dport=int(dports[i]),
                    proto=int(protos[i]),
                    ingress=int(directions[i]) == 0,
                    allowed=True,
                    proxy_port=int(proxy[i]),
                    match_kind=int(kind[i]),
                )
            )
        else:
            reason = (
                DROP_FRAG_CODE
                if kind[i] == MATCH_FRAG_DROP
                else DROP_POLICY_CODE
            )
            bus.publish(
                DropNotify(
                    source=int(ep_ids[i]),
                    src_label=int(identities[i]),
                    reason=reason,
                )
            )
        n += 1
    return n
