"""The monitor event bus + verdict-batch folding.

Fan-out mirrors monitor/monitor.go: the node monitor reads the perf
ring and multiplexes to subscribed listeners; slow listeners in the
reference get disconnected — here `lost_events` counts what a bounded
subscriber queue dropped (the perf ring's lost-samples counter,
pkg/bpf/perf.go).

`verdicts_to_events` folds a batched engine output into DropNotify /
PolicyVerdictNotify events host-side.  The datapath stays batched; the
event bus is a control-plane consumer, so the per-event Python cost
only applies to the (sampled or denied) slice that gets folded.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

from cilium_tpu.engine.oracle import (
    MATCH_FRAG_DROP,
    MATCH_L3,
    MATCH_L4,
    MATCH_L4_WILD,
    MATCH_NONE,
)
from cilium_tpu.monitor.events import (
    DropNotify,
    PolicyVerdictNotify,
)

DROP_POLICY_CODE = 133  # magnitude of DROP_POLICY (common.h:240)
DROP_FRAG_CODE = 157  # magnitude of DROP_FRAG_NOSUPPORT (common.h:264)


class MonitorBus:
    def __init__(self, queue_size: int = 65536) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._subscribers: List[Deque] = []
        self._callbacks: List[Callable] = []
        self.queue_size = queue_size
        self.lost_events = 0  # bus-global (all subscribers)
        self._drops: dict = {}  # id(queue) → that subscriber's drops

    def subscribe_queue(self) -> Deque:
        """Bounded queue subscriber; overflow counts lost events."""
        q: Deque = deque(maxlen=self.queue_size)
        with self._lock:
            self._subscribers.append(q)
        return q

    def subscribe(self, fn: Callable) -> None:
        with self._lock:
            self._callbacks.append(fn)

    def unsubscribe_queue(self, q: Deque) -> bool:
        """Detach a queue subscriber (monitor listener hang-up,
        monitor.go listener cleanup)."""
        with self._lock:
            self._drops.pop(id(q), None)
            try:
                self._subscribers.remove(q)
                return True
            except ValueError:
                return False

    def wait_for_events(self, q: Deque, timeout: float) -> bool:
        """Block until `q` has events or the timeout lapses — the
        long-poll wakeup (no 50 ms spin; publish() notifies)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cond:
            while not q:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    def queue_drops(self, q: Deque, reset: bool = False) -> int:
        """Overflow drops charged to ONE subscriber's queue.  With
        `reset` the counter reads as a delta (long-poll replies report
        drops SINCE the last poll, not a forever-cumulative number)."""
        with self._lock:
            if reset:
                return self._drops.pop(id(q), 0)
            return self._drops.get(id(q), 0)

    def publish(self, event) -> None:
        with self._lock:
            for q in self._subscribers:
                if len(q) == q.maxlen:
                    # full ring: drop the NEWEST event, like a full
                    # perf ring rejecting the producer's write.  The
                    # old deque-maxlen append silently evicted the
                    # OLDEST instead, so the lost-event counter
                    # disagreed with which event was actually gone.
                    self.lost_events += 1
                    self._drops[id(q)] = (
                        self._drops.get(id(q), 0) + 1
                    )
                    continue
                q.append(event)
            callbacks = list(self._callbacks)
            self._cond.notify_all()
        for fn in callbacks:
            fn(event)


def verdicts_to_events(
    bus: MonitorBus,
    verdicts,
    ep_ids: np.ndarray,
    identities: np.ndarray,
    dports: np.ndarray,
    protos: np.ndarray,
    directions: np.ndarray,
    emit_allowed: bool = False,
    verdict_eps: "Optional[set]" = None,
    emit_drops: bool = True,
    emit_trace: bool = False,
    sample: Optional[int] = None,
    metrics_registry=None,
) -> int:
    """Fold a batch: denied tuples → DropNotify (+ verdict events when
    PolicyVerdictNotification is on / emit_allowed).  `verdict_eps`
    scopes allowed-verdict emission to specific endpoint ids — the
    per-endpoint PolicyVerdictNotification option (`cilium endpoint
    config`), which the reference compiles into that endpoint's
    datapath alone.  `emit_drops` is the DropNotification option
    (DROP_NOTIFY #define); `emit_trace` emits a per-flow TraceNotify
    for allowed tuples — the TraceNotification option at
    MonitorAggregationLevel none (TRACE_NOTIFY; higher aggregation
    levels suppress per-packet traces, monitor.go).  `sample` caps
    the number of per-tuple events PUBLISHED this call (the
    MonitorAggregation analog for batch folds: the aggregate
    counters below stay exact over the whole batch; only the
    per-event fan-out is head-sampled) — None publishes everything.
    `metrics_registry` redirects the counter feed away from the
    process-global registry — callers whose traffic was ALREADY
    folded there (e.g. from the device telemetry accumulator) pass a
    private Registry so the same tuples aren't counted twice.
    Returns the number of events published."""
    allowed = np.asarray(verdicts.allowed)
    kind = np.asarray(verdicts.match_kind)
    proxy = np.asarray(verdicts.proxy_port)
    # datapath traffic counters (metrics.go drop_count_total /
    # forward_count_total / policy_verdict_total), batched — one inc
    # per label set, canonical bpf/lib/common.h reason names
    if metrics_registry is None:
        from cilium_tpu.metrics import registry as _metrics
    else:
        _metrics = metrics_registry
    from cilium_tpu.monitor.events import drop_reason_name

    for dirv, dname in ((0, "INGRESS"), (1, "EGRESS")):
        in_dir = np.asarray(directions) == dirv
        fwd = int((allowed.astype(bool) & in_dir).sum())
        if fwd:
            _metrics.forward_count.inc(dname, value=fwd)
        denied = (~allowed.astype(bool)) & in_dir
        frag = denied & (kind == MATCH_FRAG_DROP)
        pol = denied & ~frag
        if int(pol.sum()):
            _metrics.drop_count.inc(
                drop_reason_name(-DROP_POLICY_CODE), dname,
                value=int(pol.sum()),
            )
        if int(frag.sum()):
            _metrics.drop_count.inc(
                drop_reason_name(-DROP_FRAG_CODE), dname,
                value=int(frag.sum()),
            )
        # the lattice verdict histogram (match kind implies action)
        for code, match, action in (
            (MATCH_L4, "l4", "allowed"),
            (MATCH_L3, "l3", "allowed"),
            (MATCH_L4_WILD, "l4_wild", "allowed"),
            (MATCH_NONE, "none", "denied"),
            (MATCH_FRAG_DROP, "frag", "denied"),
        ):
            n_kind = int(((kind == code) & in_dir).sum())
            if n_kind:
                _metrics.policy_verdict_total.inc(
                    dname, match, action, value=n_kind
                )
    import time as _time

    _metrics.event_ts.set("api", value=_time.time())
    n = 0
    per_ep = None
    if emit_allowed:
        idx = np.arange(len(allowed))
    elif verdict_eps:
        ep_arr = np.asarray(ep_ids)
        per_ep = np.isin(ep_arr, np.asarray(sorted(verdict_eps)))
        idx = np.nonzero((allowed == 0) | per_ep)[0]
    else:
        idx = np.nonzero(allowed == 0)[0]

    def _verdict_event(i, is_allowed: bool) -> PolicyVerdictNotify:
        return PolicyVerdictNotify(
            source=int(ep_ids[i]),
            src_label=int(identities[i]),
            dst_label=0,
            dport=int(dports[i]),
            proto=int(protos[i]),
            ingress=int(directions[i]) == 0,
            allowed=is_allowed,
            proxy_port=int(proxy[i]),
            match_kind=int(kind[i]),
        )

    if emit_trace:
        from cilium_tpu.monitor.events import TraceNotify

        for i in np.nonzero(allowed)[0]:
            if sample is not None and n >= sample:
                break
            # the local endpoint is the DESTINATION of an ingress
            # flow and the SOURCE of an egress one (send_trace_notify
            # carries distinct src/dst; 0 = remote/unknown)
            ingress_i = int(directions[i]) == 0
            bus.publish(
                TraceNotify(
                    source=0 if ingress_i else int(ep_ids[i]),
                    src_label=int(identities[i]),
                    dst_id=int(ep_ids[i]) if ingress_i else 0,
                )
            )
            n += 1
    for i in idx:
        if sample is not None and n >= sample:
            break
        if allowed[i]:
            bus.publish(_verdict_event(i, True))
        else:
            if emit_allowed or (
                per_ep is not None and per_ep[i]
            ):
                # PolicyVerdictNotification covers BOTH outcomes in
                # the reference (monitor/datapath_policy.go): opted-in
                # endpoints see the deny verdict alongside the drop
                bus.publish(_verdict_event(i, False))
                n += 1
            if not emit_drops:
                continue
            reason = (
                DROP_FRAG_CODE
                if kind[i] == MATCH_FRAG_DROP
                else DROP_POLICY_CODE
            )
            bus.publish(
                DropNotify(
                    source=int(ep_ids[i]),
                    src_label=int(identities[i]),
                    reason=reason,
                )
            )
        n += 1
    return n
