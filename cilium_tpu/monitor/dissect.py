"""Monitor payload dissection — the `cilium monitor -v` renderer.

Behavioral analog of /root/reference/pkg/monitor/dissect.go (+ the
per-event formatters of pkg/monitor/{drop,trace,logrecord}.go): the
reference decodes the raw packet bytes riding each perf event into a
connection summary ("tcp 10.1.2.3:80 -> 10.4.5.6:4001") and renders
each notification as one human line.  This framework's "payload" is
the native 24-byte flow record (native/tupledec.cpp `struct
flow_record`): `dissect_flow_buffer` walks a record buffer through the
native decoder and emits the same connection-summary shape, and
`dissect_event` renders monitor events the way the reference's
monitor formatters do.
"""

from __future__ import annotations

import ipaddress
from typing import Iterator, List

from cilium_tpu.monitor.events import drop_reason_name

_PROTO_NAMES = {1: "icmp", 6: "tcp", 17: "udp", 58: "icmpv6"}


def proto_name(proto: int) -> str:
    return _PROTO_NAMES.get(int(proto), str(int(proto)))


def _ip(addr: int) -> str:
    return str(ipaddress.IPv4Address(int(addr) & 0xFFFFFFFF))


def connection_summary(
    saddr: int, daddr: int, sport: int, dport: int, proto: int
) -> str:
    """GetConnectionSummary's output shape for one flow tuple."""
    return (
        f"{proto_name(proto)} "
        f"{_ip(saddr)}:{int(sport)} -> {_ip(daddr)}:{int(dport)}"
    )


def dissect_flow_buffer(buf: bytes) -> Iterator[str]:
    """Decode a native flow-record buffer (tupledec.cpp records) and
    yield one dissected line per record — the Dissect(true, data)
    path over this framework's wire format."""
    from cilium_tpu.native import decode_flow_records

    rec = decode_flow_records(buf)
    n = len(rec["saddr"])
    for i in range(n):
        direction = "ingress" if int(rec["direction"][i]) == 0 else "egress"
        yield (
            f"{connection_summary(rec['saddr'][i], rec['daddr'][i], rec['sport'][i], rec['dport'][i], rec['proto'][i])} "
            f"{direction} ep={int(rec['ep_id'][i])} "
            f"identity={int(rec['identity'][i])}"
        )


def dissect_event(ev: dict) -> str:
    """One monitor event (the REST stream's JSON form) → the
    reference's one-line monitor rendering."""
    kind = ev.get("event", "")
    if kind == "DropNotify":
        # "xx drop (reason) flow ... to endpoint N" (drop.go)
        return (
            f"xx drop ({drop_reason_name(-abs(int(ev.get('reason', 0))))}) "
            f"to endpoint {ev.get('source', 0)}, "
            f"identity {ev.get('src_label', 0)}"
        )
    if kind == "TraceNotify":
        # "-> endpoint N flow ..." (trace.go observation points)
        return (
            f"-> endpoint {ev.get('dst_id', 0)} "
            f"from endpoint {ev.get('source', 0)}, "
            f"identity {ev.get('src_label', 0)}"
        )
    if kind == "PolicyVerdictNotify":
        action = "allow" if ev.get("allowed") else "deny"
        direction = "ingress" if ev.get("ingress") else "egress"
        line = (
            f"Policy verdict log: flow to endpoint "
            f"{ev.get('source', 0)}, {direction}, "
            f"identity {ev.get('src_label', 0)}, "
            f"dport {ev.get('dport', 0)}/"
            f"{proto_name(ev.get('proto', 0))}, action {action}"
        )
        if ev.get("proxy_port"):
            line += f", redirected to proxy {ev['proxy_port']}"
        return line
    if kind == "LogRecordNotify":
        return (
            f"{ev.get('l7_proto', 'l7')} "
            f"{ev.get('verdict', '')} {ev.get('info', '')}".rstrip()
        )
    if kind == "AgentNotify":
        return f"agent: {ev.get('text', '')}"
    # unknown kinds render their raw fields, never drop silently
    return f"{kind or 'unknown'}: {ev}"


def dissect_events(events: List[dict]) -> List[str]:
    return [dissect_event(ev) for ev in events]
