"""Numeric security identities and the local identity allocator.

Re-design of /root/reference/pkg/identity/{numericidentity.go,identity.go,
allocator.go,cache.go}.  In the reference, identities are allocated
cluster-wide through a kvstore CAS allocator; here the allocator is an
in-process store with the same semantics (sorted-label key -> id,
refcounted), pluggable onto the distributed kvstore shim in
cilium_tpu.runtime.kvstore for multi-host operation.

The identity *universe* (id -> LabelArray) is the object the policy
compiler consumes: every table tensor is indexed by NumericIdentity, so
the universe snapshot (reference getLabelsMap, pkg/endpoint/policy.go:194)
is the shape-defining input of a compilation.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from cilium_tpu import labels as lbl
from cilium_tpu.labels import Label, LabelArray, Labels

# numericidentity.go:20-35
MINIMAL_NUMERIC_IDENTITY = 256
USER_RESERVED_NUMERIC_IDENTITY = 128
INVALID_IDENTITY = 0

# numericidentity.go:38-58
IDENTITY_UNKNOWN = 0
RESERVED_HOST = 1
RESERVED_WORLD = 2
RESERVED_CLUSTER = 3
RESERVED_HEALTH = 4
RESERVED_INIT = 5

RESERVED_IDENTITIES: Dict[str, int] = {
    lbl.ID_NAME_HOST: RESERVED_HOST,
    lbl.ID_NAME_WORLD: RESERVED_WORLD,
    lbl.ID_NAME_HEALTH: RESERVED_HEALTH,
    lbl.ID_NAME_CLUSTER: RESERVED_CLUSTER,
    lbl.ID_NAME_INIT: RESERVED_INIT,
}

RESERVED_IDENTITY_NAMES: Dict[int, str] = {
    v: k for k, v in RESERVED_IDENTITIES.items()
}

# ClusterID partitioning (numericidentity.go:162): identity 24-bit local
# id + 8-bit cluster id.
CLUSTER_ID_SHIFT = 16


def get_reserved_id(name: str) -> int:
    return RESERVED_IDENTITIES.get(name, IDENTITY_UNKNOWN)


def is_user_reserved_identity(num_id: int) -> bool:
    return USER_RESERVED_NUMERIC_IDENTITY <= num_id < MINIMAL_NUMERIC_IDENTITY

def is_reserved_identity(num_id: int) -> bool:
    return num_id < MINIMAL_NUMERIC_IDENTITY


@dataclass
class Identity:
    """identity.go:27: numeric id + the labels that produced it."""

    id: int
    labels: Labels

    @property
    def label_array(self) -> LabelArray:
        # labels never mutate after allocation, so the array form is
        # computed once — identity_cache() walks every identity per
        # snapshot and the conversion dominated control-plane latency
        arr = self.__dict__.get("_label_array")
        if arr is None:
            arr = self.labels.to_label_array()
            self.__dict__["_label_array"] = arr
        return arr

    @property
    def sha256(self) -> str:
        return self.labels.sha256sum()

    def __repr__(self) -> str:
        return f"Identity({self.id}, {sorted(self.labels)})"


def reserved_identity(num_id: int) -> Identity:
    name = RESERVED_IDENTITY_NAMES[num_id]
    return Identity(
        id=num_id,
        labels=Labels(
            {name: Label(key=name, value="", source=lbl.SOURCE_RESERVED)}
        ),
    )


# id -> LabelArray; the compiler's shape-defining input.
IdentityCache = Dict[int, LabelArray]


class IdentityAllocator:
    """Label-set -> numeric identity allocator (allocator.go:122,534).

    Same contract as the reference's kvstore allocator: the key is the
    canonical sorted-label serialization; allocation is idempotent and
    refcounted; ids start at MINIMAL_NUMERIC_IDENTITY.  `local_only`
    allocations (CIDR identities, allocator.go:112) live in a disjoint
    id range so they never collide with cluster-scope ids.
    """

    # Local (CIDR) identities: the reference marks them with the top bit
    # of the 32-bit space via identity.LocalIdentityFlag in later
    # versions; v1.2 allocates them from the shared pool but never
    # publishes them.  We use a dedicated high range for clarity.
    LOCAL_IDENTITY_BASE = 1 << 24

    def __init__(self, backend=None):
        self._lock = threading.RLock()
        self._by_key: Dict[bytes, Identity] = {}
        self._by_id: Dict[int, Identity] = {}
        self._refs: Dict[int, int] = {}
        self._next_id = MINIMAL_NUMERIC_IDENTITY
        self._next_local = self.LOCAL_IDENTITY_BASE
        self._events: List = []
        self._listeners: List = []
        # universe version: bumps whenever the id → labels map
        # changes; identity_cache() snapshots are cached against it
        # and the fleet compiler uses it as its universe_token
        self._version = 0
        self._cache_snapshot = None
        # Optional distributed backend (runtime.kvstore.Allocator shim).
        self._backend = backend

    # -- allocation ----------------------------------------------------------

    def allocate(self, labels_in: Labels,
                 local_only: bool = False) -> (Identity, bool):
        """AllocateIdentity (identity/allocator.go:122).

        Reserved label sets resolve to reserved identities without
        touching the allocator (allocator.go:131-140).  Returns
        (identity, is_new).
        """
        reserved = self._lookup_reserved(labels_in)
        if reserved is not None:
            return reserved, False

        key = Labels(labels_in).sorted_list()
        with self._lock:
            existing = self._by_key.get(key)
            if existing is not None:
                self._refs[existing.id] += 1
                return existing, False
            if self._backend is not None and not local_only:
                num = self._backend.allocate(key)
            elif local_only:
                num = self._next_local
                self._next_local += 1
            else:
                num = self._next_id
                self._next_id += 1
            ident = Identity(id=num, labels=Labels(labels_in))
            self._by_key[key] = ident
            self._by_id[num] = ident
            self._refs[num] = 1
            self._version += 1
            self._notify("upsert", ident)
            return ident, True

    def release(self, ident: Identity) -> bool:
        """Refcounted release; True when the last ref is gone."""
        if is_reserved_identity(ident.id) and ident.id < USER_RESERVED_NUMERIC_IDENTITY:
            return False
        key = ident.labels.sorted_list()
        with self._lock:
            if ident.id not in self._refs:
                return False
            self._refs[ident.id] -= 1
            if self._refs[ident.id] > 0:
                return False
            del self._refs[ident.id]
            self._by_key.pop(key, None)
            self._by_id.pop(ident.id, None)
            self._version += 1
            if self._backend is not None:
                self._backend.release(key)
            self._notify("delete", ident)
            return True

    # -- lookup --------------------------------------------------------------

    def _lookup_reserved(self, labels_in: Labels) -> Optional[Identity]:
        """Reserved-source label -> reserved identity (allocator.go:250)."""
        if len(labels_in) != 1:
            return None
        (only,) = labels_in.values()
        if only.source != lbl.SOURCE_RESERVED:
            return None
        num = get_reserved_id(only.key)
        if num == IDENTITY_UNKNOWN:
            return None
        return reserved_identity(num)

    def lookup_by_id(self, num_id: int) -> Optional[Identity]:
        if num_id in RESERVED_IDENTITY_NAMES:
            return reserved_identity(num_id)
        with self._lock:
            return self._by_id.get(num_id)

    def lookup_by_labels(self, labels_in: Labels) -> Optional[Identity]:
        reserved = self._lookup_reserved(labels_in)
        if reserved is not None:
            return reserved
        with self._lock:
            return self._by_key.get(Labels(labels_in).sorted_list())

    # -- universe snapshot ---------------------------------------------------

    @property
    def version(self) -> int:
        """Universe version — pairs with identity_cache() snapshots
        (the fleet compiler's universe_token)."""
        with self._lock:
            return self._version

    def identity_cache(self) -> IdentityCache:
        """GetIdentityCache + reserved ids (endpoint getLabelsMap,
        pkg/endpoint/policy.go:194-211): snapshot of all known identities
        including the reserved ones.

        Cached against the allocator version: rebuilding this map is
        O(universe) and used to dominate every regeneration sweep.
        Consumers treat the returned dict as read-only."""
        with self._lock:
            cached = self._cache_snapshot
            if cached is not None and cached[0] == self._version:
                return cached[1]
            cache: IdentityCache = {
                num: ident.label_array
                for num, ident in self._by_id.items()
            }
            for num in RESERVED_IDENTITY_NAMES:
                cache[num] = reserved_identity(num).label_array
            self._cache_snapshot = (self._version, cache)
            return cache

    def identity_cache_versioned(self) -> Tuple[IdentityCache, int]:
        """(identity_cache(), version) read under one lock."""
        with self._lock:
            return self.identity_cache(), self._version

    # -- events (identity/cache.go:82 identityWatcher) -----------------------

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def _notify(self, kind: str, ident: Identity) -> None:
        for fn in list(self._listeners):
            fn(kind, ident)
