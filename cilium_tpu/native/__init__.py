"""Native runtime components (C++ via ctypes).

Build-on-demand: the shared library compiles with g++ the first time
it's needed and is cached next to the source (the reference compiles
its datapath C at runtime too — pkg/datapath/loader/compile.go).  If
the toolchain is unavailable the pure-NumPy fallbacks in
`loader` keep everything functional (DryMode analog).
"""

from cilium_tpu.native.loader import (
    NativeUnavailable,
    alignment_check,
    decode_flow_records,
    encode_flow_records,
    native_available,
    parse_packets,
)

__all__ = [
    "decode_flow_records",
    "encode_flow_records",
    "parse_packets",
    "alignment_check",
    "native_available",
    "NativeUnavailable",
]
