// Native flow-tuple decoder: packets / flow records → SoA tuple arrays.
//
// TPU-native equivalent of the reference's native parsing layers: the
// eBPF header parse of bpf/bpf_lxc.c:718-760 (ethertype dispatch, IPv4
// header walk, fragment detection, L4 port extraction) and the
// monitor's payload decoding (pkg/monitor/dissect.go), done in C++ so
// the replay/ingest path feeds the device at memory bandwidth instead
// of Python-loop speed.  Compiled by cilium_tpu.native at import time
// (g++ -O2 -shared), bound via ctypes — no pybind11 in the image.
//
// ABI contract: all functions use plain C types over SoA arrays; the
// struct layouts below are mirrored by ctypes in
// cilium_tpu/native/loader.py and verified by the alignchecker
// (analog of pkg/alignchecker: Go-vs-C struct layout verification).

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// Hubble-style binary flow record, little-endian, 24 bytes.
struct flow_record {
    uint32_t ep_id;
    uint32_t identity;
    uint32_t saddr;
    uint32_t daddr;
    uint16_t sport;
    uint16_t dport;
    uint8_t proto;
    uint8_t direction;
    uint8_t flags;  // bit0: is_fragment
    uint8_t pad;
};

// layout probes for the alignchecker
size_t flow_record_size() { return sizeof(struct flow_record); }
size_t flow_record_offset(int field) {
    switch (field) {
        case 0: return offsetof(struct flow_record, ep_id);
        case 1: return offsetof(struct flow_record, identity);
        case 2: return offsetof(struct flow_record, saddr);
        case 3: return offsetof(struct flow_record, daddr);
        case 4: return offsetof(struct flow_record, sport);
        case 5: return offsetof(struct flow_record, dport);
        case 6: return offsetof(struct flow_record, proto);
        case 7: return offsetof(struct flow_record, direction);
        case 8: return offsetof(struct flow_record, flags);
        default: return (size_t)-1;
    }
}

// Decode n fixed-size flow records into SoA arrays.
// Returns the number of records decoded.
size_t decode_flow_records(const uint8_t* buf, size_t n,
                           uint32_t* ep_id, uint32_t* identity,
                           uint32_t* saddr, uint32_t* daddr,
                           uint16_t* sport, uint16_t* dport,
                           uint8_t* proto, uint8_t* direction,
                           uint8_t* is_fragment) {
    const struct flow_record* rec =
        reinterpret_cast<const struct flow_record*>(buf);
    for (size_t i = 0; i < n; i++) {
        ep_id[i] = rec[i].ep_id;
        identity[i] = rec[i].identity;
        saddr[i] = rec[i].saddr;
        daddr[i] = rec[i].daddr;
        sport[i] = rec[i].sport;
        dport[i] = rec[i].dport;
        proto[i] = rec[i].proto;
        direction[i] = rec[i].direction;
        is_fragment[i] = rec[i].flags & 1;
    }
    return n;
}

static inline uint16_t load_be16(const uint8_t* p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline uint32_t load_be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

#define ETH_HLEN 14
#define ETH_P_IP 0x0800
#define IP_MF_AND_OFFSET 0x3FFF  // IP_MF | IP_OFFSET mask

// Parse n raw Ethernet frames (offsets[i]..offsets[i+1] in buf) into
// tuple arrays — the from-container parse (bpf_lxc.c:718: ethertype
// validate → IPv4 header → fragment check → L4 ports; fragments get
// zeroed ports, matching the datapath's is_fragment handling).
// Non-IPv4 / truncated frames get proto 0 and valid[i] = 0.
size_t parse_packets(const uint8_t* buf, const uint64_t* offsets,
                     size_t n, uint32_t* saddr, uint32_t* daddr,
                     uint16_t* sport, uint16_t* dport, uint8_t* proto,
                     uint8_t* is_fragment, uint8_t* valid,
                     uint32_t* pkt_len) {
    size_t ok = 0;
    for (size_t i = 0; i < n; i++) {
        const uint8_t* pkt = buf + offsets[i];
        size_t len = (size_t)(offsets[i + 1] - offsets[i]);
        saddr[i] = daddr[i] = 0;
        sport[i] = dport[i] = 0;
        proto[i] = 0;
        is_fragment[i] = 0;
        valid[i] = 0;
        pkt_len[i] = (uint32_t)len;
        if (len < ETH_HLEN + 20) continue;
        if (load_be16(pkt + 12) != ETH_P_IP) continue;
        const uint8_t* ip = pkt + ETH_HLEN;
        uint8_t ihl = (uint8_t)(ip[0] & 0x0F);
        if ((ip[0] >> 4) != 4 || ihl < 5) continue;
        size_t ip_hlen = (size_t)ihl * 4;
        if (len < ETH_HLEN + ip_hlen) continue;
        uint16_t frag_off = load_be16(ip + 6);
        proto[i] = ip[9];
        saddr[i] = load_be32(ip + 12);
        daddr[i] = load_be32(ip + 16);
        if ((frag_off & IP_MF_AND_OFFSET) != 0) {
            is_fragment[i] = 1;
        } else if ((proto[i] == 6 || proto[i] == 17) &&
                   len >= ETH_HLEN + ip_hlen + 4) {
            const uint8_t* l4 = ip + ip_hlen;
            sport[i] = load_be16(l4);
            dport[i] = load_be16(l4 + 2);
        }
        valid[i] = 1;
        ok++;
    }
    return ok;
}

// Encode flow records (test/bench harness generator, C-side so large
// replay files are produced at full speed too).
void encode_flow_records(uint8_t* buf, size_t n, const uint32_t* ep_id,
                         const uint32_t* identity, const uint32_t* saddr,
                         const uint32_t* daddr, const uint16_t* sport,
                         const uint16_t* dport, const uint8_t* proto,
                         const uint8_t* direction,
                         const uint8_t* is_fragment) {
    struct flow_record* rec = reinterpret_cast<struct flow_record*>(buf);
    for (size_t i = 0; i < n; i++) {
        rec[i].ep_id = ep_id[i];
        rec[i].identity = identity[i];
        rec[i].saddr = saddr[i];
        rec[i].daddr = daddr[i];
        rec[i].sport = sport[i];
        rec[i].dport = dport[i];
        rec[i].proto = proto[i];
        rec[i].direction = direction[i];
        rec[i].flags = is_fragment[i] ? 1 : 0;
        rec[i].pad = 0;
    }
}

}  // extern "C"
