"""ctypes bindings + build-on-demand for the native decoder.

Includes the alignchecker (analog of /root/reference/pkg/alignchecker:
verify at load time that the Python-side record layout byte-matches
the C++ struct — the ABI race detector between the two languages) and
NumPy fallbacks mirroring the C semantics exactly (used when g++ is
missing, and as the differential-testing oracle).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tupledec.cpp")
_LIB = os.path.join(_HERE, "_tupledec.so")

# Python-side declaration of struct flow_record (must byte-match C++).
FLOW_RECORD_DTYPE = np.dtype(
    [
        ("ep_id", "<u4"),
        ("identity", "<u4"),
        ("saddr", "<u4"),
        ("daddr", "<u4"),
        ("sport", "<u2"),
        ("dport", "<u2"),
        ("proto", "u1"),
        ("direction", "u1"),
        ("flags", "u1"),
        ("pad", "u1"),
    ]
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


class NativeUnavailable(RuntimeError):
    pass


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(
        _SRC
    ):
        return ctypes.CDLL(_LIB)
    try:
        subprocess.run(
            [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                _SRC, "-o", _LIB,
            ],
            check=True,
            capture_output=True,
        )
        return ctypes.CDLL(_LIB)
    except (subprocess.CalledProcessError, FileNotFoundError):
        _build_failed = True
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is None and not _build_failed:
            lib = _build()
            if lib is not None:
                _configure(lib)
                try:
                    alignment_check(lib)
                except NativeUnavailable:
                    # ABI skew: never serve the mismatched library —
                    # permanently fall back to the NumPy path (first
                    # call raises so the skew is loud, later calls
                    # degrade safely)
                    _build_failed = True
                    raise
                _lib = lib
        return _lib


def _configure(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.flow_record_size.restype = ctypes.c_size_t
    lib.flow_record_offset.restype = ctypes.c_size_t
    lib.flow_record_offset.argtypes = [ctypes.c_int]
    lib.decode_flow_records.restype = ctypes.c_size_t
    lib.decode_flow_records.argtypes = [
        u8p, ctypes.c_size_t, u32p, u32p, u32p, u32p, u16p, u16p,
        u8p, u8p, u8p,
    ]
    lib.parse_packets.restype = ctypes.c_size_t
    lib.parse_packets.argtypes = [
        u8p, u64p, ctypes.c_size_t, u32p, u32p, u16p, u16p, u8p, u8p,
        u8p, u32p,
    ]
    lib.encode_flow_records.restype = None
    lib.encode_flow_records.argtypes = [
        u8p, ctypes.c_size_t, u32p, u32p, u32p, u32p, u16p, u16p,
        u8p, u8p, u8p,
    ]


def alignment_check(lib: Optional[ctypes.CDLL] = None) -> None:
    """pkg/alignchecker analog: NumPy dtype layout == C++ struct."""
    lib = lib or _get_lib()
    if lib is None:
        return
    if int(lib.flow_record_size()) != FLOW_RECORD_DTYPE.itemsize:
        raise NativeUnavailable(
            f"flow_record size mismatch: C++ {lib.flow_record_size()} "
            f"vs Python {FLOW_RECORD_DTYPE.itemsize}"
        )
    for i, name in enumerate(
        ["ep_id", "identity", "saddr", "daddr", "sport", "dport",
         "proto", "direction", "flags"]
    ):
        c_off = int(lib.flow_record_offset(i))
        py_off = FLOW_RECORD_DTYPE.fields[name][1]
        if c_off != py_off:
            raise NativeUnavailable(
                f"flow_record.{name} offset mismatch: C++ {c_off} vs "
                f"Python {py_off}"
            )


def native_available() -> bool:
    return _get_lib() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# flow records
# ---------------------------------------------------------------------------


def decode_flow_records(buf: bytes):
    """Binary flow records → SoA dict of arrays.

    A buffer whose length is not a whole number of records is
    REJECTED with ValueError: a truncated/corrupt stream silently
    dropping its tail (native path) or crashing deep in numpy
    (fallback path) would either hide data loss or take the daemon
    down — the API server maps this to HTTP 400."""
    from cilium_tpu import faultinject

    faultinject.fire("native.decode")
    buf = faultinject.corrupt_bytes("native.decode", buf)
    if len(buf) % FLOW_RECORD_DTYPE.itemsize:
        raise ValueError(
            f"truncated flow record buffer: {len(buf)} bytes is not "
            f"a multiple of the {FLOW_RECORD_DTYPE.itemsize}-byte "
            f"record size"
        )
    n = len(buf) // FLOW_RECORD_DTYPE.itemsize
    out = {
        "ep_id": np.empty(n, np.uint32),
        "identity": np.empty(n, np.uint32),
        "saddr": np.empty(n, np.uint32),
        "daddr": np.empty(n, np.uint32),
        "sport": np.empty(n, np.uint16),
        "dport": np.empty(n, np.uint16),
        "proto": np.empty(n, np.uint8),
        "direction": np.empty(n, np.uint8),
        "is_fragment": np.empty(n, np.uint8),
    }
    lib = _get_lib()
    if lib is not None:
        raw = np.frombuffer(buf, dtype=np.uint8)
        lib.decode_flow_records(
            _ptr(raw, ctypes.c_uint8), n,
            _ptr(out["ep_id"], ctypes.c_uint32),
            _ptr(out["identity"], ctypes.c_uint32),
            _ptr(out["saddr"], ctypes.c_uint32),
            _ptr(out["daddr"], ctypes.c_uint32),
            _ptr(out["sport"], ctypes.c_uint16),
            _ptr(out["dport"], ctypes.c_uint16),
            _ptr(out["proto"], ctypes.c_uint8),
            _ptr(out["direction"], ctypes.c_uint8),
            _ptr(out["is_fragment"], ctypes.c_uint8),
        )
        return out
    rec = np.frombuffer(buf, dtype=FLOW_RECORD_DTYPE)
    for name in out:
        if name == "is_fragment":
            out[name] = (rec["flags"] & 1).astype(np.uint8)
        else:
            out[name] = rec[name].copy()
    return out


def encode_flow_records(
    ep_id, identity, saddr, daddr, sport, dport, proto, direction,
    is_fragment,
) -> bytes:
    n = len(ep_id)
    rec = np.zeros(n, dtype=FLOW_RECORD_DTYPE)
    rec["ep_id"] = ep_id
    rec["identity"] = identity
    rec["saddr"] = saddr
    rec["daddr"] = daddr
    rec["sport"] = sport
    rec["dport"] = dport
    rec["proto"] = proto
    rec["direction"] = direction
    rec["flags"] = np.asarray(is_fragment, np.uint8) & 1
    return rec.tobytes()


# ---------------------------------------------------------------------------
# raw packets
# ---------------------------------------------------------------------------


def parse_packets(buf: bytes, offsets: np.ndarray):
    """Raw Ethernet frames → tuple arrays.  `offsets` is [n+1] u64
    frame boundaries into buf."""
    n = len(offsets) - 1
    out = {
        "saddr": np.zeros(n, np.uint32),
        "daddr": np.zeros(n, np.uint32),
        "sport": np.zeros(n, np.uint16),
        "dport": np.zeros(n, np.uint16),
        "proto": np.zeros(n, np.uint8),
        "is_fragment": np.zeros(n, np.uint8),
        "valid": np.zeros(n, np.uint8),
        "pkt_len": np.zeros(n, np.uint32),
    }
    lib = _get_lib()
    offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
    # The C++ decoder computes offsets[i+1]-offsets[i] as size_t and
    # indexes buf with it; validate here so bad input fails loudly in
    # Python instead of under/overflowing in native code.
    if n > 0:
        if (np.diff(offsets.astype(np.int64)) < 0).any():
            raise ValueError("packet offsets must be non-decreasing")
        if int(offsets[-1]) > len(buf):
            raise ValueError(
                f"packet offsets exceed buffer length ({int(offsets[-1])}"
                f" > {len(buf)})"
            )
    if lib is not None:
        raw = np.frombuffer(buf, dtype=np.uint8)
        lib.parse_packets(
            _ptr(raw, ctypes.c_uint8),
            _ptr(offsets, ctypes.c_uint64), n,
            _ptr(out["saddr"], ctypes.c_uint32),
            _ptr(out["daddr"], ctypes.c_uint32),
            _ptr(out["sport"], ctypes.c_uint16),
            _ptr(out["dport"], ctypes.c_uint16),
            _ptr(out["proto"], ctypes.c_uint8),
            _ptr(out["is_fragment"], ctypes.c_uint8),
            _ptr(out["valid"], ctypes.c_uint8),
            _ptr(out["pkt_len"], ctypes.c_uint32),
        )
        return out
    # NumPy fallback — semantics identical to the C++ (and used as its
    # differential-test oracle in tests/test_native.py)
    for i in range(n):
        pkt = buf[int(offsets[i]) : int(offsets[i + 1])]
        out["pkt_len"][i] = len(pkt)
        if len(pkt) < 34 or pkt[12:14] != b"\x08\x00":
            continue
        ip = pkt[14:]
        ihl = ip[0] & 0x0F
        if (ip[0] >> 4) != 4 or ihl < 5 or len(ip) < ihl * 4:
            continue
        frag_off = int.from_bytes(ip[6:8], "big")
        out["proto"][i] = ip[9]
        out["saddr"][i] = int.from_bytes(ip[12:16], "big")
        out["daddr"][i] = int.from_bytes(ip[16:20], "big")
        if frag_off & 0x3FFF:
            out["is_fragment"][i] = 1
        elif ip[9] in (6, 17) and len(ip) >= ihl * 4 + 4:
            l4 = ip[ihl * 4 :]
            out["sport"][i] = int.from_bytes(l4[0:2], "big")
            out["dport"][i] = int.from_bytes(l4[2:4], "big")
        out["valid"][i] = 1
    return out
