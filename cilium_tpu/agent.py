"""The agent process: Daemon + REST API on a unix socket.

The analog of the reference's `cilium-agent` binary (daemon/main.go):
constructs the Daemon (optionally against a remote kvstore and a
state dir for checkpoint/restore) and serves the api/v1 surface on a
unix socket for the CLI and other clients.

    python -m cilium_tpu.agent --socket /tmp/cilium-tpu.sock \
        [--kvstore host:port] [--state-dir DIR] [--node NAME]
"""

from __future__ import annotations

import argparse
import signal
import threading


def main() -> None:
    ap = argparse.ArgumentParser(prog="cilium-tpu-agent")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--kvstore", default=None, help="host:port")
    ap.add_argument("--state-dir", default=None)
    ap.add_argument("--node", default="node-0")
    ap.add_argument(
        "--trace-sample-rate", type=float, default=None,
        help="span-plane head-sampling probability (default 1.0: "
        "trace every request; turn down under load)",
    )
    args = ap.parse_args()

    if args.trace_sample_rate is not None:
        from cilium_tpu import tracing

        tracing.tracer.sample_rate = args.trace_sample_rate

    kvstore = None
    if args.kvstore:
        from cilium_tpu.kvstore.client import RemoteBackend

        host, sep, port = args.kvstore.rpartition(":")
        if not sep or not port.isdigit():
            ap.error(
                f"--kvstore expects host:port, got {args.kvstore!r}"
            )
        kvstore = RemoteBackend(host=host or "127.0.0.1", port=int(port))

    from cilium_tpu.api.server import APIServer
    from cilium_tpu.daemon import Daemon

    daemon = Daemon(
        kvstore=kvstore,
        node_name=args.node,
        state_dir=args.state_dir,
    )
    server = APIServer(daemon, args.socket).start()
    stop = threading.Event()

    def _term(signum, frame):
        server.stop()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    print(f"cilium-tpu-agent serving on {args.socket}", flush=True)
    try:
        stop.wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
