"""ToFQDNs DNS poller.

Behavioral port of /root/reference/pkg/fqdn (dnspoller.go) and its
daemon wiring (daemon/policy.go:172 MarkToFQDNRules + NewDaemon's
DNSPoller with AddGeneratedRules → PolicyAdd):
  - rules with ToFQDNs.MatchName are marked and tracked;
  - the poller periodically resolves each name (resolver injectable —
    the reference uses net.LookupIP; tests use a fake) and, when the
    IP set changes, regenerates the rule's ToCIDRSet with generated
    /32 entries and re-injects the rule via PolicyAdd(Replace);
  - generated rules carry the cilium-generated label so deletes and
    reverts stay scoped.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Set

from cilium_tpu.labels import Label, LabelArray
from cilium_tpu.policy.api.rule import CIDRRule, Rule

# dnspoller.go DNSPollerInterval default
DEFAULT_POLL_INTERVAL = 5.0

GENERATED_LABEL = Label(
    "ToFQDN-UUID", "", "cilium-generated"
)

Resolver = Callable[[str], List[str]]  # name → IPs


def has_to_fqdns(rule: Rule) -> bool:
    return any(e.to_fqdns for e in rule.egress)


def system_resolver(name: str) -> List[str]:
    """Resolve via the host stack (the reference's DNSPoller uses the
    Go resolver the same way, pkg/fqdn/dnspoller.go LookupIPs).
    Returns [] on failure — an unresolvable name simply generates no
    toCIDRSet entries this poll, like a DNS timeout in the
    reference."""
    import socket

    try:
        infos = socket.getaddrinfo(name, None, proto=socket.IPPROTO_TCP)
    except (socket.gaierror, OSError):
        return []
    out = []
    for _family, _type, _proto, _canon, addr in infos:
        ip = addr[0]
        if ip not in out:
            out.append(ip)
    return out


class DNSPoller:
    def __init__(
        self,
        policy_add: Callable[[List[Rule]], int],
        resolver: Resolver,
        poll_interval: float = DEFAULT_POLL_INTERVAL,
    ) -> None:
        self.policy_add = policy_add
        self.resolver = resolver
        self.poll_interval = poll_interval
        self._lock = threading.Lock()
        # MarkToFQDNRules: tracked source rules keyed by their label
        # string (dnspoller.go's uuid association)
        self._rules: Dict[str, Rule] = {}
        self._last_ips: Dict[str, Set[str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration (daemon/policy.go:172) --------------------------------

    def mark_to_fqdn_rules(self, rules: List[Rule]) -> None:
        with self._lock:
            for rule in rules:
                if has_to_fqdns(rule):
                    key = ",".join(str(l) for l in rule.labels)
                    self._rules[key] = copy.deepcopy(rule)

    @property
    def managed(self) -> bool:
        with self._lock:
            return bool(self._rules)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop_managing(self, label_key: str) -> None:
        with self._lock:
            self._rules.pop(label_key, None)

    # -- polling -------------------------------------------------------------

    def poll_once(self) -> int:
        """One resolution pass; returns the number of rules
        re-injected."""
        with self._lock:
            rules = dict(self._rules)
        updated = 0
        for key, rule in rules.items():
            names = [
                sel.match_name
                for egress in rule.egress
                for sel in egress.to_fqdns
            ]
            changed = False
            resolved: Dict[str, List[str]] = {}
            for name in names:
                try:
                    ips = sorted(self.resolver(name))
                except Exception:
                    continue  # resolution errors keep old state
                resolved[name] = ips
                if set(ips) != self._last_ips.get(f"{key}/{name}", set()):
                    changed = True
            if not changed:
                continue
            generated = copy.deepcopy(rule)
            # tag the re-injected rule (dnspoller.go: generated rules
            # carry a cilium-generated ToFQDN label for scoping)
            if not any(
                l.source == "cilium-generated" for l in generated.labels
            ):
                generated.labels = LabelArray(
                    list(generated.labels) + [GENERATED_LABEL]
                )
            for egress in generated.egress:
                if not egress.to_fqdns:
                    continue
                egress.to_cidr_set = [
                    c for c in egress.to_cidr_set if not c.generated
                ]
                for sel in egress.to_fqdns:
                    for ip in resolved.get(sel.match_name, []):
                        plen = 128 if ":" in ip else 32
                        egress.to_cidr_set.append(
                            CIDRRule(cidr=f"{ip}/{plen}", generated=True)
                        )
            # AddGeneratedRules → PolicyAdd(Replace) keyed by labels
            self.policy_add([generated])
            for name, ips in resolved.items():
                self._last_ips[f"{key}/{name}"] = set(ips)
            updated += 1
        return updated

    def start(self) -> "DNSPoller":
        def loop() -> None:
            while not self._stop.wait(self.poll_interval):
                self.poll_once()

        self._thread = threading.Thread(
            target=loop, name="fqdn-poller", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
