"""Service load balancing (bpf/lib/lb.h + pkg/loadbalancer/service).

Host side manages frontends/backends with service-ID allocation;
device side selects backends and produces DNAT rewrites for batches.
"""

from cilium_tpu.lb.service import L3n4Addr, Service, ServiceManager
from cilium_tpu.lb.device import LBTables, compile_lb, lb_select_batch

__all__ = [
    "L3n4Addr",
    "Service",
    "ServiceManager",
    "LBTables",
    "compile_lb",
    "lb_select_batch",
]
