"""Host service model.

Behavioral port of /root/reference/pkg/loadbalancer (L3n4Addr,
LBSVC), pkg/service (service ID allocation) and the lbmap layout
(pkg/maps/lbmap: master slot 0 holds the backend count, slots 1..N
hold backends; RevNAT map id → frontend for reply rewriting).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class L3n4Addr:
    """pkg/loadbalancer L3n4Addr: ip + port + proto."""

    ip: str
    port: int
    protocol: int = 6

    def ip_u32(self) -> int:
        return int(ipaddress.IPv4Address(self.ip))


@dataclass
class Backend:
    addr: L3n4Addr
    weight: int = 0


@dataclass
class Service:
    frontend: L3n4Addr
    backends: List[Backend] = field(default_factory=list)
    id: int = 0  # service / rev-NAT id


class ServiceManager:
    """pkg/service: frontend → service with stable id allocation (the
    id doubles as the rev_nat_index stored in CT entries)."""

    def __init__(self) -> None:
        self.by_frontend: Dict[L3n4Addr, Service] = {}
        self.by_id: Dict[int, Service] = {}
        self._next_id = 1

    def upsert(
        self, frontend: L3n4Addr, backends: List[L3n4Addr]
    ) -> Service:
        svc = self.by_frontend.get(frontend)
        if svc is None:
            svc = Service(frontend=frontend, id=self._next_id)
            self._next_id += 1
            self.by_frontend[frontend] = svc
            self.by_id[svc.id] = svc
        svc.backends = [Backend(b) for b in backends]
        return svc

    def delete(self, frontend: L3n4Addr) -> bool:
        svc = self.by_frontend.pop(frontend, None)
        if svc is None:
            return False
        self.by_id.pop(svc.id, None)
        return True

    def lookup(self, frontend: L3n4Addr) -> Optional[Service]:
        return self.by_frontend.get(frontend)

    def rev_nat(self, rev_nat_index: int) -> Optional[L3n4Addr]:
        """RevNAT map: id → frontend (reply-path source rewrite)."""
        svc = self.by_id.get(rev_nat_index)
        return svc.frontend if svc else None
