"""Device LB: batched service lookup + backend selection + DNAT.

Reproduces the datapath semantics of bpf/lib/lb.h:
  - lb4_lookup_service (lb.h:604): exact (vip, dport, proto) match —
    here a device hash-table probe;
  - lb4_select_slave (lb.h:158): `slave = (hash % count) + 1` on the
    flow hash (lb.h:185).  The kernel uses skb->hash (kernel jhash);
    we use the same FNV-1a flow hash as the CT table — the invariant
    that matters (stable per-flow backend, uniform spread) is
    preserved, the exact hash function is kernel-internal either way;
  - established flows reuse ct_state.slave instead of re-hashing
    (lb.h lb4_local path) — pass `ct_slave` from the CT lookup;
  - DNAT: daddr/dport rewritten to the chosen backend; rev_nat_index
    returned for the CT entry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from cilium_tpu.engine.hashtable import (
    HashTable,
    build_hash_table,
    fnv1a_device,
    lookup_batch,
)
from cilium_tpu.lb.service import ServiceManager

MAX_BACKENDS = 64


@dataclass
class LBTables:
    """svc hash table over (vip, port<<8|proto) + backend matrix."""

    table: HashTable
    svc_rev_nat: np.ndarray  # u16 [S]
    svc_count: np.ndarray  # i32 [S] backend count
    backend_ip: np.ndarray  # u32 [S, MAX_BACKENDS]
    backend_port: np.ndarray  # u16 [S, MAX_BACKENDS]

    def tree_flatten(self):
        return (
            (
                self.table,
                self.svc_rev_nat,
                self.svc_count,
                self.backend_ip,
                self.backend_port,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            LBTables,
            lambda t: t.tree_flatten(),
            lambda aux, ch: LBTables.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def compile_lb(mgr: ServiceManager) -> LBTables:
    services = sorted(mgr.by_frontend.values(), key=lambda s: s.id)
    s = max(len(services), 1)
    keys = np.zeros((len(services), 2), dtype=np.uint32)
    rev_nat = np.zeros(s, dtype=np.uint16)
    count = np.zeros(s, dtype=np.int32)
    backend_ip = np.zeros((s, MAX_BACKENDS), dtype=np.uint32)
    backend_port = np.zeros((s, MAX_BACKENDS), dtype=np.uint16)
    for i, svc in enumerate(services):
        if len(svc.backends) > MAX_BACKENDS:
            raise ValueError(
                f"service {svc.frontend} has more than {MAX_BACKENDS} "
                f"backends"
            )
        keys[i, 0] = svc.frontend.ip_u32()
        keys[i, 1] = (svc.frontend.port << 8) | svc.frontend.protocol
        rev_nat[i] = svc.id
        count[i] = len(svc.backends)
        for j, backend in enumerate(svc.backends):
            backend_ip[i, j] = backend.addr.ip_u32()
            backend_port[i, j] = backend.addr.port
    table = build_hash_table(keys)
    return LBTables(
        table=table,
        svc_rev_nat=rev_nat,
        svc_count=count,
        backend_ip=backend_ip,
        backend_port=backend_port,
    )


def flow_hash(saddr, daddr, sport, dport, proto):
    """The flow hash used for slave selection (≙ get_hash_recalc)."""
    import jax.numpy as jnp

    words = jnp.stack(
        [
            saddr.astype(jnp.uint32),
            daddr.astype(jnp.uint32),
            (sport.astype(jnp.uint32) << 16) | dport.astype(jnp.uint32),
            proto.astype(jnp.uint32),
        ],
        axis=1,
    )
    return fnv1a_device(words)


def lb_select_batch(
    tables: LBTables,
    saddr,
    daddr,
    sport,
    dport,
    proto,
    ct_slave=None,
):
    """Returns (is_service bool [B], slave i32 [B], new_daddr u32 [B],
    new_dport i32 [B], rev_nat i32 [B]).  Non-service flows pass
    through with their original daddr/dport and rev_nat 0."""
    import jax.numpy as jnp

    query = jnp.stack(
        [
            daddr.astype(jnp.uint32),
            (dport.astype(jnp.uint32) << 8) | proto.astype(jnp.uint32),
        ],
        axis=1,
    )
    found, svc_idx = lookup_batch(tables.table, query)
    count = jnp.asarray(tables.svc_count)[svc_idx]
    found = found & (count > 0)

    h = flow_hash(saddr, daddr, sport, dport, proto)
    slave = (h % jnp.maximum(count, 1).astype(jnp.uint32)).astype(
        jnp.int32
    ) + 1
    if ct_slave is not None:
        # established flows stick to their backend (lb4_local)
        reuse = (ct_slave > 0) & (ct_slave <= count)
        slave = jnp.where(reuse, ct_slave, slave)

    backend = jnp.clip(slave - 1, 0, MAX_BACKENDS - 1)
    new_daddr = jnp.asarray(tables.backend_ip)[svc_idx, backend]
    new_dport = jnp.asarray(tables.backend_port)[svc_idx, backend].astype(
        jnp.int32
    )
    rev_nat = jnp.asarray(tables.svc_rev_nat)[svc_idx].astype(jnp.int32)

    new_daddr = jnp.where(found, new_daddr, daddr.astype(jnp.uint32))
    new_dport = jnp.where(found, new_dport, dport.astype(jnp.int32))
    rev_nat = jnp.where(found, rev_nat, 0)
    slave = jnp.where(found, slave, 0)
    return found, slave, new_daddr, new_dport, rev_nat
