"""Device LB: batched service lookup + backend selection + DNAT.

Reproduces the datapath semantics of bpf/lib/lb.h:
  - lb4_lookup_service (lb.h:604): exact (vip, dport, proto) match;
  - lb4_select_slave (lb.h:158): `slave = (hash % count) + 1` on the
    flow hash (lb.h:185).  The kernel uses skb->hash (kernel jhash);
    we use the same FNV-1a flow hash as the CT table — the invariant
    that matters (stable per-flow backend, uniform spread) is
    preserved, the exact hash function is kernel-internal either way;
  - established flows reuse ct_state.slave instead of re-hashing
    (lb.h lb4_local path) — pass `ct_slave` from the CT lookup;
  - DNAT: daddr/dport rewritten to the chosen backend; rev_nat_index
    returned for the CT entry.

TPU-first layout (same reasoning as ct/device.py): the service map is
BUCKETIZED [Cs, 128] u32 rows — one row gather resolves the service —
and each service's backends live in ONE [128]-lane row of a backend
row table (a second row gather), with the chosen backend extracted by
a masked lane sum instead of a per-backend gather.

Service entry packing (4 × u32, 32 entries per bucket), PLANAR within
the row — lanes [32k, 32k+32) hold word k of entries 0..31, so the
kernel extracts each word as a contiguous [B, 32] slice (interleaved
layouts force padded reshapes; see ct/device.py):
  w0  vip
  w1  dport << 16 | proto
  w2  rev_nat << 16 | backend count
  w3  backend row index
Backend row (128 × u32): lanes [0, 64) backend ips; lanes [64, 96)
backend ports packed two per lane (low half = even backend).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from cilium_tpu.engine.hashtable import _fnv1a_host, fnv1a_device
from cilium_tpu.lb.service import ServiceManager

MAX_BACKENDS = 64
SVC_ENTRY_WORDS = 4
BUCKET_LANES = 128
SVC_PER_BUCKET = BUCKET_LANES // SVC_ENTRY_WORDS  # 32
SVC_STASH = 64
_EMPTY_W1 = np.uint32(0xFFFFFFFF)  # dport<<16|proto can't be all-ones

# -- inline layout (the default): service + backends in ONE row -------------
# Each 128-lane row holds two 64-lane service slots:
#   lane 0 = vip, lane 1 = dport << 16 | proto,
#   lane 2 = rev_nat << 16 | backend count, lane 3 = pad,
#   lanes [4, 44)  = backend ips (40),
#   lanes [44, 64) = backend ports, two per lane (low half = even).
# One row gather resolves service AND backends; the separate backend-
# row gather of the classic layout (~7 ns/flow on v5e) disappears.
# Services with more than INLINE_MAX_BACKENDS fall back to the classic
# two-gather LBTables at compile time.
INLINE_MAX_BACKENDS = 40
INLINE_SLOT = 64
INLINE_STASH = 8


@dataclass
class LBInline:
    """Inline service rows + small stash (pytree)."""

    rows: np.ndarray  # u32 [R, 128] — two 64-lane service slots per row
    stash: np.ndarray  # u32 [INLINE_STASH, 64]
    n_buckets: int

    def tree_flatten(self):
        return ((self.rows, self.stash), self.n_buckets)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


@dataclass
class LBTables:
    """svc bucket rows + stash + backend row table (pytree)."""

    buckets: np.ndarray  # u32 [Cs, 128]
    stash: np.ndarray  # u32 [SVC_STASH, 4]
    backend_rows: np.ndarray  # u32 [S, 128]
    n_buckets: int

    def tree_flatten(self):
        return (
            (self.buckets, self.stash, self.backend_rows),
            self.n_buckets,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux)


def _register_pytree() -> None:
    try:
        import jax

        for cls in (LBTables, LBInline):
            jax.tree_util.register_pytree_node(
                cls,
                lambda t: t.tree_flatten(),
                lambda aux, ch, c=cls: c.tree_unflatten(aux, ch),
            )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def _svc_slot(svc) -> np.ndarray:
    """Pack one service into a 64-lane inline slot."""
    slot = np.zeros(INLINE_SLOT, dtype=np.uint32)
    slot[0] = svc.frontend.ip_u32()
    slot[1] = ((svc.frontend.port & 0xFFFF) << 16) | (
        svc.frontend.protocol & 0xFF
    )
    slot[2] = ((svc.id & 0xFFFF) << 16) | (len(svc.backends) & 0xFFFF)
    for j, backend in enumerate(svc.backends):
        slot[4 + j] = backend.addr.ip_u32()
        slot[4 + INLINE_MAX_BACKENDS + (j >> 1)] |= np.uint32(
            (backend.addr.port & 0xFFFF) << (16 * (j & 1))
        )
    return slot


def compile_lb_inline(mgr: ServiceManager) -> "LBInline | None":
    """Inline single-gather layout; None if any service exceeds the
    inline backend budget (caller falls back to compile_lb)."""
    services = sorted(mgr.by_frontend.values(), key=lambda s: s.id)
    if any(len(s.backends) > INLINE_MAX_BACKENDS for s in services):
        return None
    nb = 16
    while nb < len(services):
        nb *= 2
    # identical full-hash frontends never separate by doubling; cap
    # the growth and fall back to the classic layout (32 per bucket +
    # larger stash) instead of doubling unboundedly
    nb_cap = max(nb * 64, 1 << 12)
    while nb <= nb_cap:
        rows = np.zeros((nb, BUCKET_LANES), dtype=np.uint32)
        rows[:, 1] = _EMPTY_W1
        rows[:, INLINE_SLOT + 1] = _EMPTY_W1
        stash = np.zeros((INLINE_STASH, INLINE_SLOT), dtype=np.uint32)
        stash[:, 1] = _EMPTY_W1
        fill = [0] * nb
        stash_fill = 0
        ok = True
        for svc in services:
            vip = svc.frontend.ip_u32()
            w1 = ((svc.frontend.port & 0xFFFF) << 16) | (
                svc.frontend.protocol & 0xFF
            )
            words = np.array([[vip, w1]], dtype=np.uint32)
            b = int(_fnv1a_host(words)[0]) & (nb - 1)
            if fill[b] < 2:
                rows[b, fill[b] * INLINE_SLOT : (fill[b] + 1) * INLINE_SLOT] = (
                    _svc_slot(svc)
                )
                fill[b] += 1
            elif stash_fill < INLINE_STASH:
                stash[stash_fill] = _svc_slot(svc)
                stash_fill += 1
            else:
                ok = False
                break
        if ok:
            # ship the stash at its occupied pow2 prefix (trimmed
            # lanes can never match — the probe broadcast-compares
            # every stash row per tuple, so capacity rows are wasted
            # hot-path work; empty at realistic service counts)
            from cilium_tpu.engine.hashtable import trim_pow2_prefix

            return LBInline(
                rows=rows,
                stash=trim_pow2_prefix(stash, stash_fill),
                n_buckets=nb,
            )
        nb *= 2
    return None  # pathological hash collisions: caller uses classic


def compile_lb(mgr: ServiceManager):
    """Compile the service map for the datapath: the inline
    single-gather layout when every service fits the inline backend
    budget (the overwhelmingly common case — the classic layout costs
    a second dependent row gather per flow), else the classic
    bucketized layout with separate backend rows."""
    inline = compile_lb_inline(mgr)
    if inline is not None:
        return inline
    return compile_lb_classic(mgr)


def compile_lb_classic(mgr: ServiceManager) -> LBTables:
    services = sorted(mgr.by_frontend.values(), key=lambda s: s.id)
    nb = 16
    while nb * 8 < max(len(services), 1):
        nb *= 2
    buckets = np.zeros((nb, BUCKET_LANES), dtype=np.uint32)
    buckets[:, SVC_PER_BUCKET : 2 * SVC_PER_BUCKET] = _EMPTY_W1
    stash = np.zeros((SVC_STASH, SVC_ENTRY_WORDS), dtype=np.uint32)
    stash[:, 1] = _EMPTY_W1
    fill = [0] * nb
    stash_fill = 0
    backend_rows = np.zeros(
        (max(len(services), 1), BUCKET_LANES), dtype=np.uint32
    )
    for row_idx, svc in enumerate(services):
        if len(svc.backends) > MAX_BACKENDS:
            raise ValueError(
                f"service {svc.frontend} has more than {MAX_BACKENDS} "
                f"backends"
            )
        vip = svc.frontend.ip_u32()
        w1 = ((svc.frontend.port & 0xFFFF) << 16) | (
            svc.frontend.protocol & 0xFF
        )
        for j, backend in enumerate(svc.backends):
            backend_rows[row_idx, j] = backend.addr.ip_u32()
            half = 16 * (j & 1)
            backend_rows[row_idx, 64 + (j >> 1)] |= np.uint32(
                (backend.addr.port & 0xFFFF) << half
            )
        entry = (
            vip,
            w1,
            ((svc.id & 0xFFFF) << 16) | (len(svc.backends) & 0xFFFF),
            row_idx,
        )
        words = np.array([[vip, w1]], dtype=np.uint32)
        b = int(_fnv1a_host(words)[0]) & (nb - 1)
        if fill[b] < SVC_PER_BUCKET:
            i = fill[b]
            for k in range(SVC_ENTRY_WORDS):
                buckets[b, k * SVC_PER_BUCKET + i] = entry[k]
            fill[b] += 1
        elif stash_fill < SVC_STASH:
            stash[stash_fill] = entry
            stash_fill += 1
        else:
            raise ValueError("LB service bucket and stash overflow")
    return LBTables(
        buckets=buckets,
        stash=stash,
        backend_rows=backend_rows,
        n_buckets=nb,
    )


def flow_hash(saddr, daddr, sport, dport, proto):
    """The flow hash used for slave selection (≙ get_hash_recalc)."""
    import jax.numpy as jnp

    words = jnp.stack(
        [
            saddr.astype(jnp.uint32),
            daddr.astype(jnp.uint32),
            (sport.astype(jnp.uint32) << 16) | dport.astype(jnp.uint32),
            proto.astype(jnp.uint32),
        ],
        axis=1,
    )
    return fnv1a_device(words)


def lb_service_key(daddr, dport, proto):
    """(vip, w1) compare words of the service probe — shared by the
    single-chip and routed (mesh) selects."""
    import jax.numpy as jnp

    vip = daddr.astype(jnp.uint32)
    w1 = ((dport.astype(jnp.uint32) & 0xFFFF) << 16) | (
        proto.astype(jnp.uint32) & 0xFF
    )
    return vip, w1


def lb_slot_outputs(slot, found, fh, ct_slave=None):
    """Backend selection from a resolved 64-lane inline service slot.
    Returns RAW outputs (found, slave, new_daddr, new_dport, rev_nat)
    with every column zero-masked by `found` and the not-found
    passthrough NOT applied — so two disjoint slot sources (a bucket
    row on its owning mesh shard, the replicated stash) sum exactly,
    and the caller applies the passthrough once after combining."""
    import jax.numpy as jnp

    meta = slot[:, 2]
    count = (meta & 0xFFFF).astype(jnp.int32)
    rev_nat = (meta >> 16).astype(jnp.int32)
    found = found & (count > 0)

    slave = (fh % jnp.maximum(count, 1).astype(jnp.uint32)).astype(
        jnp.int32
    ) + 1
    if ct_slave is not None:
        # established flows stick to their backend (lb4_local)
        reuse = (ct_slave > 0) & (ct_slave <= count)
        slave = jnp.where(reuse, ct_slave, slave)

    k = (slave - 1).astype(jnp.int32)
    lane = jnp.arange(INLINE_MAX_BACKENDS, dtype=jnp.int32)
    ip_mask = lane[None, :] == k[:, None]
    new_daddr = jnp.sum(
        jnp.where(ip_mask, slot[:, 4 : 4 + INLINE_MAX_BACKENDS], 0),
        axis=1,
        dtype=jnp.uint32,
    )
    plane = jnp.arange(INLINE_MAX_BACKENDS // 2, dtype=jnp.int32)
    port_mask = plane[None, :] == (k >> 1)[:, None]
    port_pair = jnp.sum(
        jnp.where(
            port_mask,
            slot[:, 4 + INLINE_MAX_BACKENDS : 4 + INLINE_MAX_BACKENDS
                 + INLINE_MAX_BACKENDS // 2],
            0,
        ),
        axis=1,
        dtype=jnp.uint32,
    )
    new_dport = (
        (port_pair >> (16 * (k & 1)).astype(jnp.uint32)) & 0xFFFF
    ).astype(jnp.int32)
    return (
        found,
        jnp.where(found, slave, 0),
        jnp.where(found, new_daddr, 0),
        jnp.where(found, new_dport, 0),
        jnp.where(found, rev_nat, 0),
    )


def lb_inline_slot(rows, vip, w1, owns=None):
    """Resolve the matching 64-lane service slot from gathered
    inline bucket rows (with an optional ownership mask for the
    routed mesh probe).  Returns (slot u32 [B, 64], found [B])."""
    import jax.numpy as jnp

    half = rows.reshape(-1, 2, INLINE_SLOT)  # [B, 2, 64]
    hit2 = (half[:, :, 0] == vip[:, None]) & (
        half[:, :, 1] == w1[:, None]
    )  # [B, 2]
    if owns is not None:
        hit2 = hit2 & owns[:, None]
    slot = jnp.sum(
        jnp.where(hit2[:, :, None], half, 0), axis=1, dtype=jnp.uint32
    )  # [B, 64]
    return slot, jnp.any(hit2, axis=1)


def lb_inline_stash_slot(tables, vip, w1):
    """Stash half of the inline service probe (replicated on a mesh
    — computed once per shard, added after the row-part psum)."""
    import jax.numpy as jnp

    stash = jnp.asarray(tables.stash)  # [S, 64]
    s_hit = (stash[None, :, 0] == vip[:, None]) & (
        stash[None, :, 1] == w1[:, None]
    )  # [B, S]
    slot = jnp.sum(
        jnp.where(s_hit[:, :, None], stash[None, :, :], 0),
        axis=1,
        dtype=jnp.uint32,
    )
    return slot, jnp.any(s_hit, axis=1)


def _lb_select_inline(
    tables: "LBInline",
    saddr,
    daddr,
    sport,
    dport,
    proto,
    ct_slave=None,
):
    """Inline-layout select: ONE row gather resolves the service AND
    its backends; the matching 64-lane slot is combined in-register."""
    import jax.numpy as jnp

    vip, w1 = lb_service_key(daddr, dport, proto)
    h = fnv1a_device(jnp.stack([vip, w1], axis=1))
    bucket = (h & jnp.uint32(tables.n_buckets - 1)).astype(jnp.int32)
    rows = jnp.asarray(tables.rows)[bucket]  # [B, 128] — THE gather
    slot, row_found = lb_inline_slot(rows, vip, w1)
    s_slot, s_found = lb_inline_stash_slot(tables, vip, w1)
    slot = slot + s_slot
    found = row_found | s_found

    fh = flow_hash(saddr, daddr, sport, dport, proto)
    found, slave, new_daddr, new_dport, rev_nat = lb_slot_outputs(
        slot, found, fh, ct_slave
    )
    new_daddr = jnp.where(found, new_daddr, daddr.astype(jnp.uint32))
    new_dport = jnp.where(found, new_dport, dport.astype(jnp.int32))
    return found, slave, new_daddr, new_dport, rev_nat


def lb_select_batch(
    tables,
    saddr,
    daddr,
    sport,
    dport,
    proto,
    ct_slave=None,
):
    """Returns (is_service bool [B], slave i32 [B], new_daddr u32 [B],
    new_dport i32 [B], rev_nat i32 [B]).  Non-service flows pass
    through with their original daddr/dport and rev_nat 0.

    Inline layout: one row gather resolves service and backends.
    Classic layout: one bucket row gather resolves the service; one
    backend row gather plus a masked lane sum picks the backend."""
    import jax.numpy as jnp

    if isinstance(tables, LBInline):
        return _lb_select_inline(
            tables, saddr, daddr, sport, dport, proto, ct_slave
        )

    vip = daddr.astype(jnp.uint32)
    w1 = ((dport.astype(jnp.uint32) & 0xFFFF) << 16) | (
        proto.astype(jnp.uint32) & 0xFF
    )
    h = fnv1a_device(jnp.stack([vip, w1], axis=1))
    bucket = (h & jnp.uint32(tables.n_buckets - 1)).astype(jnp.int32)
    rows = jnp.asarray(tables.buckets)[bucket]  # [B, 128] — 1 gather
    p = SVC_PER_BUCKET
    # planar extraction: word k of all entries = one contiguous slice
    ent = [rows[:, k * p : (k + 1) * p] for k in range(SVC_ENTRY_WORDS)]
    hit = (ent[0] == vip[:, None]) & (ent[1] == w1[:, None])

    stash = jnp.asarray(tables.stash)
    s_hit = (stash[None, :, 0] == vip[:, None]) & (
        stash[None, :, 1] == w1[:, None]
    )

    def _pick(col):
        return jnp.sum(
            jnp.where(hit, ent[col], 0), axis=1, dtype=jnp.uint32
        ) + jnp.sum(
            jnp.where(s_hit, stash[None, :, col], 0),
            axis=1,
            dtype=jnp.uint32,
        )

    found = jnp.any(hit, axis=1) | jnp.any(s_hit, axis=1)
    meta = _pick(2)
    base = _pick(3).astype(jnp.int32)
    count = (meta & 0xFFFF).astype(jnp.int32)
    rev_nat = (meta >> 16).astype(jnp.int32)
    found = found & (count > 0)

    fh = flow_hash(saddr, daddr, sport, dport, proto)
    slave = (fh % jnp.maximum(count, 1).astype(jnp.uint32)).astype(
        jnp.int32
    ) + 1
    if ct_slave is not None:
        # established flows stick to their backend (lb4_local)
        reuse = (ct_slave > 0) & (ct_slave <= count)
        slave = jnp.where(reuse, ct_slave, slave)

    row_idx = jnp.clip(base, 0, tables.backend_rows.shape[0] - 1)
    brow = jnp.asarray(tables.backend_rows)[row_idx]  # [B,128] — 1 gather
    k = (slave - 1).astype(jnp.int32)
    lane = jnp.arange(MAX_BACKENDS, dtype=jnp.int32)
    ip_mask = lane[None, :] == k[:, None]
    new_daddr = jnp.sum(
        jnp.where(ip_mask, brow[:, :MAX_BACKENDS], 0),
        axis=1,
        dtype=jnp.uint32,
    )
    plane = jnp.arange(MAX_BACKENDS // 2, dtype=jnp.int32)
    port_mask = plane[None, :] == (k >> 1)[:, None]
    port_pair = jnp.sum(
        jnp.where(
            port_mask,
            brow[:, MAX_BACKENDS : MAX_BACKENDS + MAX_BACKENDS // 2],
            0,
        ),
        axis=1,
        dtype=jnp.uint32,
    )
    new_dport = (
        (port_pair >> (16 * (k & 1)).astype(jnp.uint32)) & 0xFFFF
    ).astype(jnp.int32)

    new_daddr = jnp.where(found, new_daddr, daddr.astype(jnp.uint32))
    new_dport = jnp.where(found, new_dport, dport.astype(jnp.int32))
    rev_nat = jnp.where(found, rev_nat, 0)
    slave = jnp.where(found, slave, 0)
    return found, slave, new_daddr, new_dport, rev_nat
