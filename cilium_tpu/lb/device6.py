"""Device LB for IPv6: batched lb6_lookup_service / lb6_local analog.

The v4 inline layout (lb/device.py) generalized limb-for-limb, as
bpf/lib/lb.h's lb6_* functions mirror lb4_*: one 128-lane row per
bucket holds TWO 64-lane service slots, each carrying the service key
AND its backends — a single row gather resolves the service and the
chosen backend.

Slot layout (64 lanes):
  lanes [0, 4)    vip limbs (big-endian u32 limbs)
  lane  4         dport << 16 | proto
  lane  5         rev_nat << 16 | backend count
  lanes [6, 8)    pad
  lanes [8, 56)   backend address limbs, LIMB-PLANAR: lanes
                  [8 + 12k, 8 + 12k + 12) hold limb k of backends
                  0..11 (masked per-backend extraction stays a
                  contiguous 12-lane slice per limb)
  lanes [56, 62)  backend ports, two per lane (low half = even)
Backends per service cap: 12 (INLINE6_MAX_BACKENDS); larger services
raise — the reference's lb6 maps scale further, and growing this
means a second row per service, a straightforward extension.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass

import numpy as np

from cilium_tpu.engine.hashtable import _fnv1a_host, fnv1a_device
from cilium_tpu.ipcache.lpm6 import ip6_limbs
from cilium_tpu.lb.service import ServiceManager

INLINE6_MAX_BACKENDS = 12
INLINE6_SLOT = 64
INLINE6_STASH = 8
_EMPTY_KEY = np.uint32(0xFFFFFFFF)  # dport<<16|proto plane marker


@dataclass
class LB6Inline:
    """v6 inline service rows + small stash (pytree)."""

    rows: np.ndarray  # u32 [R, 128]
    stash: np.ndarray  # u32 [INLINE6_STASH, 64]
    n_buckets: int

    def tree_flatten(self):
        return ((self.rows, self.stash), self.n_buckets)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


def _register() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            LB6Inline,
            lambda t: t.tree_flatten(),
            lambda aux, ch: LB6Inline.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register()


def _is_v6(ip: str) -> bool:
    return ":" in ip


def _svc_slot6(svc) -> np.ndarray:
    slot = np.zeros(INLINE6_SLOT, dtype=np.uint32)
    slot[0:4] = ip6_limbs(svc.frontend.ip)
    slot[4] = ((svc.frontend.port & 0xFFFF) << 16) | (
        svc.frontend.protocol & 0xFF
    )
    slot[5] = ((svc.id & 0xFFFF) << 16) | (len(svc.backends) & 0xFFFF)
    for j, backend in enumerate(svc.backends):
        limbs = ip6_limbs(backend.addr.ip)
        for k in range(4):
            slot[8 + 12 * k + j] = limbs[k]
        slot[56 + (j >> 1)] |= np.uint32(
            (backend.addr.port & 0xFFFF) << (16 * (j & 1))
        )
    return slot


def compile_lb6(mgr: ServiceManager) -> LB6Inline:
    """Compile the v6 services of the manager (v4 frontends are the
    v4 compiler's job; the reference keeps lb4/lb6 maps separate)."""
    services = sorted(
        (
            s
            for s in mgr.by_frontend.values()
            if _is_v6(s.frontend.ip)
        ),
        key=lambda s: s.id,
    )
    for svc in services:
        if len(svc.backends) > INLINE6_MAX_BACKENDS:
            raise ValueError(
                f"v6 service {svc.frontend} exceeds "
                f"{INLINE6_MAX_BACKENDS} backends"
            )
        if any(not _is_v6(b.addr.ip) for b in svc.backends):
            raise ValueError("v6 service with v4 backend (NAT46 scope)")
    nb = 16
    while nb < len(services):
        nb *= 2
    nb_cap = max(nb * 64, 1 << 12)
    while nb <= nb_cap:
        rows = np.zeros((nb, 128), dtype=np.uint32)
        rows[:, 4] = _EMPTY_KEY
        rows[:, INLINE6_SLOT + 4] = _EMPTY_KEY
        stash = np.zeros((INLINE6_STASH, INLINE6_SLOT), dtype=np.uint32)
        stash[:, 4] = _EMPTY_KEY
        fill = [0] * nb
        sfill = 0
        ok = True
        for svc in services:
            limbs = ip6_limbs(svc.frontend.ip)
            w4 = ((svc.frontend.port & 0xFFFF) << 16) | (
                svc.frontend.protocol & 0xFF
            )
            words = np.array([[*limbs, w4]], dtype=np.uint32)
            b = int(_fnv1a_host(words)[0]) & (nb - 1)
            if fill[b] < 2:
                rows[
                    b, fill[b] * INLINE6_SLOT : (fill[b] + 1) * INLINE6_SLOT
                ] = _svc_slot6(svc)
                fill[b] += 1
            elif sfill < INLINE6_STASH:
                stash[sfill] = _svc_slot6(svc)
                sfill += 1
            else:
                ok = False
                break
        if ok:
            # occupied pow2 prefix only (see v4 compile_lb_inline)
            from cilium_tpu.engine.hashtable import trim_pow2_prefix

            return LB6Inline(
                rows=rows,
                stash=trim_pow2_prefix(stash, sfill),
                n_buckets=nb,
            )
        nb *= 2
    raise ValueError("LB6 bucket overflow (pathological collisions)")


def flow_hash6(saddr, daddr, sport, dport, proto):
    """v6 flow hash for slave selection (get_hash_recalc over the
    limb tuple; same invariants as the v4 hash)."""
    import jax.numpy as jnp

    words = jnp.concatenate(
        [
            saddr.astype(jnp.uint32),
            daddr.astype(jnp.uint32),
            (
                (sport.astype(jnp.uint32) << 16)
                | dport.astype(jnp.uint32)
            )[:, None],
            proto.astype(jnp.uint32)[:, None],
        ],
        axis=1,
    )
    return fnv1a_device(words)


def lb6_select_batch(
    tables: LB6Inline,
    saddr,  # u32 [B, 4]
    daddr,  # u32 [B, 4]
    sport,
    dport,
    proto,
    ct_slave=None,
):
    """Returns (is_service bool [B], slave i32 [B],
    new_daddr u32 [B, 4], new_dport i32 [B], rev_nat i32 [B])."""
    import jax.numpy as jnp

    vip = daddr.astype(jnp.uint32)
    w4 = ((dport.astype(jnp.uint32) & 0xFFFF) << 16) | (
        proto.astype(jnp.uint32) & 0xFF
    )
    h = fnv1a_device(jnp.concatenate([vip, w4[:, None]], axis=1))
    bucket = (h & jnp.uint32(tables.n_buckets - 1)).astype(jnp.int32)
    rows = jnp.asarray(tables.rows)[bucket]  # [B, 128] — THE gather
    half = rows.reshape(-1, 2, INLINE6_SLOT)  # [B, 2, 64]
    hit2 = jnp.ones(half.shape[:2], bool)
    for k in range(4):
        hit2 = hit2 & (half[:, :, k] == vip[:, k : k + 1])
    hit2 = hit2 & (half[:, :, 4] == w4[:, None])
    slot = jnp.sum(
        jnp.where(hit2[:, :, None], half, 0), axis=1, dtype=jnp.uint32
    )  # [B, 64]
    stash = jnp.asarray(tables.stash)  # [S, 64]
    s_hit = jnp.ones((vip.shape[0], stash.shape[0]), bool)
    for k in range(4):
        s_hit = s_hit & (stash[None, :, k] == vip[:, k : k + 1])
    s_hit = s_hit & (stash[None, :, 4] == w4[:, None])
    slot = slot + jnp.sum(
        jnp.where(s_hit[:, :, None], stash[None, :, :], 0),
        axis=1,
        dtype=jnp.uint32,
    )
    found = jnp.any(hit2, axis=1) | jnp.any(s_hit, axis=1)

    meta = slot[:, 5]
    count = (meta & 0xFFFF).astype(jnp.int32)
    rev_nat = (meta >> 16).astype(jnp.int32)
    found = found & (count > 0)

    fh = flow_hash6(saddr, daddr, sport, dport, proto)
    slave = (fh % jnp.maximum(count, 1).astype(jnp.uint32)).astype(
        jnp.int32
    ) + 1
    if ct_slave is not None:
        reuse = (ct_slave > 0) & (ct_slave <= count)
        slave = jnp.where(reuse, ct_slave, slave)

    k_sel = (slave - 1).astype(jnp.int32)
    lane = jnp.arange(INLINE6_MAX_BACKENDS, dtype=jnp.int32)
    mask = lane[None, :] == k_sel[:, None]  # [B, 12]
    limbs = []
    for k in range(4):
        limbs.append(
            jnp.sum(
                jnp.where(
                    mask,
                    slot[:, 8 + 12 * k : 8 + 12 * k + 12],
                    0,
                ),
                axis=1,
                dtype=jnp.uint32,
            )
        )
    new_daddr = jnp.stack(limbs, axis=1)  # [B, 4]
    plane = jnp.arange(INLINE6_MAX_BACKENDS // 2, dtype=jnp.int32)
    port_mask = plane[None, :] == (k_sel >> 1)[:, None]
    port_pair = jnp.sum(
        jnp.where(port_mask, slot[:, 56:62], 0), axis=1, dtype=jnp.uint32
    )
    new_dport = (
        (port_pair >> (16 * (k_sel & 1)).astype(jnp.uint32)) & 0xFFFF
    ).astype(jnp.int32)

    new_daddr = jnp.where(
        found[:, None], new_daddr, daddr.astype(jnp.uint32)
    )
    new_dport = jnp.where(found, new_dport, dport.astype(jnp.int32))
    rev_nat = jnp.where(found, rev_nat, 0)
    slave = jnp.where(found, slave, 0)
    return found, slave, new_daddr, new_dport, rev_nat


def lb6_lookup_host(mgr: ServiceManager, daddr: str, dport: int,
                    proto: int):
    """Host-side lb6_lookup_service (oracle)."""
    from cilium_tpu.lb.service import L3n4Addr

    return mgr.lookup(L3n4Addr(daddr, dport, proto))


def slave_for_host(svc, saddr: str, daddr: str, sport: int, dport: int,
                   proto: int) -> int:
    """Host-side hashed slave selection (matches flow_hash6)."""
    words = np.array(
        [[
            *ip6_limbs(saddr),
            *ip6_limbs(daddr),
            ((sport & 0xFFFF) << 16) | (dport & 0xFFFF),
            proto & 0xFF,
        ]],
        dtype=np.uint32,
    )
    return (int(_fnv1a_host(words)[0]) % len(svc.backends)) + 1
