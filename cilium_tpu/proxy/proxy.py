"""Redirect manager + batched L7 request verdicts.

Port of /root/reference/pkg/proxy/proxy.go:
  - proxy-port allocation from a fixed range with reuse per proxy ID
    (allocatePort; the range comes from StartProxySupport,
    daemon/daemon.go:236: 10000-20000);
  - CreateOrUpdateRedirect (proxy.go:153,217-225): parser type picks
    the implementation — kafka → Kafka matcher, http & default →
    HTTP/DFA matcher (where the reference spawns Envoy);
  - RemoveRedirect releases the port;
  - the REQUEST-VERDICT path: a flow the datapath marked
    `proxy_port>0` lands on its Redirect (lookup by proxy port, like
    the proxymap orig-dst recovery in envoy/cilium_bpf_metadata.cc),
    the parser-specific matcher produces per-request allow/deny
    (403-close / Kafka error response in the reference,
    envoy/cilium_l7policy.cc + pkg/proxy/kafka.go:116-151), and each
    request emits an access-log record
    (pkg/proxy/logger / accesslog_server.go:174);
  - access records → MonitorBus LogRecordNotify (pkg/proxy/logger).

The returned proxy ports feed the endpoint's realized_redirects, which
computeDesiredPolicyMapState writes into L4 entries (the redirect
loop of pkg/endpoint/bpf.go:488).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from cilium_tpu.identity import IdentityCache
from cilium_tpu.l7.http import (
    HTTPPolicy,
    compile_http_rules,
    resolve_selector_indices,
    specs_from_filter,
)
from cilium_tpu.l7.kafka import (
    KafkaTables,
    compile_kafka_rules,
    rule_spec_from_port_rule,
)
from cilium_tpu.l7.proxylib import GenericL7Tables
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.monitor.bus import MonitorBus
from cilium_tpu.monitor.events import LogRecordNotify
from cilium_tpu.policy.l4 import L4Filter, proxy_id

PORT_MIN = 10000  # daemon/daemon.go:236
PORT_MAX = 20000

PARSER_HTTP = "http"
PARSER_KAFKA = "kafka"


@dataclass
class _PidState:
    """Per-proxy-id bookkeeping (see Proxy._pids)."""

    port: int
    endpoint_id: int
    gen: int = 0


@dataclass
class Redirect:
    """proxy.go Redirect."""

    id: str  # proxy ID string (epID:direction:proto:port)
    proxy_port: int
    parser: str
    endpoint_id: int
    ingress: bool
    http_policy: Optional[HTTPPolicy] = None
    kafka_tables: Optional[KafkaTables] = None
    generic_tables: Optional[GenericL7Tables] = None
    # fingerprint of the resolved matcher inputs the compiled tables
    # reflect — an unchanged redirect skips the tensor recompile on
    # the next regeneration sweep (the xDS cache's version-unchanged
    # no-op; recompiling every redirect per sweep dominated
    # incremental policy updates)
    resolved_fp: object = None


def _resolved_fingerprint(parser: str, resolved, n_identities: int):
    """Hashable digest of a redirect's resolved matcher inputs: equal
    fingerprints ⇒ the compiled tables would be identical (table
    shapes include the identity axis, so n_identities participates)."""
    if parser == PARSER_KAFKA:
        body = tuple(
            (
                tuple(sorted(s.api_keys)),
                s.api_version,
                s.client_id,
                s.topic,
                s.scope_key,
                tuple(sorted(s.identity_indices)),
            )
            for s in resolved
        )
    elif parser not in (PARSER_HTTP, ""):
        body = tuple(
            (tuple(sorted(indices)), tuple(repr(r) for r in rules))
            for indices, rules in resolved
        )
    else:
        body = tuple(
            (
                s.method,
                s.path,
                s.host,
                tuple(s.headers),
                s.scope_key,
                tuple(sorted(s.identity_indices)),
            )
            for s in resolved
        )
    return (parser, n_identities, body)


class Proxy:
    def __init__(
        self,
        monitor: Optional[MonitorBus] = None,
        port_min: int = PORT_MIN,
        port_max: int = PORT_MAX,
    ) -> None:
        self._lock = threading.Lock()
        self.redirects: Dict[str, Redirect] = {}
        self.monitor = monitor
        self._port_min = port_min
        self._port_max = port_max
        self._next_port = port_min
        self._ports_in_use: set = set()
        # pid → (stable port, compile generation, endpoint) — a pid
        # owns its port from first allocation to remove_redirect,
        # even while a compile is pending, and only the NEWEST
        # generation's result may be installed
        self._pids: Dict[str, _PidState] = {}
        # matcher compiles ACK asynchronously (the NPDS push → Envoy
        # ACK shape, pkg/envoy/xds/ack.go): one worker keeps update
        # order per the reference's serialized xDS stream
        from concurrent.futures import ThreadPoolExecutor

        self._compiler = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="proxy-compile"
        )
        # versioned view of installed redirects (pkg/envoy/xds cache:
        # every install/remove is one cache transaction; NPDS-style
        # consumers observe versions and long-poll get_resources)
        from cilium_tpu.proxy.xds import Cache as _XDSCache

        self.xds = _XDSCache()

    # -- port allocation (proxy.go allocatePort) ----------------------------

    def _allocate_port(self) -> int:
        for _ in range(self._port_max - self._port_min + 1):
            port = self._next_port
            self._next_port += 1
            if self._next_port > self._port_max:
                self._next_port = self._port_min
            if port not in self._ports_in_use:
                self._ports_in_use.add(port)
                return port
        raise RuntimeError("no available proxy ports")

    # -- redirects -----------------------------------------------------------

    def create_or_update_redirect(
        self,
        l4: L4Filter,
        pid: str,
        endpoint_id: int,
        identity_cache: IdentityCache,
        id_index: Dict[int, int],
        n_identities: int,
        selector_cache=None,
        wait_group=None,
    ) -> Redirect:
        """proxy.go:153: compile (or recompile) the L7 matcher for one
        redirect; the proxy port is stable across updates (including
        pending ones — the pid owns its port until remove_redirect).

        Rule/selector resolution happens SYNCHRONOUSLY on the caller
        (no shared control-plane state crosses threads); with
        `wait_group` (a utils.completion.WaitGroup) the tensor compile
        runs ASYNC and the new redirect is swapped in — and its
        completion ACKed — only when the compile finishes AND this
        call has not been superseded or removed: the xDS version-ACK
        contract (pkg/envoy/xds/ack.go).  A failed compile NACKs, so
        the waiter fails fast; the OLD redirect keeps serving either
        way — a timed-out regeneration keeps old state
        (pkg/endpoint/bpf.go:442)."""
        with self._lock:
            state = self._pids.get(pid)
            if state is None:
                state = _PidState(
                    port=self._allocate_port(),
                    endpoint_id=endpoint_id,
                )
                self._pids[pid] = state
            state.gen += 1
            gen = state.gen
            port = state.port
        redirect = Redirect(
            id=pid,
            proxy_port=port,
            parser=l4.l7_parser or PARSER_HTTP,
            endpoint_id=endpoint_id,
            ingress=l4.ingress,
        )
        # resolve the rules here, on the regeneration thread — the
        # async job must not read live selector/identity caches
        resolved = self._resolve_matcher_inputs(
            redirect, l4, identity_cache, id_index, selector_cache
        )
        redirect.resolved_fp = _resolved_fingerprint(
            redirect.parser, resolved, n_identities
        )
        with self._lock:
            prev = self.redirects.get(pid)
        if (
            prev is not None
            and prev.parser == redirect.parser
            and prev.resolved_fp == redirect.resolved_fp
        ):
            # inputs unchanged: reuse the compiled tables (the xDS
            # cache's version-unchanged no-op) — no compile job, the
            # completion ACKs immediately
            redirect.http_policy = prev.http_policy
            redirect.kafka_tables = prev.kafka_tables
            redirect.generic_tables = prev.generic_tables
            with self._lock:
                if self._pids.get(pid) is state and state.gen == gen:
                    self.redirects[pid] = redirect
            self._publish_xds(redirect, prev)
            self._update_redirect_gauge()
            if wait_group is not None:
                wait_group.add_completion().complete()
            return redirect
        if wait_group is None:
            self._compile_tables(redirect, resolved, n_identities)
            with self._lock:
                if self._pids.get(pid) is state and state.gen == gen:
                    installed = True
                    self.redirects[pid] = redirect
                else:
                    installed = False
            if installed:
                self._publish_xds(redirect, prev)
            self._update_redirect_gauge()
            return redirect

        completion = wait_group.add_completion()

        def job() -> None:
            try:
                self._compile_tables(redirect, resolved, n_identities)
            except Exception:
                completion.fail()  # NACK: the waiter fails fast
                return
            with self._lock:
                # superseded by a newer compile, or removed: do not
                # resurrect — the newest generation wins
                if self._pids.get(pid) is state and state.gen == gen:
                    installed = True
                    self.redirects[pid] = redirect
                else:
                    installed = False
            if installed:
                self._publish_xds(redirect, prev)
            self._update_redirect_gauge()
            completion.complete()

        self._compiler.submit(job)
        return redirect

    def _resolve_matcher_inputs(
        self,
        redirect: Redirect,
        l4: L4Filter,
        identity_cache: IdentityCache,
        id_index: Dict[int, int],
        selector_cache=None,
    ):
        """Selector → identity-index resolution (control-plane state;
        must run on the regeneration thread)."""
        if redirect.parser == PARSER_KAFKA:
            specs = []
            for selector, l7 in l4.l7_rules_per_ep.items():
                indices = resolve_selector_indices(
                    selector, identity_cache, id_index, selector_cache
                )
                if not (l7.kafka or []):
                    # empty rules = L7 allow-all: wildcard spec
                    from cilium_tpu.l7.kafka import KafkaRuleSpec

                    specs.append(
                        KafkaRuleSpec(identity_indices=indices)
                    )
                for rule in l7.kafka or []:
                    specs.append(
                        rule_spec_from_port_rule(rule, indices)
                    )
            return specs
        if redirect.parser not in (PARSER_HTTP, ""):
            # generic proxylib parser, dispatched by l7proto name
            # (proxy.go:217 createOrUpdateRedirect → proxylib);
            # bundled parsers register at cilium_tpu.l7 import time
            per_selector = []
            for selector, l7 in l4.l7_rules_per_ep.items():
                indices = resolve_selector_indices(
                    selector, identity_cache, id_index, selector_cache
                )
                per_selector.append((indices, list(l7.l7 or [])))
            return per_selector
        return specs_from_filter(
            l4, identity_cache, id_index, selector_cache
        )

    def _compile_tables(
        self, redirect: Redirect, resolved, n_identities: int
    ) -> None:
        """Tensor compile from pre-resolved inputs (pure; safe off
        the control-plane thread)."""
        if redirect.parser == PARSER_KAFKA:
            redirect.kafka_tables = compile_kafka_rules(
                resolved, n_identities
            )
        elif redirect.parser not in (PARSER_HTTP, ""):
            from cilium_tpu.l7.proxylib import compile_generic_rules

            redirect.generic_tables = compile_generic_rules(
                redirect.parser, resolved, n_identities
            )
        else:
            redirect.http_policy = compile_http_rules(
                resolved, n_identities
            )

    def remove_redirect(self, pid: str) -> bool:
        """proxy.go RemoveRedirect: releases the pid's port and
        invalidates any in-flight compile for it."""
        with self._lock:
            state = self._pids.pop(pid, None)
            removed = self.redirects.pop(pid, None)
            if state is None:
                return False
            self._ports_in_use.discard(state.port)
        if removed is not None:
            self.xds.delete(
                self._xds_typeurl(removed.parser), removed.id
            )
        self._update_redirect_gauge()
        return True

    @staticmethod
    def _xds_typeurl(parser: str) -> str:
        return f"type.cilium.io/{parser}NetworkPolicy"

    def _publish_xds(
        self, redirect: "Redirect", prev: "Optional[Redirect]" = None
    ) -> None:
        if prev is not None and prev.parser != redirect.parser:
            # a pid whose parser changed must not linger under the
            # old type URL for long-polling consumers
            self.xds.delete(self._xds_typeurl(prev.parser), prev.id)
        self.xds.upsert(
            self._xds_typeurl(redirect.parser), redirect.id, redirect
        )

    def _update_redirect_gauge(self) -> None:
        """proxy_redirects{protocol} (metrics.go): installed
        redirects by parser."""
        from collections import Counter as _C

        with self._lock:
            by_parser = _C(r.parser for r in self.redirects.values())
            # zero every label ever seen, then set current counts —
            # a parser whose last redirect vanished must not stay
            # stale in the exposition.  Snapshot under the lock:
            # concurrent installs mutate the seen-set.
            seen = self._gauge_parsers = getattr(
                self, "_gauge_parsers", set()
            )
            seen.update(by_parser)
            seen.update((PARSER_HTTP, PARSER_KAFKA))
            snapshot = tuple(seen)
        for parser in snapshot:
            metrics.proxy_redirects.set(
                parser, value=float(by_parser.get(parser, 0))
            )

    def redirect_for(
        self, endpoint_id: int, ingress: bool, protocol: str, port: int
    ) -> Optional[Redirect]:
        return self.redirects.get(
            proxy_id(endpoint_id, ingress, protocol, port)
        )

    def redirect_by_port(self, proxy_port: int) -> Optional[Redirect]:
        """The proxymap recovery step: a datapath verdict carries only
        the proxy port (policy.h proxy_port>0); map it back to the
        redirect whose matcher owns the flow."""
        for redirect in self.redirects.values():
            if redirect.proxy_port == proxy_port:
                return redirect
        return None

    # -- request verdicts (the L7 hot path) ----------------------------------

    def _verdict_batch(
        self,
        redirect: Redirect,
        tables,
        evaluate,
        requests,
        ident_idx,
        known,
        log: bool,
        parser_label: str,
        info_fn,
    ):
        """Shared skeleton of the per-parser verdict methods: guard,
        known default, batched evaluate, per-request access log."""
        import numpy as np

        if tables is None:
            raise ValueError(
                f"redirect {redirect.id} has no {parser_label} tables"
            )
        if known is None:
            known = np.ones(len(requests), dtype=bool)
        allowed = evaluate(tables, requests, ident_idx, known)
        n_fwd = int(np.asarray(allowed).sum())
        metrics.policy_l7_total.inc("received", value=len(requests))
        metrics.policy_l7_total.inc("forwarded", value=n_fwd)
        metrics.policy_l7_total.inc(
            "denied", value=len(requests) - n_fwd
        )
        if log and self.monitor is not None:
            for i, request in enumerate(requests):
                self.log_record(
                    redirect.endpoint_id,
                    parser_label,
                    "Forwarded" if allowed[i] else "Denied",
                    info=info_fn(request),
                )
        return allowed

    def verdict_http(
        self,
        redirect: Redirect,
        requests,  # [(method, path, host) bytes]
        ident_idx,  # i32 [B] identity index into the compiled universe
        known=None,  # bool [B]; default all-known
        headers=None,  # optional per-request {name: value} dicts
        log: bool = True,
    ):
        """Batched HTTP request verdicts through this redirect's
        compiled policy (device DFAs + host fallback for header rules
        and over-length fields).  Returns allowed bool [B]; emits one
        access-log record per request (verdict Forwarded/Denied, like
        cilium_l7policy.cc's 403 + accesslog)."""
        from cilium_tpu.l7.http import evaluate_with_host_fallback

        return self._verdict_batch(
            redirect,
            redirect.http_policy,
            lambda t, r, i, k: evaluate_with_host_fallback(
                t, r, i, k, headers
            ),
            requests,
            ident_idx,
            known,
            log,
            PARSER_HTTP,
            lambda req: b" ".join([req[0], req[1]]).decode(
                "latin-1", "replace"
            ),
        )

    def verdict_kafka(
        self,
        redirect: Redirect,
        requests,  # [KafkaRequest] (use l7.kafka_wire to parse frames)
        ident_idx,
        known=None,
        log: bool = True,
    ):
        """Batched Kafka request verdicts (pkg/proxy/kafka.go:116
        canAccess).  Returns allowed bool [B]."""
        from cilium_tpu.l7.kafka import evaluate_with_host_fallback

        return self._verdict_batch(
            redirect,
            redirect.kafka_tables,
            evaluate_with_host_fallback,
            requests,
            ident_idx,
            known,
            log,
            PARSER_KAFKA,
            lambda req: f"key={req.kind} topics={list(req.topics)}",
        )

    def verdict_generic(
        self,
        redirect: Redirect,
        requests,  # [l7.proxylib.L7Request]
        ident_idx,
        known=None,
        log: bool = True,
    ):
        """Batched verdicts through a generic proxylib parser's
        compiled rules (proxylib policymap matching,
        /root/reference/proxylib/proxylib/policymap.go:150).  Returns
        allowed bool [B]."""
        from cilium_tpu.l7.proxylib import evaluate_requests

        return self._verdict_batch(
            redirect,
            redirect.generic_tables,
            evaluate_requests,
            requests,
            ident_idx,
            known,
            log,
            redirect.parser,
            lambda req: " ".join(f"{k}={v}" for k, v in req.fields),
        )

    # -- endpoint integration (pkg/endpoint/bpf.go:488) ---------------------

    def update_endpoint_redirects(
        self,
        endpoint,
        identity_cache: IdentityCache,
        id_index: Dict[int, int],
        n_identities: int,
        selector_cache=None,
        wait_group=None,
    ) -> Dict[str, int]:
        """addNewRedirects/removeOldRedirects for one endpoint; returns
        the realized proxy-id → port map to feed back into the next
        computeDesiredPolicyMapState.  Runs under a `proxy.upcall`
        span (error status on an injected/real failure), so a traced
        regeneration shows which endpoint's redirect realization cost
        or failed the sweep."""
        # chaos seam: an armed proxy.upcall site fails redirect
        # realization the way a dead envoy fails the xDS upcall — the
        # regeneration's ACK gate rolls back, exactly the failure the
        # rollback exists for
        from cilium_tpu import faultinject, tracing

        with tracing.tracer.span(
            "proxy.upcall", site="proxy.upcall",
            attrs={"endpoint": endpoint.id},
        ) as sp:
            faultinject.fire("proxy.upcall")
            realized: Dict[str, int] = {}
            l4_policy = endpoint.desired_l4_policy
            wanted = set()
            if l4_policy is not None:
                for l4map in (l4_policy.ingress, l4_policy.egress):
                    for f in l4map.values():
                        if not f.is_redirect():
                            continue
                        pid = proxy_id(
                            endpoint.id, f.ingress, f.protocol, f.port
                        )
                        redirect = self.create_or_update_redirect(
                            f, pid, endpoint.id, identity_cache,
                            id_index, n_identities, selector_cache,
                            wait_group=wait_group,
                        )
                        realized[pid] = redirect.proxy_port
                        wanted.add(pid)
            with self._lock:
                stale = [
                    p
                    for p, st in self._pids.items()
                    if st.endpoint_id == endpoint.id
                    and p not in wanted
                ]
            for pid in stale:
                self.remove_redirect(pid)
            endpoint.realized_redirects = realized
            sp.attrs["redirects"] = len(realized)
            return realized

    # -- access logging (pkg/proxy/logger) -----------------------------------

    def log_record(
        self, endpoint_id: int, l7_proto: str, verdict: str, info: str = ""
    ) -> None:
        if self.monitor is not None:
            self.monitor.publish(
                LogRecordNotify(
                    endpoint_id=endpoint_id,
                    l7_proto=l7_proto,
                    verdict=verdict,
                    info=info,
                )
            )
