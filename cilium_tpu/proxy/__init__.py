"""L7 proxy redirect management.

Re-design of /root/reference/pkg/proxy: the redirect manager allocates
proxy ports (10000-20000, daemon/daemon.go:236) and instantiates the
right L7 matcher per parser type — the reference picks the Go Kafka
proxy or Envoy (proxy.go:217-225); here every parser compiles to
device tables (l7.http / l7.kafka), and request batches are verdicted
by the engine, with access-log records published on the monitor bus
(≙ Envoy access-log socket → pkg/proxy/logger).
"""

from cilium_tpu.proxy.proxy import Proxy, Redirect

__all__ = ["Proxy", "Redirect"]
