"""Terminating Kafka TCP proxy — the kafkaListener loop.

Behavioral port of /root/reference/pkg/proxy/kafka.go:405
(kafkaRedirect.Listen + handleRequestConnection/
handleResponseConnection): a real socket listener on the redirect's
proxy port terminates client connections, decodes Kafka request
frames off the stream (l7/kafka_wire.decode_request over a growing
buffer), applies the redirect's compiled policy per request, FORWARDS
allowed frames to the upstream broker over a second connection, and
answers denied requests itself with the synthesized error response
(TopicAuthorizationFailed) — the broker never sees them.  Broker
responses stream back matched through the CorrelationCache, so the
access log can pair verdicts with responses the way
correlation_cache.go does.

The identity of the client connection comes from a caller-provided
resolver (the reference derives it from the socket mark the datapath
set; here the datapath's ipcache serves the same answer by source
address)."""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Callable, Optional, Tuple

from cilium_tpu.l7.kafka import matches_rules_host
from cilium_tpu.l7.kafka_wire import (
    CorrelationCache,
    KafkaIncompleteFrame,
    KafkaParseError,
    decode_request,
    encode_deny_response,
)
from cilium_tpu.metrics import registry as metrics


class KafkaProxyListener:
    """One redirect's terminating listener."""

    def __init__(
        self,
        redirect,  # proxy.Redirect with kafka_tables compiled
        identity_resolver: Callable[[Tuple[str, int]], int],
        upstream: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 0,  # 0 = ephemeral (tests); redirect.proxy_port
        access_log: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        self.redirect = redirect
        self.identity_resolver = identity_resolver
        self.upstream = upstream
        self.access_log = access_log
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one client connection
                outer._handle_connection(self.request,
                                         self.client_address)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.address = self._server.server_address

    def start(self) -> "KafkaProxyListener":
        threading.Thread(
            target=self._server.serve_forever,
            name="kafka-proxy",
            daemon=True,
        ).start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- the connection loop (handleRequestConnection) ----------------------

    def _handle_connection(self, client: socket.socket, addr) -> None:
        tables = self.redirect.kafka_tables
        if tables is None:
            client.close()
            return
        ident_idx = int(self.identity_resolver(addr))
        cache = CorrelationCache()
        try:
            broker = socket.create_connection(self.upstream, timeout=5)
        except OSError:
            client.close()
            return

        stop = threading.Event()

        def pump_responses() -> None:
            """handleResponseConnection: broker → client, pairing
            responses with their requests for the access log."""
            rbuf = b""
            try:
                while not stop.is_set():
                    chunk = broker.recv(65536)
                    if not chunk:
                        break
                    rbuf += chunk
                    # responses: i32 length + i32 correlation id
                    while len(rbuf) >= 8:
                        (length,) = struct.unpack_from(">i", rbuf)
                        if length < 4:
                            # framing error: connection-fatal, as the
                            # reference closes on an invalid frame —
                            # break-ing with the malformed prefix
                            # retained would buffer the broker stream
                            # unboundedly while forwarding nothing.
                            # shutdown (not just close): the request
                            # pump blocks in recv on these sockets
                            # and must wake to tear down its side
                            stop.set()
                            for s in (broker, client):
                                try:
                                    s.shutdown(socket.SHUT_RDWR)
                                except OSError:
                                    pass
                                try:
                                    s.close()
                                except OSError:
                                    pass
                            return
                        if len(rbuf) < 4 + length:
                            break
                        (cid,) = struct.unpack_from(">i", rbuf, 4)
                        req = cache.match(cid)
                        if req is not None and self.access_log:
                            self.access_log(
                                "Response", f"cid={cid}"
                            )
                        client.sendall(rbuf[: 4 + length])
                        rbuf = rbuf[4 + length :]
            except OSError:
                pass
            finally:
                stop.set()
                try:
                    client.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        resp_thread = threading.Thread(
            target=pump_responses, daemon=True
        )
        resp_thread.start()

        buf = b""
        try:
            while not stop.is_set():
                chunk = client.recv(65536)
                if not chunk:
                    break
                buf += chunk
                off = 0
                while True:
                    try:
                        req, cid, end = decode_request(buf, off)
                    except KafkaIncompleteFrame:
                        break
                    except KafkaParseError:
                        # connection-fatal, as the reference closes on
                        # unparseable frames
                        stop.set()
                        break
                    frame = buf[off:end]
                    off = end
                    allowed = matches_rules_host(
                        req, tables.specs, ident_idx
                    )
                    metrics.policy_l7_total.inc("received")
                    if allowed:
                        metrics.policy_l7_total.inc("forwarded")
                        cache.record(cid, req)
                        broker.sendall(frame)
                        if self.access_log:
                            self.access_log(
                                "Forwarded", f"cid={cid}"
                            )
                    else:
                        metrics.policy_l7_total.inc("denied")
                        client.sendall(
                            encode_deny_response(req, cid)
                        )
                        if self.access_log:
                            self.access_log("Denied", f"cid={cid}")
                buf = buf[off:]
        except OSError:
            pass
        finally:
            stop.set()
            for s in (broker, client):
                try:
                    s.close()
                except OSError:
                    pass
