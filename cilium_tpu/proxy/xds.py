"""Generic versioned-resource cache + ACK-gated observation — the
xDS machinery.

Behavioral port of /root/reference/pkg/envoy/xds/{cache.go,set.go,
ack.go}: a Cache holds resource sets keyed (typeURL, name); every
mutation through a transaction bumps ONE monotonically increasing
version shared by all type URLs (cache.go:34-140), observers learn of
new versions (set.go ResourceVersionObserver), and `get_resources`
blocks until the cache moves past the subscriber's last-known version
— the long-poll the reference's gRPC stream performs.  The
AckingVersionObserver pattern (ack.go) is carried by
utils/completion.py's NACK-capable WaitGroup: `wait_for_version`
completes a Completion when an observer acknowledges having applied a
version, which is exactly how the proxy's redirect publication gates
table flips.

The Proxy publishes every installed redirect's compiled matcher
generation into the shared cache (type URL per parser), so
out-of-band consumers (tests, tooling, a future NPDS server) observe
the same versioned view Envoy would."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


class Cache:
    """pkg/envoy/xds/cache.go — versioned resource sets."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        # typeURL → {name: resource}
        self._resources: Dict[str, Dict[str, object]] = {}
        # typeURL → version of the last tx that changed that set
        self._type_versions: Dict[str, int] = {}
        self._version = 0  # cache-wide, monotonically increasing
        self._observers: Dict[str, List[Callable[[str, int], None]]] = {}

    # -- transactions (cache.go tx) -----------------------------------------

    def _tx(
        self,
        typeurl: str,
        upserts: Dict[str, object],
        deletes: Tuple[str, ...],
        force: bool = False,
    ) -> Tuple[int, bool]:
        with self._lock:
            res = self._resources.setdefault(typeurl, {})
            updated = False
            for name, resource in upserts.items():
                if force or res.get(name) is not resource:
                    res[name] = resource
                    updated = True
            for name in deletes:
                if name in res:
                    del res[name]
                    updated = True
            if not updated and not force:
                return self._version, False
            self._version += 1
            self._type_versions[typeurl] = self._version
            version = self._version
            observers = list(self._observers.get(typeurl, ()))
            self._lock.notify_all()
        for observer in observers:
            observer(typeurl, version)
        return version, True

    def upsert(self, typeurl: str, name: str, resource,
               force: bool = False) -> Tuple[int, bool]:
        return self._tx(typeurl, {name: resource}, (), force)

    def delete(self, typeurl: str, name: str) -> Tuple[int, bool]:
        return self._tx(typeurl, {}, (name,))

    def clear(self, typeurl: str) -> Tuple[int, bool]:
        with self._lock:
            names = tuple(self._resources.get(typeurl, ()))
        return self._tx(typeurl, {}, names)

    def lookup(self, typeurl: str, name: str):
        with self._lock:
            return self._resources.get(typeurl, {}).get(name)

    def version(self) -> int:
        with self._lock:
            return self._version

    # -- observation (set.go) ------------------------------------------------

    def add_observer(
        self, typeurl: str, observer: Callable[[str, int], None]
    ) -> None:
        with self._lock:
            self._observers.setdefault(typeurl, []).append(observer)

    # -- the stream read (cache.go GetResources) -----------------------------

    def get_resources(
        self,
        typeurl: str,
        last_version: Optional[int] = None,
        names: Optional[List[str]] = None,
        timeout: Optional[float] = None,
    ) -> Optional[Tuple[int, Dict[str, object]]]:
        """Current (version, resources) for a type URL; with
        `last_version`, BLOCKS until that type's set has changed past
        it (the gRPC stream's deferred response, cache.go:184-240).
        None on timeout."""
        import time as _time

        deadline = (
            None if timeout is None else _time.monotonic() + timeout
        )
        with self._lock:
            while (
                last_version is not None
                and self._type_versions.get(typeurl, 0) <= last_version
            ):
                remaining = (
                    None
                    if deadline is None
                    else deadline - _time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._lock.wait(timeout=remaining)
            res = dict(self._resources.get(typeurl, {}))
            if names is not None:
                res = {n: res[n] for n in names if n in res}
            return self._version, res


def wait_for_version(
    cache: Cache,
    typeurl: str,
    version: int,
    wait_group,
) -> None:
    """AckingVersionObserver (ack.go): adds a Completion to the wait
    group that completes once an observer reports the cache reaching
    `version` for `typeurl` — the NACK-capable ACK gate the daemon's
    regeneration waits on."""
    completion = wait_group.add_completion()
    done = threading.Event()

    def observer(t: str, v: int) -> None:
        if v >= version and not done.is_set():
            done.set()
            completion.complete()

    cache.add_observer(typeurl, observer)
    # the version may already be reached (observer registered late)
    if cache.version() >= version:
        if not done.is_set():
            done.set()
            completion.complete()
