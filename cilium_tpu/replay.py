"""Flow-replay harness: binary flow records → streamed device verdicts.

The framework's data-loader (SURVEY §7 step 5: "flow-replay harness,
Hubble-tuple reader"): reads fixed 24-byte flow records (decoded by
the native C++ decoder at memory bandwidth), streams fixed-size padded
batches through the verdict engine with pipelined dispatch (the
double-buffered H2D pattern of SURVEY §7 hard part 6), accumulates
per-entry counters back into the endpoints' realized map states, and
optionally folds denied flows into monitor events.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional

import numpy as np

from cilium_tpu.engine.verdict import (
    TupleBatch,
    _verdict_kernel_with_counters,
)
from cilium_tpu.maps.policymap import PolicyKey
from cilium_tpu.native import decode_flow_records


@dataclass
class ReplayStats:
    total: int = 0
    allowed: int = 0
    denied: int = 0
    redirected: int = 0
    batches: int = 0
    seconds: float = 0.0

    @property
    def verdicts_per_sec(self) -> float:
        return self.total / self.seconds if self.seconds else 0.0


def read_batches(
    buf: bytes, batch_size: int, ep_map: Optional[Dict[int, int]] = None
) -> Iterator[TupleBatch]:
    """Decode flow records and yield padded TupleBatches.  `ep_map`
    translates record endpoint ids to table endpoint-axis indices
    (unknown endpoints map to 0 — callers should pre-filter)."""
    rec = decode_flow_records(buf)
    n = len(rec["ep_id"])
    ep_index = rec["ep_id"].astype(np.int32)
    if ep_map is not None:
        lut = np.zeros(max(ep_map.keys(), default=0) + 1, dtype=np.int32)
        for ep_id, idx in ep_map.items():
            lut[ep_id] = idx
        ep_index = lut[np.clip(ep_index, 0, len(lut) - 1)]
    for start in range(0, n, batch_size):
        end = min(start + batch_size, n)
        pad = batch_size - (end - start)
        def padded(a, fill=0):
            chunk = a[start:end]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.full(pad, fill, dtype=a.dtype)]
                )
            return chunk
        yield (
            TupleBatch.from_numpy(
                ep_index=padded(ep_index),
                identity=padded(rec["identity"]),
                dport=padded(rec["dport"].astype(np.int32)),
                proto=padded(rec["proto"].astype(np.int32)),
                direction=padded(rec["direction"].astype(np.int32)),
                is_fragment=padded(
                    rec["is_fragment"].astype(bool), fill=False
                ),
            ),
            end - start,
        )


def replay(
    tables,
    buf: bytes,
    batch_size: int = 1 << 20,
    accumulate_counters: bool = True,
) -> tuple:
    """Run all records through the full datapath step.  Returns
    (ReplayStats, l4_counts, l3_counts) with counters summed across
    batches (u64 to survive long replays)."""
    import time

    import jax

    step = jax.jit(_verdict_kernel_with_counters)
    stats = ReplayStats()
    l4_total = None
    l3_total = None

    pending = []  # pipelined dispatch, bounded depth
    t0 = time.perf_counter()
    for batch, valid in read_batches(buf, batch_size):
        out = step(tables, batch)
        pending.append((out, valid))
        stats.batches += 1
        if len(pending) >= 4:
            _drain(pending.pop(0), stats)
    while pending:
        _drain(pending.pop(0), stats)
    stats.seconds = time.perf_counter() - t0

    if accumulate_counters:
        # counters from the last dispatch carry the per-batch sums; we
        # need all batches — rerun cheaply? No: accumulate during drain.
        pass
    return stats


def _drain(item, stats: ReplayStats) -> None:
    (verdicts, l4_counts, l3_counts), valid = item
    allowed = np.asarray(verdicts.allowed)[:valid]
    proxy = np.asarray(verdicts.proxy_port)[:valid]
    stats.total += int(valid)
    stats.allowed += int(allowed.sum())
    stats.denied += int(valid - allowed.sum())
    stats.redirected += int((proxy > 0).sum())
    if not hasattr(stats, "_l4"):
        stats._l4 = np.zeros(l4_counts.shape, dtype=np.uint64)
        stats._l3 = np.zeros(l3_counts.shape, dtype=np.uint64)
    stats._l4 += np.asarray(l4_counts).astype(np.uint64)
    stats._l3 += np.asarray(l3_counts).astype(np.uint64)


def sync_counters_to_endpoints(
    stats: ReplayStats, manager, id_table: np.ndarray
) -> int:
    """Fold accumulated device counters back into the endpoints'
    realized map states (the packets field of policy_entry the agent
    reads back from the datapath).  Returns entries updated."""
    if not hasattr(stats, "_l4"):
        return 0
    _, tables, index = manager.published()
    if tables is None:
        return 0
    updated = 0
    rev_index = {v: k for k, v in index.items()}
    # L3 counters are indexed by identity index
    for (e, d, idx), count in np.ndenumerate(stats._l3):
        if count == 0:
            continue
        ep = manager.lookup(rev_index.get(e, -1))
        if ep is None:
            continue
        identity = int(id_table[idx])
        key = PolicyKey(identity, 0, 0, d)
        entry = ep.realized_map_state.get(key)
        if entry is not None:
            entry.packets += int(count)
            updated += 1
    return updated
