"""Flow-replay harness: binary flow records → streamed device verdicts.

The framework's data-loader (SURVEY §7 step 5: "flow-replay harness,
Hubble-tuple reader"): reads fixed 24-byte flow records (decoded by
the native C++ decoder at memory bandwidth), streams fixed-size padded
batches through the FUSED datapath step — prefilter → LB/DNAT → CT →
ipcache LPM → policy lattice in one jit (engine/datapath.py, the
analog of bpf_lxc.c:440/899 being ONE program) — with pipelined
dispatch (the double-buffered H2D pattern of SURVEY §7 hard part 6),
accumulates per-entry counters back into the endpoints' realized map
states, and optionally applies CT writeback between batches so NEW
flows become ESTABLISHED mid-replay (sustained-churn mode).

`replay_lattice` keeps the bare policy-lattice path for callers that
have only compiled PolicyTables (no CT/LB/ipcache state) — identity
comes pre-resolved from the record, as in a Hubble post-hoc replay.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from cilium_tpu.engine.verdict import (
    TupleBatch,
    _verdict_kernel_with_counters,
)
from cilium_tpu.maps.policymap import PolicyKey
from cilium_tpu.native import decode_flow_records

# fold the carried u32 counter buffers into host u64 sums before any
# cell could have gained 2^31 increments (each batch adds ≤ batch_size
# to a cell), leaving 2× headroom below the u32 wrap
_COUNTER_FOLD_MAX_INCR = 1 << 31

# churn-mode intent compaction capacity: create/delete intents per
# batch round that travel device→host (the transport is
# latency/bandwidth constrained, so only deduped flagged rows move;
# burstier rounds spill into extra convergence passes)
_CT_INTENT_CAP = 1 << 16
# claim-table slots for the on-device intent dedup (scatter-min);
# larger = fewer convergence re-runs from slot collisions
_CT_CLAIM_SLOTS = 1 << 19


@dataclass
class ReplayStats:
    total: int = 0
    allowed: int = 0
    denied: int = 0
    redirected: int = 0
    batches: int = 0
    seconds: float = 0.0
    ct_created: int = 0
    ct_deleted: int = 0

    @property
    def verdicts_per_sec(self) -> float:
        return self.total / self.seconds if self.seconds else 0.0


def _ep_index_of(rec, ep_map: Optional[Dict[int, int]]) -> np.ndarray:
    # int64: a u32 ep_id near 2^32 must not wrap negative pre-LUT
    ep_index = rec["ep_id"].astype(np.int64)
    if ep_map is not None:
        lut = np.zeros(max(ep_map.keys(), default=0) + 1, dtype=np.int32)
        for ep_id, idx in ep_map.items():
            lut[ep_id] = idx
        in_range = ep_index < len(lut)
        ep_index = np.where(
            in_range, lut[np.minimum(ep_index, len(lut) - 1)], 0
        )
    return ep_index.astype(np.int32)


def _batch_slices(n: int, batch_size: int):
    for start in range(0, n, batch_size):
        yield start, min(start + batch_size, n)


def _padded(a: np.ndarray, start: int, end: int, size: int, fill=0):
    chunk = a[start:end]
    pad = size - (end - start)
    if pad:
        chunk = np.concatenate(
            [chunk, np.full(pad, fill, dtype=a.dtype)]
        )
    return chunk


def read_batches(
    buf: bytes, batch_size: int, ep_map: Optional[Dict[int, int]] = None
) -> Iterator[Tuple[TupleBatch, int]]:
    """Decode flow records and yield padded TupleBatches (identity
    pre-resolved from the record).  `ep_map` translates record
    endpoint ids to table endpoint-axis indices (unknown endpoints map
    to 0 — callers should pre-filter)."""
    rec = decode_flow_records(buf)
    n = len(rec["ep_id"])
    ep_index = _ep_index_of(rec, ep_map)
    for start, end in _batch_slices(n, batch_size):
        p = lambda a, fill=0: _padded(a, start, end, batch_size, fill)
        yield (
            TupleBatch.from_numpy(
                ep_index=p(ep_index),
                identity=p(rec["identity"]),
                dport=p(rec["dport"].astype(np.int32)),
                proto=p(rec["proto"].astype(np.int32)),
                direction=p(rec["direction"].astype(np.int32)),
                is_fragment=p(rec["is_fragment"].astype(bool), fill=False),
            ),
            end - start,
        )


def read_flow_batches(
    buf: bytes, batch_size: int, ep_map: Optional[Dict[int, int]] = None
) -> Iterator[tuple]:
    """Decode flow records and yield padded FlowBatches (raw 5-tuples
    with addresses — identity resolution happens on device via the
    ipcache LPM inside the fused step)."""
    from cilium_tpu.engine.datapath import FlowBatch

    rec = decode_flow_records(buf)
    n = len(rec["ep_id"])
    ep_index = _ep_index_of(rec, ep_map)
    for start, end in _batch_slices(n, batch_size):
        p = lambda a, fill=0: _padded(a, start, end, batch_size, fill)
        yield (
            FlowBatch.from_numpy(
                ep_index=p(ep_index),
                saddr=p(rec["saddr"]),
                daddr=p(rec["daddr"]),
                sport=p(rec["sport"].astype(np.int32)),
                dport=p(rec["dport"].astype(np.int32)),
                proto=p(rec["proto"].astype(np.int32)),
                direction=p(rec["direction"].astype(np.int32)),
                is_fragment=p(rec["is_fragment"].astype(bool), fill=False),
            ),
            end - start,
        )


def replay(
    tables,
    buf: bytes,
    batch_size: int = 1 << 20,
    accumulate_counters: bool = True,
    ep_map: Optional[Dict[int, int]] = None,
    manager=None,
    ct_map=None,
) -> tuple:
    """Run all records through the FULL fused datapath step
    (engine/datapath.datapath_step_accum — counters scatter into
    carried, donated device buffers) with pipelined dispatch.

    `tables` is a DatapathTables (prefilter/ipcache/CT/LB/policy).
    With `ct_map` (the authoritative host CTMap) replay runs in
    sustained-churn mode: batches are drained in order, CT writeback
    (create/delete intents) is applied after each batch, and the
    device CT snapshot is recompiled whenever it changed — so a flow
    created by batch i is ESTABLISHED from batch i+1 on, mirroring the
    kernel datapath seeing its own CT writes.  Without it batches
    evaluate against the fixed snapshot and stay pipelined.

    Returns (ReplayStats, l4_counts, l3_counts); the counter arrays
    are u64 sums across batches with shapes [E, 2, Kg] and [E, 2, N]
    (policy_entry packets, bpf/lib/policy.h:66-68), or (stats, None,
    None) when `accumulate_counters` is False.
    """
    import time

    import jax
    import jax.numpy as jnp

    from cilium_tpu.ct.device import (
        CTBucketIndex,
        apply_bucket_delta,
    )
    from cilium_tpu.engine.datapath import (
        DatapathTables,
        apply_ct_writeback_host,
        datapath_step,
        datapath_step_accum,
    )
    from cilium_tpu.engine.verdict import (
        make_counter_buffers,
        split_counters,
    )

    if manager is not None:
        # stale-table guard at the layer that actually reads the
        # stacked per-endpoint rows: tables 2+ publishes old have had
        # those rows rewritten in place (FleetCompiler double
        # buffering) and would return wrong verdicts silently
        manager.check_tables_current(tables.policy)

    stats = ReplayStats()
    # pin every table on device once — jitted steps re-upload host
    # numpy leaves on EVERY call otherwise (268 MB of policy tables
    # per batch at config5 scale)
    tables = jax.device_put(tables)
    # counters scatter into a carried u32 device buffer, donated
    # across batches — one D2H fold per _COUNTER_FOLD_BATCHES into
    # host u64 sums (a cell can gain ≤ batch_size per batch, so u32
    # can't wrap within a fold interval), instead of [E, 2, N]
    # tensors per batch
    acc = None
    acc_total = None
    batches_since_fold = 0
    fold_every = max(1, _COUNTER_FOLD_MAX_INCR // max(batch_size, 1))
    if accumulate_counters:
        acc = jax.device_put(make_counter_buffers(tables.policy))

    def _fold_counters():
        nonlocal acc, acc_total, batches_since_fold
        host = np.asarray(acc).astype(np.uint64)
        acc_total = host if acc_total is None else acc_total + host
        acc = jax.device_put(make_counter_buffers(tables.policy))
        batches_since_fold = 0

    ct_index = None
    if ct_map is not None:
        # incremental churn machinery: a host mirror of the device
        # bucket layout (built once), a donated device snapshot, and
        # one packed D2H per batch.  The kernel owns the map, the
        # agent folds writes back — with per-bucket row updates
        # instead of full-snapshot rebuilds (bpf/lib/conntrack.h's
        # map writes are per-bucket too).
        ct_index = CTBucketIndex(ct_map)
        dev_snap = jax.device_put(ct_index.full_snapshot())
        tables = DatapathTables(
            prefilter=tables.prefilter,
            ipcache=tables.ipcache,
            ct=dev_snap,
            lb=tables.lb,
            policy=tables.policy,
        )
        _delta_jit = jax.jit(apply_bucket_delta, donate_argnums=(0,))
        # device-side intent compaction: host↔device transfers through
        # the runtime cost ~100 ms latency + low bandwidth, so only
        # the create/delete-flagged rows travel (fixed capacity; the
        # overflow count rides along in the header row).  Layout:
        # [11, cap] u32, transferred flat — rows 0-9 intent columns,
        # row 10 header (count, allowed, redirected, remaining at
        # cols 0-3)
        cap = _CT_INTENT_CAP
        claim_m = _CT_CLAIM_SLOTS

        def _compact(out, flows, valid):
            """Dedup + compact the batch's create/delete intents on
            device: a scatter-min claim table keeps the FIRST flagged
            row per flow-hash slot (distinct flows sharing a slot lose
            the round and surface in the header's `remaining`, which
            drives a convergence re-run), so the D2H transfer is
            O(unique intents), never O(batch)."""
            from cilium_tpu.engine.hashtable import fnv1a_device

            b = out.ct_create.shape[0]
            flag = (
                out.ct_create.astype(bool) | out.ct_delete.astype(bool)
            )
            in_valid = jnp.arange(b, dtype=jnp.int32) < valid
            flag = flag & in_valid

            h = fnv1a_device(
                jnp.stack(
                    [
                        out.final_daddr.astype(jnp.uint32),
                        flows.saddr.astype(jnp.uint32),
                        (
                            out.final_dport.astype(jnp.uint32) << 16
                        )
                        | (flows.sport.astype(jnp.uint32) & 0xFFFF),
                        (flows.proto.astype(jnp.uint32) << 8)
                        | flows.direction.astype(jnp.uint32),
                    ],
                    axis=1,
                )
            )
            slot = (h & jnp.uint32(claim_m - 1)).astype(jnp.int32)
            row_id = jnp.arange(b, dtype=jnp.int32)
            claim = jnp.full(claim_m, b, jnp.int32).at[slot].min(
                jnp.where(flag, row_id, b)
            )
            winner_row = claim[slot]
            winner = flag & (winner_row == row_id)
            # losers whose full hash equals their slot winner's are
            # (almost surely) later packets of the SAME flow — the
            # winner's create covers them, no convergence re-run
            # needed.  A 32-bit-hash collision between distinct flows
            # defers that flow's create to its next appearance in the
            # stream, the same race the per-packet kernel datapath
            # has (conntrack.h ct_create4 is best-effort too).
            wr = jnp.clip(winner_row, 0, b - 1)
            true_loser = flag & ~winner & (h[wr] != h)

            # compaction via argsort, NOT scatter: a scatter routing
            # millions of non-winner rows at one trash index is
            # pathologically slow on TPU (duplicate-index collision
            # handling); sorting 'winner-first' and slicing the head
            # is a single O(B log B) sort plus tiny gathers
            take = min(cap, b)
            order = jnp.argsort(
                jnp.where(winner, row_id, jnp.int32(b))
            )[:take]
            keep = winner[order]  # mask off the tail when < cap win
            cols = jnp.stack(
                [
                    out.ct_create.astype(jnp.uint32),
                    out.ct_delete.astype(jnp.uint32),
                    out.final_daddr.astype(jnp.uint32),
                    out.final_dport.astype(jnp.uint32),
                    flows.saddr.astype(jnp.uint32),
                    flows.sport.astype(jnp.uint32),
                    flows.proto.astype(jnp.uint32),
                    flows.direction.astype(jnp.uint32),
                    out.rev_nat.astype(jnp.uint32),
                    out.lb_slave.astype(jnp.uint32),
                ]
            )  # [10, B]
            buf = jnp.zeros((11, cap), jnp.uint32)
            buf = buf.at[:10, :take].set(
                jnp.where(keep[None, :], cols[:, order], 0)
            )
            n_tx = jnp.minimum(
                winner.sum(dtype=jnp.uint32), jnp.uint32(take)
            )
            allowed = jnp.sum(
                out.allowed.astype(jnp.uint32) * in_valid,
                dtype=jnp.uint32,
            )
            redirected = jnp.sum(
                (out.proxy_port > 0) & in_valid, dtype=jnp.uint32
            )
            overflow = winner.sum(dtype=jnp.uint32) - n_tx
            remaining = true_loser.sum(dtype=jnp.uint32) + overflow
            buf = buf.at[10, :4].set(
                jnp.stack([n_tx, allowed, redirected, remaining])
            )
            return buf.reshape(-1)  # flat: fastest D2H layout

        _compact_jit = jax.jit(_compact)

    pending = []  # pipelined dispatch, bounded depth
    t0 = time.perf_counter()
    for flows, valid in read_flow_batches(buf, batch_size, ep_map):
        if ct_map is not None:
            tables = DatapathTables(
                prefilter=tables.prefilter,
                ipcache=tables.ipcache,
                ct=dev_snap,
                lb=tables.lb,
                policy=tables.policy,
            )
        if accumulate_counters:
            out, acc = datapath_step_accum(tables, flows, acc)
            batches_since_fold += 1
            if batches_since_fold >= fold_every:
                _fold_counters()
        else:
            out = datapath_step(tables, flows)
        if ct_map is not None:
            # sustained churn: drain in order via ONE compacted,
            # deduped D2H; fold intents back on host; scatter the
            # changed bucket rows into the donated device snapshot.
            # Claim-table losers (distinct flows sharing a dedup
            # slot, or >cap unique intents) drive convergence
            # re-runs of the same batch against the updated
            # snapshot, so the next batch sees every flow this one
            # created (up to the documented 32-bit-hash-collision
            # deferral in _compact).
            first_pass = True
            while True:
                packed = np.asarray(
                    _compact_jit(out, flows, valid)
                ).reshape(11, cap)
                if first_pass:
                    stats.total += int(valid)
                    allowed = int(packed[10, 1])
                    stats.allowed += allowed
                    stats.denied += int(valid) - allowed
                    stats.redirected += int(packed[10, 2])
                    stats.batches += 1
                    first_pass = False
                k = int(packed[10, 0])
                remaining = int(packed[10, 3])
                created_keys, deleted_keys = apply_ct_writeback_host(
                    ct_map,
                    packed[0, :k].astype(bool),
                    packed[1, :k].astype(bool),
                    *(packed[j, :k] for j in range(2, 10)),
                )
                stats.ct_created += len(created_keys)
                stats.ct_deleted += len(deleted_keys)
                if created_keys or deleted_keys:
                    idx, rows, new_stash = ct_index.apply(
                        created_keys, deleted_keys
                    )
                    if len(idx) or new_stash is not None:
                        dev_snap = _delta_jit(
                            dev_snap,
                            idx,
                            rows,
                            new_stash,
                        )
                if remaining == 0:
                    break
                # convergence pass: re-evaluate against the updated
                # snapshot (no counter re-accumulation)
                tables = DatapathTables(
                    prefilter=tables.prefilter,
                    ipcache=tables.ipcache,
                    ct=dev_snap,
                    lb=tables.lb,
                    policy=tables.policy,
                )
                out = datapath_step(tables, flows)
            continue
        pending.append((out, valid))
        stats.batches += 1
        if len(pending) >= 4:
            _drain_fused(pending.pop(0), stats)
    while pending:
        _drain_fused(pending.pop(0), stats)
    stats.seconds = time.perf_counter() - t0

    if not accumulate_counters:
        return stats, None, None
    _fold_counters()
    kg = tables.policy.l4_meta.shape[2]
    return stats, acc_total[:, :, :kg], acc_total[:, :, kg:]


def replay_lattice(
    tables,
    buf: bytes,
    batch_size: int = 1 << 20,
    accumulate_counters: bool = True,
    ep_map: Optional[Dict[int, int]] = None,
    manager=None,
) -> tuple:
    """Replay through the bare policy lattice (PolicyTables only,
    identity pre-resolved from the record) — the post-hoc Hubble
    audit path.  Same return shape as replay()."""
    import time

    if manager is not None:
        manager.check_tables_current(tables)
    step = _replay_step()
    stats = ReplayStats()
    acc = _CounterAccumulator() if accumulate_counters else None

    pending = []  # pipelined dispatch, bounded depth
    t0 = time.perf_counter()
    for batch, valid in read_batches(buf, batch_size, ep_map):
        out = step(tables, batch)
        pending.append((out, valid))
        stats.batches += 1
        if len(pending) >= 4:
            _drain(pending.pop(0), stats, acc)
    while pending:
        _drain(pending.pop(0), stats, acc)
    stats.seconds = time.perf_counter() - t0

    if acc is None:
        return stats, None, None
    return stats, acc.l4, acc.l3


class _CounterAccumulator:
    l4: Optional[np.ndarray] = None
    l3: Optional[np.ndarray] = None

    def add(self, l4_counts, l3_counts) -> None:
        if self.l4 is None:
            self.l4 = np.zeros(l4_counts.shape, dtype=np.uint64)
            self.l3 = np.zeros(l3_counts.shape, dtype=np.uint64)
        self.l4 += np.asarray(l4_counts).astype(np.uint64)
        self.l3 += np.asarray(l3_counts).astype(np.uint64)


def _tally(verdicts, valid, stats: ReplayStats) -> None:
    allowed = np.asarray(verdicts.allowed)[:valid]
    proxy = np.asarray(verdicts.proxy_port)[:valid]
    stats.total += int(valid)
    stats.allowed += int(allowed.sum())
    stats.denied += int(valid - allowed.sum())
    stats.redirected += int((proxy > 0).sum())


def _drain(item, stats: ReplayStats, acc: Optional[_CounterAccumulator]) -> None:
    (verdicts, l4_counts, l3_counts), valid = item
    _tally(verdicts, valid, stats)
    if acc is not None:
        acc.add(l4_counts, l3_counts)


def _drain_fused(item, stats: ReplayStats) -> None:
    """Fused-path drain: counters live in the carried device
    accumulators, so the item is just (verdicts, valid)."""
    verdicts, valid = item
    _tally(verdicts, valid, stats)


_REPLAY_STEP = None


def _replay_step():
    """Module-level jitted lattice step (one compilation cache across
    replay_lattice() calls, like engine.verdict.evaluate_batch)."""
    global _REPLAY_STEP
    if _REPLAY_STEP is None:
        import jax

        _REPLAY_STEP = jax.jit(_verdict_kernel_with_counters)
    return _REPLAY_STEP


def slot_keys_from_tables(tables) -> Dict[int, Tuple[int, int]]:
    """Recover global L4 slot → (dport, proto) from the compiled
    port_slot table (the inverse of lower_map_state's slot_of)."""
    from cilium_tpu.compiler.tables import NO_SLOT

    port_slot = np.asarray(tables.port_slot)
    protos, dports = np.nonzero(port_slot != NO_SLOT)
    slots = port_slot[protos, dports]
    return {
        int(j): (int(dport), int(proto))
        for j, dport, proto in zip(slots, dports, protos)
    }


def sync_counters_to_endpoints(
    l4_counts: Optional[np.ndarray],
    l3_counts: Optional[np.ndarray],
    manager,
    tables=None,
    index: Optional[Dict[int, int]] = None,
) -> int:
    """Fold accumulated device counters back into the endpoints'
    realized map states (the packets field of policy_entry the agent
    reads back from the datapath, pkg/maps/policymap PolicyEntry).

    Pass the `tables`/`index` the counters were computed against; a
    republish between replay() and sync would otherwise shift the
    identity/slot indexing and misattribute counts.  Falls back to the
    currently-published version when not given.  Returns entries
    updated."""
    if tables is None or index is None:
        _, tables, index = manager.published()
    if tables is None:
        return 0
    # NOTE: no staleness guard needed here — this function reads only
    # tables.id_table (freshly allocated per rebuild) and
    # tables.port_slot (write-once cells), both of which stay valid in
    # arbitrarily old snapshots.  The in-place-mutation hazard is the
    # stacked per-endpoint rows, guarded at replay()/evaluation time.
    updated = 0
    rev_index = {v: k for k, v in index.items()}
    id_table = np.asarray(tables.id_table)
    if l3_counts is not None:
        # L3 counters are indexed by identity index.  Re-read the
        # realized state under the endpoint lock per update: a
        # concurrent sync_policy_map publishes a NEW array-backed
        # state (copy-on-write), and an increment applied through a
        # pre-sync view would land in the superseded snapshot.
        for e, d, idx in zip(*np.nonzero(l3_counts)):
            ep = manager.lookup(rev_index.get(int(e), -1))
            if ep is None:
                continue
            key = PolicyKey(int(id_table[idx]), 0, 0, int(d))
            with ep.lock:
                entry = ep.realized_map_state.get(key)
                if entry is not None:
                    entry.packets += int(l3_counts[e, d, idx])
                    updated += 1
    if l4_counts is not None:
        # L4 counters are indexed by global slot; a slot hit covers
        # every (identity, dport, proto) entry of that filter — the
        # wildcard entry takes the count (exact-entry attribution
        # would need per-(slot, identity) counters; the reference
        # bumps the entry the probe hit, which for MATCH_L4 is the
        # exact key and for MATCH_L4_WILD the wildcard — we fold both
        # into the slot's wildcard-or-first entry, preserving totals).
        slot_keys = slot_keys_from_tables(tables)
        for e, d, j in zip(*np.nonzero(l4_counts)):
            ep = manager.lookup(rev_index.get(int(e), -1))
            if ep is None or int(j) not in slot_keys:
                continue
            dport, proto = slot_keys[int(j)]
            count = int(l4_counts[e, d, j])
            wild = PolicyKey(0, dport, proto, int(d))
            with ep.lock:
                entry = ep.realized_map_state.get(wild)
                if entry is None:
                    for key, cand in ep.realized_map_state.items():
                        if (
                            key.dest_port == dport
                            and key.nexthdr == proto
                            and key.traffic_direction == int(d)
                        ):
                            entry = cand
                            break
                if entry is not None:
                    entry.packets += count
                    updated += 1
    return updated
