"""Flow-replay harness: binary flow records → streamed device verdicts.

The framework's data-loader (SURVEY §7 step 5: "flow-replay harness,
Hubble-tuple reader"): reads fixed 24-byte flow records (decoded by
the native C++ decoder at memory bandwidth), streams fixed-size padded
batches through the FUSED datapath step — prefilter → LB/DNAT → CT →
ipcache LPM → policy lattice in one jit (engine/datapath.py, the
analog of bpf_lxc.c:440/899 being ONE program) — with pipelined
dispatch (the double-buffered H2D pattern of SURVEY §7 hard part 6),
accumulates per-entry counters back into the endpoints' realized map
states, and optionally applies CT writeback between batches so NEW
flows become ESTABLISHED mid-replay (sustained-churn mode).

`replay_lattice` keeps the bare policy-lattice path for callers that
have only compiled PolicyTables (no CT/LB/ipcache state) — identity
comes pre-resolved from the record, as in a Hubble post-hoc replay.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from cilium_tpu.engine.verdict import (
    TupleBatch,
    _verdict_kernel_with_counters,
)
from cilium_tpu.maps.policymap import PolicyKey
from cilium_tpu.native import decode_flow_records

# fold the carried u32 counter buffers into host u64 sums before any
# cell could have gained 2^31 increments (each batch adds ≤ batch_size
# to a cell), leaving 2× headroom below the u32 wrap
_COUNTER_FOLD_MAX_INCR = 1 << 31

def _guarded_dispatch(fn, *args, donated=False):
    """One jitted dispatch under the shared guard
    (resilience.guarded_dispatch): engine.dispatch fault seam +
    bounded retry; the Daemon's breaker + host-path failover handles
    anything persistent.  `donated=True` for the accumulator-carrying
    steps whose jit donates buffers — those retry only the
    pre-launch injected fault (see guarded_dispatch)."""
    from cilium_tpu.resilience import guarded_dispatch

    return guarded_dispatch(fn, *args, donated=donated)

# churn-mode intent compaction capacity: create/delete intents per
# batch round that travel device→host (the transport is
# latency/bandwidth constrained, so only deduped flagged rows move;
# burstier rounds spill into extra convergence passes)
_CT_INTENT_CAP = 1 << 16
# claim-table slots for the on-device intent dedup (scatter-min);
# larger = fewer convergence re-runs from slot collisions
_CT_CLAIM_SLOTS = 1 << 19
# intent-fetch slice buckets: the D2H transport costs ~100 ms of fixed
# latency plus ~17 MB/s, so the fetch moves the smallest power-of-two
# column slice covering the round's intent count instead of the full
# [12, cap] buffer (3.1 MB).  Static sizes keep the slice kernels in
# the jit cache.
_CT_FETCH_BUCKETS = (1 << 10, 1 << 13, _CT_INTENT_CAP)


def _churn_compact(out, flows, valid):
    """Dedup + compact a batch's create/delete intents on device: a
    scatter-min claim table keeps the FIRST flagged row per flow-hash
    slot (distinct flows sharing a slot lose the round and surface in
    the header's `remaining`, which drives a convergence re-run), so
    the D2H transfer is O(unique intents), never O(batch).

    Returns (header u32 [4] = count/allowed/redirected/remaining,
    intents u32 [12, cap]) as SEPARATE outputs so the caller can pull
    the 16-byte header alone on quiet rounds — the transport costs
    ~100 ms of fixed latency per fetch, so the intent buffer only
    moves when the header says something is in it."""
    import jax.numpy as jnp

    from cilium_tpu.engine.hashtable import fnv1a_device

    cap = _CT_INTENT_CAP
    claim_m = _CT_CLAIM_SLOTS
    b = out.ct_create.shape[0]
    flag = out.ct_create.astype(bool) | out.ct_delete.astype(bool)
    in_valid = jnp.arange(b, dtype=jnp.int32) < valid
    flag = flag & in_valid

    h = fnv1a_device(
        jnp.stack(
            [
                out.final_daddr.astype(jnp.uint32),
                flows.saddr.astype(jnp.uint32),
                (out.final_dport.astype(jnp.uint32) << 16)
                | (flows.sport.astype(jnp.uint32) & 0xFFFF),
                (flows.proto.astype(jnp.uint32) << 8)
                | flows.direction.astype(jnp.uint32),
            ],
            axis=1,
        )
    )
    slot = (h & jnp.uint32(claim_m - 1)).astype(jnp.int32)
    row_id = jnp.arange(b, dtype=jnp.int32)
    claim = jnp.full(claim_m, b, jnp.int32).at[slot].min(
        jnp.where(flag, row_id, b)
    )
    winner_row = claim[slot]
    winner = flag & (winner_row == row_id)
    # losers whose full hash equals their slot winner's are (almost
    # surely) later packets of the SAME flow — the winner's create
    # covers them, no convergence re-run needed.  A 32-bit-hash
    # collision between distinct flows defers that flow's create to
    # its next appearance in the stream, the same race the per-packet
    # kernel datapath has (conntrack.h ct_create4 is best-effort too).
    wr = jnp.clip(winner_row, 0, b - 1)
    true_loser = flag & ~winner & (h[wr] != h)

    # compaction via argsort, NOT scatter: a scatter routing millions
    # of non-winner rows at one trash index is pathologically slow on
    # TPU (duplicate-index collision handling); sorting 'winner-first'
    # and slicing the head is a single O(B log B) sort + tiny gathers
    take = min(cap, b)
    order = jnp.argsort(jnp.where(winner, row_id, jnp.int32(b)))[:take]
    keep = winner[order]  # mask off the tail when < cap win
    cols = jnp.stack(
        [
            out.ct_create.astype(jnp.uint32),
            out.ct_delete.astype(jnp.uint32),
            out.final_daddr.astype(jnp.uint32),
            out.final_dport.astype(jnp.uint32),
            flows.saddr.astype(jnp.uint32),
            flows.sport.astype(jnp.uint32),
            flows.proto.astype(jnp.uint32),
            flows.direction.astype(jnp.uint32),
            out.rev_nat.astype(jnp.uint32),
            out.lb_slave.astype(jnp.uint32),
            # pre-DNAT frontend, for service-entry creation and
            # dual-homed bucket placement (apply_ct_writeback_host)
            flows.daddr.astype(jnp.uint32),
            flows.dport.astype(jnp.uint32),
        ]
    )  # [12, B]
    intents = jnp.zeros((12, cap), jnp.uint32)
    intents = intents.at[:, :take].set(
        jnp.where(keep[None, :], cols[:, order], 0)
    )
    n_tx = jnp.minimum(winner.sum(dtype=jnp.uint32), jnp.uint32(take))
    allowed = jnp.sum(
        out.allowed.astype(jnp.uint32) * in_valid, dtype=jnp.uint32
    )
    redirected = jnp.sum(
        (out.proxy_port > 0) & in_valid, dtype=jnp.uint32
    )
    overflow = winner.sum(dtype=jnp.uint32) - n_tx
    remaining = true_loser.sum(dtype=jnp.uint32) + overflow
    header = jnp.stack([n_tx, allowed, redirected, remaining])
    return header, intents


_CHURN_FNS = None


def _flows_from_pool(pool_packed, picks):
    """Device-side flow materialization: gather pool rows by pick
    index inside the fused program, split via the shared
    FLOW_COLUMNS contract.  The pool-mode data loader exists because
    the operator host has ONE core shared with the transport relay —
    every host-touched byte (decode, pack, upload serialization)
    competes with the tunnel for that core, so the loader moves
    4 bytes/tuple (the pick) instead of ~88 (decode read + pack write
    + record upload)."""
    from cilium_tpu.engine.datapath import flow_batch_from_packed

    return flow_batch_from_packed(pool_packed[:, picks])


_POOL_PACK_KEY = "__device_pack__"


def pack_flow_pool(pool: Dict[str, np.ndarray]) -> np.ndarray:
    """Flow-universe dict → [8, P] u32 pack (one upload, device
    gathers per batch).  Row order is datapath.FLOW_COLUMNS — the
    same contract FlowBatch.from_numpy packs with."""
    from cilium_tpu.engine.datapath import FLOW_COLUMNS

    p = len(pool["saddr"])
    packed = np.empty((len(FLOW_COLUMNS), p), dtype=np.uint32)
    for j, k in enumerate(FLOW_COLUMNS):
        packed[j] = np.asarray(pool[k]).astype(np.uint32, copy=False)
    return packed


def _churn_fns():
    """Jitted fused churn programs: datapath step + intent compaction
    in ONE dispatch (the churn loop's critical path is serial —
    step → header D2H → CT fold → snapshot delta — so every extra
    dispatch adds a full transport round trip).  Returns
    (step, step_accum, step_pool); step_pool additionally fuses the
    pool-row gather (see _flows_from_pool)."""
    global _CHURN_FNS
    if _CHURN_FNS is None:
        import jax

        from cilium_tpu.engine.datapath import (
            _datapath_kernel,
            _datapath_kernel_accum,
        )

        def step(tables, flows, valid):
            out = _datapath_kernel(tables, flows)
            return _churn_compact(out, flows, valid)

        def step_accum(tables, flows, valid, acc):
            out, acc = _datapath_kernel_accum(tables, flows, acc)
            header, intents = _churn_compact(out, flows, valid)
            return header, intents, acc

        def step_pool(tables, pool_packed, picks, valid):
            flows = _flows_from_pool(pool_packed, picks)
            out = _datapath_kernel(tables, flows)
            return _churn_compact(out, flows, valid)

        def step_pool_rand(tables, pool_packed, key, batch_size, valid):
            # device-side pick generation: the serial churn loop pays
            # the transport's full H2D latency per upload, so moving
            # an [B] index array per round dominates when the link is
            # slow — an 8-byte PRNG key replaces it (uniform picks,
            # same distribution the host sampler draws)
            import jax.numpy as jnp
            import jax.random as jrandom

            picks = jrandom.randint(
                key,
                (batch_size,),
                0,
                pool_packed.shape[1],
                dtype=jnp.uint32,
            )
            flows = _flows_from_pool(pool_packed, picks)
            out = _datapath_kernel(tables, flows)
            return _churn_compact(out, flows, valid)

        _CHURN_FNS = (
            jax.jit(step),
            jax.jit(step_accum, donate_argnums=(3,)),
            jax.jit(step_pool),
            jax.jit(step_pool_rand, static_argnums=(3,)),
        )
    return _CHURN_FNS


_FETCH_SLICE = {}


def _fetch_intents(intents_dev, k: int) -> np.ndarray:
    """Pull the first k intent columns via the smallest static slice
    bucket (each bucket is one tiny cached jit program; the transport
    charges ~100 ms latency + ~17 MB/s bandwidth per fetch, so a
    quiet round moves kilobytes, not the full 2.6 MB buffer)."""
    import jax

    bucket = next(
        (b for b in _CT_FETCH_BUCKETS if k <= b), _CT_INTENT_CAP
    )
    fn = _FETCH_SLICE.get(bucket)
    if fn is None:
        fn = jax.jit(lambda x, n=bucket: x[:, :n])
        _FETCH_SLICE[bucket] = fn
    return np.asarray(fn(intents_dev))[:, :k]


class _ChurnDriver:
    """Shared churn-mode machinery for replay()/replay_pool(): the
    bucket-index + device-snapshot cache, and the per-round drain
    (header parse → bucketed intent fetch → host CT fold → per-bucket
    device delta).

    The bucket index (O(entries) host hash placement) and the
    full-snapshot upload are the churn path's fixed setup cost — both
    cache on the CTMap across calls.  Validity gate: the CTMap
    mutation counter (bumped by create/probe/gc — catches host-side
    lookups between replays that mutate lifetime/closing flags in
    place) plus the exact key set (catches direct `entries` dict
    manipulation).  The only remaining bypass is mutating a CTEntry
    object's fields directly without touching the map; such callers
    must `del ct_map._device_churn_cache`.  Within the loop every
    mutation flows through ct_index.apply, keeping all three (map,
    index, device snapshot) in lockstep.
    """

    def __init__(self, ct_map) -> None:
        import jax

        from cilium_tpu.ct.device import CTBucketIndex

        self.ct_map = ct_map
        self._delta_jit = _delta_fn()
        cached = getattr(ct_map, "_device_churn_cache", None)
        if (
            cached is not None
            and cached[2] == getattr(ct_map, "mutations", -1)
            and cached[0].key_home.keys() == ct_map.entries.keys()
        ):
            self.ct_index, self.dev_snap = cached[:2]
        else:
            self.ct_index = CTBucketIndex(ct_map)
            self.dev_snap = jax.device_put(
                self.ct_index.full_snapshot()
            )

    def drain(
        self, header_d, intents_d, stats: "ReplayStats",
        valid: int, first_pass: bool,
    ) -> int:
        """One convergence round: fold the round's intents into the
        host CT + device snapshot, update stats on the first pass.
        Returns the header's `remaining` count (>0 ⇒ the caller must
        re-run the batch against the updated snapshot)."""
        from cilium_tpu.engine.datapath import apply_ct_writeback_host

        header = np.asarray(header_d)
        k = int(header[0])
        remaining = int(header[3])
        if first_pass:
            stats.total += valid
            allowed = int(header[1])
            stats.allowed += allowed
            stats.denied += valid - allowed
            stats.redirected += int(header[2])
            stats.batches += 1
        if k:
            packed = _fetch_intents(intents_d, k)
            created_keys, deleted_keys = apply_ct_writeback_host(
                self.ct_map,
                packed[0].astype(bool),
                packed[1].astype(bool),
                *(packed[j] for j in range(2, 10)),
                orig_daddr=packed[10],
                orig_dport=packed[11],
                # stamp lifetimes on the MAP's clock: the daemon's GC
                # runs on ct.now() (map age), and a now=0 stamp would
                # read as already-expired once uptime passes the
                # timeout
                now=self.ct_map.now(),
            )
            stats.ct_created += len(created_keys)
            stats.ct_deleted += len(deleted_keys)
            if created_keys or deleted_keys:
                idx, rows, new_stash = self.ct_index.apply(
                    created_keys, deleted_keys
                )
                if len(idx) or new_stash is not None:
                    self.dev_snap = self._delta_jit(
                        self.dev_snap, idx, rows, new_stash
                    )
        return remaining

    def stash(self) -> None:
        self.ct_map._device_churn_cache = (
            self.ct_index,
            self.dev_snap,
            self.ct_map.mutations,
        )


_DELTA_FN = None


def _delta_fn():
    """Module-level cached jit of apply_bucket_delta (donated
    snapshot) — per-driver jits would re-trace on every replay call."""
    global _DELTA_FN
    if _DELTA_FN is None:
        import jax

        from cilium_tpu.ct.device import apply_bucket_delta

        _DELTA_FN = jax.jit(apply_bucket_delta, donate_argnums=(0,))
    return _DELTA_FN


@dataclass
class ReplayStats:
    total: int = 0
    allowed: int = 0
    denied: int = 0
    redirected: int = 0
    batches: int = 0
    seconds: float = 0.0
    ct_created: int = 0
    ct_deleted: int = 0
    # records discarded BEFORE evaluation (e.g. unknown endpoint ids
    # filtered by Daemon.process_flows) — totals must account for
    # every input record
    dropped: int = 0
    # flows shed by bounded admission (Daemon.process_flows overload
    # shedding; like `dropped`, NOT part of `total`)
    shed: int = 0
    # batches served by the host-path fallback while the dispatch
    # circuit breaker was open/failing (verdicts bit-identical)
    degraded_batches: int = 0
    # per-tuple verdict columns in stream order (process_flows
    # collect_verdicts=True): {"allowed", "match_kind", "proxy_port"}
    verdicts: object = None
    # per-phase wall-time accumulators (SpanStats: host_pack /
    # dispatch / drain), populated by replay()'s instrumented loop
    spans: object = None
    # [2, TELEM_COLS] u64 stage/drop histogram of the replayed
    # traffic (replay(collect_telemetry=True))
    telemetry: object = None

    @property
    def verdicts_per_sec(self) -> float:
        return self.total / self.seconds if self.seconds else 0.0


def _ep_index_of(rec, ep_map: Optional[Dict[int, int]]) -> np.ndarray:
    # int64: a u32 ep_id near 2^32 must not wrap negative pre-LUT
    ep_index = rec["ep_id"].astype(np.int64)
    if ep_map is not None:
        lut = np.zeros(max(ep_map.keys(), default=0) + 1, dtype=np.int32)
        for ep_id, idx in ep_map.items():
            lut[ep_id] = idx
        in_range = ep_index < len(lut)
        ep_index = np.where(
            in_range, lut[np.minimum(ep_index, len(lut) - 1)], 0
        )
    return ep_index.astype(np.int32)


def _batch_slices(n: int, batch_size: int):
    for start in range(0, n, batch_size):
        yield start, min(start + batch_size, n)


def _padded(a: np.ndarray, start: int, end: int, size: int, fill=0):
    chunk = a[start:end]
    pad = size - (end - start)
    if pad:
        chunk = np.concatenate(
            [chunk, np.full(pad, fill, dtype=a.dtype)]
        )
    return chunk


def read_batches(
    buf: bytes, batch_size: int, ep_map: Optional[Dict[int, int]] = None
) -> Iterator[Tuple[TupleBatch, int]]:
    """Decode flow records and yield padded TupleBatches (identity
    pre-resolved from the record).  `ep_map` translates record
    endpoint ids to table endpoint-axis indices (unknown endpoints map
    to 0 — callers should pre-filter)."""
    return read_batches_from_rec(
        decode_flow_records(buf), batch_size, ep_map
    )


def read_batches_from_rec(
    rec: Dict[str, np.ndarray],
    batch_size: int,
    ep_map: Optional[Dict[int, int]] = None,
    ep_index: Optional[np.ndarray] = None,
) -> Iterator[Tuple[TupleBatch, int]]:
    """read_batches over an ALREADY-decoded record SoA — callers that
    pre-filter records (Daemon.process_flows) avoid a second decode
    pass over the buffer.  `ep_index` supplies an already-computed
    endpoint-axis translation (callers that keep one host-side for
    event folding skip the second O(n) LUT pass)."""
    n = len(rec["ep_id"])
    if ep_index is None:
        ep_index = _ep_index_of(rec, ep_map)
    for start, end in _batch_slices(n, batch_size):
        p = lambda a, fill=0: _padded(a, start, end, batch_size, fill)
        yield (
            TupleBatch.from_numpy(
                ep_index=p(ep_index),
                identity=p(rec["identity"]),
                dport=p(rec["dport"].astype(np.int32)),
                proto=p(rec["proto"].astype(np.int32)),
                direction=p(rec["direction"].astype(np.int32)),
                is_fragment=p(rec["is_fragment"].astype(bool), fill=False),
            ),
            end - start,
        )


def read_flow_batches(
    buf: bytes, batch_size: int, ep_map: Optional[Dict[int, int]] = None
) -> Iterator[tuple]:
    """Decode flow records and yield padded FlowBatches (raw 5-tuples
    with addresses — identity resolution happens on device via the
    ipcache LPM inside the fused step)."""
    from cilium_tpu.engine.datapath import FlowBatch

    rec = decode_flow_records(buf)
    n = len(rec["ep_id"])
    ep_index = _ep_index_of(rec, ep_map)
    for start, end in _batch_slices(n, batch_size):
        p = lambda a, fill=0: _padded(a, start, end, batch_size, fill)
        yield (
            FlowBatch.from_numpy(
                ep_index=p(ep_index),
                saddr=p(rec["saddr"]),
                daddr=p(rec["daddr"]),
                sport=p(rec["sport"].astype(np.int32)),
                dport=p(rec["dport"].astype(np.int32)),
                proto=p(rec["proto"].astype(np.int32)),
                direction=p(rec["direction"].astype(np.int32)),
                is_fragment=p(rec["is_fragment"].astype(bool), fill=False),
            ),
            end - start,
        )


def replay(
    tables,
    buf: bytes,
    batch_size: int = 1 << 20,
    accumulate_counters: bool = True,
    ep_map: Optional[Dict[int, int]] = None,
    manager=None,
    ct_map=None,
    collect_telemetry: bool = False,
    flow_store=None,
    chip: int = 0,
) -> tuple:
    """Run all records through the FULL fused datapath step
    (engine/datapath.datapath_step_accum — counters scatter into
    carried, donated device buffers) with pipelined dispatch.

    `tables` is a DatapathTables (prefilter/ipcache/CT/LB/policy).
    With `ct_map` (the authoritative host CTMap) replay runs in
    sustained-churn mode: batches are drained in order, CT writeback
    (create/delete intents) is applied after each batch, and the
    device CT snapshot is recompiled whenever it changed — so a flow
    created by batch i is ESTABLISHED from batch i+1 on, mirroring the
    kernel datapath seeing its own CT writes.  Without it batches
    evaluate against the fixed snapshot and stay pipelined.

    With `collect_telemetry` the fused dispatch additionally carries
    the [2, TELEM_COLS] stage/drop accumulator
    (datapath_step_accum_telem); the folded histogram lands in
    stats.telemetry AND increments the process metrics registry
    (cilium_drop_count_total / policy_verdict_total / ...).  Not
    offered in churn mode (the churn programs fuse intent compaction
    instead).

    Phase wall times (host_pack / dispatch / drain) accumulate into
    stats.spans, and per-iteration wall time feeds the registry's
    batch-duration histogram — the SpanStat instrumentation the
    reference hangs off its regeneration phases, applied to the
    datapath loop.

    With `flow_store` (a cilium_tpu.flow.FlowStore) every drained
    batch folds flow records into the ring — all drops plus allows
    head-sampled per the MonitorAggregationLevel knob — tagged with
    `chip` and classified through the shared telemetry_masks
    definitions; the peer identity rides src/dst per direction, the
    local side is 0 (replay has no endpoint-identity context).  Not
    offered in churn mode, like collect_telemetry.

    Returns (ReplayStats, l4_counts, l3_counts); the counter arrays
    are u64 sums across batches with shapes [E, 2, Kg] and [E, 2, N]
    (policy_entry packets, bpf/lib/policy.h:66-68), or (stats, None,
    None) when `accumulate_counters` is False.
    """
    import time

    import jax

    from cilium_tpu.engine.datapath import (
        DatapathTables,
        datapath_step,
        datapath_step_accum,
        datapath_step_accum_telem,
    )
    from cilium_tpu.engine.verdict import make_counter_buffers
    from cilium_tpu.metrics import registry as _metrics
    from cilium_tpu.spanstat import SpanStats

    if manager is not None:
        # stale-table guard at the layer that actually reads the
        # stacked per-endpoint rows: tables 2+ publishes old have had
        # those rows rewritten in place (FleetCompiler double
        # buffering) and would return wrong verdicts silently
        manager.check_tables_current(tables.policy)
    if flow_store is not None and ct_map is not None:
        raise ValueError(
            "flow capture is not offered in churn mode (the churn "
            "programs fuse intent compaction instead of returning "
            "per-tuple verdict columns)"
        )

    stats = ReplayStats()
    spans = SpanStats()
    stats.spans = spans
    # pin every table on device once — jitted steps re-upload host
    # numpy leaves on EVERY call otherwise (268 MB of policy tables
    # per batch at config5 scale)
    tables = jax.device_put(tables)
    # counters scatter into a carried u32 device buffer, donated
    # across batches — one D2H fold per _COUNTER_FOLD_BATCHES into
    # host u64 sums (a cell can gain ≤ batch_size per batch, so u32
    # can't wrap within a fold interval), instead of [E, 2, N]
    # tensors per batch
    acc = None
    acc_total = None
    batches_since_fold = 0
    fold_every = max(1, _COUNTER_FOLD_MAX_INCR // max(batch_size, 1))
    if accumulate_counters:
        acc = jax.device_put(make_counter_buffers(tables.policy))
    telem_dev = None
    telem_total = None
    if collect_telemetry and ct_map is None:
        from cilium_tpu.engine.verdict import (
            TELEM_COLS,
            make_telemetry_buffers,
        )

        telem_total = np.zeros((2, TELEM_COLS), np.uint64)
        if accumulate_counters:
            telem_dev = jax.device_put(make_telemetry_buffers())

    def _fold_counters():
        nonlocal acc, acc_total, batches_since_fold, telem_dev
        nonlocal telem_total
        host = np.asarray(acc).astype(np.uint64)
        acc_total = host if acc_total is None else acc_total + host
        acc = jax.device_put(make_counter_buffers(tables.policy))
        if telem_dev is not None:
            # the telemetry buffer wraps at the same u32 horizon as
            # the counter buffer — fold it on the same cadence
            from cilium_tpu.engine.verdict import (
                make_telemetry_buffers,
            )

            telem_total = telem_total + np.asarray(telem_dev).astype(
                np.uint64
            )
            telem_dev = jax.device_put(make_telemetry_buffers())
        batches_since_fold = 0

    churn = None
    if ct_map is not None:
        # incremental churn machinery (_ChurnDriver): a host mirror
        # of the device bucket layout, a donated device snapshot, and
        # a two-phase D2H per batch (16-byte header always; intent
        # columns only on rounds that flagged any).  The kernel owns
        # the map, the agent folds writes back — with per-bucket row
        # updates instead of full-snapshot rebuilds
        # (bpf/lib/conntrack.h's map writes are per-bucket too).
        churn = _ChurnDriver(ct_map)
        tables = DatapathTables(
            prefilter=tables.prefilter,
            ipcache=tables.ipcache,
            ct=churn.dev_snap,
            lb=tables.lb,
            policy=tables.policy,
            tunnel=tables.tunnel,
        )
        churn_step, churn_step_accum = _churn_fns()[:2]

    id_table_host = (
        np.asarray(tables.policy.id_table)
        if flow_store is not None
        else None
    )
    # out.sec_id is a raw identity INDEX only when BOTH hold: the
    # dispatch was the emit_sec_id=False telem program AND the
    # ipcache is idx-form (the hash-form branch emits the real id
    # regardless of emit_sec_id) — see _datapath_core
    ipcache_idx_form = False
    if flow_store is not None:
        from cilium_tpu.ipcache.lpm import IPCacheDevice

        ipcache_idx_form = bool(
            isinstance(tables.ipcache, IPCacheDevice)
            and tables.ipcache.values_are_idx
        )
    # record ep_ids must be ENDPOINT ids: invert the record→axis
    # translation the loader applied (the daemon path's rev_lut)
    ep_rev_lut = None
    if flow_store is not None and ep_map:
        ep_rev_lut = np.zeros(
            max(ep_map.values()) + 1, dtype=np.int64
        )
        for rev_ep_id, rev_idx in ep_map.items():
            ep_rev_lut[rev_idx] = rev_ep_id

    def _drain_item(item):
        """Drain one pending batch; host-fold its telemetry when the
        dispatch couldn't carry the device accumulator (partial tail
        batches, or the no-counter audit path), and fold flow records
        when a flow_store rides along."""
        nonlocal telem_total
        out, valid, fold_direction, flows_ref, sec_is_idx = item
        spans.span("drain").start()
        _drain_fused((out, valid), stats)
        if fold_direction is not None:
            from cilium_tpu.telemetry import telemetry_from_outputs

            telem_total = telem_total + telemetry_from_outputs(
                out, np.asarray(fold_direction), valid=valid
            )
        if flow_store is not None:
            _capture_replay_flows(
                flow_store, out, flows_ref, int(valid), sec_is_idx,
                id_table_host, chip, ep_rev_lut,
            )
        spans.span("drain").end()

    pending = []  # pipelined dispatch, bounded depth
    t0 = time.perf_counter()
    batch_iter = iter(read_flow_batches(buf, batch_size, ep_map))
    while True:
        # host pack phase: record decode + pad + H2D upload of the
        # next batch (read_flow_batches does all three in next())
        spans.span("host_pack").start()
        item = next(batch_iter, None)
        spans.span("host_pack").end(success=item is not None)
        if item is None:
            break
        flows, valid = item
        iter_t0 = time.perf_counter()
        if ct_map is not None:
            # sustained churn: the compaction runs FUSED with the
            # datapath step (one dispatch per round), the 16-byte
            # header is the only unconditional D2H, and intent
            # columns travel in the smallest slice bucket covering
            # the round's count.  Claim-table losers (distinct flows
            # sharing a dedup slot, or >cap unique intents) drive
            # convergence re-runs of the same batch against the
            # updated snapshot, so the next batch sees every flow
            # this one created (up to the documented
            # 32-bit-hash-collision deferral in _churn_compact).
            first_pass = True
            while True:
                tables = DatapathTables(
                    prefilter=tables.prefilter,
                    ipcache=tables.ipcache,
                    ct=churn.dev_snap,
                    lb=tables.lb,
                    policy=tables.policy,
                    tunnel=tables.tunnel,
                )
                spans.span("dispatch").start()
                if first_pass and accumulate_counters:
                    header_d, intents_d, acc = _guarded_dispatch(
                        churn_step_accum, tables, flows, valid, acc,
                        donated=True,
                    )
                    batches_since_fold += 1
                    if batches_since_fold >= fold_every:
                        _fold_counters()
                else:
                    # convergence passes skip counter accumulation —
                    # the first pass already counted this batch
                    header_d, intents_d = _guarded_dispatch(
                        churn_step, tables, flows, valid
                    )
                spans.span("dispatch").end()
                spans.span("drain").start()
                remaining = churn.drain(
                    header_d, intents_d, stats, int(valid), first_pass
                )
                spans.span("drain").end()
                first_pass = False
                if remaining == 0:
                    break
            _metrics.batch_duration.observe(
                time.perf_counter() - iter_t0
            )
            continue
        fold_direction = None
        sec_is_idx = False
        spans.span("dispatch").start()
        if accumulate_counters:
            # BOTH accum kernels run emit_sec_id=False: with an
            # idx-form ipcache their sec output is the raw identity
            # index, which flow capture translates through id_table
            # host-side (the non-counter datapath_step emits the
            # real id, so it stays False)
            sec_is_idx = ipcache_idx_form
            if telem_dev is not None and valid == batch_size:
                out, acc, telem_dev = _guarded_dispatch(
                    datapath_step_accum_telem,
                    tables, flows, acc, telem_dev,
                    donated=True,
                )
            else:
                out, acc = _guarded_dispatch(
                    datapath_step_accum, tables, flows, acc,
                    donated=True,
                )
                if telem_total is not None:
                    # partial tail batch: the device accumulator
                    # would count the padding rows, so this batch's
                    # histogram folds host-side on the valid prefix
                    fold_direction = flows.direction
            batches_since_fold += 1
            if batches_since_fold >= fold_every:
                _fold_counters()
        else:
            out = _guarded_dispatch(datapath_step, tables, flows)
            if telem_total is not None:
                fold_direction = flows.direction
        spans.span("dispatch").end()
        pending.append(
            (
                out,
                valid,
                fold_direction,
                flows if flow_store is not None else None,
                sec_is_idx,
            )
        )
        stats.batches += 1
        if len(pending) >= 4:
            _drain_item(pending.pop(0))
        _metrics.batch_duration.observe(time.perf_counter() - iter_t0)
    while pending:
        _drain_item(pending.pop(0))
    if churn is not None:
        churn.stash()
    if telem_total is not None:
        from cilium_tpu.telemetry import fold_telemetry

        if telem_dev is not None:
            telem_total = telem_total + np.asarray(telem_dev).astype(
                np.uint64
            )
            telem_dev = None  # consumed; the trailing counter fold
            # must not fold this buffer a second time
        stats.telemetry = telem_total
        fold_telemetry(telem_total)
    stats.seconds = time.perf_counter() - t0

    if not accumulate_counters:
        return stats, None, None
    _fold_counters()
    kg = tables.policy.l4_meta.shape[2]
    return stats, acc_total[:, :, :kg], acc_total[:, :, kg:]


def _capture_replay_flows(
    flow_store, out, flows, valid: int, sec_is_idx: bool,
    id_table_host: np.ndarray, chip: int,
    ep_rev_lut: "Optional[np.ndarray]" = None,
) -> None:
    """Fold one drained batch's DatapathVerdicts into the flow ring
    (replay's Hubble feed): the full fused-path columns — CT state,
    prefilter attribution, post-DNAT dport — are available here,
    unlike the lattice-only audit path.  The derived peer identity
    (out.sec_id: src of an ingress flow, dst of an egress one) rides
    the matching side of the pair; the other side is 0 (replay has
    no endpoint-identity context)."""
    from cilium_tpu import option as _option
    from cilium_tpu.flow import allow_sample_for_level, capture_batch

    sec = np.asarray(out.sec_id)[:valid].astype(np.int64)
    if sec_is_idx:
        sec = id_table_host[
            np.minimum(sec, len(id_table_host) - 1)
        ].astype(np.int64)
    dirs = np.asarray(flows.direction)[:valid]
    zeros = np.zeros(valid, np.int64)
    ep_ids = np.asarray(flows.ep_index)[:valid]
    if ep_rev_lut is not None:
        ep_ids = ep_rev_lut[
            np.minimum(ep_ids, len(ep_rev_lut) - 1)
        ]
    capture_batch(
        flow_store,
        ep_ids=ep_ids,
        src_identities=np.where(dirs == 0, sec, zeros),
        dst_identities=np.where(dirs == 0, zeros, sec),
        dports=np.asarray(out.final_dport)[:valid],
        protos=np.asarray(flows.proto)[:valid],
        directions=dirs,
        allowed=np.asarray(out.allowed)[:valid],
        match_kind=np.asarray(out.match_kind)[:valid],
        proxy_port=np.asarray(out.proxy_port)[:valid],
        pre_dropped=np.asarray(out.pre_dropped)[:valid],
        ct_result=np.asarray(out.ct_result)[:valid],
        ct_delete=np.asarray(out.ct_delete)[:valid],
        lb_slave=np.asarray(out.lb_slave)[:valid],
        ipcache_miss=np.asarray(out.ipcache_miss)[:valid],
        chip=chip,
        allow_sample=allow_sample_for_level(
            _option.Config.opts.level(_option.MONITOR_AGGREGATION)
        ),
    )


def replay_pool(
    tables,
    pool: Dict[str, np.ndarray],
    picks: "np.ndarray | int",
    batch_size: int = 1 << 21,
    *,
    ct_map,
) -> ReplayStats:
    """Sustained-churn replay over a FLOW-UNIVERSE loader: the pool
    (unique flows, as real traffic repeats flows) uploads once and
    each batch moves only its u32 pick indices; the fused program
    gathers the flow columns on device (_flows_from_pool) before the
    datapath step + intent compaction.

    `picks` is either an explicit index array (caller-chosen flow
    order, one [B] u32 upload per batch) or an INT — "this many
    uniform picks, generated on device from an 8-byte PRNG key per
    batch" — the mode for slow H2D links where per-batch index
    uploads would dominate the serial churn loop.

    Identical verdict/CT semantics to replay() with a record buffer of
    pool[picks] — only the transport changes: 4 bytes/tuple instead of
    decoding+packing+uploading 24-byte records through the single host
    core the transport relay shares.  `ct_map` is required: pool mode
    IS the churn loader (for churn-free pool replay, pre-stage device
    batches as bench.run_config5's headline loop does).  Counter
    accumulation is not offered here for the same reason.
    """
    import time

    import jax

    from cilium_tpu.engine.datapath import DatapathTables

    stats = ReplayStats()
    tables = jax.device_put(tables)
    # the packed device copy caches ON the pool dict itself (seed +
    # timed churn reuse one universe; a dict-id-keyed cache would go
    # stale when CPython recycles a freed dict's id).  The dunder key
    # keeps consumers that iterate pool.items() for FLOW COLUMNS from
    # picking up the [8, P] device array as a bogus column; helpers
    # that take the pool dict should iterate FLOW_COLUMNS, not items.
    # The pool arrays are treated as immutable once replayed —
    # callers that mutate them must drop the cache key or pass a
    # fresh dict.
    pool_dev = pool.get(_POOL_PACK_KEY)
    if pool_dev is None:
        pool_dev = jax.device_put(pack_flow_pool(pool))
        pool[_POOL_PACK_KEY] = pool_dev
    churn_pool = _churn_fns()[2]
    churn_pool_rand = _churn_fns()[3]
    churn = _ChurnDriver(ct_map)

    # `picks` as an INT means "n uniform picks, generated on device":
    # the serial churn loop pays the transport's full H2D latency for
    # every upload, so shipping a [B] index array per round can
    # dominate on a slow link — an 8-byte PRNG key per batch replaces
    # it.  An explicit array keeps the caller-chosen flow order.
    if isinstance(picks, (int, np.integer)):
        import jax.random as jrandom

        n = int(picks)
        base_key = jrandom.PRNGKey(len(ct_map.entries) ^ n)
        t0 = time.perf_counter()
        batch_idx = 0
        for start in range(0, n, batch_size):
            valid = min(batch_size, n - start)
            key = jrandom.fold_in(base_key, batch_idx)
            batch_idx += 1
            first_pass = True
            while True:
                t = DatapathTables(
                    prefilter=tables.prefilter,
                    ipcache=tables.ipcache,
                    ct=churn.dev_snap,
                    lb=tables.lb,
                    policy=tables.policy,
                    tunnel=tables.tunnel,
                )
                header_d, intents_d = churn_pool_rand(
                    t, pool_dev, key, batch_size, valid
                )
                remaining = churn.drain(
                    header_d, intents_d, stats, valid, first_pass
                )
                first_pass = False
                if remaining == 0:
                    break
        churn.stash()
        stats.seconds = time.perf_counter() - t0
        return stats

    picks = np.asarray(picks).astype(np.uint32, copy=False)
    t0 = time.perf_counter()
    for start in range(0, len(picks), batch_size):
        chunk = picks[start : start + batch_size]
        valid = len(chunk)
        if valid < batch_size:
            chunk = np.concatenate(
                [
                    chunk,
                    np.zeros(batch_size - valid, dtype=np.uint32),
                ]
            )
        picks_dev = jax.device_put(chunk)
        first_pass = True
        while True:
            t = DatapathTables(
                prefilter=tables.prefilter,
                ipcache=tables.ipcache,
                ct=churn.dev_snap,
                lb=tables.lb,
                policy=tables.policy,
                tunnel=tables.tunnel,
            )
            header_d, intents_d = churn_pool(
                t, pool_dev, picks_dev, valid
            )
            remaining = churn.drain(
                header_d, intents_d, stats, valid, first_pass
            )
            first_pass = False
            if remaining == 0:
                break
    churn.stash()
    stats.seconds = time.perf_counter() - t0
    return stats


def replay_lattice(
    tables,
    buf: bytes,
    batch_size: int = 1 << 20,
    accumulate_counters: bool = True,
    ep_map: Optional[Dict[int, int]] = None,
    manager=None,
) -> tuple:
    """Replay through the bare policy lattice (PolicyTables only,
    identity pre-resolved from the record) — the post-hoc Hubble
    audit path.  Same return shape as replay()."""
    import time

    if manager is not None:
        manager.check_tables_current(tables)
    step = _replay_step()
    stats = ReplayStats()
    acc = _CounterAccumulator() if accumulate_counters else None

    pending = []  # pipelined dispatch, bounded depth
    t0 = time.perf_counter()
    for batch, valid in read_batches(buf, batch_size, ep_map):
        out = _guarded_dispatch(step, tables, batch)
        pending.append((out, valid))
        stats.batches += 1
        if len(pending) >= 4:
            _drain(pending.pop(0), stats, acc)
    while pending:
        _drain(pending.pop(0), stats, acc)
    stats.seconds = time.perf_counter() - t0

    if acc is None:
        return stats, None, None
    return stats, acc.l4, acc.l3


class _CounterAccumulator:
    l4: Optional[np.ndarray] = None
    l3: Optional[np.ndarray] = None

    def add(self, l4_counts, l3_counts) -> None:
        if self.l4 is None:
            self.l4 = np.zeros(l4_counts.shape, dtype=np.uint64)
            self.l3 = np.zeros(l3_counts.shape, dtype=np.uint64)
        self.l4 += np.asarray(l4_counts).astype(np.uint64)
        self.l3 += np.asarray(l3_counts).astype(np.uint64)


def _tally(verdicts, valid, stats: ReplayStats) -> None:
    allowed = np.asarray(verdicts.allowed)[:valid]
    proxy = np.asarray(verdicts.proxy_port)[:valid]
    stats.total += int(valid)
    stats.allowed += int(allowed.sum())
    stats.denied += int(valid - allowed.sum())
    stats.redirected += int((proxy > 0).sum())


def _drain(item, stats: ReplayStats, acc: Optional[_CounterAccumulator]) -> None:
    (verdicts, l4_counts, l3_counts), valid = item
    _tally(verdicts, valid, stats)
    if acc is not None:
        acc.add(l4_counts, l3_counts)


def _drain_fused(item, stats: ReplayStats) -> None:
    """Fused-path drain: counters live in the carried device
    accumulators, so the item is just (verdicts, valid)."""
    verdicts, valid = item
    _tally(verdicts, valid, stats)


_REPLAY_STEP = None


def _replay_step():
    """Module-level jitted lattice step (one compilation cache across
    replay_lattice() calls, like engine.verdict.evaluate_batch)."""
    global _REPLAY_STEP
    if _REPLAY_STEP is None:
        import jax

        _REPLAY_STEP = jax.jit(_verdict_kernel_with_counters)
    return _REPLAY_STEP


def slot_keys_from_tables(tables) -> Dict[int, Tuple[int, int]]:
    """Recover global L4 slot → (dport, proto) from the compiled
    port_slot table (the inverse of lower_map_state's slot_of)."""
    from cilium_tpu.compiler.tables import NO_SLOT

    port_slot = np.asarray(tables.port_slot)
    protos, dports = np.nonzero(port_slot != NO_SLOT)
    slots = port_slot[protos, dports]
    return {
        int(j): (int(dport), int(proto))
        for j, dport, proto in zip(slots, dports, protos)
    }


def sync_counters_to_endpoints(
    l4_counts: Optional[np.ndarray],
    l3_counts: Optional[np.ndarray],
    manager,
    tables=None,
    index: Optional[Dict[int, int]] = None,
) -> int:
    """Fold accumulated device counters back into the endpoints'
    realized map states (the packets field of policy_entry the agent
    reads back from the datapath, pkg/maps/policymap PolicyEntry).

    Pass the `tables`/`index` the counters were computed against; a
    republish between replay() and sync would otherwise shift the
    identity/slot indexing and misattribute counts.  Falls back to the
    currently-published version when not given.  Returns entries
    updated."""
    if tables is None or index is None:
        _, tables, index = manager.published()
    if tables is None:
        return 0
    # NOTE: no staleness guard needed here — this function reads only
    # tables.id_table (freshly allocated per rebuild) and
    # tables.port_slot (write-once cells), both of which stay valid in
    # arbitrarily old snapshots.  The in-place-mutation hazard is the
    # stacked per-endpoint rows, guarded at replay()/evaluation time.
    updated = 0
    rev_index = {v: k for k, v in index.items()}
    id_table = np.asarray(tables.id_table)
    if l3_counts is not None:
        # L3 counters are indexed by identity index.  Re-read the
        # realized state under the endpoint lock per update: a
        # concurrent sync_policy_map publishes a NEW array-backed
        # state (copy-on-write), and an increment applied through a
        # pre-sync view would land in the superseded snapshot.
        for e, d, idx in zip(*np.nonzero(l3_counts)):
            ep = manager.lookup(rev_index.get(int(e), -1))
            if ep is None:
                continue
            key = PolicyKey(int(id_table[idx]), 0, 0, int(d))
            with ep.lock:
                entry = ep.realized_map_state.get(key)
                if entry is not None:
                    entry.packets += int(l3_counts[e, d, idx])
                    updated += 1
    if l4_counts is not None:
        # L4 counters are indexed by global slot; a slot hit covers
        # every (identity, dport, proto) entry of that filter — the
        # wildcard entry takes the count (exact-entry attribution
        # would need per-(slot, identity) counters; the reference
        # bumps the entry the probe hit, which for MATCH_L4 is the
        # exact key and for MATCH_L4_WILD the wildcard — we fold both
        # into the slot's wildcard-or-first entry, preserving totals).
        slot_keys = slot_keys_from_tables(tables)
        for e, d, j in zip(*np.nonzero(l4_counts)):
            ep = manager.lookup(rev_index.get(int(e), -1))
            if ep is None or int(j) not in slot_keys:
                continue
            dport, proto = slot_keys[int(j)]
            count = int(l4_counts[e, d, j])
            wild = PolicyKey(0, dport, proto, int(d))
            with ep.lock:
                entry = ep.realized_map_state.get(wild)
                if entry is None:
                    for key, cand in ep.realized_map_state.items():
                        if (
                            key.dest_port == dport
                            and key.nexthdr == proto
                            and key.traffic_direction == int(d)
                        ):
                            entry = cand
                            break
                if entry is not None:
                    entry.packets += count
                    updated += 1
    return updated
