"""Policy rule schema (the analog of pkg/policy/api)."""

from cilium_tpu.policy.api.selector import (  # noqa: F401
    EndpointSelector,
    RESERVED_ENDPOINT_SELECTORS,
    Requirement,
    WILDCARD_SELECTOR,
    selects_all_endpoints,
)
from cilium_tpu.policy.api.rule import (  # noqa: F401
    CIDRRule,
    EgressRule,
    FQDNSelector,
    IngressRule,
    L7Rules,
    PROTO_ANY,
    PROTO_TCP,
    PROTO_UDP,
    PolicyValidationError,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleKafka,
    PortRuleL7,
    Rule,
    Service,
    compute_resultant_cidr_set,
)
from cilium_tpu.policy.api.parse import rule_from_dict, rules_from_json  # noqa: F401
