"""EndpointSelector: k8s-style label selectors over LabelArrays.

Re-design of /root/reference/pkg/policy/api/selector.go.  The reference
wraps k8s.io LabelSelector; we implement the identical matching semantics
natively: match_labels (AND of key==value) plus match_expressions with
In/NotIn/Exists/DoesNotExist operators, evaluated against
LabelArray.has/get (reference selector.go:277-302 and
k8s.io/apimachinery labels.Requirement.Matches).

IMPORTANT identity semantics: the reference keys L7DataMap by the
EndpointSelector *struct*, whose embedded pointers give it pointer
equality as a map key (pkg/policy/l4.go:32).  We mirror that: selectors
hash/compare by object identity, and module-level singletons
(WILDCARD_SELECTOR, reserved selectors) play the role of the reference's
package-level vars so wildcard lookups hit the same key.  Use
``deep_equal`` for structural comparison.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu import labels as lbl
from cilium_tpu.labels import Label, LabelArray

# Operators (k8s LabelSelectorOperator)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"


class Requirement:
    """One selector requirement: (key, operator, values).

    Matching semantics are those of k8s labels.Requirement.Matches, with
    keys being extended keys (``source.key``) evaluated against
    LabelArray.has/get.
    """

    __slots__ = ("key", "operator", "values")

    def __init__(self, key: str, operator: str, values: Sequence[str] = ()):
        self.key = key
        self.operator = operator
        self.values = list(values)

    def matches(self, labels: LabelArray) -> bool:
        if self.operator == OP_IN:
            if not labels.has(self.key):
                return False
            return labels.get(self.key) in self.values
        if self.operator == OP_NOT_IN:
            if not labels.has(self.key):
                return True
            return labels.get(self.key) not in self.values
        if self.operator == OP_EXISTS:
            return labels.has(self.key)
        if self.operator == OP_DOES_NOT_EXIST:
            return not labels.has(self.key)
        return False

    def copy(self) -> "Requirement":
        return Requirement(self.key, self.operator, list(self.values))

    def __repr__(self) -> str:
        return f"Requirement({self.key!r},{self.operator},{self.values})"


class EndpointSelector:
    """Selector over endpoint labels (selector.go:32).

    match_labels keys are stored in extended-key form (``any.role``,
    ``k8s.app`` ...) exactly as the reference converts them on
    UnmarshalJSON (selector.go:66-72).
    """

    def __init__(
        self,
        match_labels: Optional[Dict[str, str]] = None,
        match_expressions: Optional[List[Requirement]] = None,
    ):
        self.match_labels: Dict[str, str] = dict(match_labels or {})
        self.match_expressions: List[Requirement] = list(
            match_expressions or []
        )

    # -- construction helpers ------------------------------------------------

    @staticmethod
    def from_labels(*labels_in: Label) -> "EndpointSelector":
        """NewESFromLabels (selector.go:178)."""
        ml = {l.get_extended_key(): l.value for l in labels_in}
        return EndpointSelector(match_labels=ml)

    @staticmethod
    def from_dict(d: dict) -> "EndpointSelector":
        """Parse the JSON form {matchLabels: {...}, matchExpressions: [...]}.

        Keys get extended-key conversion like UnmarshalJSON
        (selector.go:60-83).
        """
        ml = {
            lbl.get_extended_key_from(k): v
            for k, v in (d.get("matchLabels") or {}).items()
        }
        mes = [
            Requirement(
                lbl.get_extended_key_from(e["key"]),
                e["operator"],
                e.get("values") or [],
            )
            for e in (d.get("matchExpressions") or [])
        ]
        return EndpointSelector(match_labels=ml, match_expressions=mes)

    def to_dict(self) -> dict:
        d: dict = {}
        if self.match_labels:
            d["matchLabels"] = {
                lbl.get_cilium_key_from(k): v
                for k, v in self.match_labels.items()
            }
        if self.match_expressions:
            d["matchExpressions"] = [
                {
                    "key": lbl.get_cilium_key_from(e.key),
                    "operator": e.operator,
                    "values": list(e.values),
                }
                for e in self.match_expressions
            ]
        return d

    # -- matching ------------------------------------------------------------

    def requirements(self) -> List[Requirement]:
        """Flatten match_labels into In-requirements + match_expressions.

        Mirrors LabelSelectorAsSelector: matchLabels become single-value In
        requirements.  Sorted by key for determinism.
        """
        reqs = [
            Requirement(k, OP_IN, [v])
            for k, v in sorted(self.match_labels.items())
        ]
        reqs.extend(self.match_expressions)
        return reqs

    def matches(self, labels_to_match: Optional[LabelArray]) -> bool:
        """selector.go:277: reserved.all short-circuits; else AND of reqs.

        Memoized per label-array OBJECT (identity-pinned): per-endpoint
        resolution matches the same selectors against the same cached
        identity label arrays every sweep, and both sides are stable
        after construction.  The requirement list is cached too —
        matches() used to rebuild it per call."""
        memoize = labels_to_match is not None
        if labels_to_match is None:
            # fresh object per call — memoizing it would only churn
            # the cache with never-hittable ids
            labels_to_match = LabelArray()
        memo = self.__dict__.setdefault("_match_memo", {})
        if memoize:
            hit = memo.get(id(labels_to_match))
            if hit is not None and hit[0] is labels_to_match:
                return hit[1]
        for k in self.match_labels:
            if k == lbl.SOURCE_RESERVED_KEY_PREFIX + lbl.ID_NAME_ALL:
                # no memo insert: the short-circuit is already O(1),
                # and memoizing here would grow a wildcard selector's
                # memo unboundedly (this path skips the cap below)
                return True
        reqs = self.__dict__.get("_reqs_cache")
        if reqs is None:
            reqs = self.requirements()
            self.__dict__["_reqs_cache"] = reqs
        result = all(r.matches(labels_to_match) for r in reqs)
        if memoize:
            if len(memo) > 4096:
                memo.clear()
            memo[id(labels_to_match)] = (labels_to_match, result)
        return result

    def is_wildcard(self) -> bool:
        """selector.go:305."""
        return len(self.match_labels) + len(self.match_expressions) == 0

    def has_key(self, key: str) -> bool:
        if key in self.match_labels:
            return True
        return any(e.key == key for e in self.match_expressions)

    def has_key_prefix(self, prefix: str) -> bool:
        if any(k.startswith(prefix) for k in self.match_labels):
            return True
        return any(e.key.startswith(prefix) for e in self.match_expressions)

    def get_match(self, key: str) -> Tuple[Optional[List[str]], bool]:
        """selector.go:143."""
        if key in self.match_labels:
            return [self.match_labels[key]], True
        for e in self.match_expressions:
            if e.key == key and e.operator == OP_IN:
                return list(e.values), True
        return None, False

    def convert_to_requirements(self) -> List[Requirement]:
        """ConvertToLabelSelectorRequirementSlice (selector.go:313)."""
        reqs = [e.copy() for e in self.match_expressions]
        for k in sorted(self.match_labels):
            reqs.append(Requirement(k, OP_IN, [self.match_labels[k]]))
        return reqs

    def add_requirements(self, reqs: List[Requirement]) -> "EndpointSelector":
        """Return a copy with extra requirements appended.

        Used for FromRequires/ToRequires injection
        (pkg/policy/rule.go:247-257).  A copy to mirror the reference's
        DeepCopy-then-modify.
        """
        out = EndpointSelector(
            match_labels=dict(self.match_labels),
            match_expressions=[e.copy() for e in self.match_expressions],
        )
        out.match_expressions.extend(r.copy() for r in reqs)
        return out

    # -- identity / display --------------------------------------------------

    def deep_equal(self, other: "EndpointSelector") -> bool:
        if self.match_labels != other.match_labels:
            return False
        if len(self.match_expressions) != len(other.match_expressions):
            return False
        for a, b in zip(self.match_expressions, other.match_expressions):
            if (a.key, a.operator, a.values) != (b.key, b.operator, b.values):
                return False
        return True

    def label_selector_string(self) -> str:
        """Stable human-readable form (FormatLabelSelector analog)."""
        parts = [f"{k}={v}" for k, v in sorted(self.match_labels.items())]
        for e in self.match_expressions:
            if e.operator == OP_IN:
                parts.append(f"{e.key} in ({','.join(sorted(e.values))})")
            elif e.operator == OP_NOT_IN:
                parts.append(f"{e.key} notin ({','.join(sorted(e.values))})")
            elif e.operator == OP_EXISTS:
                parts.append(e.key)
            elif e.operator == OP_DOES_NOT_EXIST:
                parts.append(f"!{e.key}")
        return ",".join(parts)

    def __repr__(self) -> str:
        return f"EndpointSelector({self.label_selector_string() or '<all>'})"

    # Pointer-identity hashing (see module docstring).
    __hash__ = object.__hash__

    def __eq__(self, other):  # noqa: D105
        return self is other


def new_reserved_endpoint_selector(name: str) -> EndpointSelector:
    """selector.go:215."""
    return EndpointSelector.from_labels(
        Label(key=name, value="", source=lbl.SOURCE_RESERVED)
    )


# Package-level singletons (selector.go:220-231): these mirror the
# reference's globals so identity-keyed L7 maps behave identically.
WILDCARD_SELECTOR = EndpointSelector.from_labels()

RESERVED_ENDPOINT_SELECTORS = {
    lbl.ID_NAME_HOST: new_reserved_endpoint_selector(lbl.ID_NAME_HOST),
    lbl.ID_NAME_WORLD: new_reserved_endpoint_selector(lbl.ID_NAME_WORLD),
}


def selects_all_endpoints(selectors: Sequence[EndpointSelector]) -> bool:
    """EndpointSelectorSlice.SelectsAllEndpoints (selector.go:356)."""
    if len(selectors) == 0:
        return True
    return any(s.is_wildcard() for s in selectors)


def slice_matches(selectors: Sequence[EndpointSelector],
                  ctx: LabelArray) -> bool:
    """EndpointSelectorSlice.Matches (selector.go:344)."""
    return any(s.matches(ctx) for s in selectors)
