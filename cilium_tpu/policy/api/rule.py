"""Policy rule schema.

Re-design of /root/reference/pkg/policy/api/{rule.go,ingress.go,egress.go,
l4.go,http.go,kafka.go,l7.go,cidr.go,entity.go,fqdn.go,service.go,
rule_validation.go}.  Pure host-side model: rules are sanitized here,
then lowered to tensors by cilium_tpu.compiler.
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from cilium_tpu import labels as lbl
from cilium_tpu.labels import Label, LabelArray
from cilium_tpu.policy.api.selector import (
    EndpointSelector,
    RESERVED_ENDPOINT_SELECTORS,
    WILDCARD_SELECTOR,
)
from cilium_tpu.utils import cidr as cidr_util


class PolicyValidationError(ValueError):
    """Raised by sanitize() on an invalid rule (reference: error returns)."""


# ---------------------------------------------------------------------------
# L4 (api/l4.go)
# ---------------------------------------------------------------------------

PROTO_TCP = "TCP"
PROTO_UDP = "UDP"
PROTO_ANY = "ANY"

MAX_PORTS = 40  # rule_validation.go:27
MAX_CIDR_PREFIX_LENGTHS = 40  # rule_validation.go:29

# pkg/u8proto numeric protocol values
U8PROTO = {"ANY": 0, "ICMP": 1, "TCP": 6, "UDP": 17, "ICMPv6": 58}


def parse_go_uint16(s: str) -> int:
    """Go strconv.ParseUint(s, 0, 16): base inferred from prefix, with
    legacy leading-zero octal ("010" == 8) which Python's int(s, 0)
    rejects."""
    s = s.strip()
    neg = s.startswith("-")
    if neg:
        raise PolicyValidationError(f"invalid port syntax: {s!r}")
    try:
        if s.lower().startswith(("0x", "0b", "0o")):
            v = int(s, 0)
        elif len(s) > 1 and s.startswith("0"):
            v = int(s, 8)
        else:
            v = int(s, 10)
    except ValueError as e:
        raise PolicyValidationError(f"Unable to parse port: {e}")
    if not 0 <= v <= 0xFFFF:
        raise PolicyValidationError(f"Port out of 16-bit range: {v}")
    return v


def parse_l4_proto(proto: str) -> str:
    """api/utils.go:103: empty -> ANY; validate tcp/udp/any."""
    if proto == "":
        return PROTO_ANY
    p = proto.upper()
    if p not in (PROTO_ANY, PROTO_TCP, PROTO_UDP):
        raise PolicyValidationError(
            f"invalid protocol {proto!r}, must be {{ tcp | udp | any }}"
        )
    return p


@dataclass
class PortProtocol:
    """api/l4.go:27."""

    port: str
    protocol: str = ""

    def sanitize(self) -> None:
        """api/rule_validation.go:309."""
        if self.port == "":
            raise PolicyValidationError("Port must be specified")
        p = parse_go_uint16(self.port)
        if p == 0:
            raise PolicyValidationError("Port cannot be 0")
        self.protocol = parse_l4_proto(self.protocol)

    def numeric_port(self) -> int:
        return parse_go_uint16(self.port)


@dataclass
class PortRuleHTTP:
    """api/http.go:28: extended-regex constraints on an HTTP request."""

    path: str = ""
    method: str = ""
    host: str = ""
    headers: List[str] = field(default_factory=list)

    def sanitize(self) -> None:
        """api/http.go:66: path/method must be valid regexes."""
        for pattern in (self.path, self.method):
            if pattern:
                try:
                    re.compile(pattern)
                except re.error as e:
                    raise PolicyValidationError(
                        f"invalid regex {pattern!r}: {e}"
                    )

    def equal(self, o: "PortRuleHTTP") -> bool:
        return (
            self.path == o.path
            and self.method == o.method
            and self.host == o.host
            and self.headers == o.headers
        )

    def exists(self, rules: "L7Rules") -> bool:
        return any(self.equal(r) for r in rules.http)


# -- Kafka (api/kafka.go) ----------------------------------------------------

KAFKA_API_KEY_MAP: Dict[str, int] = {
    "produce": 0, "fetch": 1, "offsets": 2, "metadata": 3,
    "leaderandisr": 4, "stopreplica": 5, "updatemetadata": 6,
    "controlledshutdown": 7, "offsetcommit": 8, "offsetfetch": 9,
    "findcoordinator": 10, "joingroup": 11, "heartbeat": 12,
    "leavegroup": 13, "syncgroup": 14, "describegroups": 15,
    "listgroups": 16, "saslhandshake": 17, "apiversions": 18,
    "createtopics": 19, "deletetopics": 20, "deleterecords": 21,
    "initproducerid": 22, "offsetforleaderepoch": 23,
    "addpartitionstotxn": 24, "addoffsetstotxn": 25, "endtxn": 26,
    "writetxnmarkers": 27, "txnoffsetcommit": 28, "describeacls": 29,
    "createacls": 30, "deleteacls": 31, "describeconfigs": 32,
    "alterconfigs": 33,
}
KAFKA_REVERSE_API_KEY_MAP = {v: k for k, v in KAFKA_API_KEY_MAP.items()}

KAFKA_PRODUCE_KEY = 0
KAFKA_FETCH_KEY = 1
KAFKA_OFFSETS_KEY = 2
KAFKA_METADATA_KEY = 3
KAFKA_OFFSET_COMMIT_KEY = 8
KAFKA_OFFSET_FETCH_KEY = 9
KAFKA_FIND_COORDINATOR_KEY = 10
KAFKA_JOIN_GROUP_KEY = 11
KAFKA_HEARTBEAT_KEY = 12
KAFKA_LEAVE_GROUP_KEY = 13
KAFKA_SYNC_GROUP_KEY = 14
KAFKA_API_VERSIONS_KEY = 18

KAFKA_PRODUCE_ROLE = "produce"
KAFKA_CONSUME_ROLE = "consume"

KAFKA_MAX_TOPIC_LEN = 255
# api/kafka.go:244 — reference regex `^[a-zA-Z0-9\\._\\-]+$` (RE2: the
# doubled backslashes make `\\`, `.`, `_`, `\\`, `-` literal inside the
# class; net effect is [a-zA-Z0-9\._\-\\]).
KAFKA_TOPIC_VALID_CHAR = re.compile(r"^[a-zA-Z0-9\\._\-]+$")


@dataclass
class PortRuleKafka:
    """api/kafka.go:26."""

    role: str = ""
    api_key: str = ""
    api_version: str = ""
    client_id: str = ""
    topic: str = ""
    # private, filled by sanitize (kafka.go:100-107)
    api_key_int: List[int] = field(default_factory=list)
    api_version_int: Optional[int] = None

    def sanitize(self) -> None:
        """api/rule_validation.go:203."""
        if self.api_key and self.role:
            raise PolicyValidationError(
                f"Cannot set both Role:{self.role!r} and APIKey :{self.api_key!r} together"
            )
        if self.api_key:
            n = KAFKA_API_KEY_MAP.get(self.api_key.lower())
            if n is None:
                raise PolicyValidationError(
                    f"invalid Kafka APIKey :{self.api_key!r}"
                )
            self.api_key_int.append(n)
        if self.role:
            self.map_role_to_api_key()
        if self.api_version:
            try:
                n = int(self.api_version, 10)
            except ValueError:
                raise PolicyValidationError(
                    f"invalid Kafka APIVersion :{self.api_version!r}"
                )
            if not -(2 ** 15) <= n < 2 ** 15:
                raise PolicyValidationError(
                    f"invalid Kafka APIVersion :{self.api_version!r}"
                )
            self.api_version_int = n
        if self.topic:
            if len(self.topic) > KAFKA_MAX_TOPIC_LEN:
                raise PolicyValidationError(
                    f"kafka topic exceeds maximum len of {KAFKA_MAX_TOPIC_LEN}"
                )
            if not KAFKA_TOPIC_VALID_CHAR.match(self.topic):
                raise PolicyValidationError(
                    f'invalid Kafka Topic name "{self.topic}"'
                )

    def map_role_to_api_key(self) -> None:
        """api/kafka.go:274: role -> mandatory APIKey set."""
        role = self.role.lower()
        if role == KAFKA_PRODUCE_ROLE:
            self.api_key_int = [
                KAFKA_PRODUCE_KEY, KAFKA_METADATA_KEY, KAFKA_API_VERSIONS_KEY,
            ]
        elif role == KAFKA_CONSUME_ROLE:
            self.api_key_int = [
                KAFKA_FETCH_KEY, KAFKA_OFFSETS_KEY, KAFKA_METADATA_KEY,
                KAFKA_OFFSET_COMMIT_KEY, KAFKA_OFFSET_FETCH_KEY,
                KAFKA_FIND_COORDINATOR_KEY, KAFKA_JOIN_GROUP_KEY,
                KAFKA_HEARTBEAT_KEY, KAFKA_LEAVE_GROUP_KEY,
                KAFKA_SYNC_GROUP_KEY, KAFKA_API_VERSIONS_KEY,
            ]
        else:
            raise PolicyValidationError(f"Invalid Kafka Role {self.role}")

    def check_api_key_role(self, kind: int) -> bool:
        """api/kafka.go:248: empty set is a wildcard."""
        if not self.api_key_int:
            return True
        return kind in self.api_key_int

    def get_api_version(self) -> tuple:
        """api/kafka.go:265: (version, is_wildcard)."""
        if self.api_version_int is None:
            return 0, True
        return self.api_version_int, False

    def equal(self, o: "PortRuleKafka") -> bool:
        return (
            self.api_version == o.api_version and self.api_key == o.api_key
            and self.topic == o.topic and self.client_id == o.client_id
            and self.role == o.role
        )

    def exists(self, rules: "L7Rules") -> bool:
        return any(self.equal(r) for r in rules.kafka)


class PortRuleL7(dict):
    """api/l7.go: key-value pair rule for generic parsers."""

    def sanitize(self) -> None:
        for k in self:
            if k == "":
                raise PolicyValidationError("Empty key not allowed")

    def equal(self, o: "PortRuleL7") -> bool:
        return dict(self) == dict(o)

    def exists(self, rules: "L7Rules") -> bool:
        return any(self.equal(r) for r in rules.l7)


@dataclass
class L7Rules:
    """api/l4.go:65: union of L7 rule types; exactly one kind may be set.

    Mirrors the Go nil-vs-empty distinction: ``http``/``kafka``/``l7``
    are None when absent, possibly-empty lists when present (IsEmpty,
    api/l4.go:97 is nil-based).
    """

    http: Optional[List[PortRuleHTTP]] = None
    kafka: Optional[List[PortRuleKafka]] = None
    l7proto: str = ""
    l7: Optional[List[PortRuleL7]] = None

    def __len__(self) -> int:
        """api/l4.go:89 Len()."""
        return (
            len(self.http or ()) + len(self.kafka or ()) + len(self.l7 or ())
        )

    def is_empty(self) -> bool:
        """api/l4.go:97: nil receiver or all-kinds-nil."""
        return self.http is None and self.kafka is None and self.l7 is None

    def copy(self) -> "L7Rules":
        """Struct-copy semantics: new list containers, shared (immutable)
        rule entries — the analog of Go's by-value map storage
        (l4.go:143), so merge appends never reach the originating
        api.Rule."""
        return L7Rules(
            http=list(self.http) if self.http is not None else None,
            kafka=list(self.kafka) if self.kafka is not None else None,
            l7proto=self.l7proto,
            l7=list(self.l7) if self.l7 is not None else None,
        )

    def sanitize(self) -> None:
        """api/rule_validation.go:248."""
        n_types = 0
        if self.http is not None:
            n_types += 1
            for h in self.http:
                h.sanitize()
        if self.kafka is not None:
            n_types += 1
            for k in self.kafka:
                k.sanitize()
        if self.l7 is not None and self.l7proto == "":
            raise PolicyValidationError(
                "'l7' may only be specified when a 'l7proto' is also specified"
            )
        if self.l7proto != "":
            n_types += 1
            for r in self.l7 or []:
                r.sanitize()
        if n_types > 1:
            raise PolicyValidationError(
                "multiple L7 protocol rule types specified in single rule"
            )


def l7rules_is_empty(rules: Optional[L7Rules]) -> bool:
    return rules is None or rules.is_empty()


def l7rules_len(rules: Optional[L7Rules]) -> int:
    return 0 if rules is None else len(rules)


@dataclass
class PortRule:
    """api/l4.go:44."""

    ports: List[PortProtocol] = field(default_factory=list)
    rules: Optional[L7Rules] = None

    def sanitize(self) -> None:
        """api/rule_validation.go:287."""
        if len(self.ports) > MAX_PORTS:
            raise PolicyValidationError(
                f"too many ports, the max is {MAX_PORTS}"
            )
        for pp in self.ports:
            pp.sanitize()
            if not l7rules_is_empty(self.rules) and pp.protocol != PROTO_TCP:
                raise PolicyValidationError(
                    f"L7 rules can only apply exclusively to TCP, not {pp.protocol}"
                )
        if not l7rules_is_empty(self.rules):
            self.rules.sanitize()


# ---------------------------------------------------------------------------
# CIDR (api/cidr.go)
# ---------------------------------------------------------------------------

CIDR_MATCH_ALL = ("0.0.0.0/0", "::/0")


def cidr_matches_all(cidr: str) -> bool:
    return cidr in CIDR_MATCH_ALL


@dataclass
class CIDRRule:
    """api/cidr.go:44: a prefix with carve-out exceptions."""

    cidr: str
    except_cidrs: List[str] = field(default_factory=list)
    generated: bool = False

    def sanitize(self) -> int:
        """api/rule_validation.go:361; returns the prefix length."""
        try:
            net = ipaddress.ip_network(self.cidr, strict=False)
        except ValueError as e:
            raise PolicyValidationError(
                f"Unable to parse CIDRRule {self.cidr!r}: {e}"
            )
        for p in self.except_cidrs:
            try:
                except_net = ipaddress.ip_network(p, strict=False)
            except ValueError as e:
                raise PolicyValidationError(str(e))
            if except_net.version != net.version or not (
                int(net.network_address)
                <= int(except_net.network_address)
                <= int(net.broadcast_address)
            ):
                raise PolicyValidationError(
                    f"allow CIDR prefix {self.cidr} does not contain "
                    f"exclude CIDR prefix {p}"
                )
        return net.prefixlen


def sanitize_cidr(cidr: str) -> int:
    """api/rule_validation.go:333: plain CIDR or bare IP; returns prefix
    length (0 for a bare IP, matching the reference's quirk)."""
    if cidr == "":
        raise PolicyValidationError("IP must be specified")
    if "/" in cidr:
        try:
            net = ipaddress.ip_network(cidr, strict=False)
        except ValueError as e:
            raise PolicyValidationError(f"Unable to parse CIDR: {e}")
        return net.prefixlen
    try:
        ipaddress.ip_address(cidr)
    except ValueError as e:
        raise PolicyValidationError(f"Unable to parse CIDR: {e}")
    return 0


def compute_resultant_cidr_set(cidr_rules: Sequence[CIDRRule]) -> List[str]:
    """api/cidr.go:115: expand each CIDRRule minus its exceptions."""
    out: List[str] = []
    for r in cidr_rules:
        allow = cidr_util.parse_cidr(r.cidr)
        remove = [cidr_util.parse_cidr(t) for t in r.except_cidrs]
        for net in cidr_util.remove_cidrs([allow], remove):
            out.append(str(net))
    return out


def cidr_slice_as_selectors(cidrs: Sequence[str]) -> List[EndpointSelector]:
    """api/cidr.go:70: CIDRs -> selectors over cidr: labels, with the
    match-all CIDR adding reserved:world once."""
    out: List[EndpointSelector] = []
    world_added = False
    for c in cidrs:
        if cidr_matches_all(c) and not world_added:
            world_added = True
            out.append(RESERVED_ENDPOINT_SELECTORS[lbl.ID_NAME_WORLD])
        label = lbl.ip_string_to_label(c)
        if label is not None:
            out.append(EndpointSelector.from_labels(label))
    return out


def cidr_rule_slice_as_selectors(
    rules: Sequence[CIDRRule],
) -> List[EndpointSelector]:
    """api/cidr.go:104."""
    return cidr_slice_as_selectors(compute_resultant_cidr_set(rules))


# ---------------------------------------------------------------------------
# Entities (api/entity.go)
# ---------------------------------------------------------------------------

ENTITY_ALL = "all"
ENTITY_WORLD = "world"
ENTITY_CLUSTER = "cluster"
ENTITY_HOST = "host"
ENTITY_INIT = "init"

ENTITY_SELECTOR_MAPPING: Dict[str, EndpointSelector] = {
    ENTITY_ALL: WILDCARD_SELECTOR,
    ENTITY_WORLD: EndpointSelector.from_labels(
        Label(key=lbl.ID_NAME_WORLD, value="", source=lbl.SOURCE_RESERVED)
    ),
    ENTITY_CLUSTER: EndpointSelector.from_labels(
        Label(key=lbl.ID_NAME_CLUSTER, value="", source=lbl.SOURCE_RESERVED)
    ),
    ENTITY_HOST: EndpointSelector.from_labels(
        Label(key=lbl.ID_NAME_HOST, value="", source=lbl.SOURCE_RESERVED)
    ),
    ENTITY_INIT: EndpointSelector.from_labels(
        Label(key=lbl.ID_NAME_INIT, value="", source=lbl.SOURCE_RESERVED)
    ),
}


def entities_as_selectors(entities: Sequence[str]) -> List[EndpointSelector]:
    """api/entity.go:96."""
    return [
        ENTITY_SELECTOR_MAPPING[e]
        for e in entities
        if e in ENTITY_SELECTOR_MAPPING
    ]


# ---------------------------------------------------------------------------
# FQDN / Service (api/fqdn.go, api/service.go)
# ---------------------------------------------------------------------------


@dataclass
class FQDNSelector:
    """api/fqdn.go: DNS name whose resolved IPs become ToCIDRSet rules."""

    match_name: str = ""

    def sanitize(self) -> None:
        if self.match_name == "":
            raise PolicyValidationError("FQDN matchName cannot be empty")


@dataclass
class K8sServiceNamespace:
    service_name: str = ""
    namespace: str = ""


@dataclass
class Service:
    """api/service.go: k8s service reference for ToServices."""

    k8s_service: Optional[K8sServiceNamespace] = None
    k8s_service_selector: Optional[dict] = None


# ---------------------------------------------------------------------------
# Ingress / Egress / Rule
# ---------------------------------------------------------------------------


@dataclass
class IngressRule:
    """api/ingress.go:35."""

    from_endpoints: List[EndpointSelector] = field(default_factory=list)
    from_requires: List[EndpointSelector] = field(default_factory=list)
    to_ports: List[PortRule] = field(default_factory=list)
    from_cidr: List[str] = field(default_factory=list)
    from_cidr_set: List[CIDRRule] = field(default_factory=list)
    from_entities: List[str] = field(default_factory=list)

    def get_source_endpoint_selectors(self) -> List[EndpointSelector]:
        """api/ingress.go:111."""
        res = list(self.from_endpoints)
        res.extend(entities_as_selectors(self.from_entities))
        res.extend(cidr_slice_as_selectors(self.from_cidr))
        res.extend(cidr_rule_slice_as_selectors(self.from_cidr_set))
        return res

    def is_label_based(self) -> bool:
        """api/ingress.go:120."""
        return (
            len(self.from_requires)
            + len(self.from_cidr)
            + len(self.from_cidr_set)
        ) == 0

    def sanitize(self) -> None:
        """api/rule_validation.go:67."""
        l3_members = {
            "FromEndpoints": len(self.from_endpoints),
            "FromCIDR": len(self.from_cidr),
            "FromCIDRSet": len(self.from_cidr_set),
            "FromEntities": len(self.from_entities),
        }
        l3_l4_support = {
            "FromEndpoints": True,
            "FromCIDR": False,
            "FromCIDRSet": False,
            "FromEntities": True,
        }
        names = list(l3_members)
        for m1 in names:
            for m2 in names:
                if m2 != m1 and l3_members[m1] > 0 and l3_members[m2] > 0:
                    raise PolicyValidationError(
                        f"Combining {m1} and {m2} is not supported yet"
                    )
        for member in names:
            if (
                l3_members[member] > 0
                and len(self.to_ports) > 0
                and not l3_l4_support[member]
            ):
                raise PolicyValidationError(
                    f"Combining {member} and ToPorts is not supported yet"
                )
        for pr in self.to_ports:
            pr.sanitize()
        prefix_lengths = set()
        for c in self.from_cidr:
            prefix_lengths.add(sanitize_cidr(c))
        for cr in self.from_cidr_set:
            prefix_lengths.add(cr.sanitize())
        for e in self.from_entities:
            if e not in ENTITY_SELECTOR_MAPPING:
                raise PolicyValidationError(f"unsupported entity: {e}")
        if len(prefix_lengths) > MAX_CIDR_PREFIX_LENGTHS:
            raise PolicyValidationError(
                f"too many ingress CIDR prefix lengths "
                f"{len(prefix_lengths)}/{MAX_CIDR_PREFIX_LENGTHS}"
            )

    def deep_copy(self) -> "IngressRule":
        return IngressRule(
            from_endpoints=[
                s.add_requirements([]) for s in self.from_endpoints
            ],
            from_requires=[s.add_requirements([]) for s in self.from_requires],
            to_ports=list(self.to_ports),
            from_cidr=list(self.from_cidr),
            from_cidr_set=list(self.from_cidr_set),
            from_entities=list(self.from_entities),
        )


@dataclass
class EgressRule:
    """api/egress.go:28."""

    to_endpoints: List[EndpointSelector] = field(default_factory=list)
    to_requires: List[EndpointSelector] = field(default_factory=list)
    to_ports: List[PortRule] = field(default_factory=list)
    to_cidr: List[str] = field(default_factory=list)
    to_cidr_set: List[CIDRRule] = field(default_factory=list)
    to_entities: List[str] = field(default_factory=list)
    to_services: List[Service] = field(default_factory=list)
    to_fqdns: List[FQDNSelector] = field(default_factory=list)

    def get_destination_endpoint_selectors(self) -> List[EndpointSelector]:
        """api/egress.go:139."""
        res = list(self.to_endpoints)
        res.extend(entities_as_selectors(self.to_entities))
        res.extend(cidr_slice_as_selectors(self.to_cidr))
        res.extend(cidr_rule_slice_as_selectors(self.to_cidr_set))
        return res

    def is_label_based(self) -> bool:
        """api/egress.go:148."""
        return (
            len(self.to_requires)
            + len(self.to_cidr)
            + len(self.to_cidr_set)
            + len(self.to_services)
        ) == 0

    def sanitize(self) -> None:
        """api/rule_validation.go:132."""
        l3_members = {
            "ToCIDR": len(self.to_cidr),
            "ToCIDRSet": len(self.to_cidr_set),
            "ToEndpoints": len(self.to_endpoints),
            "ToEntities": len(self.to_entities),
            "ToServices": len(self.to_services),
            "ToFQDNs": len(self.to_fqdns),
        }
        names = list(l3_members)
        for m1 in names:
            for m2 in names:
                if m2 != m1 and l3_members[m1] > 0 and l3_members[m2] > 0:
                    raise PolicyValidationError(
                        f"Combining {m1} and {m2} is not supported yet"
                    )
        # All egress L3 members support ToPorts (rule_validation.go:141).
        for pr in self.to_ports:
            pr.sanitize()
        prefix_lengths = set()
        for c in self.to_cidr:
            prefix_lengths.add(sanitize_cidr(c))
        for cr in self.to_cidr_set:
            prefix_lengths.add(cr.sanitize())
        for e in self.to_entities:
            if e not in ENTITY_SELECTOR_MAPPING:
                raise PolicyValidationError(f"unsupported entity: {e}")
        for f in self.to_fqdns:
            f.sanitize()
        if len(prefix_lengths) > MAX_CIDR_PREFIX_LENGTHS:
            raise PolicyValidationError(
                f"too many egress CIDR prefix lengths "
                f"{len(prefix_lengths)}/{MAX_CIDR_PREFIX_LENGTHS}"
            )

    def deep_copy(self) -> "EgressRule":
        return EgressRule(
            to_endpoints=[s.add_requirements([]) for s in self.to_endpoints],
            to_requires=[s.add_requirements([]) for s in self.to_requires],
            to_ports=list(self.to_ports),
            to_cidr=list(self.to_cidr),
            to_cidr_set=list(self.to_cidr_set),
            to_entities=list(self.to_entities),
            to_services=list(self.to_services),
            to_fqdns=list(self.to_fqdns),
        )


@dataclass
class Rule:
    """api/rule.go:32: selector + ingress[] + egress[] + labels."""

    endpoint_selector: Optional[EndpointSelector] = None
    ingress: List[IngressRule] = field(default_factory=list)
    egress: List[EgressRule] = field(default_factory=list)
    labels: LabelArray = field(default_factory=LabelArray)
    description: str = ""

    def sanitize(self) -> None:
        """api/rule_validation.go:37."""
        for label in self.labels:
            if label.source == lbl.SOURCE_CILIUM_GENERATED:
                raise PolicyValidationError(
                    "rule labels cannot have cilium-generated source"
                )
        if self.endpoint_selector is None:
            raise PolicyValidationError("rule cannot have nil EndpointSelector")
        for i in self.ingress:
            i.sanitize()
        for e in self.egress:
            e.sanitize()
