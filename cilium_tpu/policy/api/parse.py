"""JSON (de)serialization of the rule tree.

Accepts the CiliumNetworkPolicy-style JSON used in the reference's
``examples/policies`` and ``cilium policy import`` (daemon/policy.go:329).
"""

from __future__ import annotations

import json
from typing import List, Union

from cilium_tpu.labels import Label, LabelArray, parse_label


def _label_from_json(v) -> Label:
    """Reference Label.UnmarshalJSON (labels.go:356): accepts the full
    {source,key,value} object form or the "[SOURCE:]KEY[=VALUE]" string
    short form."""
    if isinstance(v, str):
        return parse_label(v)
    return Label(
        key=v.get("key", ""),
        value=v.get("value", ""),
        source=v.get("source", ""),
    )
from cilium_tpu.policy.api.rule import (
    CIDRRule,
    EgressRule,
    FQDNSelector,
    IngressRule,
    K8sServiceNamespace,
    L7Rules,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleKafka,
    PortRuleL7,
    Rule,
    Service,
)
from cilium_tpu.policy.api.selector import EndpointSelector


def _port_rule_http_from_dict(d: dict) -> PortRuleHTTP:
    return PortRuleHTTP(
        path=d.get("path", ""),
        method=d.get("method", ""),
        host=d.get("host", ""),
        headers=list(d.get("headers") or []),
    )


def _port_rule_kafka_from_dict(d: dict) -> PortRuleKafka:
    return PortRuleKafka(
        role=d.get("role", ""),
        api_key=d.get("apiKey", ""),
        api_version=d.get("apiVersion", ""),
        client_id=d.get("clientID", ""),
        topic=d.get("topic", ""),
    )


def _l7rules_from_dict(d: dict) -> L7Rules:
    return L7Rules(
        http=(
            [_port_rule_http_from_dict(h) for h in d["http"]]
            if d.get("http") is not None
            else None
        ),
        kafka=(
            [_port_rule_kafka_from_dict(k) for k in d["kafka"]]
            if d.get("kafka") is not None
            else None
        ),
        l7proto=d.get("l7proto", ""),
        l7=(
            [PortRuleL7(e) for e in d["l7"]]
            if d.get("l7") is not None
            else None
        ),
    )


def _port_rule_from_dict(d: dict) -> PortRule:
    return PortRule(
        ports=[
            PortProtocol(port=p.get("port", ""), protocol=p.get("protocol", ""))
            for p in d.get("ports") or []
        ],
        rules=(
            _l7rules_from_dict(d["rules"]) if d.get("rules") is not None else None
        ),
    )


def _cidr_rule_from_dict(d: dict) -> CIDRRule:
    return CIDRRule(
        cidr=d.get("cidr", ""), except_cidrs=list(d.get("except") or [])
    )


def _ingress_from_dict(d: dict) -> IngressRule:
    return IngressRule(
        from_endpoints=[
            EndpointSelector.from_dict(s) for s in d.get("fromEndpoints") or []
        ],
        from_requires=[
            EndpointSelector.from_dict(s) for s in d.get("fromRequires") or []
        ],
        to_ports=[_port_rule_from_dict(p) for p in d.get("toPorts") or []],
        from_cidr=list(d.get("fromCIDR") or []),
        from_cidr_set=[
            _cidr_rule_from_dict(c) for c in d.get("fromCIDRSet") or []
        ],
        from_entities=list(d.get("fromEntities") or []),
    )


def _service_from_dict(d: dict) -> Service:
    svc = d.get("k8sService")
    return Service(
        k8s_service=(
            K8sServiceNamespace(
                service_name=svc.get("serviceName", ""),
                namespace=svc.get("namespace", ""),
            )
            if svc
            else None
        ),
        k8s_service_selector=d.get("k8sServiceSelector"),
    )


def _egress_from_dict(d: dict) -> EgressRule:
    return EgressRule(
        to_endpoints=[
            EndpointSelector.from_dict(s) for s in d.get("toEndpoints") or []
        ],
        to_requires=[
            EndpointSelector.from_dict(s) for s in d.get("toRequires") or []
        ],
        to_ports=[_port_rule_from_dict(p) for p in d.get("toPorts") or []],
        to_cidr=list(d.get("toCIDR") or []),
        to_cidr_set=[_cidr_rule_from_dict(c) for c in d.get("toCIDRSet") or []],
        to_entities=list(d.get("toEntities") or []),
        to_services=[_service_from_dict(s) for s in d.get("toServices") or []],
        to_fqdns=[
            FQDNSelector(match_name=f.get("matchName", ""))
            for f in d.get("toFQDNs") or []
        ],
    )


def rule_from_dict(d: dict) -> Rule:
    return Rule(
        endpoint_selector=(
            EndpointSelector.from_dict(d["endpointSelector"])
            if "endpointSelector" in d
            else None
        ),
        ingress=[_ingress_from_dict(i) for i in d.get("ingress") or []],
        egress=[_egress_from_dict(e) for e in d.get("egress") or []],
        labels=LabelArray(_label_from_json(s) for s in d.get("labels") or []),
        description=d.get("description", ""),
    )


def rules_from_json(text: str) -> List[Rule]:
    """Parse a JSON rule list (or single rule object)."""
    data = json.loads(text)
    if isinstance(data, dict):
        data = [data]
    return [rule_from_dict(d) for d in data]
