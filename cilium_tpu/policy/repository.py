"""The policy repository: ordered rules + revisioned verdict resolution.

Re-design of /root/reference/pkg/policy/repository.go.  This is the
control-plane source of truth; every compiled table tensor carries the
repository revision it was generated from, and table swaps on device are
gated on revision (the ACK-flip pattern, SURVEY.md §5).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from cilium_tpu.labels import LabelArray

from cilium_tpu.policy.api.rule import (
    PROTO_TCP,
    PROTO_UDP,
    PortRuleHTTP,
    PortRuleKafka,
    L7Rules,
    Rule,
)
from cilium_tpu.policy.api.selector import Requirement
from cilium_tpu.policy.l3 import CIDRPolicy
from cilium_tpu.policy.l4 import (
    L4Policy,
    L4PolicyMap,
    PARSER_TYPE_HTTP,
    PARSER_TYPE_KAFKA,
    PARSER_TYPE_NONE,
)
from cilium_tpu.policy.rule_resolve import L4MergeError, PolicyRule, TraceState
from cilium_tpu.policy.search import Decision, SearchContext

from cilium_tpu.logging import get_logger

log = get_logger("policy")


class Repository:
    """repository.go:31: rules + revision."""

    def __init__(self):
        self.lock = threading.RLock()
        self.rules: List[PolicyRule] = []
        self.revision = 1

    # -- trace helper (repository.go:66) ------------------------------------

    def _trace(self, state: TraceState, ctx: SearchContext) -> None:
        ctx.policy_trace(
            "%d/%d rules selected\n", state.selected_rules, len(self.rules)
        )
        if state.constrained_rules > 0:
            ctx.policy_trace("Found unsatisfied FromRequires constraint\n")
        elif state.matched_rules > 0:
            ctx.policy_trace("Found allow rule\n")
        else:
            ctx.policy_trace("Found no allow rule\n")

    # -- label-level verdicts ------------------------------------------------

    def can_reach_ingress(self, ctx: SearchContext) -> Decision:
        """CanReachIngressRLocked (repository.go:80): first Denied breaks;
        Allowed is remembered but later rules may still deny."""
        decision = Decision.UNDECIDED
        state = TraceState()
        for i, r in enumerate(self.rules):
            state.rule_id = i
            v = r.can_reach_ingress(ctx, state)
            if v == Decision.DENIED:
                decision = Decision.DENIED
                break
            elif v == Decision.ALLOWED:
                decision = Decision.ALLOWED
        self._trace(state, ctx)
        return decision

    def can_reach_egress(self, ctx: SearchContext) -> Decision:
        """CanReachEgressRLocked (repository.go:466)."""
        decision = Decision.UNDECIDED
        state = TraceState()
        for i, r in enumerate(self.rules):
            state.rule_id = i
            v = r.can_reach_egress(ctx, state)
            if v == Decision.DENIED:
                decision = Decision.DENIED
                break
            elif v == Decision.ALLOWED:
                decision = Decision.ALLOWED
        self._trace(state, ctx)
        return decision

    def allows_ingress_label_access(self, ctx: SearchContext) -> Decision:
        """AllowsIngressLabelAccess (repository.go:111): label-only verdict
        with default deny."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = Decision.DENIED
        if len(self.rules) == 0:
            ctx.policy_trace("  No rules found\n")
        else:
            if self.can_reach_ingress(ctx) == Decision.ALLOWED:
                decision = Decision.ALLOWED
        ctx.policy_trace("Label verdict: %s", str(decision))
        return decision

    def allows_egress_label_access(self, ctx: SearchContext) -> Decision:
        """repository.go:448."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = Decision.DENIED
        if len(self.rules) == 0:
            ctx.policy_trace("  No rules found\n")
        else:
            decision = self.can_reach_egress(ctx)
        ctx.policy_trace("Egress label verdict: %s", str(decision))
        return decision

    # -- L4 resolution -------------------------------------------------------

    def _collect_ingress_requirements(
        self, ctx: SearchContext, rules=None
    ) -> List[Requirement]:
        """repository.go:252-266: flatten all FromRequires of rules
        selecting ctx.To into selector requirements."""
        reqs: List[Requirement] = []
        for r in self.rules if rules is None else rules:
            for ingress_rule in r.rule.ingress:
                if r.endpoint_selector.matches(ctx.to_labels):
                    for requirement in ingress_rule.from_requires:
                        reqs.extend(requirement.convert_to_requirements())
        return reqs

    def _collect_egress_requirements(
        self, ctx: SearchContext, rules=None
    ) -> List[Requirement]:
        """repository.go:297-311."""
        reqs: List[Requirement] = []
        for r in self.rules if rules is None else rules:
            for egress_rule in r.rule.egress:
                if r.endpoint_selector.matches(ctx.from_labels):
                    for requirement in egress_rule.to_requires:
                        reqs.extend(requirement.convert_to_requirements())
        return reqs

    def resolve_l4_ingress_policy(
        self, ctx: SearchContext, rules=None
    ) -> L4PolicyMap:
        """ResolveL4IngressPolicy (repository.go:245).

        `rules` restricts the walk to an ordered subset; callers must
        guarantee it contains every rule whose endpoint_selector
        matches ctx.to_labels (the RuleIndex invariant) — other rules
        are no-ops in this resolution."""
        result = L4Policy()
        ctx.policy_trace("\n")
        ctx.policy_trace(
            "Resolving ingress port policy for %+s\n", ctx.to_labels
        )
        state = TraceState()
        requirements = self._collect_ingress_requirements(ctx, rules)

        for r in self.rules if rules is None else rules:
            found = r.resolve_l4_ingress_policy(
                ctx, state, result, requirements
            )
            state.rule_id += 1
            if found is not None:
                state.matched_rules += 1

        self._wildcard_l3l4_rules(ctx, True, result.ingress, rules)
        self._trace(state, ctx)
        return result.ingress

    def resolve_l4_egress_policy(
        self, ctx: SearchContext, rules=None
    ) -> L4PolicyMap:
        """ResolveL4EgressPolicy (repository.go:291)."""
        result = L4Policy()
        ctx.policy_trace("\n")
        ctx.policy_trace(
            "Resolving egress port policy for %+s\n", ctx.to_labels
        )
        requirements = self._collect_egress_requirements(ctx, rules)
        state = TraceState()
        for i, r in enumerate(self.rules if rules is None else rules):
            state.rule_id = i
            found = r.resolve_l4_egress_policy(
                ctx, state, result, requirements
            )
            state.rule_id += 1
            if found is not None:
                state.matched_rules += 1

        result.revision = self.revision
        self._wildcard_l3l4_rules(ctx, False, result.egress, rules)
        self._trace(state, ctx)
        return result.egress

    # -- L3-allow -> L7-wildcard injection (repository.go:128-235) ----------

    @staticmethod
    def _l7_filter_index(l4_policy: L4PolicyMap):
        """(protocol → port → [keys]) over the L7-carrying filters —
        _wildcard_l3l4_rule's scan was O(rules × filters) per resolve;
        protocol/port/parser of a filter never change while the
        wildcard pass runs, so one index serves every rule."""
        index: Dict[str, Dict[int, List]] = {}
        for k, f in l4_policy.items():
            if f.l7_parser == PARSER_TYPE_NONE:
                continue
            index.setdefault(f.protocol, {}).setdefault(
                f.port, []
            ).append(k)
        return index

    def _wildcard_l3l4_rule(
        self,
        proto: str,
        port: int,
        endpoints: List,
        rule_labels: LabelArray,
        l4_policy: L4PolicyMap,
        index=None,
    ) -> None:
        """repository.go:128: endpoints allowed at L3/L4 get wildcarded
        into every L7 filter on a matching (proto, port)."""
        if index is not None:
            ports = index.get(proto, {})
            keys = (
                [k for lst in ports.values() for k in lst]
                if port == 0
                else list(ports.get(port, ()))
            )
            items = [(k, l4_policy[k]) for k in keys]
        else:
            items = list(l4_policy.items())
        for k, f in items:
            if proto != f.protocol or (port != 0 and port != f.port):
                continue
            if f.l7_parser == PARSER_TYPE_NONE:
                continue
            elif f.l7_parser == PARSER_TYPE_HTTP:
                for sel in endpoints:
                    f.l7_rules_per_ep[sel] = L7Rules(http=[PortRuleHTTP()])
            elif f.l7_parser == PARSER_TYPE_KAFKA:
                for sel in endpoints:
                    rule = PortRuleKafka()
                    rule.sanitize()
                    f.l7_rules_per_ep[sel] = L7Rules(kafka=[rule])
            else:
                for sel in endpoints:
                    f.l7_rules_per_ep[sel] = L7Rules(
                        l7proto=f.l7_parser, l7=[]
                    )
            f.endpoints = f.endpoints + list(endpoints)
            f.derived_from_rules.append(rule_labels)
            l4_policy[k] = f

    def _wildcard_l3l4_rules(
        self,
        ctx: SearchContext,
        ingress: bool,
        l4_policy: L4PolicyMap,
        rules=None,
    ) -> None:
        """repository.go:170."""
        index = self._l7_filter_index(l4_policy)
        for r in self.rules if rules is None else rules:
            if ingress:
                if not r.endpoint_selector.matches(ctx.to_labels):
                    continue
                for rule in r.rule.ingress:
                    if not rule.is_label_based():
                        continue
                    from_endpoints = rule.get_source_endpoint_selectors()
                    rule_labels = LabelArray(r.rule.labels)
                    if len(rule.to_ports) == 0:
                        self._wildcard_l3l4_rule(
                            PROTO_TCP, 0, from_endpoints, rule_labels,
                            l4_policy, index,
                        )
                        self._wildcard_l3l4_rule(
                            PROTO_UDP, 0, from_endpoints, rule_labels,
                            l4_policy, index,
                        )
                    else:
                        for to_port in rule.to_ports:
                            if (
                                to_port.rules is None
                                or to_port.rules.is_empty()
                            ):
                                for p in to_port.ports:
                                    self._wildcard_l3l4_rule(
                                        p.protocol,
                                        p.numeric_port(),
                                        from_endpoints,
                                        rule_labels,
                                        l4_policy,
                                        index,
                                    )
            else:
                if not r.endpoint_selector.matches(ctx.from_labels):
                    continue
                for rule in r.rule.egress:
                    if not rule.is_label_based():
                        continue
                    to_endpoints = rule.get_destination_endpoint_selectors()
                    rule_labels = LabelArray(r.rule.labels)
                    if len(rule.to_ports) == 0:
                        self._wildcard_l3l4_rule(
                            PROTO_TCP, 0, to_endpoints, rule_labels,
                            l4_policy, index,
                        )
                        self._wildcard_l3l4_rule(
                            PROTO_UDP, 0, to_endpoints, rule_labels,
                            l4_policy, index,
                        )
                    else:
                        for to_port in rule.to_ports:
                            if (
                                to_port.rules is None
                                or to_port.rules.is_empty()
                            ):
                                for p in to_port.ports:
                                    self._wildcard_l3l4_rule(
                                        p.protocol,
                                        p.numeric_port(),
                                        to_endpoints,
                                        rule_labels,
                                        l4_policy,
                                        index,
                                    )

    # -- CIDR ----------------------------------------------------------------

    def resolve_cidr_policy(
        self, ctx: SearchContext, rules=None
    ) -> CIDRPolicy:
        """ResolveCIDRPolicy (repository.go:340)."""
        result = CIDRPolicy()
        ctx.policy_trace("Resolving L3 (CIDR) policy for %+s\n", ctx.to_labels)
        state = TraceState()
        for r in self.rules if rules is None else rules:
            r.resolve_cidr_policy(ctx, state, result)
            state.rule_id += 1
        self._trace(state, ctx)
        return result

    # -- full-context verdicts (repository.go:355-442) -----------------------

    def _allows_l4_egress(self, ctx: SearchContext) -> Decision:
        """repository.go:355: a resolve error degrades to Undecided (the
        caller turns that into Denied) rather than propagating."""
        verdict = Decision.UNDECIDED
        try:
            egress_policy = self.resolve_l4_egress_policy(ctx)
        except L4MergeError as e:
            log.warning("Evaluation error while resolving L4 egress policy: %s", e)
            egress_policy = None
        if egress_policy is not None and len(egress_policy) > 0:
            verdict = egress_policy.egress_covers_context(ctx)
        if len(ctx.dports) == 0:
            ctx.policy_trace("L4 egress verdict: [no port context specified]")
        else:
            ctx.policy_trace("L4 egress verdict: %s", str(verdict))
        return verdict

    def _allows_l4_ingress(self, ctx: SearchContext) -> Decision:
        """repository.go:374: resolve errors degrade to Undecided."""
        verdict = Decision.UNDECIDED
        try:
            ingress_policy = self.resolve_l4_ingress_policy(ctx)
        except L4MergeError as e:
            log.warning("Evaluation error while resolving L4 ingress policy: %s", e)
            ingress_policy = None
        if ingress_policy is not None and len(ingress_policy) > 0:
            verdict = ingress_policy.ingress_covers_context(ctx)
        if len(ctx.dports) == 0:
            ctx.policy_trace("L4 ingress verdict: [no port context specified]")
        else:
            ctx.policy_trace("L4 ingress verdict: %s", str(verdict))
        return verdict

    def allows_ingress(self, ctx: SearchContext) -> Decision:
        """AllowsIngressRLocked (repository.go:397): label verdict, else L4
        if ports present; default deny."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = self.can_reach_ingress(ctx)
        ctx.policy_trace("Label verdict: %s", str(decision))
        if decision == Decision.ALLOWED:
            ctx.policy_trace("L4 ingress policies skipped")
            return decision
        if len(ctx.dports) != 0:
            decision = self._allows_l4_ingress(ctx)
        if decision != Decision.ALLOWED:
            decision = Decision.DENIED
        return decision

    def allows_egress(self, ctx: SearchContext) -> Decision:
        """AllowsEgressRLocked (repository.go:422)."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = self.can_reach_egress(ctx)
        ctx.policy_trace("Egress label verdict: %s", str(decision))
        if decision == Decision.ALLOWED:
            ctx.policy_trace("L4 egress policies skipped")
            return decision
        if len(ctx.dports) != 0:
            decision = self._allows_l4_egress(ctx)
        if decision != Decision.ALLOWED:
            decision = Decision.DENIED
        return decision

    # -- mutation (repository.go:525-685) ------------------------------------

    def add(self, rule: Rule) -> int:
        """repository.go:529: sanitize + insert."""
        with self.lock:
            rule.sanitize()
            return self.add_list([rule])

    def add_list(self, rules: List[Rule]) -> int:
        """repository.go:544 (rules must already be sanitized)."""
        with self.lock:
            self.rules.extend(PolicyRule(r) for r in rules)
            self.revision += 1
            return self.revision

    def delete_by_labels(self, labels: LabelArray) -> Tuple[int, int]:
        """repository.go:566."""
        with self.lock:
            deleted = 0
            kept: List[PolicyRule] = []
            for r in self.rules:
                if not r.labels.contains(labels):
                    kept.append(r)
                else:
                    deleted += 1
            if deleted > 0:
                self.revision += 1
                self.rules = kept
            return self.revision, deleted

    def search(self, labels: LabelArray) -> List[Rule]:
        """repository.go:495."""
        return [r.rule for r in self.rules if r.labels.contains(labels)]

    def contains_all(self, needed: List[LabelArray]) -> bool:
        """repository.go:510."""
        for needed_label in needed:
            if not any(
                len(r.labels) > 0 and needed_label.contains(r.labels)
                for r in self.rules
            ):
                return False
        return True

    def get_rules_matching(
        self, labels: LabelArray, rules=None
    ) -> Tuple[bool, bool]:
        """repository.go:624: (ingress_match, egress_match).  `rules`
        restricts the walk to a pre-matched sublist (the RuleIndex
        invariant: every rule in it selects `labels`), and the
        per-rule selector check is SKIPPED in that case — callers must
        not pass a superset."""
        ingress_match = False
        egress_match = False
        for r in self.rules if rules is None else rules:
            if rules is not None or r.endpoint_selector.matches(labels):
                if len(r.rule.ingress) > 0:
                    ingress_match = True
                if len(r.rule.egress) > 0:
                    egress_match = True
            if ingress_match and egress_match:
                break
        return ingress_match, egress_match

    def num_rules(self) -> int:
        return len(self.rules)

    def get_revision(self) -> int:
        return self.revision

    def empty(self) -> bool:
        return len(self.rules) == 0

    def bump_revision(self) -> None:
        with self.lock:
            self.revision += 1

    def translate_rules(self, translator) -> None:
        """repository.go:667: apply a rule translator (used by the k8s
        service-to-CIDR rewriter, pkg/k8s/rule_translate.go)."""
        with self.lock:
            for r in self.rules:
                translator.translate(r.rule)
