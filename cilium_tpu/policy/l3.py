"""CIDR (L3) policy maps.

Re-design of /root/reference/pkg/policy/l3.go: per-direction CIDR allow
maps with per-prefix-length refcounts.  The prefix-length sets drive the
LPM table compiler (cilium_tpu.compiler.lpm): like the reference's
unrolled LPM fallback (bpf/lib/eps.h:86-108), the TPU LPM kernel probes
a fixed, longest-to-shortest list of prefix lengths, so the list is part
of the compiled artifact.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api.rule import (
    MAX_CIDR_PREFIX_LENGTHS,
    PolicyValidationError,
)
from cilium_tpu.utils import cidr as cidr_util

# Cluster ranges used for the default prefix lengths (l3.go:53; the
# reference reads them from node config — these are its defaults).
DEFAULT_IPV4_CLUSTER_PREFIX = 8
DEFAULT_IPV6_CLUSTER_PREFIX = 64


def get_default_prefix_lengths() -> Tuple[List[int], List[int]]:
    """l3.go:53: (v6, v4) lengths for host/cluster/world, longest first."""
    s6 = [128, DEFAULT_IPV6_CLUSTER_PREFIX, 0]
    s4 = [32, DEFAULT_IPV4_CLUSTER_PREFIX, 0]
    return s6, s4


@dataclass
class CIDRPolicyMapRule:
    """l3.go:30."""

    prefix: object  # ipaddress network
    derived_from_rules: List[LabelArray] = field(default_factory=list)


class CIDRPolicyMap:
    """l3.go:41: allowed prefixes + per-prefix-length counts."""

    def __init__(self):
        self.map: Dict[str, CIDRPolicyMapRule] = {}
        self.ipv6_prefix_count: Dict[int, int] = {}
        self.ipv4_prefix_count: Dict[int, int] = {}

    def insert(self, cidr: str, rule_labels: LabelArray) -> int:
        """l3.go:66: parse (with Go classful-default-mask quirks), key by
        masked address, count new prefix lengths."""
        try:
            ipnet = cidr_util.parse_cidr_or_ip_classful(cidr)
        except ValueError:
            return 0
        ones = ipnet.prefixlen
        key = f"{ipnet.network_address}/{ones}"
        if key not in self.map:
            self.map[key] = CIDRPolicyMapRule(
                prefix=ipnet, derived_from_rules=[rule_labels]
            )
            if ipnet.version == 6:
                self.ipv6_prefix_count[ones] = (
                    self.ipv6_prefix_count.get(ones, 0) + 1
                )
            else:
                self.ipv4_prefix_count[ones] = (
                    self.ipv4_prefix_count.get(ones, 0) + 1
                )
            return 1
        self.map[key].derived_from_rules.append(rule_labels)
        return 0


class CIDRPolicy:
    """l3.go:111: ingress+egress CIDR maps with default prefix lengths
    pre-seeded (l3.go:117-142)."""

    def __init__(self):
        self.ingress = CIDRPolicyMap()
        self.egress = CIDRPolicyMap()
        s6, s4 = get_default_prefix_lengths()
        for i in s6:
            self.ingress.ipv6_prefix_count.setdefault(i, 0)
            self.egress.ipv6_prefix_count.setdefault(i, 0)
        for i in s4:
            self.ingress.ipv4_prefix_count.setdefault(i, 0)
            self.egress.ipv4_prefix_count.setdefault(i, 0)

    def to_bpf_data(self) -> Tuple[List[int], List[int]]:
        """l3.go:152: distinct prefix lengths, longest-to-shortest.

        This is the probe schedule of the LPM kernel.
        """
        s6, s4 = set(), set()
        for m in (self.ingress, self.egress):
            s6.update(m.ipv6_prefix_count)
            s4.update(m.ipv4_prefix_count)
        return sorted(s6, reverse=True), sorted(s4, reverse=True)

    def validate(self) -> None:
        """l3.go:206."""
        if len(self.ingress.ipv6_prefix_count) > MAX_CIDR_PREFIX_LENGTHS:
            raise PolicyValidationError(
                f"too many ingress CIDR prefix lengths "
                f"{len(self.ingress.ipv6_prefix_count)}/{MAX_CIDR_PREFIX_LENGTHS}"
            )
