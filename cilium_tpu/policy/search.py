"""Search context and policy decisions.

Re-design of /root/reference/pkg/policy/policy.go (SearchContext, trace)
and pkg/policy/api/decision.go.  The trace buffer reproduces the
reference's `cilium policy trace` output format so explain-mode goldens
are comparable.
"""

from __future__ import annotations

import enum
import io
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from cilium_tpu.labels import LabelArray


class Decision(enum.IntEnum):
    """api/decision.go: Undecided / Allowed / Denied."""

    UNDECIDED = 0
    ALLOWED = 1
    DENIED = 2

    def __str__(self) -> str:
        return {0: "undecided", 1: "allowed", 2: "denied"}[int(self)]


class Tracing(enum.IntEnum):
    """policy.go:29."""

    DISABLED = 0
    ENABLED = 1
    VERBOSE = 2


@dataclass
class Port:
    """api/v1 models.Port: a destination port in the search context."""

    port: int
    protocol: str = "ANY"  # "TCP" | "UDP" | "ANY" | ""


@dataclass
class SearchContext:
    """policy.go:64: the question being asked of the repository.

    ``from_labels``/``to_labels`` of None mirror the reference's nil
    LabelArray (relevant in mergeL4Ingress's ctx.From != nil check,
    rule.go:152).
    """

    from_labels: Optional[LabelArray] = None
    to_labels: Optional[LabelArray] = None
    dports: List[Port] = field(default_factory=list)
    trace: Tracing = Tracing.DISABLED
    depth: int = 0
    logging: Optional[io.StringIO] = None

    def policy_trace(self, fmt: str, *args) -> None:
        """policy.go:39 (format string compatible)."""
        if self.trace in (Tracing.ENABLED, Tracing.VERBOSE):
            if self.logging is not None:
                pad = "" .ljust(self.depth * 2)
                self.logging.write(pad + (fmt % args if args else fmt))

    def policy_trace_verbose(self, fmt: str, *args) -> None:
        """policy.go:53."""
        if self.trace == Tracing.VERBOSE and self.logging is not None:
            self.logging.write(fmt % args if args else fmt)

    def __str__(self) -> str:
        frm = ", ".join(str(l) for l in (self.from_labels or []))
        to = ", ".join(str(l) for l in (self.to_labels or []))
        ret = f"From: [{frm}] => To: [{to}]"
        if self.dports:
            ports = ", ".join(
                f"{p.port}/{p.protocol}" for p in self.dports
            )
            ret += f" Ports: [{ports}]"
        return ret

    def trace_output(self) -> str:
        return self.logging.getvalue() if self.logging is not None else ""
