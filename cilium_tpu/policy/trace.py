"""Policy trace / explain mode.

The reference exposes decision tracing at two levels, both kept here:
  - rule-level: `cilium policy trace` / GET /policy/resolve
    (daemon/policy.go:66) runs the repository verdict with
    SearchContext.Trace enabled and returns the decision plus the
    human-readable trace buffer (pkg/policy/policy.go:39-61);
  - datapath-level: per-tuple attribution — which policy-map entry
    (exact / L3-only / wildcard probe) produced the verdict
    (the per-entry counters of bpf/lib/policy.h:66 made queryable).
"""

from __future__ import annotations

import io
from typing import Tuple

from cilium_tpu.engine.oracle import (
    MATCH_FRAG_DROP,
    MATCH_L3,
    MATCH_L4,
    MATCH_L4_WILD,
    policy_can_access,
)
from cilium_tpu.maps.policymap import PolicyMapState
from cilium_tpu.policy.search import Decision, SearchContext, Tracing


def trace_policy(repo, ctx: SearchContext, verbose: bool = False):
    """GET /policy/resolve (daemon/policy.go:66): ingress verdict with
    a populated trace buffer.  Returns (Decision, trace_text)."""
    ctx.trace = Tracing.VERBOSE if verbose else Tracing.ENABLED
    if ctx.logging is None:
        ctx.logging = io.StringIO()
    verdict = repo.allows_ingress(ctx)
    return verdict, ctx.trace_output()


def explain_tuple(
    state: PolicyMapState,
    identity: int,
    dport: int,
    proto: int,
    direction: int,
    is_fragment: bool = False,
) -> Tuple[bool, str]:
    """Datapath attribution for one tuple against one endpoint's map
    state: which probe of the 3-probe lattice decided, and on which
    entry."""
    import copy

    verdict = policy_can_access(
        copy.deepcopy(state), identity, dport, proto, direction,
        is_fragment,
    )
    direction_name = "ingress" if direction == 0 else "egress"
    if verdict.match_kind == MATCH_L4:
        why = (
            f"L4 exact entry ({identity}, {dport}/{proto}, "
            f"{direction_name})"
            + (
                f" → proxy port {verdict.proxy_port}"
                if verdict.proxy_port
                else ""
            )
        )
    elif verdict.match_kind == MATCH_L3:
        why = f"L3-only entry ({identity}, {direction_name})"
    elif verdict.match_kind == MATCH_L4_WILD:
        why = (
            f"L4 wildcard entry (any identity, {dport}/{proto}, "
            f"{direction_name})"
            + (
                f" → proxy port {verdict.proxy_port}"
                if verdict.proxy_port
                else ""
            )
        )
    elif verdict.match_kind == MATCH_FRAG_DROP:
        why = "fragment without L3-only allow (DROP_FRAG_NOSUPPORT)"
    else:
        why = "no matching entry (DROP_POLICY)"
    action = "ALLOW" if verdict.allowed else "DENY"
    return verdict.allowed, f"{action}: {why}"
