"""Policy trace / explain mode.

The reference exposes decision tracing at two levels, both kept here:
  - rule-level: `cilium policy trace` / GET /policy/resolve
    (daemon/policy.go:66) runs the repository verdict with
    SearchContext.Trace enabled and returns the decision plus the
    human-readable trace buffer (pkg/policy/policy.go:39-61);
  - datapath-level: per-tuple attribution — which policy-map entry
    (exact / L3-only / wildcard probe) produced the verdict
    (the per-entry counters of bpf/lib/policy.h:66 made queryable).

`trace_tuple` is the telemetry plane's single-tuple EXPLAIN kernel:
it reruns the whole fused-pipeline stage order (prefilter → LB/DNAT
→ CT → ipcache → lattice → combine) host-side against the daemon's
live state, reporting every stage's intermediate decision plus the
repository rules that produced the matched map entry — the
`cilium policy trace` analogue made stage-accurate.
"""

from __future__ import annotations

import io
import ipaddress
from typing import Tuple

from cilium_tpu.engine.oracle import (
    MATCH_FRAG_DROP,
    MATCH_L3,
    MATCH_L4,
    MATCH_L4_WILD,
    MATCH_NONE,
    policy_can_access,
)
from cilium_tpu.maps.policymap import PolicyMapState
from cilium_tpu.policy.search import Decision, SearchContext, Tracing


def trace_policy(repo, ctx: SearchContext, verbose: bool = False):
    """GET /policy/resolve (daemon/policy.go:66): ingress verdict with
    a populated trace buffer.  Returns (Decision, trace_text)."""
    ctx.trace = Tracing.VERBOSE if verbose else Tracing.ENABLED
    if ctx.logging is None:
        ctx.logging = io.StringIO()
    verdict = repo.allows_ingress(ctx)
    return verdict, ctx.trace_output()


def explain_tuple(
    state: PolicyMapState,
    identity: int,
    dport: int,
    proto: int,
    direction: int,
    is_fragment: bool = False,
) -> Tuple[bool, str]:
    """Datapath attribution for one tuple against one endpoint's map
    state: which probe of the 3-probe lattice decided, and on which
    entry."""
    verdict, why = _explain_verdict(
        state, identity, dport, proto, direction, is_fragment
    )
    action = "ALLOW" if verdict.allowed else "DENY"
    return verdict.allowed, f"{action}: {why}"


def _explain_verdict(
    state, identity, dport, proto, direction, is_fragment=False
):
    """One lattice evaluation + attribution text.  Deepcopies the
    state once (probe hits bump entry counters, policy.h:66, and an
    explain must not perturb what it reads); returns (Verdict, why)
    so trace_tuple gets match_kind/proxy_port without a second
    evaluation."""
    import copy

    verdict = policy_can_access(
        copy.deepcopy(state), identity, dport, proto, direction,
        is_fragment,
    )
    direction_name = "ingress" if direction == 0 else "egress"
    if verdict.match_kind == MATCH_L4:
        why = (
            f"L4 exact entry ({identity}, {dport}/{proto}, "
            f"{direction_name})"
            + (
                f" → proxy port {verdict.proxy_port}"
                if verdict.proxy_port
                else ""
            )
        )
    elif verdict.match_kind == MATCH_L3:
        why = f"L3-only entry ({identity}, {direction_name})"
    elif verdict.match_kind == MATCH_L4_WILD:
        why = (
            f"L4 wildcard entry (any identity, {dport}/{proto}, "
            f"{direction_name})"
            + (
                f" → proxy port {verdict.proxy_port}"
                if verdict.proxy_port
                else ""
            )
        )
    elif verdict.match_kind == MATCH_FRAG_DROP:
        why = "fragment without L3-only allow (DROP_FRAG_NOSUPPORT)"
    else:
        why = "no matching entry (DROP_POLICY)"
    return verdict, why


def _ip_u32(ip) -> int:
    return (
        int(ip)
        if isinstance(ip, int)
        else int(ipaddress.IPv4Address(ip))
    )


def _lpm_match(mappings, ip_u32: int):
    """Longest-prefix match over a {cidr: identity} dict; returns
    (prefix, identity) or (None, 0).  Single-tuple explain path —
    clarity over speed."""
    best = (None, 0, -1)
    for cidr, num_id in mappings.items():
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            continue
        if (ip_u32 & int(net.netmask)) == int(net.network_address):
            if net.prefixlen > best[2]:
                best = (cidr, num_id, net.prefixlen)
    return best[0], best[1]


def _matching_rules(daemon, ep_labels, peer_labels, peer_addr_u32,
                    dport, proto, direction, match_kind):
    """Repository rules consistent with the matched map entry: the
    rule must select the endpoint, and its direction clause must
    admit the peer (from_endpoints/to_endpoints selector over the
    peer identity's labels, or a CIDR clause covering the address)
    on the matched port (exact/wildcard L4) or with no port clause
    (L3-only).  Returns [(rule index, rule labels string)]."""
    proto_name = {6: "TCP", 17: "UDP"}.get(proto, str(proto))
    out = []
    for i, repo_rule in enumerate(daemon.repo.rules):
        # repo entries are PolicyRule wrappers around the api.Rule
        rule = getattr(repo_rule, "rule", repo_rule)
        if not rule.endpoint_selector.matches(ep_labels):
            continue
        clauses = rule.ingress if direction == 0 else rule.egress
        for clause in clauses:
            sels = (
                clause.from_endpoints
                if direction == 0
                else getattr(clause, "to_endpoints", [])
            )
            peer_ok = any(
                s.matches(peer_labels) for s in sels
            ) if peer_labels is not None else False
            cidrs = [str(c) for c in getattr(
                clause, "from_cidr" if direction == 0 else "to_cidr", []
            )] + [str(c.cidr) for c in getattr(
                clause,
                "from_cidr_set" if direction == 0 else "to_cidr_set",
                [],
            )]
            for cidr in cidrs:
                net = ipaddress.ip_network(cidr, strict=False)
                if net.version == 4 and (
                    peer_addr_u32 & int(net.netmask)
                ) == int(net.network_address):
                    peer_ok = True
            ports = [
                (pp.port, (pp.protocol or "TCP").upper())
                for pr in clause.to_ports
                for pp in pr.ports
            ]
            if match_kind == MATCH_L3:
                port_ok = not ports
            elif match_kind in (MATCH_L4, MATCH_L4_WILD):
                port_ok = any(
                    p == str(dport) and pn in (proto_name, "ANY", "")
                    for p, pn in ports
                )
                # an L4 wildcard entry needs no peer selector at all
                if match_kind == MATCH_L4_WILD and port_ok and not sels:
                    peer_ok = True
            else:
                port_ok = False
            if peer_ok and port_ok:
                out.append((i, str(rule.labels)))
                break
    return out


def trace_tuple(
    daemon,
    ep_id: int,
    saddr,
    daddr,
    dport: int,
    proto: int = 6,
    direction: int = 0,
    sport: int = 0,
    is_fragment: bool = False,
) -> dict:
    """Single-tuple datapath explain: rerun the fused pipeline's
    stage order host-side against the daemon's live state, emitting
    each stage's intermediate decision and the matching rules.

    Returns {"verdict", "allowed", "proxy_port", "stages": [{stage,
    decision, detail}], "rules": [{index, labels}], "text"} — the
    payload behind POST /policy/trace-tuple and
    `cilium-tpu policy trace-tuple`."""
    from cilium_tpu.ct.table import (
        CT_EGRESS,
        CT_ESTABLISHED,
        CT_INGRESS,
        CT_NEW,
        CT_RELATED,
        CT_REPLY,
        CTTuple,
    )
    from cilium_tpu.identity import RESERVED_WORLD
    from cilium_tpu.lb.service import L3n4Addr

    stages = []

    def stage(name, decision, detail):
        stages.append(
            {"stage": name, "decision": decision, "detail": detail}
        )

    saddr_u32 = _ip_u32(saddr)
    daddr_u32 = _ip_u32(daddr)
    dir_name = "ingress" if direction == 0 else "egress"

    endpoint = daemon.endpoint_manager.lookup(ep_id)
    if endpoint is None:
        raise KeyError(f"no endpoint {ep_id}")

    # -- 1. XDP prefilter ---------------------------------------------------
    pre_cidr, _ = _lpm_match(
        {c: 1 for c in daemon.prefilter.dump()}, saddr_u32
    )
    pre_drop = pre_cidr is not None
    stage(
        "prefilter",
        "DROP" if pre_drop else "pass",
        f"source in denied CIDR {pre_cidr}" if pre_drop
        else "source not in any denied CIDR",
    )

    # -- 2. LB service / DNAT (egress only) ---------------------------------
    eff_daddr, eff_dport = daddr_u32, int(dport)
    if direction != 0:
        frontend = L3n4Addr(
            str(ipaddress.IPv4Address(daddr_u32)), int(dport), proto
        )
        svc = daemon.services.lookup(frontend)
        if svc is not None and svc.backends:
            from cilium_tpu.engine.hostpath import lb_select_host

            slave, sticky = lb_select_host(
                daemon.ct, svc, saddr_u32, daddr_u32, sport, dport,
                proto,
            )
            backend = svc.backends[slave - 1]
            eff_daddr = backend.addr.ip_u32()
            eff_dport = backend.addr.port
            stage(
                "lb",
                "DNAT",
                f"service {frontend.ip}:{frontend.port} -> backend "
                f"{backend.addr.ip}:{backend.addr.port} "
                f"(slave {slave}, "
                f"{'CT-sticky' if sticky else 'hash-selected'})",
            )
        else:
            stage("lb", "pass", "destination is not a service VIP")
    else:
        stage("lb", "skip", "ingress flows do not traverse lb4_local")

    # -- 3. conntrack -------------------------------------------------------
    ct_res = daemon.ct.lookup(
        CTTuple(eff_daddr, saddr_u32, eff_dport, sport, proto),
        CT_INGRESS if direction == 0 else CT_EGRESS,
    )
    ct_name = {
        CT_NEW: "NEW",
        CT_ESTABLISHED: "ESTABLISHED",
        CT_REPLY: "REPLY",
        CT_RELATED: "RELATED",
    }[ct_res]
    stage("conntrack", ct_name, f"ct_lookup4 on the {dir_name} tuple")

    # -- 4. ipcache identity derivation -------------------------------------
    sec_ip = saddr_u32 if direction == 0 else eff_daddr
    prefix, sec_id = _lpm_match(
        dict(daemon.lpm_builder.mappings), sec_ip
    )
    if sec_id == 0:
        sec_id = RESERVED_WORLD
        stage(
            "ipcache",
            f"identity {sec_id}",
            "no ipcache entry — WORLD fallback",
        )
    else:
        stage(
            "ipcache",
            f"identity {sec_id}",
            f"LPM hit {prefix}",
        )

    # -- 5. policy lattice --------------------------------------------------
    state = endpoint.realized_map_state
    verdict, why = _explain_verdict(
        state, sec_id, eff_dport, proto, direction, is_fragment
    )
    allowed_pol = verdict.allowed
    stage("policy", "ALLOW" if allowed_pol else "DENY", why)

    # -- 6. combine (bpf_lxc.c:962-985) -------------------------------------
    pass_ct = ct_res in (CT_REPLY, CT_RELATED)
    allowed = (not pre_drop) and (pass_ct or allowed_pol)
    proxy_port = (
        verdict.proxy_port
        if allowed_pol
        and ct_res in (CT_NEW, CT_ESTABLISHED)
        and allowed
        else 0
    )
    if pre_drop:
        detail = "prefilter drop overrides everything"
    elif pass_ct and not allowed_pol:
        detail = f"{ct_name} flow bypasses the policy deny"
    elif proxy_port:
        detail = f"allowed, redirected to proxy port {proxy_port}"
    else:
        detail = "policy verdict stands"
    stage("combine", "ALLOW" if allowed else "DROP", detail)

    # -- rule attribution ---------------------------------------------------
    peer_labels = daemon.identity_cache().get(sec_id)
    ep_labels = (
        endpoint.security_identity.label_array
        if endpoint.security_identity is not None
        else None
    )
    rules = []
    if ep_labels is not None and verdict.match_kind != MATCH_NONE:
        rules = [
            {"index": i, "labels": labels}
            for i, labels in _matching_rules(
                daemon, ep_labels, peer_labels, sec_ip,
                eff_dport, proto, direction, verdict.match_kind,
            )
        ]

    lines = [
        f"Tracing {dir_name} tuple ep={ep_id} "
        f"{ipaddress.IPv4Address(saddr_u32)}:{sport} -> "
        f"{ipaddress.IPv4Address(daddr_u32)}:{dport} proto={proto}"
    ]
    for s in stages:
        lines.append(
            f"  [{s['stage']:>9}] {s['decision']}: {s['detail']}"
        )
    for r in rules:
        lines.append(
            f"  matched rule #{r['index']} labels={r['labels']}"
        )
    lines.append(
        f"Final verdict: {'ALLOWED' if allowed else 'DENIED'}"
    )
    return {
        "verdict": "allowed" if allowed else "denied",
        "allowed": allowed,
        "proxy_port": proxy_port,
        "match_kind": int(verdict.match_kind),
        "identity": int(sec_id),
        "stages": stages,
        "rules": rules,
        "text": "\n".join(lines) + "\n",
    }
