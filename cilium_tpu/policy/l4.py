"""L4 filters and policy maps.

Re-design of /root/reference/pkg/policy/l4.go.  An L4PolicyMap keyed by
"port/proto" is the host-side intermediate representation the compiler
lowers into dense per-endpoint filter tensors (port/proto arrays +
identity bitmask rows); see cilium_tpu.compiler.tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cilium_tpu.labels import LabelArray
from cilium_tpu.policy import api
from cilium_tpu.policy.api.rule import (
    L7Rules,
    PROTO_TCP,
    PortProtocol,
    PortRule,
    U8PROTO,
    l7rules_is_empty,
    l7rules_len,
)
from cilium_tpu.policy.api.selector import (
    EndpointSelector,
    WILDCARD_SELECTOR,
    selects_all_endpoints,
)
from cilium_tpu.policy.search import Decision, Port, SearchContext

# L7 parser types (l4.go:80-87)
PARSER_TYPE_NONE = ""
PARSER_TYPE_HTTP = "http"
PARSER_TYPE_KAFKA = "kafka"


class L7DataMap(dict):
    """selector -> L7Rules, keyed by selector identity (l4.go:31).

    The reference's map key is the EndpointSelector struct whose
    embedded pointers give pointer-equality keying; our selectors hash
    by object identity, matching that (see api.selector docstring).
    """

    def get_relevant_rules(self, identity_labels: Optional[LabelArray]) -> L7Rules:
        """l4.go:118: union of rules whose selector matches the identity,
        with wildcard-selector rules always appended."""
        rules = L7Rules(http=[], kafka=[], l7proto="", l7=[])
        if identity_labels is not None:
            # NB: the wildcard entry both matches in this loop and is
            # appended again below — reproducing the reference's
            # double-append quirk (l4.go:122-138) exactly.
            for selector, ep_rules in self.items():
                if selector.matches(identity_labels):
                    rules.http.extend(ep_rules.http or [])
                    rules.kafka.extend(ep_rules.kafka or [])
                    rules.l7proto = ep_rules.l7proto
                    rules.l7.extend(ep_rules.l7 or [])
        wild = self.get(WILDCARD_SELECTOR)
        if wild is not None:
            rules.http.extend(wild.http or [])
            rules.kafka.extend(wild.kafka or [])
            rules.l7proto = wild.l7proto
            rules.l7.extend(wild.l7 or [])
        return rules

    def add_rules_for_endpoints(self, rules: L7Rules,
                                endpoints: List[EndpointSelector]) -> None:
        """l4.go:143."""
        if l7rules_len(rules) == 0:
            return
        # Store a copy per key (Go stores struct copies by value,
        # l4.go:150-154) so later merge appends don't corrupt the
        # originating api.Rule or sibling keys.
        if endpoints:
            for epsel in endpoints:
                self[epsel] = rules.copy()
        else:
            self[WILDCARD_SELECTOR] = rules.copy()


@dataclass
class L4Filter:
    """l4.go:89: the per-(port,proto) allow filter."""

    port: int
    protocol: str
    u8proto: int
    endpoints: List[EndpointSelector] = field(default_factory=list)
    l7_parser: str = PARSER_TYPE_NONE
    l7_rules_per_ep: L7DataMap = field(default_factory=L7DataMap)
    ingress: bool = True
    derived_from_rules: List[LabelArray] = field(default_factory=list)

    def allows_all_at_l3(self) -> bool:
        """l4.go:112."""
        return selects_all_endpoints(self.endpoints)

    def is_redirect(self) -> bool:
        """l4.go:236."""
        return self.l7_parser != PARSER_TYPE_NONE

    def matches_labels(self, labels: Optional[LabelArray]) -> bool:
        """l4.go:258."""
        if self.allows_all_at_l3():
            return True
        if not labels:
            return False
        return any(sel.matches(labels) for sel in self.endpoints)


def create_l4_filter(
    peer_endpoints: List[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
    ingress: bool,
) -> L4Filter:
    """l4.go:162."""
    p = port.numeric_port()
    u8p = U8PROTO.get(protocol, 0)

    filter_endpoints = peer_endpoints
    if selects_all_endpoints(peer_endpoints):
        filter_endpoints = [WILDCARD_SELECTOR]

    l4 = L4Filter(
        port=p,
        protocol=protocol,
        u8proto=u8p,
        endpoints=list(filter_endpoints),
        derived_from_rules=[rule_labels],
        ingress=ingress,
    )

    if protocol == PROTO_TCP and rule.rules is not None:
        if rule.rules.http:
            l4.l7_parser = PARSER_TYPE_HTTP
        elif rule.rules.kafka:
            l4.l7_parser = PARSER_TYPE_KAFKA
        elif rule.rules.l7proto != "":
            l4.l7_parser = rule.rules.l7proto
        if not l7rules_is_empty(rule.rules):
            l4.l7_rules_per_ep.add_rules_for_endpoints(
                rule.rules, list(filter_endpoints)
            )
    return l4


def create_l4_ingress_filter(
    from_endpoints: List[EndpointSelector],
    endpoints_with_l3_override: List[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
) -> L4Filter:
    """l4.go:209: host/world L3 overrides become L7 allow-all."""
    f = create_l4_filter(
        from_endpoints, rule, port, protocol, rule_labels, True
    )
    if not l7rules_is_empty(rule.rules):
        for selector in endpoints_with_l3_override:
            f.l7_rules_per_ep[selector] = L7Rules()
    return f


def create_l4_egress_filter(
    to_endpoints: List[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
) -> L4Filter:
    """l4.go:229."""
    return create_l4_filter(
        to_endpoints, rule, port, protocol, rule_labels, False
    )


class L4PolicyMap(dict):
    """"port/proto" -> L4Filter (l4.go:276)."""

    def has_redirect(self) -> bool:
        return any(f.is_redirect() for f in self.values())

    def contains_all_l3l4(self, labels: Optional[LabelArray],
                          ports: List[Port]) -> Decision:
        """l4.go:300: the L4 coverage verdict."""
        if len(self) == 0:
            return Decision.ALLOWED
        if len(ports) == 0:
            return Decision.DENIED
        for l4ctx in ports:
            proto = l4ctx.protocol
            if proto in ("", "ANY"):
                tcp_filter = self.get(f"{l4ctx.port}/TCP")
                tcp_match = tcp_filter is not None and tcp_filter.matches_labels(labels)
                udp_filter = self.get(f"{l4ctx.port}/UDP")
                udp_match = udp_filter is not None and udp_filter.matches_labels(labels)
                if not tcp_match and not udp_match:
                    return Decision.DENIED
            else:
                f = self.get(f"{l4ctx.port}/{proto}")
                if f is None or not f.matches_labels(labels):
                    return Decision.DENIED
        return Decision.ALLOWED

    def ingress_covers_context(self, ctx: SearchContext) -> Decision:
        """l4.go:355."""
        return self.contains_all_l3l4(ctx.from_labels, ctx.dports)

    def egress_covers_context(self, ctx: SearchContext) -> Decision:
        """l4.go:361."""
        return self.contains_all_l3l4(ctx.to_labels, ctx.dports)


@dataclass
class L4Policy:
    """l4.go:337."""

    ingress: L4PolicyMap = field(default_factory=L4PolicyMap)
    egress: L4PolicyMap = field(default_factory=L4PolicyMap)
    revision: int = 0

    def has_redirect(self) -> bool:
        return self.ingress.has_redirect() or self.egress.has_redirect()

    def requires_conntrack(self) -> bool:
        return len(self.ingress) > 0 or len(self.egress) > 0


def proxy_id(endpoint_id: int, ingress: bool, protocol: str, port: int) -> str:
    """proxyid.go: unique redirect key."""
    direction = "ingress" if ingress else "egress"
    return f"{endpoint_id}:{direction}:{protocol}:{port}"


def parse_proxy_id(pid: str):
    comps = pid.split(":")
    if len(comps) != 4:
        raise ValueError(f"invalid proxy ID structure: {pid}")
    return int(comps[0]), comps[1] == "ingress", comps[2], int(comps[3])
