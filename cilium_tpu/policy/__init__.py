"""Policy core: rule model, repository, resolution."""

from cilium_tpu.policy.search import Decision, Port, SearchContext, Tracing  # noqa: F401
