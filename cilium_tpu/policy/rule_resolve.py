"""Per-rule resolution: label verdicts, L4 merge, CIDR merge.

Re-design of /root/reference/pkg/policy/rule.go.  All the precedence
subtleties live here:

  * FromRequires/ToRequires deny-precedence: an unmet Requires denies
    immediately and overrides any Allow (rule.go:352-391, 399-440);
  * L3-only match => Allowed, ToPorts present => defer to L4
    (rule.go:374-389);
  * per-(port,proto) L4 merge with wildcard-L3 absorption and
    L7-parser/type conflict errors (rule.go:36-109);
  * ANY protocol expanding to TCP+UDP (rule.go:191-210);
  * requirements injection into FromEndpoints/ToEndpoints
    (rule.go:247-257, 541-551).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from cilium_tpu import option
from cilium_tpu import labels as lbl
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api.rule import (
    EgressRule,
    IngressRule,
    PROTO_ANY,
    PROTO_TCP,
    PROTO_UDP,
    PortRule,
    PortRuleKafka,
    L7Rules,
    Rule,
    compute_resultant_cidr_set,
    l7rules_is_empty,
)
from cilium_tpu.policy.api.selector import (
    EndpointSelector,
    RESERVED_ENDPOINT_SELECTORS,
    Requirement,
    WILDCARD_SELECTOR,
    slice_matches,
)
from cilium_tpu.policy.l3 import CIDRPolicy, CIDRPolicyMap
from cilium_tpu.policy.l4 import (
    L4Filter,
    L4Policy,
    L4PolicyMap,
    PARSER_TYPE_HTTP,
    PARSER_TYPE_KAFKA,
    PARSER_TYPE_NONE,
    create_l4_egress_filter,
    create_l4_ingress_filter,
)
from cilium_tpu.policy.search import Decision, SearchContext


class L4MergeError(ValueError):
    """L7 parser/type merge conflict (rule.go:57,67)."""


class TraceState:
    """repository.go:51."""

    def __init__(self):
        self.selected_rules = 0
        self.matched_rules = 0
        self.constrained_rules = 0
        self.rule_id = 0

    def select_rule(self, ctx: SearchContext, r: "PolicyRule") -> None:
        ctx.policy_trace("* Rule %s: selected\n", r)
        self.selected_rules += 1

    def unselect_rule(self, ctx: SearchContext, labels, r: "PolicyRule") -> None:
        ctx.policy_trace_verbose(
            "  Rule %s: did not select %+s\n", r, labels
        )


def _merge_l4_port_shared(
    ctx: SearchContext,
    endpoints: List[EndpointSelector],
    existing: L4Filter,
    to_merge: L4Filter,
) -> None:
    """mergeL4Port (rule.go:36): merge to_merge into existing."""
    # Case 1: either side allows all at L3 -> collapse to wildcard.
    if existing.allows_all_at_l3() or to_merge.allows_all_at_l3():
        existing.endpoints = [WILDCARD_SELECTOR]
    else:
        existing.endpoints = existing.endpoints + list(endpoints)

    if to_merge.l7_parser != PARSER_TYPE_NONE:
        if existing.l7_parser == PARSER_TYPE_NONE:
            existing.l7_parser = to_merge.l7_parser
        elif to_merge.l7_parser != existing.l7_parser:
            ctx.policy_trace(
                "   Merge conflict: mismatching parsers %s/%s\n",
                to_merge.l7_parser, existing.l7_parser,
            )
            raise L4MergeError(
                f"Cannot merge conflicting L7 parsers "
                f"({to_merge.l7_parser}/{existing.l7_parser})"
            )

    for sel, new_rules in to_merge.l7_rules_per_ep.items():
        ep = existing.l7_rules_per_ep.get(sel)
        if ep is None:
            existing.l7_rules_per_ep[sel] = new_rules.copy()
            continue
        if new_rules.http:
            if (ep.kafka and len(ep.kafka) > 0) or ep.l7proto != "":
                ctx.policy_trace(
                    "   Merge conflict: mismatching L7 rule types.\n"
                )
                raise L4MergeError("Cannot merge conflicting L7 rule types")
            if ep.http is None:
                ep.http = []
            for nr in new_rules.http:
                if not nr.exists(ep):
                    ep.http.append(nr)
        elif new_rules.kafka:
            if (ep.http and len(ep.http) > 0) or ep.l7proto != "":
                ctx.policy_trace(
                    "   Merge conflict: mismatching L7 rule types.\n"
                )
                raise L4MergeError("Cannot merge conflicting L7 rule types")
            if ep.kafka is None:
                ep.kafka = []
            for nr in new_rules.kafka:
                if not nr.exists(ep):
                    ep.kafka.append(nr)
        elif new_rules.l7proto != "":
            if (
                (ep.kafka and len(ep.kafka) > 0)
                or (ep.http and len(ep.http) > 0)
                or (ep.l7proto != "" and ep.l7proto != new_rules.l7proto)
            ):
                ctx.policy_trace(
                    "   Merge conflict: mismatching L7 rule types.\n"
                )
                raise L4MergeError("Cannot merge conflicting L7 rule types")
            if ep.l7proto == "":
                ep.l7proto = new_rules.l7proto
            if ep.l7 is None:
                ep.l7 = []
            for nr in new_rules.l7 or []:
                if not nr.exists(ep):
                    ep.l7.append(nr)
        else:
            ctx.policy_trace("   No L7 rules to merge.\n")
        existing.l7_rules_per_ep[sel] = ep


def merge_l4_ingress_port(
    ctx: SearchContext,
    endpoints: List[EndpointSelector],
    endpoints_with_l3_override: List[EndpointSelector],
    r: PortRule,
    p,  # PortProtocol
    proto: str,
    rule_labels: LabelArray,
    res_map: L4PolicyMap,
) -> int:
    """rule.go:121."""
    key = f"{p.port}/{proto}"
    existing = res_map.get(key)
    if existing is None:
        res_map[key] = create_l4_ingress_filter(
            endpoints, endpoints_with_l3_override, r, p, proto, rule_labels
        )
        return 1
    to_merge = create_l4_ingress_filter(
        endpoints, endpoints_with_l3_override, r, p, proto, rule_labels
    )
    _merge_l4_port_shared(ctx, endpoints, existing, to_merge)
    existing.derived_from_rules.append(rule_labels)
    res_map[key] = existing
    return 1


def merge_l4_egress_port(
    ctx: SearchContext,
    endpoints: List[EndpointSelector],
    r: PortRule,
    p,
    proto: str,
    rule_labels: LabelArray,
    res_map: L4PolicyMap,
) -> int:
    """rule.go:499."""
    key = f"{p.port}/{proto}"
    existing = res_map.get(key)
    if existing is None:
        res_map[key] = create_l4_egress_filter(
            endpoints, r, p, proto, rule_labels
        )
        return 1
    to_merge = create_l4_egress_filter(endpoints, r, p, proto, rule_labels)
    _merge_l4_port_shared(ctx, endpoints, existing, to_merge)
    existing.derived_from_rules.append(rule_labels)
    res_map[key] = existing
    return 1


def _l3_override_endpoints() -> List[EndpointSelector]:
    """rule.go:166-172: daemon options may force host/world L3 allows."""
    out: List[EndpointSelector] = []
    if option.Config.always_allow_localhost():
        out.append(RESERVED_ENDPOINT_SELECTORS[lbl.ID_NAME_HOST])
        if option.Config.host_allows_world:
            out.append(RESERVED_ENDPOINT_SELECTORS[lbl.ID_NAME_WORLD])
    return out


def merge_l4_ingress(
    ctx: SearchContext,
    rule: IngressRule,
    rule_labels: LabelArray,
    res_map: L4PolicyMap,
) -> int:
    """rule.go:143."""
    if len(rule.to_ports) == 0:
        ctx.policy_trace("    No L4 %s rules\n", "Ingress")
        return 0

    from_endpoints = rule.get_source_endpoint_selectors()
    found = 0

    if ctx.from_labels is not None and len(from_endpoints) > 0:
        if not slice_matches(from_endpoints, ctx.from_labels):
            ctx.policy_trace("    Labels %s not found", ctx.from_labels)
            return 0

    ctx.policy_trace("    Found all required labels")

    endpoints_with_l3_override = _l3_override_endpoints()

    for r in rule.to_ports:
        ctx.policy_trace(
            "    Allows %s port %s from endpoints %s\n",
            "Ingress", [ (p.port, p.protocol) for p in r.ports], from_endpoints,
        )
        for p in r.ports:
            if p.protocol != PROTO_ANY:
                found += merge_l4_ingress_port(
                    ctx, from_endpoints, endpoints_with_l3_override,
                    r, p, p.protocol, rule_labels, res_map,
                )
            else:
                found += merge_l4_ingress_port(
                    ctx, from_endpoints, endpoints_with_l3_override,
                    r, p, PROTO_TCP, rule_labels, res_map,
                )
                found += merge_l4_ingress_port(
                    ctx, from_endpoints, endpoints_with_l3_override,
                    r, p, PROTO_UDP, rule_labels, res_map,
                )
    return found


def merge_l4_egress(
    ctx: SearchContext,
    rule: EgressRule,
    rule_labels: LabelArray,
    res_map: L4PolicyMap,
) -> int:
    """rule.go:442."""
    if len(rule.to_ports) == 0:
        ctx.policy_trace("    No L4 %s rules\n", "Egress")
        return 0

    to_endpoints = rule.get_destination_endpoint_selectors()
    found = 0

    for r in rule.to_ports:
        ctx.policy_trace(
            "    Allows %s port %s to endpoints %s\n",
            "Egress", [(p.port, p.protocol) for p in r.ports], to_endpoints,
        )
        for p in r.ports:
            if p.protocol != PROTO_ANY:
                found += merge_l4_egress_port(
                    ctx, to_endpoints, r, p, p.protocol, rule_labels, res_map
                )
            else:
                found += merge_l4_egress_port(
                    ctx, to_endpoints, r, p, PROTO_TCP, rule_labels, res_map
                )
                found += merge_l4_egress_port(
                    ctx, to_endpoints, r, p, PROTO_UDP, rule_labels, res_map
                )
    return found


class PolicyRule:
    """pkg/policy rule (rule.go:28): an api.Rule inside the repository."""

    def __init__(self, rule: Rule):
        self.rule = rule

    @property
    def endpoint_selector(self) -> EndpointSelector:
        return self.rule.endpoint_selector

    @property
    def labels(self) -> LabelArray:
        return self.rule.labels

    def __str__(self) -> str:
        return repr(self.rule.endpoint_selector)

    # -- label-level verdicts (rule.go:352,399) -----------------------------

    def can_reach_ingress(self, ctx: SearchContext,
                          state: TraceState) -> Decision:
        if not self.endpoint_selector.matches(ctx.to_labels):
            state.unselect_rule(ctx, ctx.to_labels, self)
            return Decision.UNDECIDED

        state.select_rule(ctx, self)
        for r in self.rule.ingress:
            for sel in r.from_requires:
                ctx.policy_trace("    Requires from labels %+s", sel)
                if not sel.matches(ctx.from_labels):
                    ctx.policy_trace(
                        "-     Labels %s not found\n", ctx.from_labels
                    )
                    state.constrained_rules += 1
                    return Decision.DENIED
                ctx.policy_trace("+     Found all required labels\n")

        # Separate loop: FromRequires failure takes precedence.
        for r in self.rule.ingress:
            for sel in r.get_source_endpoint_selectors():
                ctx.policy_trace("    Allows from labels %+s", sel)
                if sel.matches(ctx.from_labels):
                    ctx.policy_trace("      Found all required labels")
                    if len(r.to_ports) == 0:
                        ctx.policy_trace("+       No L4 restrictions\n")
                        state.matched_rules += 1
                        return Decision.ALLOWED
                    ctx.policy_trace(
                        "        Rule restricts traffic to specific L4 "
                        "destinations; deferring policy decision to L4 "
                        "policy stage\n"
                    )
                else:
                    ctx.policy_trace(
                        "      Labels %s not found\n", ctx.from_labels
                    )
        return Decision.UNDECIDED

    def can_reach_egress(self, ctx: SearchContext,
                         state: TraceState) -> Decision:
        if not self.endpoint_selector.matches(ctx.from_labels):
            state.unselect_rule(ctx, ctx.from_labels, self)
            return Decision.UNDECIDED

        state.select_rule(ctx, self)
        for r in self.rule.egress:
            for sel in r.to_requires:
                ctx.policy_trace("    Requires from labels %+s", sel)
                if not sel.matches(ctx.to_labels):
                    ctx.policy_trace(
                        "-     Labels %s not found\n", ctx.to_labels
                    )
                    state.constrained_rules += 1
                    return Decision.DENIED
                ctx.policy_trace("+     Found all required labels\n")

        for r in self.rule.egress:
            for sel in r.get_destination_endpoint_selectors():
                ctx.policy_trace("    Allows to labels %+s", sel)
                if sel.matches(ctx.to_labels):
                    ctx.policy_trace("      Found all required labels")
                    if len(r.to_ports) == 0:
                        ctx.policy_trace("+       No L4 restrictions\n")
                        state.matched_rules += 1
                        return Decision.ALLOWED
                    ctx.policy_trace(
                        "        Rule restricts traffic from specific L4 "
                        "destinations; deferring policy decision to L4 "
                        "policy stage\n"
                    )
                else:
                    ctx.policy_trace(
                        "      Labels %s not found\n", ctx.to_labels
                    )
        return Decision.UNDECIDED

    # -- L4 resolution (rule.go:227,521) ------------------------------------

    def resolve_l4_ingress_policy(
        self,
        ctx: SearchContext,
        state: TraceState,
        result: L4Policy,
        requirements: List[Requirement],
    ) -> Optional[L4Policy]:
        if not self.endpoint_selector.matches(ctx.to_labels):
            state.unselect_rule(ctx, ctx.to_labels, self)
            return None

        state.select_rule(ctx, self)
        found = 0

        if len(self.rule.ingress) == 0:
            ctx.policy_trace("    No L4 ingress rules\n")
        for ingress_rule in self.rule.ingress:
            rule_copy = ingress_rule
            if requirements:
                rule_copy = ingress_rule.deep_copy()
                rule_copy.from_endpoints = [
                    s.add_requirements(requirements)
                    for s in rule_copy.from_endpoints
                ]
            cnt = merge_l4_ingress(
                ctx, rule_copy, LabelArray(self.rule.labels), result.ingress
            )
            if cnt > 0:
                found += cnt
        return result if found > 0 else None

    def resolve_l4_egress_policy(
        self,
        ctx: SearchContext,
        state: TraceState,
        result: L4Policy,
        requirements: List[Requirement],
    ) -> Optional[L4Policy]:
        if not self.endpoint_selector.matches(ctx.from_labels):
            state.unselect_rule(ctx, ctx.from_labels, self)
            return None

        state.select_rule(ctx, self)
        found = 0

        if len(self.rule.egress) == 0:
            ctx.policy_trace("    No L4 rules\n")
        for egress_rule in self.rule.egress:
            rule_copy = egress_rule
            if requirements:
                rule_copy = egress_rule.deep_copy()
                rule_copy.to_endpoints = [
                    s.add_requirements(requirements)
                    for s in rule_copy.to_endpoints
                ]
            cnt = merge_l4_egress(
                ctx, rule_copy, LabelArray(self.rule.labels), result.egress
            )
            if cnt > 0:
                found += cnt
        return result if found > 0 else None

    # -- CIDR resolution (rule.go:296) --------------------------------------

    def resolve_cidr_policy(
        self, ctx: SearchContext, state: TraceState, result: CIDRPolicy
    ) -> Optional[CIDRPolicy]:
        if not self.endpoint_selector.matches(ctx.to_labels):
            state.unselect_rule(ctx, ctx.to_labels, self)
            return None

        state.select_rule(ctx, self)
        found = 0

        for ingress_rule in self.rule.ingress:
            all_cidrs = list(ingress_rule.from_cidr)
            all_cidrs.extend(
                compute_resultant_cidr_set(ingress_rule.from_cidr_set)
            )
            # CIDR+L4 handled via merge_l4_ingress; skip here (rule.go:314).
            if all_cidrs and len(ingress_rule.to_ports) > 0:
                continue
            found += _merge_cidr(
                ctx, "Ingress", all_cidrs, self.rule.labels, result.ingress
            )

        # Egress counts CIDR+L4 too, for prefix-length accounting
        # (rule.go:327-339).
        for egress_rule in self.rule.egress:
            all_cidrs = list(egress_rule.to_cidr)
            all_cidrs.extend(
                compute_resultant_cidr_set(egress_rule.to_cidr_set)
            )
            found += _merge_cidr(
                ctx, "Egress", all_cidrs, self.rule.labels, result.egress
            )

        if found > 0:
            return result
        ctx.policy_trace("    No L3 rules\n")
        return None


def _merge_cidr(
    ctx: SearchContext,
    direction: str,
    ip_rules: List[str],
    rule_labels: LabelArray,
    res_map: CIDRPolicyMap,
) -> int:
    """rule.go:279."""
    found = 0
    for r in ip_rules:
        ctx.policy_trace("  Allows %s IP %s\n", direction, r)
        found += res_map.insert(r, rule_labels)
    return found
