"""Live performance plane: continuous hot-path self-profiling.

Every perf win since the hot/cold split is validated OFFLINE by
bench.py and the gatherprof byte model; in production the agent was
blind to its own hot path.  This module is the always-on counterpart:
a low-overhead observability layer riding the existing dispatch seams
— nothing here adds a kernel, a sync, or a lock on the device path.

  * **Phase windows.**  Per coalesced batch, the serve loop feeds the
    pack / dispatch-enqueue / drain / fold / wall durations (lifted
    from AsyncBatchDispatcher's overlap bookkeeping plus the
    drain-side fold timing) into decaying windowed histograms: exact
    nearest-rank p50/p99/max over the last `window` batches AND the
    last `horizon_s` seconds, whichever is smaller — an idle plane's
    stale tail decays out instead of haunting the gauges.

  * **Ingest-starvation detector.**  Wall time the serve loop spends
    waiting with a NONEMPTY queue while NOTHING is in flight on the
    device accumulates into `cilium_serve_ingest_stall_seconds_total`
    — the line-rate-ingest item's headline symptom (the device idles
    because the host trickle-feeds it, not because there is no work).

  * **SLO compliance.**  Per tenant, deadline hit/miss counters plus
    an error-budget burn rate: the windowed miss fraction over the
    class's allowed miss fraction (1 - `objective`, default 0.99) —
    burn > 1 means the tenant is eating budget faster than its class
    allows.

  * **Live byte model.**  The gatherprof/autotune model evaluated
    against the PUBLISHED layout stamp and the OBSERVED cache-hit /
    dedup factors (Daemon.perf_snapshot assembles it): effective
    bytes-per-tuple and modeled GB/s as gauges, per-leaf breakdown on
    demand.

  * **Retune history.**  `engine.autotune.online_retune` records
    every layout swap here (trigger, knobs moved, layout stamps
    before/after) — the `/debug/perf` since-cursor surface replays
    what changed and why.

Everything windowed is exported to Prometheus at a bounded cadence
(every `EXPORT_EVERY` batches + at snapshot time), and the plane
accounts its OWN bookkeeping seconds (`overhead_s`) so bench's
`perfplane_overhead_pct` gate is measured inside the instrumented
loop, the tracing_overhead_pct discipline.

Simulation boundary: on this container the "device" is XLA's CPU
backend — absolute phase durations and modeled GB/s are only
meaningful on real hardware; the tier-1 suite pins the semantics
(window math, reset, stall accounting, SLO ledger, snapshot shape,
and that the plane's numbers agree with a harness's own wall clock).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from cilium_tpu.metrics import registry as metrics

# serve-loop phases, in pipeline order.  "device" is the observable
# device-side lower bound (enqueue + drain block); true device-busy
# needs the overlap aggregates (a per-batch sync would cost the very
# overlap this plane observes).
PHASES = ("pack", "dispatch", "drain", "device", "fold", "wall")

EXPORT_EVERY = 16  # batches between Prometheus gauge pushes

_STATS = ("p50", "p99", "max")


class PhaseWindow:
    """Decaying window of raw observations: bounded by COUNT
    (`maxlen` most recent) and by AGE (`horizon_s`) — quantiles are
    exact nearest-rank over what survives both bounds."""

    __slots__ = (
        "_obs", "horizon_s", "count", "total", "lifetime_max",
    )

    def __init__(
        self, maxlen: int = 512, horizon_s: float = 60.0
    ) -> None:
        self._obs: deque = deque(maxlen=maxlen)  # (t, value)
        self.horizon_s = float(horizon_s)
        self.count = 0  # lifetime observations (survives decay)
        self.total = 0.0
        self.lifetime_max = 0.0

    def observe(self, value: float, now: float) -> None:
        self._obs.append((now, value))
        self.count += 1
        self.total += value
        if value > self.lifetime_max:
            self.lifetime_max = value

    def _prune(self, now: float) -> None:
        floor = now - self.horizon_s
        obs = self._obs
        while obs and obs[0][0] < floor:
            obs.popleft()

    def values(self, now: float) -> List[float]:
        self._prune(now)
        return [v for _, v in self._obs]

    def stats(self, now: float) -> Dict[str, float]:
        """{"p50", "p99", "max", "n"} over the decayed window
        (nearest-rank, the WindowedHistogram/quantile_ms estimator)."""
        vals = sorted(self.values(now))
        if not vals:
            return {"p50": 0.0, "p99": 0.0, "max": 0.0, "n": 0}

        def q(p: float) -> float:
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return {
            "p50": q(0.50),
            "p99": q(0.99),
            "max": vals[-1],
            "n": len(vals),
        }

    def reset(self) -> None:
        self._obs.clear()


class PerfPlane:
    """The daemon's always-on performance plane.  One instance per
    daemon; the serving plane feeds it per batch, the autotuner's
    online re-tune loop reads it for drift and writes its history
    back.  All methods are thread-safe and self-account their cost
    into `overhead_s`."""

    def __init__(
        self, window: int = 512, horizon_s: float = 60.0
    ) -> None:
        self._lock = threading.Lock()
        self.window = int(window)
        self.horizon_s = float(horizon_s)
        self.phases: Dict[str, PhaseWindow] = {
            p: PhaseWindow(window, horizon_s) for p in PHASES
        }
        self.fill = PhaseWindow(window, horizon_s)
        self.queue_delay = PhaseWindow(window, horizon_s)
        # ingest-starvation accumulator: (t, waited) pairs for the
        # windowed fraction + a lifetime total mirroring the counter
        self._stalls = PhaseWindow(window * 4, horizon_s)
        self.stall_seconds_total = 0.0
        # per-tenant SLO ledger: {tenant: {"slo_class", "hits",
        # "misses", "window": deque of 0/1 misses, "objective"}}
        self._slo: Dict[str, dict] = {}
        # monotone batch cursor: the /debug/perf since-cursor —
        # bumps once per observed batch
        self.seq = 0
        self.overhead_s = 0.0
        # throughput: EWMA of valid-tuples/batch-wall (the modeled
        # GB/s multiplier)
        self._vps_ewma: Optional[float] = None
        # retune plumbing (engine.autotune.online_retune)
        self.retunes: deque = deque(maxlen=64)
        self.baseline_p99_ms: Optional[float] = None
        self.last_retune_monotonic: Optional[float] = None
        self.batches_at_retune = 0

    # -- feeding (serve loop) -------------------------------------------------

    def observe_batch(
        self,
        *,
        pack_s: float = 0.0,
        dispatch_s: float = 0.0,
        drain_s: float = 0.0,
        fold_s: float = 0.0,
        wall_s: float = 0.0,
        fill_pct: float = 0.0,
        valid: int = 0,
    ) -> None:
        t0 = time.perf_counter()
        now = time.monotonic()
        with self._lock:
            ph = self.phases
            ph["pack"].observe(pack_s, now)
            ph["dispatch"].observe(dispatch_s, now)
            ph["drain"].observe(drain_s, now)
            ph["device"].observe(dispatch_s + drain_s, now)
            ph["fold"].observe(fold_s, now)
            ph["wall"].observe(wall_s, now)
            self.fill.observe(fill_pct, now)
            if wall_s > 0 and valid > 0:
                vps = valid / wall_s
                self._vps_ewma = (
                    vps
                    if self._vps_ewma is None
                    else 0.8 * self._vps_ewma + 0.2 * vps
                )
            self.seq += 1
            export = self.seq % EXPORT_EVERY == 0
        if export:
            self.export_gauges()
        self.overhead_s += time.perf_counter() - t0

    def observe_queue_delay(self, delay_s: float) -> None:
        t0 = time.perf_counter()
        now = time.monotonic()
        with self._lock:
            self.queue_delay.observe(delay_s, now)
        self.overhead_s += time.perf_counter() - t0

    def note_stall(self, waited_s: float) -> None:
        """Device-idle-while-queue-nonempty wall time (the serve
        loop's coalescing wait with nothing in flight)."""
        if waited_s <= 0:
            return
        t0 = time.perf_counter()
        now = time.monotonic()
        with self._lock:
            self._stalls.observe(waited_s, now)
            self.stall_seconds_total += waited_s
        metrics.serve_ingest_stall_seconds.inc(value=waited_s)
        self.overhead_s += time.perf_counter() - t0

    def note_deadline(
        self,
        tenant: str,
        slo_class: Optional[str],
        hit: bool,
        objective: float = 0.99,
    ) -> None:
        """One completed submission's deadline outcome, against the
        PR 15 slo_classes assignment."""
        t0 = time.perf_counter()
        cls = slo_class or "default"
        with self._lock:
            row = self._slo.get(tenant)
            if row is None:
                row = self._slo[tenant] = {
                    "slo_class": cls,
                    "hits": 0,
                    "misses": 0,
                    "objective": float(objective),
                    "window": deque(maxlen=256),
                }
            row["slo_class"] = cls
            row["objective"] = float(objective)
            if hit:
                row["hits"] += 1
            else:
                row["misses"] += 1
            row["window"].append(0 if hit else 1)
        metrics.serve_slo_deadline_total.inc(
            tenant, cls, "hit" if hit else "miss"
        )
        self.overhead_s += time.perf_counter() - t0

    def note_retune(self, record: dict) -> dict:
        """Append one online re-tune to the history (the since-cursor
        surface) and re-baseline the drift detector at the post-swap
        window."""
        with self._lock:
            record = dict(record)
            record["seq"] = self.seq
            self.retunes.append(record)
            self.last_retune_monotonic = time.monotonic()
            self.batches_at_retune = self.seq
            self.baseline_p99_ms = None  # re-learn after the swap
        return record

    # -- reading --------------------------------------------------------------

    def stall_fraction(self, now: Optional[float] = None) -> float:
        """Stalled fraction of the decay horizon: windowed stall
        seconds over `horizon_s` (1.0 = the device sat idle with a
        nonempty queue for the whole window)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            stalled = sum(self._stalls.values(now))
        return min(1.0, stalled / self.horizon_s)

    def verdicts_per_sec(self) -> float:
        with self._lock:
            return float(self._vps_ewma or 0.0)

    def slo_burn(self, tenant: str) -> float:
        """Error-budget burn rate: windowed miss fraction over the
        class's allowed miss fraction (1 - objective).  > 1.0 = the
        tenant burns budget faster than its SLO class allows."""
        with self._lock:
            row = self._slo.get(tenant)
            if row is None or not row["window"]:
                return 0.0
            miss_rate = sum(row["window"]) / len(row["window"])
            budget = max(1.0 - row["objective"], 1e-9)
        return miss_rate / budget

    def export_gauges(self) -> None:
        """Push every windowed quantile to the Prometheus registry
        (bounded cadence: EXPORT_EVERY batches + snapshot time)."""
        now = time.monotonic()
        with self._lock:
            phase_stats = {
                p: w.stats(now) for p, w in self.phases.items()
            }
            fill_stats = self.fill.stats(now)
            delay_stats = self.queue_delay.stats(now)
            tenants = list(self._slo)
        for p, st in phase_stats.items():
            for stat in _STATS:
                metrics.serve_phase_seconds.set(
                    p, stat, value=st[stat]
                )
        for stat in _STATS:
            metrics.serve_batch_fill_window_pct.set(
                stat, value=fill_stats[stat]
            )
            metrics.serve_queue_delay_window_seconds.set(
                stat, value=delay_stats[stat]
            )
        for tenant in tenants:
            metrics.serve_slo_error_budget_burn.set(
                tenant, value=self.slo_burn(tenant)
            )

    def snapshot(self, since: Optional[int] = None) -> dict:
        """The plane's own state (the daemon layers the byte model /
        HBM / serving snapshot on top — Daemon.perf_snapshot).  With
        `since` (a previously returned `cursor`), `retunes` holds
        only the swaps that landed after it."""
        self.export_gauges()
        now = time.monotonic()
        with self._lock:
            phases = {
                p: {
                    **{
                        k: (v * 1000.0 if k != "n" else v)
                        for k, v in w.stats(now).items()
                    },
                    "total_s": w.total,
                    "count": w.count,
                }
                for p, w in self.phases.items()
            }
            fill = self.fill.stats(now)
            delay = self.queue_delay.stats(now)
            retunes = [
                dict(r)
                for r in self.retunes
                if since is None or r["seq"] > int(since)
            ]
            slo = {
                t: {
                    "slo_class": row["slo_class"],
                    "hits": row["hits"],
                    "misses": row["misses"],
                    "objective": row["objective"],
                }
                for t, row in self._slo.items()
            }
            cursor = self.seq
            overhead = self.overhead_s
            stall_total = self.stall_seconds_total
        for t in slo:
            slo[t]["error_budget_burn"] = self.slo_burn(t)
        return {
            "cursor": cursor,
            "window": self.window,
            "horizon_s": self.horizon_s,
            # phase quantiles in ms (the `top` view's unit); totals
            # in seconds for wall-clock agreement checks
            "phases_ms": phases,
            "batch_fill_pct": fill,
            "queue_delay_ms": {
                k: (v * 1000.0 if k != "n" else v)
                for k, v in delay.items()
            },
            "stall": {
                "seconds_total": stall_total,
                "fraction": self.stall_fraction(now),
            },
            "slo": slo,
            "verdicts_per_sec_ewma": self.verdicts_per_sec(),
            "retunes": retunes,
            "baseline_p99_ms": self.baseline_p99_ms,
            "overhead_s": overhead,
        }

    def reset(self) -> None:
        """The /debug/profile?reset=1 seam: clear every decaying
        window (phases, fill, queue delay, stall fraction, SLO burn
        windows) so before/after experiments don't bleed.  Lifetime
        counters and the retune history survive — they are counters,
        not windows."""
        with self._lock:
            for w in self.phases.values():
                w.reset()
            self.fill.reset()
            self.queue_delay.reset()
            self._stalls.reset()
            for row in self._slo.values():
                row["window"].clear()
            self.baseline_p99_ms = None
        self.export_gauges()


# ---------------------------------------------------------------------------
# `cilium-tpu top` rendering (shared by the CLI and bugtool)
# ---------------------------------------------------------------------------


def render_top(snap: dict) -> str:
    """One terminal frame of the live view: phase breakdown, batch
    fill, tenant SLO burn, stall fraction, modeled bytes.  Pure
    text — the CLI owns the clear-screen escapes."""
    lines: List[str] = []
    serving = snap.get("serving") or {}
    model = snap.get("byte_model") or {}
    lines.append(
        "cilium-tpu top — cursor {cursor}  batches {batches}  "
        "serving_p99 {p99:.2f} ms  vps {vps:,.0f}".format(
            cursor=snap.get("cursor", 0),
            batches=serving.get("batches", 0),
            p99=serving.get("serving_p99_ms", 0.0),
            vps=snap.get("verdicts_per_sec_ewma", 0.0),
        )
    )
    lines.append("")
    lines.append(
        f"{'phase':<10s} {'p50 ms':>10s} {'p99 ms':>10s} "
        f"{'max ms':>10s} {'n':>6s}"
    )
    for p in PHASES:
        st = (snap.get("phases_ms") or {}).get(p) or {}
        lines.append(
            f"{p:<10s} {st.get('p50', 0.0):>10.3f} "
            f"{st.get('p99', 0.0):>10.3f} "
            f"{st.get('max', 0.0):>10.3f} "
            f"{st.get('n', 0):>6d}"
        )
    fill = snap.get("batch_fill_pct") or {}
    delay = snap.get("queue_delay_ms") or {}
    stall = snap.get("stall") or {}
    lines.append("")
    lines.append(
        "batch fill   p50 {p50:6.1f}%  p99 {p99:6.1f}%".format(
            p50=fill.get("p50", 0.0), p99=fill.get("p99", 0.0)
        )
    )
    lines.append(
        "queue delay  p50 {p50:6.2f} ms  p99 {p99:6.2f} ms".format(
            p50=delay.get("p50", 0.0), p99=delay.get("p99", 0.0)
        )
    )
    lines.append(
        "ingest stall {tot:8.3f} s total   {frac:5.1%} of window".format(
            tot=stall.get("seconds_total", 0.0),
            frac=stall.get("fraction", 0.0),
        )
    )
    if model:
        lines.append(
            "byte model   hot {hot:.0f} B/tuple  effective "
            "{eff:.0f} B/tuple  modeled {gbps:.2f} GB/s "
            "(layout {layout})".format(
                hot=model.get("hot_bytes_per_tuple", 0.0),
                eff=model.get("effective_bytes_per_tuple", 0.0),
                gbps=model.get("modeled_gbps", 0.0),
                layout=model.get("layout_stamp", "?"),
            )
        )
    slo = snap.get("slo") or {}
    if slo:
        lines.append("")
        lines.append(
            f"{'tenant':<16s} {'class':<10s} {'hit':>8s} "
            f"{'miss':>8s} {'burn':>8s}"
        )
        for name in sorted(slo):
            row = slo[name]
            lines.append(
                f"{name:<16s} {row.get('slo_class', '-'):<10s} "
                f"{row.get('hits', 0):>8d} "
                f"{row.get('misses', 0):>8d} "
                f"{row.get('error_budget_burn', 0.0):>8.2f}"
            )
    hbm = snap.get("hbm") or {}
    chips = hbm.get("chip_bytes") or {}
    if chips:
        per = "  ".join(
            f"chip{c}={int(b) >> 20}MiB"
            for c, b in sorted(chips.items())
        )
        lines.append("")
        lines.append(f"hbm residency  {per}")
    retunes = snap.get("retunes") or []
    if retunes:
        last = retunes[-1]
        lines.append(
            "last retune  trigger={t} {knobs} @ seq {seq}".format(
                t=last.get("trigger", "?"),
                knobs=last.get("applied", {}),
                seq=last.get("seq", 0),
            )
        )
    return "\n".join(lines) + "\n"
