"""Typed table models — the TPU analog of /root/reference/pkg/maps.

In the reference these packages wrap pinned BPF maps (the kernel ABI).
Here they model the host-side *desired state* tables that the policy
compiler lowers into device tensors (cilium_tpu.compiler.tables).
"""
