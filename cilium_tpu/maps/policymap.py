"""Per-endpoint policy verdict table model.

Re-design of /root/reference/pkg/maps/policymap/policymap.go (PolicyKey
policymap.go:64, PolicyEntry policymap.go:73) and the endpoint-side
PolicyMapState (pkg/endpoint/endpoint.go:265).  In the reference a
PolicyMapState is synced into a per-endpoint BPF hash map consumed by
`__policy_can_access` (bpf/lib/policy.h:46); here it is the input of
the tensor lowering in cilium_tpu.compiler.tables and the host oracle
in cilium_tpu.engine.oracle.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

import numpy as np

# Traffic direction (pkg/maps/policymap/trafficdirection: Ingress=0,
# Egress=1; bpf side inverts into the `egress` bit, policy.h:57).
INGRESS = 0
EGRESS = 1

# policymap.go:37: max entries of the per-endpoint verdict table.
MAX_ENTRIES = 16384

# policymap.go:46: port 0 means "all ports" (the L3-only slot).
ALL_PORTS = 0


@dataclass(frozen=True)
class PolicyKey:
    """policymap.go:64 — must stay a 4-tuple of ints (ABI contract
    checked by cilium_tpu.native.alignchecker)."""

    identity: int  # u32 source (ingress) / dest (egress) security id
    dest_port: int = 0  # u16, host byte order; 0 = all ports
    nexthdr: int = 0  # u8 IP protocol; 0 = any
    traffic_direction: int = INGRESS  # u8

    def is_l3_only(self) -> bool:
        return self.dest_port == 0 and self.nexthdr == 0

    def __str__(self) -> str:
        d = "Ingress" if self.traffic_direction == INGRESS else "Egress"
        return f"{d}: {self.identity} {self.dest_port}/{self.nexthdr}"


@dataclass
class PolicyMapStateEntry:
    """policymap.go:73 (PolicyEntry) minus kernel padding.

    proxy_port > 0 means the verdict is redirect-to-proxy; packets and
    bytes are the per-entry counters the datapath accumulates
    (policy.h:66-68), filled back from the device by the engine.
    """

    proxy_port: int = 0  # u16, host byte order
    packets: int = 0
    bytes: int = 0


# pkg/endpoint/endpoint.go:265 — the desired/realized table of one
# endpoint.
PolicyMapState = Dict[PolicyKey, PolicyMapStateEntry]


def sort_keys(state: PolicyMapState) -> List[PolicyKey]:
    """Deterministic dump order (PolicyEntriesDump.Less,
    policymap.go:96: direction then identity)."""
    return sorted(
        state.keys(),
        key=lambda k: (k.traffic_direction, k.identity, k.dest_port, k.nexthdr),
    )


def diff_map_state(
    realized: PolicyMapState, desired: PolicyMapState
) -> Tuple[List[PolicyKey], List[PolicyKey]]:
    """syncPolicyMap's delta (pkg/endpoint/endpoint.go:2572): returns
    (keys_to_add_or_update, keys_to_delete)."""
    to_add = [
        k
        for k, v in desired.items()
        if k not in realized or realized[k].proxy_port != v.proxy_port
    ]
    to_delete = [k for k in realized if k not in desired]
    return to_add, to_delete


# ---------------------------------------------------------------------------
# Array-backed map state (the vectorized control-plane representation)
# ---------------------------------------------------------------------------
#
# At the 50k-rule / 65k-identity envelope a PolicyMapState holds tens
# of thousands of entries per endpoint; building, diffing and lowering
# them as Python dicts of PolicyKey dataclasses is the control-plane
# hot loop (the analog of computeDesiredPolicyMapState's O(N·R) walk,
# pkg/endpoint/policy.go:273 — which the reference runs in compiled
# Go).  MapStateArrays stores the same state as sorted packed-u64 key
# arrays + parallel value arrays, so build/diff/sync/lower become
# NumPy array ops, while READ access stays dict-compatible (get /
# [] / in / items / len / ==) for the oracle, checkpoint, replay
# counter-writeback and tests.

_KEY_DTYPE = np.uint64


def pack_keys(
    identity: np.ndarray,
    dest_port: np.ndarray,
    nexthdr: np.ndarray,
    direction: np.ndarray,
) -> np.ndarray:
    """PolicyKey → u64: identity<<32 | dport<<16 | proto<<8 | dir."""
    return (
        (np.asarray(identity, np.uint64) << np.uint64(32))
        | (np.asarray(dest_port, np.uint64) << np.uint64(16))
        | (np.asarray(nexthdr, np.uint64) << np.uint64(8))
        | np.asarray(direction, np.uint64)
    )


def _pack_one(key: PolicyKey) -> np.uint64:
    return np.uint64(
        (key.identity << 32)
        | (key.dest_port << 16)
        | (key.nexthdr << 8)
        | key.traffic_direction
    )


def unpack_keys(packed: np.ndarray):
    """u64 array → (identity, dest_port, nexthdr, direction) arrays."""
    packed = np.asarray(packed, np.uint64)
    identity = (packed >> np.uint64(32)).astype(np.uint32)
    dport = ((packed >> np.uint64(16)) & np.uint64(0xFFFF)).astype(np.int32)
    proto = ((packed >> np.uint64(8)) & np.uint64(0xFF)).astype(np.int32)
    direction = (packed & np.uint64(0xFF)).astype(np.int32)
    return identity, dport, proto, direction


class _EntryView:
    """A PolicyMapStateEntry view into the arrays: counter writes
    (replay's packets += fold-back) land in the backing store."""

    __slots__ = ("_state", "_pos")

    def __init__(self, state: "MapStateArrays", pos: int) -> None:
        self._state = state
        self._pos = pos

    @property
    def proxy_port(self) -> int:
        return int(self._state.proxy[self._pos])

    @property
    def packets(self) -> int:
        return int(self._state.packets[self._pos])

    @packets.setter
    def packets(self, v: int) -> None:
        self._state.packets[self._pos] = v

    @property
    def bytes(self) -> int:
        return int(self._state.bytes[self._pos])

    @bytes.setter
    def bytes(self, v: int) -> None:
        self._state.bytes[self._pos] = v

    def __eq__(self, other) -> bool:
        if isinstance(other, (PolicyMapStateEntry, _EntryView)):
            return (
                self.proxy_port == other.proxy_port
                and self.packets == other.packets
                and self.bytes == other.bytes
            )
        return NotImplemented

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"_EntryView(proxy_port={self.proxy_port}, "
            f"packets={self.packets}, bytes={self.bytes})"
        )


class MapStateArrays:
    """Sorted packed-key array map state (see module note above).

    Invariants: `keys_packed` is strictly increasing u64; `proxy`,
    `packets`, `bytes` are parallel.  Mutation model: counters mutate
    in place (through _EntryView); the KEY SET is immutable — sync
    builds a fresh instance (copy-on-write, same contract as the dict
    path so concurrent fleet-compile readers keep a stable snapshot).
    """

    __slots__ = ("keys_packed", "proxy", "packets", "bytes")

    def __init__(
        self,
        keys_packed: np.ndarray,
        proxy: np.ndarray,
        packets: np.ndarray = None,
        bytes_: np.ndarray = None,
    ) -> None:
        m = len(keys_packed)
        self.keys_packed = np.asarray(keys_packed, _KEY_DTYPE)
        self.proxy = np.asarray(proxy, np.uint32)
        self.packets = (
            np.zeros(m, np.int64) if packets is None else packets
        )
        self.bytes = np.zeros(m, np.int64) if bytes_ is None else bytes_

    # -- construction ---------------------------------------------------------

    @staticmethod
    def build(keys_packed: np.ndarray, proxy: np.ndarray) -> "MapStateArrays":
        """Sort + dedupe unsorted key/proxy arrays.  Duplicate keys
        take the LAST occurrence's value — the same overwrite
        semantics as sequential dict insertion in the dict path."""
        keys_packed = np.asarray(keys_packed, _KEY_DTYPE)
        proxy = np.asarray(proxy, np.uint32)
        uniq, first_rev = np.unique(keys_packed[::-1], return_index=True)
        last = len(keys_packed) - 1 - first_rev
        return MapStateArrays(uniq, proxy[last])

    @staticmethod
    def from_dict(state: PolicyMapState) -> "MapStateArrays":
        if isinstance(state, MapStateArrays):
            return state
        items = sorted(
            (int(_pack_one(k)), v) for k, v in state.items()
        )
        keys = np.asarray([k for k, _ in items], _KEY_DTYPE)
        proxy = np.asarray(
            [v.proxy_port for _, v in items], np.uint32
        )
        packets = np.asarray([v.packets for _, v in items], np.int64)
        bytes_ = np.asarray([v.bytes for _, v in items], np.int64)
        return MapStateArrays(keys, proxy, packets, bytes_)

    def to_dict(self) -> PolicyMapState:
        return {
            key: PolicyMapStateEntry(
                proxy_port=int(self.proxy[i]),
                packets=int(self.packets[i]),
                bytes=int(self.bytes[i]),
            )
            for i, key in enumerate(self._iter_keys())
        }

    # -- dict-compatible read access ------------------------------------------

    def _find(self, key: PolicyKey) -> int:
        packed = _pack_one(key)
        pos = int(np.searchsorted(self.keys_packed, packed))
        if (
            pos < len(self.keys_packed)
            and self.keys_packed[pos] == packed
        ):
            return pos
        return -1

    def get(self, key: PolicyKey, default=None):
        pos = self._find(key)
        return _EntryView(self, pos) if pos >= 0 else default

    def __getitem__(self, key: PolicyKey) -> _EntryView:
        pos = self._find(key)
        if pos < 0:
            raise KeyError(key)
        return _EntryView(self, pos)

    def __contains__(self, key: PolicyKey) -> bool:
        return self._find(key) >= 0

    def __len__(self) -> int:
        return len(self.keys_packed)

    def _iter_keys(self) -> Iterable[PolicyKey]:
        ident, dport, proto, direction = unpack_keys(self.keys_packed)
        for i in range(len(self.keys_packed)):
            yield PolicyKey(
                int(ident[i]), int(dport[i]), int(proto[i]),
                int(direction[i]),
            )

    def __iter__(self):
        return self._iter_keys()

    def keys(self):
        return list(self._iter_keys())

    def values(self):
        return [_EntryView(self, i) for i in range(len(self))]

    def items(self):
        return [
            (key, _EntryView(self, i))
            for i, key in enumerate(self._iter_keys())
        ]

    def __eq__(self, other) -> bool:
        if isinstance(other, MapStateArrays):
            return (
                np.array_equal(self.keys_packed, other.keys_packed)
                and np.array_equal(self.proxy, other.proxy)
                and np.array_equal(self.packets, other.packets)
                and np.array_equal(self.bytes, other.bytes)
            )
        if isinstance(other, dict):
            if len(other) != len(self):
                return False
            for key, entry in other.items():
                mine = self.get(key)
                if mine is None or mine != entry:
                    return False
            return True
        return NotImplemented

    def __bool__(self) -> bool:
        return len(self.keys_packed) > 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"MapStateArrays({len(self)} entries)"


def sync_map_arrays(
    realized: "MapStateArrays", desired: "MapStateArrays"
) -> Tuple["MapStateArrays", int, int]:
    """Vectorized syncPolicyMap (endpoint.go:2572): returns
    (new_realized, n_added_or_updated, n_deleted).  Counters of keys
    present in both states carry over (including proxy-port changes,
    matching the dict path's old.packets preservation)."""
    nd, nr = len(desired.keys_packed), len(realized.keys_packed)
    if nr:
        pos = np.searchsorted(realized.keys_packed, desired.keys_packed)
        pos_c = np.minimum(pos, nr - 1)
        present = realized.keys_packed[pos_c] == desired.keys_packed
        changed = ~present | (
            present & (realized.proxy[pos_c] != desired.proxy)
        )
        packets = np.where(present, realized.packets[pos_c], 0)
        bytes_ = np.where(present, realized.bytes[pos_c], 0)
    else:
        changed = np.ones(nd, bool)
        packets = np.zeros(nd, np.int64)
        bytes_ = np.zeros(nd, np.int64)
    n_add = int(changed.sum())
    # deletions: realized keys absent from desired
    if nd and nr:
        rpos = np.searchsorted(desired.keys_packed, realized.keys_packed)
        rpos_c = np.minimum(rpos, nd - 1)
        still = desired.keys_packed[rpos_c] == realized.keys_packed
        n_del = int((~still).sum())
    else:
        n_del = nr
    new = MapStateArrays(
        desired.keys_packed, desired.proxy.copy(), packets, bytes_
    )
    return new, n_add, n_del
