"""Per-endpoint policy verdict table model.

Re-design of /root/reference/pkg/maps/policymap/policymap.go (PolicyKey
policymap.go:64, PolicyEntry policymap.go:73) and the endpoint-side
PolicyMapState (pkg/endpoint/endpoint.go:265).  In the reference a
PolicyMapState is synced into a per-endpoint BPF hash map consumed by
`__policy_can_access` (bpf/lib/policy.h:46); here it is the input of
the tensor lowering in cilium_tpu.compiler.tables and the host oracle
in cilium_tpu.engine.oracle.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

# Traffic direction (pkg/maps/policymap/trafficdirection: Ingress=0,
# Egress=1; bpf side inverts into the `egress` bit, policy.h:57).
INGRESS = 0
EGRESS = 1

# policymap.go:37: max entries of the per-endpoint verdict table.
MAX_ENTRIES = 16384

# policymap.go:46: port 0 means "all ports" (the L3-only slot).
ALL_PORTS = 0


@dataclass(frozen=True)
class PolicyKey:
    """policymap.go:64 — must stay a 4-tuple of ints (ABI contract
    checked by cilium_tpu.native.alignchecker)."""

    identity: int  # u32 source (ingress) / dest (egress) security id
    dest_port: int = 0  # u16, host byte order; 0 = all ports
    nexthdr: int = 0  # u8 IP protocol; 0 = any
    traffic_direction: int = INGRESS  # u8

    def is_l3_only(self) -> bool:
        return self.dest_port == 0 and self.nexthdr == 0

    def __str__(self) -> str:
        d = "Ingress" if self.traffic_direction == INGRESS else "Egress"
        return f"{d}: {self.identity} {self.dest_port}/{self.nexthdr}"


@dataclass
class PolicyMapStateEntry:
    """policymap.go:73 (PolicyEntry) minus kernel padding.

    proxy_port > 0 means the verdict is redirect-to-proxy; packets and
    bytes are the per-entry counters the datapath accumulates
    (policy.h:66-68), filled back from the device by the engine.
    """

    proxy_port: int = 0  # u16, host byte order
    packets: int = 0
    bytes: int = 0


# pkg/endpoint/endpoint.go:265 — the desired/realized table of one
# endpoint.
PolicyMapState = Dict[PolicyKey, PolicyMapStateEntry]


def sort_keys(state: PolicyMapState) -> List[PolicyKey]:
    """Deterministic dump order (PolicyEntriesDump.Less,
    policymap.go:96: direction then identity)."""
    return sorted(
        state.keys(),
        key=lambda k: (k.traffic_direction, k.identity, k.dest_port, k.nexthdr),
    )


def diff_map_state(
    realized: PolicyMapState, desired: PolicyMapState
) -> Tuple[List[PolicyKey], List[PolicyKey]]:
    """syncPolicyMap's delta (pkg/endpoint/endpoint.go:2572): returns
    (keys_to_add_or_update, keys_to_delete)."""
    to_add = [
        k
        for k, v in desired.items()
        if k not in realized or realized[k].proxy_port != v.proxy_port
    ]
    to_delete = [k for k in realized if k not in desired]
    return to_add, to_delete
