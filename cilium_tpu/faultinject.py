"""Deterministic, seedable fault injection for the serving plane.

The analog of the reference's chaos suites
(/root/reference/test/runtime/chaos.go kills agents;
test/k8sT/Chaos.go restarts nodes) brought INSIDE the process: named
instrumentation sites in the hot path consult a process-global
registry, and an armed site fails its callers on a deterministic
schedule — so resilience machinery (retry, circuit breaker, host-path
failover, kvstore redial) can be *proven* instead of assumed.

Sites (dotted names; the instrumented seams):

  engine.dispatch   device verdict dispatch (Daemon.process_flows,
                    replay.replay) — the XLA launch that a wedged TPU
                    runtime or dispatch failure takes down.  Accepts
                    the `chip=` selector (below): the mesh failover
                    router (engine/failover.py) probes this site once
                    per device ordinal before each launch, so a
                    chip-scoped schedule kills exactly one chip while
                    the unscoped daemon/replay seam never sees it
  native.decode     flow-record decode (native.decode_flow_records)
  kvstore.conn      socket transport send path (kvstore RemoteBackend)
                    — custom action: the call site severs its socket
  ct.insert         host CT map insertion (CTMap.create)
  proxy.upcall      proxy redirect realization (Proxy.
                    update_endpoint_redirects)
  publish.scatter   delta-publish device scatter (engine.publish
                    DeviceTableStore._publish_delta) — probed once
                    per resident device ordinal, so `chip=` scoped
                    schedules poison the scatter only when that
                    chip holds a slice of the spare epoch; the
                    publish falls back to a FULL upload (counted in
                    publish_fallback_total) instead of leaving a
                    half-patched epoch
  memo.insert       verdict-cache insert/commit path — the host
                    commit of kernel-inserted rows (engine.memo
                    VerdictCache.commit, unscoped) and the routed
                    memo plane's per-chip probes before commit
                    (engine.failover._memo_dispatch, `chip=`
                    honored); a fired fault drops the batch's cache
                    write-back and the batch re-dispatches uncached
                    — bit-identity is unconditional either way

Schedules are deterministic and composable:

  "raise"                    fail every call while armed
  "raise:next=3"             fail the next 3 calls, then pass
  "raise:every=5"            fail every 5th call
  "raise:prob=0.1;seed=7"    seeded Bernoulli (reproducible)
  "hang:delay=0.5"           sleep `delay` then pass (watchdog bait)
  "corrupt:next=1"           data-mode: corrupt_bytes() mangles the
                             payload (truncation) instead of raising
  "raise:chip=3"             chip-scoped: fires only for callers that
                             identify as device ordinal 3 (the mesh
                             router's per-chip attribution probes);
                             call sites that pass no ordinal are
                             never affected, and non-matching
                             ordinals do not consume the schedule

Arming surfaces: `registry.arm()` in-process, the
CILIUM_TPU_FAULTS env var at import ("site=spec,site=spec"),
`PATCH /config {"faults": {...}}` via the daemon, the REST
`/debug/faults` routes, and `cilium-tpu fault arm/disarm/list`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from cilium_tpu.logging import get_logger

log = get_logger("faultinject")

# the instrumented seams; arming anything else is a caller error
SITES = (
    "engine.dispatch",
    "native.decode",
    "kvstore.conn",
    "ct.insert",
    "proxy.upcall",
    "publish.scatter",
    "memo.insert",
    # the elastic-resharding migration scatter (engine/reshard.py):
    # probed once per target-column ordinal before each bounded-byte
    # migration step, so chip-scoped schedules can kill a migration
    # mid-stream (the plan then completes via the survivors' replica
    # copies or rolls back to the source layout)
    "reshard.migrate",
)

MODES = ("raise", "hang", "corrupt")


class FaultInjected(RuntimeError):
    """An armed site fired (mode=raise)."""

    def __init__(self, site: str, chip: Optional[int] = None) -> None:
        where = site if chip is None else f"{site} (chip {chip})"
        super().__init__(f"injected fault at {where}")
        self.site = site
        self.chip = chip


@dataclass
class FaultSpec:
    """One site's failure schedule."""

    mode: str = "raise"
    next_n: int = 0  # fail the next N calls (0 = no next-N window)
    every: int = 0  # fail every Kth call (0 = off)
    prob: float = 0.0  # seeded Bernoulli (0 = off)
    seed: int = 0
    delay: float = 0.05  # hang duration (mode=hang)
    chip: int = -1  # device-ordinal scope (-1 = unscoped)

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(
                f"unknown fault mode {self.mode!r} "
                f"(one of {'/'.join(MODES)})"
            )
        if self.next_n < 0 or self.every < 0:
            raise ValueError("next/every must be >= 0")
        if not (0.0 <= self.prob <= 1.0):
            raise ValueError("prob must be in [0, 1]")

    @staticmethod
    def parse(text: str) -> "FaultSpec":
        """Spec string → FaultSpec: "mode[:k=v[;k=v...]]"."""
        mode, _, params = str(text).strip().partition(":")
        kw: Dict[str, object] = {}
        if params:
            for pair in params.split(";"):
                if not pair:
                    continue
                key, sep, value = pair.partition("=")
                if not sep:
                    raise ValueError(
                        f"fault spec param {pair!r} is not k=v"
                    )
                key = key.strip()
                if key == "next":
                    kw["next_n"] = int(value)
                elif key in ("every", "seed", "chip"):
                    kw[key] = int(value)
                elif key in ("prob", "delay"):
                    kw[key] = float(value)
                else:
                    raise ValueError(
                        f"unknown fault spec param {key!r}"
                    )
        return FaultSpec(mode=mode or "raise", **kw)

    def describe(self) -> Dict[str, object]:
        return {
            "mode": self.mode,
            "next": self.next_n,
            "every": self.every,
            "prob": self.prob,
            "seed": self.seed,
            "delay": self.delay,
            "chip": self.chip,
        }


@dataclass
class _ArmedSite:
    spec: FaultSpec
    calls: int = 0
    fired: int = 0
    rng: random.Random = field(default_factory=random.Random)

    def __post_init__(self) -> None:
        self.rng = random.Random(self.spec.seed)

    def decide(self) -> bool:
        """One call through the schedule (caller holds the lock)."""
        self.calls += 1
        spec = self.spec
        if spec.next_n:
            if self.fired < spec.next_n:
                self.fired += 1
                return True
            return False
        if spec.every:
            hit = self.calls % spec.every == 0
        elif spec.prob:
            hit = self.rng.random() < spec.prob
        else:
            hit = True
        if hit:
            self.fired += 1
        return hit


class FaultRegistry:
    """Process-global armed-site table; all decisions under one lock
    so schedules stay deterministic under concurrent callers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: Dict[str, _ArmedSite] = {}

    def arm(self, site: str, spec) -> FaultSpec:
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} "
                f"(one of {', '.join(SITES)})"
            )
        if isinstance(spec, str):
            spec = FaultSpec.parse(spec)
        with self._lock:
            self._armed[site] = _ArmedSite(spec)
        log.warning(
            "fault site armed",
            extra={"fields": {"site": site, **spec.describe()}},
        )
        return spec

    def disarm(self, site: str) -> bool:
        with self._lock:
            return self._armed.pop(site, None) is not None

    def disarm_all(self) -> int:
        with self._lock:
            n = len(self._armed)
            self._armed.clear()
        return n

    def armed(self) -> Dict[str, Dict[str, object]]:
        """Snapshot for the REST/CLI surface."""
        with self._lock:
            return {
                site: {
                    **armed.spec.describe(),
                    "calls": armed.calls,
                    "fired": armed.fired,
                }
                for site, armed in self._armed.items()
            }

    # -- the instrumentation verbs ------------------------------------------

    # NOTE on the lock-free `if not self._armed` fast paths below:
    # the instrumentation verbs sit on per-flow/per-frame hot paths
    # (every CTMap.create, every kvstore frame, every dispatch), so
    # the nothing-armed case — production — must not take the global
    # lock.  Reading the dict's emptiness without the lock is a
    # benign race: arming is advisory (a fault armed concurrently
    # with a call may miss that one call), and dict reads are atomic
    # under the GIL.

    @staticmethod
    def _in_scope(spec: FaultSpec, chip: Optional[int]) -> bool:
        """Chip-scope gate: a chip-scoped spec only matches callers
        identifying as that exact ordinal (out-of-scope calls must
        not consume the schedule — "kill chip 3" means chip 3's next
        probe, not whichever chip happens to probe first); an
        unscoped spec matches every caller, ordinal-passing or not."""
        if spec.chip < 0:
            return True
        return chip is not None and chip == spec.chip

    def any_armed(self) -> bool:
        """Lock-free production guard: True when ANY site is armed.
        Call sites whose PROBE SETUP itself has a cost (e.g.
        enumerating device ordinals for per-chip attribution) gate
        the setup on this, the same benign-race emptiness read the
        verbs below use."""
        return bool(self._armed)

    def should_fire(self, site: str, chip: Optional[int] = None) -> bool:
        """Count one call; True when the schedule says fail.  For
        call sites with a CUSTOM fault action (kvstore.conn severs
        its socket) — fire() applies the generic raise/hang action."""
        if not self._armed:
            return False
        with self._lock:
            armed = self._armed.get(site)
            if armed is None or not self._in_scope(armed.spec, chip):
                return False
            hit = armed.decide()
        if hit:
            self._count(site, armed.spec.mode, chip)
        return hit

    def fire(self, site: str, chip: Optional[int] = None) -> None:
        """The generic instrumentation hook: no-op unless armed; an
        armed raise-site raises FaultInjected, a hang-site sleeps
        its delay (the dispatch watchdog's bait).  corrupt-mode
        sites never act here — corrupt_bytes() is their verb.  Pass
        `chip` (a device ordinal) from per-chip attribution probes:
        chip-scoped specs fire only for their ordinal."""
        if not self._armed:
            return
        with self._lock:
            armed = self._armed.get(site)
            if armed is None or armed.spec.mode == "corrupt":
                return
            if not self._in_scope(armed.spec, chip):
                return
            hit = armed.decide()
            mode = armed.spec.mode
            delay = armed.spec.delay
        if not hit:
            return
        self._count(site, mode, chip)
        if mode == "hang":
            time.sleep(delay)
            return
        raise FaultInjected(site, chip)

    def corrupt_bytes(self, site: str, buf: bytes) -> bytes:
        """Data-plane verb: an armed corrupt-site mangles the buffer
        (drops the trailing byte — a truncated record stream, the
        classic partial-read corruption) on its schedule."""
        if not self._armed:
            return buf
        with self._lock:
            armed = self._armed.get(site)
            if armed is None or armed.spec.mode != "corrupt":
                return buf
            hit = armed.decide()
        if not hit or not buf:
            return buf
        self._count(site, "corrupt")
        return buf[:-1]

    @staticmethod
    def _count(site: str, mode: str, chip: Optional[int] = None) -> None:
        # late import: metrics must stay importable without this
        # module and vice versa
        from cilium_tpu.metrics import registry as metrics

        metrics.fault_injections_total.inc(site, mode)
        log.warning(
            "injected fault fired",
            extra={"fields": {"site": site, "mode": mode,
                              "chip": chip}},
        )


registry = FaultRegistry()

# module-level conveniences (the instrumented call sites use these)
arm = registry.arm
disarm = registry.disarm
disarm_all = registry.disarm_all
armed = registry.armed
any_armed = registry.any_armed
fire = registry.fire
should_fire = registry.should_fire
corrupt_bytes = registry.corrupt_bytes


class injected:
    """Context manager for tests: arm on enter, disarm on exit."""

    def __init__(self, site: str, spec="raise") -> None:
        self.site = site
        self.spec = spec

    def __enter__(self) -> FaultSpec:
        return arm(self.site, self.spec)

    def __exit__(self, *exc) -> None:
        disarm(self.site)


FAULTS_ENV = "CILIUM_TPU_FAULTS"


def _arm_from_env() -> None:
    """CILIUM_TPU_FAULTS="site=spec,site=spec" armed at import —
    chaos runs of unmodified entrypoints (agent, bench, tools)."""
    raw = os.environ.get(FAULTS_ENV, "")
    if not raw:
        return
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        site, sep, spec = item.partition("=")
        if not sep:
            raise ValueError(
                f"{FAULTS_ENV} entry {item!r} is not site=spec"
            )
        arm(site.strip(), spec)


_arm_from_env()
