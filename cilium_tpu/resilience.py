"""Resilience primitives for the verdict serving plane.

The survivability layer the reference spreads across pkg/controller
(exponential error backoff), pkg/health (degraded-mode reporting) and
the agent's restart story, distilled into three host-side primitives
the hot path composes:

  * retry_call — bounded retries with exponential backoff + jitter
    and a hard deadline (controller.go:175's backoff, per-call);
  * CircuitBreaker — closed/open/half-open over any dependency (the
    TPU dispatch, here): trip after consecutive failures, shed load
    while open, probe with limited half-open trials, close on
    success.  Transitions invoke a listener so the daemon can flip
    /healthz to degraded, publish AgentNotify monitor events and
    set the breaker_state gauge;
  * DispatchWatchdog — run a callable under a wall-clock deadline on
    a worker thread (a wedged XLA dispatch cannot be cancelled; the
    watchdog abandons it and fails the call so the breaker can open
    instead of the flow stream hanging forever);
  * AdmissionGate — bounded in-flight admission for overload
    shedding (the perf ring's finite depth: past the watermark the
    datapath drops with a reason instead of queueing unboundedly).

Everything is deterministic under a seed (jittered backoff included)
so chaos-storm runs reproduce.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from cilium_tpu import tracing
from cilium_tpu.logging import get_logger

log = get_logger("resilience")


class DeadlineExceeded(TimeoutError):
    """A watchdogged call outlived its deadline."""


class BreakerOpen(RuntimeError):
    """Fast-fail: the circuit is open; the dependency is shed."""


def retry_call(
    fn: Callable,
    *args,
    retries: int = 2,
    base_delay: float = 0.005,
    max_delay: float = 0.5,
    deadline: Optional[float] = None,
    jitter: float = 0.5,
    seed: Optional[int] = None,
    retry_on: Tuple[type, ...] = (Exception,),
    on_retry: Optional[Callable] = None,
    **kwargs,
):
    """Call `fn` with up to `retries` retries: exponential backoff
    (base * 2^attempt, capped at max_delay) with multiplicative
    jitter in [1-jitter, 1+jitter] — seeded when `seed` is given, so
    schedules are reproducible.  `deadline` bounds the WHOLE call in
    seconds: no retry starts past it, and the last failure re-raises
    (controller.go's backoff loop with pkg/endpoint's generation
    timeout semantics).  `on_retry(attempt, exc)` observes each
    retry — the daemon counts dispatch_retries_total through it."""
    rng = random.Random(seed) if seed is not None else random
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            attempt += 1
            if attempt > retries:
                raise
            if (
                deadline is not None
                and time.monotonic() - t0 >= deadline
            ):
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            delay = min(base_delay * (2 ** (attempt - 1)), max_delay)
            if jitter:
                delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
            if deadline is not None:
                delay = min(
                    delay,
                    max(0.0, deadline - (time.monotonic() - t0)),
                )
            if delay > 0:
                time.sleep(delay)


# breaker states (numeric codes are the breaker_state gauge values)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"
STATE_CODES: Dict[str, int] = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """Closed → (failure_threshold consecutive failures) → open →
    (recovery_timeout) → half-open → (success_threshold probe
    successes) → closed; a half-open probe failure re-opens.

    allow() is the admission question ("may I try the dependency?");
    callers pair it with record_success()/record_failure(), or use
    call() which wraps all three and raises BreakerOpen when shed.
    While half-open at most `half_open_max` probes are in flight at
    once — the rest are shed as if open (xDS's probe-one semantics).

    `on_transition(name, old, new, reason)` runs OUTSIDE the lock on
    every state change.

    `probe_ttl` bounds how long a half-open probe slot stays
    reserved: a probe whose owner never reports back (a dispatch
    abandoned past its watchdog deadline whose caller thread then
    died, a chip probe lost with its runtime) would otherwise pin
    `_half_open_inflight` at the limit and wedge the breaker in
    half-open forever — no probe can ever run again, so the breaker
    can neither close nor re-open.  With a TTL, allow() reclaims
    expired slots before answering the admission question.
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        recovery_timeout: float = 1.0,
        success_threshold: int = 1,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable] = None,
        probe_ttl: Optional[float] = None,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout = recovery_timeout
        self.success_threshold = max(1, success_threshold)
        self.half_open_max = max(1, half_open_max)
        self.probe_ttl = probe_ttl
        self._clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._half_open_successes = 0
        # one issue-timestamp per in-flight half-open probe slot
        # (oldest first) — per-slot so a TTL reclaim of an abandoned
        # probe can never discard a LIVE probe's reservation when
        # half_open_max > 1
        self._probe_issued: list = []
        self._opened_at = 0.0
        self.opened_total = 0

    # -- state machine --------------------------------------------------------

    def _transition(self, new: str, reason: str):
        """Caller holds the lock; returns the listener thunk to run
        outside it (a listener that logs/publishes must never hold
        the breaker lock)."""
        old, self._state = self._state, new
        if new == OPEN:
            self._opened_at = self._clock()
            self.opened_total += 1
        if new == HALF_OPEN:
            self._half_open_successes = 0
            self._probe_issued.clear()
        if new == CLOSED:
            self._consecutive_failures = 0
        listener = self.on_transition
        if listener is None or old == new:
            return None
        return lambda: listener(self.name, old, new, reason)

    def allow(self) -> bool:
        notify = None
        with self._lock:
            if self._state == OPEN:
                if (
                    self._clock() - self._opened_at
                    >= self.recovery_timeout
                ):
                    notify = self._transition(
                        HALF_OPEN, "recovery timeout elapsed"
                    )
                else:
                    ok = False
            if self._state == HALF_OPEN:
                now = self._clock()
                if self.probe_ttl is not None and self._probe_issued:
                    # probes whose owner vanished without recording:
                    # reclaim exactly the expired slots so half-open
                    # can't wedge (see the class docstring) — live
                    # probes keep their reservation
                    fresh = [
                        t for t in self._probe_issued
                        if now - t < self.probe_ttl
                    ]
                    if len(fresh) < len(self._probe_issued):
                        log.warning(
                            "reclaiming expired half-open probe "
                            "slot(s)",
                            extra={"fields": {
                                "breaker": self.name,
                                "reclaimed": len(self._probe_issued)
                                - len(fresh),
                                "inflight": len(fresh),
                                "probe_ttl_s": self.probe_ttl,
                            }},
                        )
                        self._probe_issued = fresh
                ok = len(self._probe_issued) < self.half_open_max
                if ok:
                    self._probe_issued.append(now)
            elif self._state == CLOSED:
                ok = True
        if notify is not None:
            notify()
        # span-plane attribution: the admission question's answer
        # lands on the active span (the per-batch dispatch span), so
        # a trace shows WHY a batch failed over without cross-
        # referencing the breaker gauge's scrape timeline
        tracing.add_event(
            "breaker.decision", breaker=self.name,
            state=self._state, allowed=ok,
        )
        return ok

    def record_success(self) -> None:
        notify = None
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                if self._probe_issued:
                    self._probe_issued.pop(0)
                self._half_open_successes += 1
                if (
                    self._half_open_successes
                    >= self.success_threshold
                ):
                    notify = self._transition(
                        CLOSED, "half-open probes succeeded"
                    )
        if notify is not None:
            notify()

    def record_failure(self, reason: str = "") -> None:
        tracing.add_event(
            "breaker.failure", breaker=self.name, reason=reason
        )
        notify = None
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                if self._probe_issued:
                    self._probe_issued.pop(0)
                notify = self._transition(
                    OPEN, reason or "half-open probe failed"
                )
            elif (
                self._state == CLOSED
                and self._consecutive_failures
                >= self.failure_threshold
            ):
                notify = self._transition(
                    OPEN,
                    reason
                    or f"{self._consecutive_failures} consecutive "
                    f"failures",
                )
        if notify is not None:
            notify()

    def release_probe(self) -> None:
        """Give back a half-open probe slot WITHOUT recording a
        verdict: the admitted dispatch never ran (e.g. the mesh
        routed the batch to the terminal host fold before launch),
        so the chip earned neither a success nor a failure — but
        the reservation must not pin the slot until the TTL."""
        with self._lock:
            if self._state == HALF_OPEN and self._probe_issued:
                self._probe_issued.pop()  # the newest reservation

    def call(self, fn: Callable, *args, **kwargs):
        if not self.allow():
            raise BreakerOpen(f"circuit {self.name!r} is open")
        try:
            got = fn(*args, **kwargs)
        except Exception as exc:
            self.record_failure(str(exc))
            raise
        self.record_success()
        return got

    # -- introspection --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # surface the would-be half-open state so status reads
            # don't lag behind the next allow()
            if (
                self._state == OPEN
                and self._clock() - self._opened_at
                >= self.recovery_timeout
            ):
                return HALF_OPEN
            return self._state

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opened_total": self.opened_total,
                "failure_threshold": self.failure_threshold,
                "recovery_timeout": self.recovery_timeout,
                "half_open_inflight": len(self._probe_issued),
            }

    def reset(self) -> None:
        """Force-close (tests / operator action)."""
        notify = None
        with self._lock:
            if self._state != CLOSED:
                notify = self._transition(CLOSED, "reset")
            self._consecutive_failures = 0
        if notify is not None:
            notify()


class ChipBreakerBank:
    """Per-chip circuit breakers keyed by device ordinal — the mesh
    refinement of the process-wide dispatch breaker: a mesh should
    fail PER CHIP, losing 1/N of its capacity when one chip sickens
    instead of failing the whole fleet over to the host fold.

    One CircuitBreaker per ordinal, lazily created with shared
    parameters; `allow(ordinal)` is the per-chip admission question
    the shard router asks before each launch (a half-open chip's
    allow() IS its re-admission probe — the dispatch that includes
    it), and `record_success`/`record_failure` feed per-chip failure
    attribution back.  `on_transition(ordinal, old, new, reason)`
    observes every chip's state change (the daemon wires it to the
    cilium_chip_breaker_state{chip} gauge, monitor events, and the
    store's outage tracking).  Probes carry a `probe_ttl` so a chip
    that dies mid-probe cannot wedge its breaker in half-open."""

    def __init__(
        self,
        name: str = "engine.dispatch",
        failure_threshold: int = 1,
        recovery_timeout: float = 1.0,
        success_threshold: int = 1,
        probe_ttl: Optional[float] = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable] = None,
    ) -> None:
        self.name = name
        self.failure_threshold = failure_threshold
        self.recovery_timeout = recovery_timeout
        self.success_threshold = success_threshold
        self.probe_ttl = probe_ttl
        self._clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._breakers: Dict[int, CircuitBreaker] = {}

    def breaker(self, ordinal: int) -> CircuitBreaker:
        ordinal = int(ordinal)
        with self._lock:
            b = self._breakers.get(ordinal)
            if b is None:
                # read self.on_transition at FIRE time, not breaker-
                # creation time: a breaker lazily created before the
                # failover router rewires the bank (e.g. by an early
                # states() call) must still reach the router's
                # ledger/gauge wiring
                def listener(_n, old, new, why, o=ordinal):
                    outer = self.on_transition
                    if outer is not None:
                        outer(o, old, new, why)

                b = CircuitBreaker(
                    name=f"{self.name}[chip={ordinal}]",
                    failure_threshold=self.failure_threshold,
                    recovery_timeout=self.recovery_timeout,
                    success_threshold=self.success_threshold,
                    probe_ttl=self.probe_ttl,
                    clock=self._clock,
                    on_transition=listener,
                )
                self._breakers[ordinal] = b
            return b

    def allow(self, ordinal: int) -> bool:
        return self.breaker(ordinal).allow()

    def record_success(self, ordinal: int) -> None:
        self.breaker(ordinal).record_success()

    def record_failure(self, ordinal: int, reason: str = "") -> None:
        self.breaker(ordinal).record_failure(reason)

    def release_probe(self, ordinal: int) -> None:
        self.breaker(ordinal).release_probe()

    def state(self, ordinal: int) -> str:
        return self.breaker(ordinal).state

    def states(self) -> Dict[int, str]:
        with self._lock:
            breakers = dict(self._breakers)
        return {o: b.state for o, b in sorted(breakers.items())}

    def open_chips(self) -> Tuple[int, ...]:
        return tuple(
            o for o, s in self.states().items() if s != CLOSED
        )

    def snapshot(self) -> Dict[int, Dict[str, object]]:
        with self._lock:
            breakers = dict(self._breakers)
        return {o: b.snapshot() for o, b in sorted(breakers.items())}

    def reset(self) -> None:
        with self._lock:
            breakers = list(self._breakers.values())
        for b in breakers:
            b.reset()


class DispatchWatchdog:
    """Per-batch dispatch deadline: run `fn` on a persistent worker
    thread and give up after `timeout` seconds.  The abandoned
    dispatch keeps running on its (daemon) worker — XLA launches
    cannot be cancelled — but the CALLER gets a DeadlineExceeded it
    can feed the breaker, instead of the whole flow stream wedging
    with the runtime.

    Workers are pooled and EXCLUSIVE: each run() takes (or spawns) an
    idle long-lived worker, so the deadline clocks only this call's
    execution — never queue-wait behind a concurrent caller — and an
    abandoned worker can hold nothing but its own wedged call.  A
    healthy worker returns to the pool (no thread-per-batch churn); a
    worker that blew its deadline drains its stuck call, sees the
    stop sentinel and exits, while the caller's retry gets a fresh
    one."""

    def __init__(self, timeout: float = 30.0) -> None:
        self.timeout = timeout
        self._lock = threading.Lock()
        self._idle: list = []  # stack of idle workers' queues

    @staticmethod
    def _work_loop(q) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            fn, args, out, done, ctx = item
            try:
                # run under the CALLER's contextvars snapshot: spans
                # opened inside the watchdogged call (jit.compile,
                # nested dispatch children) parent to the caller's
                # active span instead of starting orphan traces on
                # this worker thread
                out.append(("ok", ctx.run(fn, *args)))
            except BaseException as exc:  # noqa: BLE001
                out.append(("err", exc))
            done.set()

    def run(self, fn: Callable, *args, timeout: Optional[float] = None):
        import queue as _queue

        timeout = self.timeout if timeout is None else timeout
        if timeout is None or timeout <= 0:
            return fn(*args)
        with self._lock:
            q = self._idle.pop() if self._idle else None
        if q is None:
            q = _queue.Queue()
            threading.Thread(
                target=self._work_loop,
                args=(q,),
                name="dispatch-watchdog",
                daemon=True,
            ).start()
        import contextvars

        out: list = []
        done = threading.Event()
        q.put((fn, args, out, done, contextvars.copy_context()))
        if not done.wait(timeout):
            # abandon THIS worker only; it exits once the wedged
            # call drains
            q.put(None)
            log.warning(
                "dispatch exceeded watchdog deadline; abandoning "
                "worker",
                extra={"fields": {"timeout_s": timeout}},
            )
            raise DeadlineExceeded(
                f"dispatch exceeded {timeout}s watchdog deadline"
            )
        with self._lock:
            self._idle.append(q)
        status, value = out[0]
        if status == "err":
            raise value
        return value


def guarded_dispatch(
    fn: Callable,
    *args,
    retries: int = 2,
    base_delay: float = 0.002,
    watchdog: Optional["DispatchWatchdog"] = None,
    site: str = "engine.dispatch",
    seed: int = 0,
    donated: bool = False,
):
    """THE device-dispatch guard, shared by Daemon.process_flows and
    replay(): the fault seam fires BEFORE the launch (an injected
    failure never burns a donated buffer), the optional watchdog
    bounds the launch, and bounded seeded-backoff retry absorbs
    transients — each retry counted in dispatch_retries_total.
    Anything persistent propagates for the caller's breaker/failover
    to handle.

    `donated=True` marks call sites whose jit donates input buffers
    (the accumulator-carrying steps): a REAL mid-launch failure has
    already invalidated the donated argument, so only the pre-launch
    injected fault is retryable there — anything else re-raises
    immediately instead of masking the original error with an
    invalid-buffer retry."""
    from cilium_tpu import faultinject
    from cilium_tpu.metrics import registry as metrics

    def _once():
        faultinject.fire(site)
        if watchdog is not None:
            return watchdog.run(fn, *args)
        return fn(*args)

    return retry_call(
        _once,
        retries=retries,
        base_delay=base_delay,
        seed=seed,
        retry_on=(
            (faultinject.FaultInjected,) if donated else (Exception,)
        ),
        on_retry=lambda attempt, exc: (
            metrics.dispatch_retries_total.inc(),
            tracing.add_event(
                "dispatch.retry", attempt=attempt, error=repr(exc)
            ),
        ),
    )


class AdmissionGate:
    """Bounded in-flight admission (flows, not batches): reserve()
    admits `n` units when the outstanding total stays within the
    limit, else refuses — the caller sheds that batch under the
    canonical Overload drop reason.  Never blocks: backpressure on
    the datapath means dropping with attribution, not queueing
    (the perf ring overwrites, it does not wait)."""

    def __init__(self, limit: Optional[int] = None) -> None:
        self.limit = limit  # None = unbounded
        self._lock = threading.Lock()
        self._inflight = 0
        self.shed_total = 0

    def reserve(self, n: int, charge: bool = True) -> bool:
        """Admit `n` units, or refuse.  With `charge` (default) a
        refusal charges `n` straight to shed_total — the one-shot
        path's whole-batch shed.  `charge=False` is for callers that
        retry with a SUBSET after a refusal (the serving plane's
        shed-priority ordering): they charge exactly what they
        finally shed via charge_shed, so accounting stays
        exactly-once."""
        with self._lock:
            if (
                self.limit is not None
                and self._inflight + n > self.limit
            ):
                if charge:
                    self.shed_total += n
                tracing.add_event(
                    "admission.shed", flows=n,
                    inflight=self._inflight, limit=self.limit,
                )
                return False
            self._inflight += n
            return True

    def release(self, n: int) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - n)

    def charge_shed(self, n: int) -> None:
        """Account flows shed by a gate OTHER than this one (the
        serving plane's per-tenant backlog bound) so shed_total
        stays the one number health()/status() report — without
        double-counting a reserve() refusal, which already
        charged."""
        with self._lock:
            self.shed_total += n

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
