"""Resilience primitives for the verdict serving plane.

The survivability layer the reference spreads across pkg/controller
(exponential error backoff), pkg/health (degraded-mode reporting) and
the agent's restart story, distilled into three host-side primitives
the hot path composes:

  * retry_call — bounded retries with exponential backoff + jitter
    and a hard deadline (controller.go:175's backoff, per-call);
  * CircuitBreaker — closed/open/half-open over any dependency (the
    TPU dispatch, here): trip after consecutive failures, shed load
    while open, probe with limited half-open trials, close on
    success.  Transitions invoke a listener so the daemon can flip
    /healthz to degraded, publish AgentNotify monitor events and
    set the breaker_state gauge;
  * DispatchWatchdog — run a callable under a wall-clock deadline on
    a worker thread (a wedged XLA dispatch cannot be cancelled; the
    watchdog abandons it and fails the call so the breaker can open
    instead of the flow stream hanging forever);
  * AdmissionGate — bounded in-flight admission for overload
    shedding (the perf ring's finite depth: past the watermark the
    datapath drops with a reason instead of queueing unboundedly).

Everything is deterministic under a seed (jittered backoff included)
so chaos-storm runs reproduce.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from cilium_tpu import tracing
from cilium_tpu.logging import get_logger

log = get_logger("resilience")


class DeadlineExceeded(TimeoutError):
    """A watchdogged call outlived its deadline."""


class BreakerOpen(RuntimeError):
    """Fast-fail: the circuit is open; the dependency is shed."""


def retry_call(
    fn: Callable,
    *args,
    retries: int = 2,
    base_delay: float = 0.005,
    max_delay: float = 0.5,
    deadline: Optional[float] = None,
    jitter: float = 0.5,
    seed: Optional[int] = None,
    retry_on: Tuple[type, ...] = (Exception,),
    on_retry: Optional[Callable] = None,
    **kwargs,
):
    """Call `fn` with up to `retries` retries: exponential backoff
    (base * 2^attempt, capped at max_delay) with multiplicative
    jitter in [1-jitter, 1+jitter] — seeded when `seed` is given, so
    schedules are reproducible.  `deadline` bounds the WHOLE call in
    seconds: no retry starts past it, and the last failure re-raises
    (controller.go's backoff loop with pkg/endpoint's generation
    timeout semantics).  `on_retry(attempt, exc)` observes each
    retry — the daemon counts dispatch_retries_total through it."""
    rng = random.Random(seed) if seed is not None else random
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            attempt += 1
            if attempt > retries:
                raise
            if (
                deadline is not None
                and time.monotonic() - t0 >= deadline
            ):
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            delay = min(base_delay * (2 ** (attempt - 1)), max_delay)
            if jitter:
                delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
            if deadline is not None:
                delay = min(
                    delay,
                    max(0.0, deadline - (time.monotonic() - t0)),
                )
            if delay > 0:
                time.sleep(delay)


# breaker states (numeric codes are the breaker_state gauge values)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"
STATE_CODES: Dict[str, int] = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """Closed → (failure_threshold consecutive failures) → open →
    (recovery_timeout) → half-open → (success_threshold probe
    successes) → closed; a half-open probe failure re-opens.

    allow() is the admission question ("may I try the dependency?");
    callers pair it with record_success()/record_failure(), or use
    call() which wraps all three and raises BreakerOpen when shed.
    While half-open at most `half_open_max` probes are in flight at
    once — the rest are shed as if open (xDS's probe-one semantics).

    `on_transition(name, old, new, reason)` runs OUTSIDE the lock on
    every state change.
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        recovery_timeout: float = 1.0,
        success_threshold: int = 1,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable] = None,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout = recovery_timeout
        self.success_threshold = max(1, success_threshold)
        self.half_open_max = max(1, half_open_max)
        self._clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._half_open_successes = 0
        self._half_open_inflight = 0
        self._opened_at = 0.0
        self.opened_total = 0

    # -- state machine --------------------------------------------------------

    def _transition(self, new: str, reason: str):
        """Caller holds the lock; returns the listener thunk to run
        outside it (a listener that logs/publishes must never hold
        the breaker lock)."""
        old, self._state = self._state, new
        if new == OPEN:
            self._opened_at = self._clock()
            self.opened_total += 1
        if new == HALF_OPEN:
            self._half_open_successes = 0
            self._half_open_inflight = 0
        if new == CLOSED:
            self._consecutive_failures = 0
        listener = self.on_transition
        if listener is None or old == new:
            return None
        return lambda: listener(self.name, old, new, reason)

    def allow(self) -> bool:
        notify = None
        with self._lock:
            if self._state == OPEN:
                if (
                    self._clock() - self._opened_at
                    >= self.recovery_timeout
                ):
                    notify = self._transition(
                        HALF_OPEN, "recovery timeout elapsed"
                    )
                else:
                    ok = False
            if self._state == HALF_OPEN:
                ok = self._half_open_inflight < self.half_open_max
                if ok:
                    self._half_open_inflight += 1
            elif self._state == CLOSED:
                ok = True
        if notify is not None:
            notify()
        # span-plane attribution: the admission question's answer
        # lands on the active span (the per-batch dispatch span), so
        # a trace shows WHY a batch failed over without cross-
        # referencing the breaker gauge's scrape timeline
        tracing.add_event(
            "breaker.decision", breaker=self.name,
            state=self._state, allowed=ok,
        )
        return ok

    def record_success(self) -> None:
        notify = None
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._half_open_inflight = max(
                    0, self._half_open_inflight - 1
                )
                self._half_open_successes += 1
                if (
                    self._half_open_successes
                    >= self.success_threshold
                ):
                    notify = self._transition(
                        CLOSED, "half-open probes succeeded"
                    )
        if notify is not None:
            notify()

    def record_failure(self, reason: str = "") -> None:
        tracing.add_event(
            "breaker.failure", breaker=self.name, reason=reason
        )
        notify = None
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._half_open_inflight = max(
                    0, self._half_open_inflight - 1
                )
                notify = self._transition(
                    OPEN, reason or "half-open probe failed"
                )
            elif (
                self._state == CLOSED
                and self._consecutive_failures
                >= self.failure_threshold
            ):
                notify = self._transition(
                    OPEN,
                    reason
                    or f"{self._consecutive_failures} consecutive "
                    f"failures",
                )
        if notify is not None:
            notify()

    def call(self, fn: Callable, *args, **kwargs):
        if not self.allow():
            raise BreakerOpen(f"circuit {self.name!r} is open")
        try:
            got = fn(*args, **kwargs)
        except Exception as exc:
            self.record_failure(str(exc))
            raise
        self.record_success()
        return got

    # -- introspection --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # surface the would-be half-open state so status reads
            # don't lag behind the next allow()
            if (
                self._state == OPEN
                and self._clock() - self._opened_at
                >= self.recovery_timeout
            ):
                return HALF_OPEN
            return self._state

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opened_total": self.opened_total,
                "failure_threshold": self.failure_threshold,
                "recovery_timeout": self.recovery_timeout,
            }

    def reset(self) -> None:
        """Force-close (tests / operator action)."""
        notify = None
        with self._lock:
            if self._state != CLOSED:
                notify = self._transition(CLOSED, "reset")
            self._consecutive_failures = 0
        if notify is not None:
            notify()


class DispatchWatchdog:
    """Per-batch dispatch deadline: run `fn` on a persistent worker
    thread and give up after `timeout` seconds.  The abandoned
    dispatch keeps running on its (daemon) worker — XLA launches
    cannot be cancelled — but the CALLER gets a DeadlineExceeded it
    can feed the breaker, instead of the whole flow stream wedging
    with the runtime.

    Workers are pooled and EXCLUSIVE: each run() takes (or spawns) an
    idle long-lived worker, so the deadline clocks only this call's
    execution — never queue-wait behind a concurrent caller — and an
    abandoned worker can hold nothing but its own wedged call.  A
    healthy worker returns to the pool (no thread-per-batch churn); a
    worker that blew its deadline drains its stuck call, sees the
    stop sentinel and exits, while the caller's retry gets a fresh
    one."""

    def __init__(self, timeout: float = 30.0) -> None:
        self.timeout = timeout
        self._lock = threading.Lock()
        self._idle: list = []  # stack of idle workers' queues

    @staticmethod
    def _work_loop(q) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            fn, args, out, done, ctx = item
            try:
                # run under the CALLER's contextvars snapshot: spans
                # opened inside the watchdogged call (jit.compile,
                # nested dispatch children) parent to the caller's
                # active span instead of starting orphan traces on
                # this worker thread
                out.append(("ok", ctx.run(fn, *args)))
            except BaseException as exc:  # noqa: BLE001
                out.append(("err", exc))
            done.set()

    def run(self, fn: Callable, *args, timeout: Optional[float] = None):
        import queue as _queue

        timeout = self.timeout if timeout is None else timeout
        if timeout is None or timeout <= 0:
            return fn(*args)
        with self._lock:
            q = self._idle.pop() if self._idle else None
        if q is None:
            q = _queue.Queue()
            threading.Thread(
                target=self._work_loop,
                args=(q,),
                name="dispatch-watchdog",
                daemon=True,
            ).start()
        import contextvars

        out: list = []
        done = threading.Event()
        q.put((fn, args, out, done, contextvars.copy_context()))
        if not done.wait(timeout):
            # abandon THIS worker only; it exits once the wedged
            # call drains
            q.put(None)
            log.warning(
                "dispatch exceeded watchdog deadline; abandoning "
                "worker",
                extra={"fields": {"timeout_s": timeout}},
            )
            raise DeadlineExceeded(
                f"dispatch exceeded {timeout}s watchdog deadline"
            )
        with self._lock:
            self._idle.append(q)
        status, value = out[0]
        if status == "err":
            raise value
        return value


def guarded_dispatch(
    fn: Callable,
    *args,
    retries: int = 2,
    base_delay: float = 0.002,
    watchdog: Optional["DispatchWatchdog"] = None,
    site: str = "engine.dispatch",
    seed: int = 0,
    donated: bool = False,
):
    """THE device-dispatch guard, shared by Daemon.process_flows and
    replay(): the fault seam fires BEFORE the launch (an injected
    failure never burns a donated buffer), the optional watchdog
    bounds the launch, and bounded seeded-backoff retry absorbs
    transients — each retry counted in dispatch_retries_total.
    Anything persistent propagates for the caller's breaker/failover
    to handle.

    `donated=True` marks call sites whose jit donates input buffers
    (the accumulator-carrying steps): a REAL mid-launch failure has
    already invalidated the donated argument, so only the pre-launch
    injected fault is retryable there — anything else re-raises
    immediately instead of masking the original error with an
    invalid-buffer retry."""
    from cilium_tpu import faultinject
    from cilium_tpu.metrics import registry as metrics

    def _once():
        faultinject.fire(site)
        if watchdog is not None:
            return watchdog.run(fn, *args)
        return fn(*args)

    return retry_call(
        _once,
        retries=retries,
        base_delay=base_delay,
        seed=seed,
        retry_on=(
            (faultinject.FaultInjected,) if donated else (Exception,)
        ),
        on_retry=lambda attempt, exc: (
            metrics.dispatch_retries_total.inc(),
            tracing.add_event(
                "dispatch.retry", attempt=attempt, error=repr(exc)
            ),
        ),
    )


class AdmissionGate:
    """Bounded in-flight admission (flows, not batches): reserve()
    admits `n` units when the outstanding total stays within the
    limit, else refuses — the caller sheds that batch under the
    canonical Overload drop reason.  Never blocks: backpressure on
    the datapath means dropping with attribution, not queueing
    (the perf ring overwrites, it does not wait)."""

    def __init__(self, limit: Optional[int] = None) -> None:
        self.limit = limit  # None = unbounded
        self._lock = threading.Lock()
        self._inflight = 0
        self.shed_total = 0

    def reserve(self, n: int) -> bool:
        with self._lock:
            if (
                self.limit is not None
                and self._inflight + n > self.limit
            ):
                self.shed_total += n
                tracing.add_event(
                    "admission.shed", flows=n,
                    inflight=self._inflight, limit=self.limit,
                )
                return False
            self._inflight += n
            return True

    def release(self, n: int) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - n)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
