"""State-dump archiver.

Port of /root/reference/bugtool (cilium-bugtool): collect the agent's
observable state — status, endpoints with map states, policy rules,
ipcache, identities, metrics, prefix lengths — into a JSON tree +
tar.gz archive for offline debugging.
"""

from __future__ import annotations

import json
import os
import tarfile
import time
from typing import Optional

from cilium_tpu.metrics import registry as metrics


def collect(daemon, out_dir: str) -> str:
    """Write the dump tree and return the archive path."""
    stamp = time.strftime("%Y%m%d-%H%M%S")
    root = os.path.join(out_dir, f"cilium-tpu-bugtool-{stamp}")
    os.makedirs(root, exist_ok=True)

    def write(name: str, obj) -> None:
        with open(os.path.join(root, name), "w") as f:
            json.dump(obj, f, indent=2, default=str)

    write("status.json", daemon.status())
    write(
        "endpoints.json",
        [
            {
                "id": e.id,
                "name": e.name,
                "ipv4": e.ipv4,
                "state": e.state,
                "identity": (
                    e.security_identity.id if e.security_identity else None
                ),
                "policy_revision": e.policy_revision,
                "map_entries": len(e.realized_map_state),
                "redirects": e.realized_redirects,
            }
            for e in daemon.endpoint_manager.endpoints()
        ],
    )
    write(
        "policy.json",
        {
            "revision": daemon.repo.get_revision(),
            "num_rules": daemon.repo.num_rules(),
        },
    )
    write(
        "ipcache.json",
        {
            ip: {"id": ident.id, "source": ident.source}
            for ip, ident in daemon.ipcache.ip_to_identity.items()
        },
    )
    write(
        "identities.json",
        {
            str(num_id): [str(l) for l in labels]
            for num_id, labels in daemon.identity_cache().items()
        },
    )
    write("prefix_lengths.json", dict(daemon.prefix_lengths))
    # daemon-owned service/CT/tunnel/controller state (the reference
    # bugtool dumps `cilium service list`, `cilium bpf ct list`,
    # `cilium bpf tunnel list`, and controller statuses the same way)
    write(
        "services.json",
        [
            {
                "id": svc.id,
                "frontend": f"{svc.frontend.ip}:{svc.frontend.port}",
                "backends": [
                    f"{b.addr.ip}:{b.addr.port}"
                    for b in svc.backends
                ],
            }
            for svc in daemon.services.by_id.values()
        ],
    )
    write(
        "conntrack.json",
        {
            "count": len(daemon.ct.entries),
            "mutations": daemon.ct.mutations,
            "clock": daemon.ct.now(),
        },
    )
    write("tunnel.json", daemon.tunnel_map.snapshot())
    write(
        "controllers.json",
        {
            name: {
                "success_count": st.success_count,
                "failure_count": st.failure_count,
                "consecutive_failures": st.consecutive_failures,
                "last_error": st.last_error,
            }
            for name, st in daemon.controllers.statuses().items()
        },
    )
    # flow-record plane dump (the `hubble observe` snapshot the
    # reference bugtool can't have: here the ring lives in-agent)
    flow_store = getattr(daemon, "flow_store", None)
    if flow_store is not None:
        write(
            "flows.json",
            {
                "summary": flow_store.summary(),
                "records": [
                    r.to_dict() for r in flow_store.snapshot()[-4096:]
                ],
            },
        )
    # span-plane ring dump: the same trace ids the live
    # /debug/traces API serves, so offline debugging can join
    # traces ↔ flows.json records ↔ the metrics snapshot
    daemon_tracer = getattr(daemon, "tracer", None)
    if daemon_tracer is not None:
        write(
            "traces.json",
            {
                "spans": [
                    s.to_dict() for s in daemon_tracer.snapshot()
                ],
                "dropped": daemon_tracer.dropped,
                "finished_total": daemon_tracer.finished_total,
                "sample_rate": daemon_tracer.sample_rate,
            },
        )
    # the live performance plane (the same /debug/perf document
    # `cilium-tpu top --once -o json` prints): phase windows, stall
    # + SLO ledgers, the live byte model and the retune history —
    # beside metrics.prom/traces.json so a bundle carries the
    # perf-plane state of the incident, not just the counters
    if hasattr(daemon, "perf_snapshot"):
        try:
            write("perf.json", daemon.perf_snapshot(leaves=True))
        except Exception:  # pragma: no cover — defensive
            pass
    # the /metrics/prometheus text snapshot (same exposition a live
    # scrape sees — label sets join against traces.json/flows.json)
    with open(os.path.join(root, "metrics.prom"), "w") as f:
        f.write(metrics.expose())

    archive = root + ".tar.gz"
    with tarfile.open(archive, "w:gz") as tar:
        tar.add(root, arcname=os.path.basename(root))
    return archive
