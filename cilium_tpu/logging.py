"""Structured, subsystem-scoped logging.

Behavioral analog of /root/reference/pkg/logging (logrus setup with
per-package `subsys` fields, logging/logfields.go's standard field
names, and pluggable sinks — syslog/logstash hooks in the reference,
a JSON-lines handler here):

  * `get_logger(subsys)` returns a logger carrying a `subsys` field,
    the way every reference package does
    `logging.DefaultLogger.WithField(logfields.LogSubsys, ...)`;
  * `with_fields(log, **fields)` returns an adapter that stamps
    structured fields on every record (logrus `WithFields`);
  * `setup(level=..., fmt="text"|"json", stream=...)` configures the
    root framework logger once (SetupLogging, logging.go) — "json"
    emits one JSON object per line with ts/level/subsys/msg plus any
    structured fields, the shape log collectors ingest;
  * standard field names mirror pkg/logging/logfields/logfields.go
    (endpoint id, identity, ipAddr, ...), so grep-ability matches the
    reference's operational docs.

Loggers nest under the "cilium_tpu" root, so `setup()` governs the
whole framework without touching the process root logger (a library
must not hijack the host application's logging config).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict, MutableMapping, Optional, Tuple

ROOT = "cilium_tpu"

# pkg/logging/logfields/logfields.go — the standard structured keys
SUBSYS = "subsys"
ENDPOINT_ID = "endpointID"
IDENTITY = "identity"
IP_ADDR = "ipAddr"
POLICY_REVISION = "policyRevision"
NODE_NAME = "nodeName"
L7_PROTO = "l7proto"
PORT = "port"
PROTOCOL = "protocol"


class _FieldsAdapter(logging.LoggerAdapter):
    """logrus-WithFields analog: merges bound fields into each record
    (they land in `record.fields` for the formatters below)."""

    def process(
        self, msg: str, kwargs: MutableMapping[str, Any]
    ) -> Tuple[str, MutableMapping[str, Any]]:
        extra = dict(kwargs.get("extra") or {})
        fields = dict(self.extra)
        fields.update(extra.pop("fields", {}))
        extra["fields"] = fields
        kwargs["extra"] = extra
        return msg, kwargs


def get_logger(subsys: str) -> logging.LoggerAdapter:
    """Per-subsystem logger with a `subsys` field (the reference's
    per-package `log = logging.DefaultLogger.WithField(subsys, ...)`)."""
    return _FieldsAdapter(
        logging.getLogger(f"{ROOT}.{subsys}"), {SUBSYS: subsys}
    )


def with_fields(
    log: logging.LoggerAdapter, **fields: Any
) -> logging.LoggerAdapter:
    """Bind additional structured fields (logrus WithFields)."""
    merged = dict(log.extra)
    merged.update(fields)
    return _FieldsAdapter(log.logger, merged)


class _TextFormatter(logging.Formatter):
    """level=x subsys=y msg="..." extra fields appended k=v."""

    def format(self, record: logging.LogRecord) -> str:
        fields: Dict[str, Any] = getattr(record, "fields", {})
        parts = [
            f"level={record.levelname.lower()}",
            f'msg="{record.getMessage()}"',
        ]
        for k in sorted(fields):
            parts.append(f"{k}={fields[k]}")
        return " ".join(parts)


class _JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        line = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        line.update(getattr(record, "fields", {}))
        if record.exc_info:
            line["exc"] = self.formatException(record.exc_info)
        return json.dumps(line)


def setup(
    level: int = logging.INFO,
    fmt: str = "text",
    stream=None,
) -> logging.Logger:
    """Configure the framework root logger (idempotent — replaces any
    handler a previous setup() installed).  Returns the root."""
    root = logging.getLogger(ROOT)
    root.setLevel(level)
    root.propagate = False
    for h in list(root.handlers):
        if getattr(h, "_cilium_tpu_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler._cilium_tpu_handler = True
    handler.setFormatter(
        _JSONFormatter() if fmt == "json" else _TextFormatter()
    )
    root.addHandler(handler)
    return root


def set_level(level: int, subsys: Optional[str] = None) -> None:
    """Runtime level change, whole framework or one subsystem (the
    reference's debug toggles flip levels the same way)."""
    name = ROOT if subsys is None else f"{ROOT}.{subsys}"
    logging.getLogger(name).setLevel(level)
