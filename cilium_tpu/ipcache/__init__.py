"""IP/CIDR → security-identity resolution.

Host side (`ipcache.IPCache`) re-designs /root/reference/pkg/ipcache:
source-priority overwrite, endpoint-IP-shadows-CIDR, prefix-length
refcounts, listener fan-out.  Device side (`lpm`) replaces the kernel
LPM trie (bpf/lib/eps.h) with a DIR-24-8 two-level direct table:
longest-prefix match in exactly two gathers per lookup.
"""

from cilium_tpu.ipcache.ipcache import (
    FROM_AGENT_LOCAL,
    FROM_K8S,
    FROM_KVSTORE,
    IPCache,
    IPIdentity,
)
from cilium_tpu.ipcache.lpm import LPMTables, build_lpm, lpm_lookup

__all__ = [
    "IPCache",
    "IPIdentity",
    "FROM_K8S",
    "FROM_KVSTORE",
    "FROM_AGENT_LOCAL",
    "LPMTables",
    "build_lpm",
    "lpm_lookup",
]
