"""Host-side IP↔identity cache.

Behavioral port of /root/reference/pkg/ipcache/ipcache.go:
  - source-priority overwrite rules (allowOverwrite, ipcache.go:183):
    k8s < kvstore < agent-local;
  - endpoint-IP shadows equivalent full-prefix CIDR (Upsert
    ipcache.go:247-289, deleteLocked ipcache.go:372-405): listeners
    never hear about a CIDR mapping hidden behind an endpoint IP, and
    the CIDR mapping is revived when the endpoint IP goes away;
  - per-prefix-length refcounts (the datapath's LPM probe schedule);
  - listener fan-out (OnIPIdentityCacheChange) — the seam the device
    LPM table builder subscribes to (cilium_tpu.ipcache.lpm.LPMBuilder,
    analog of pkg/datapath/ipcache/listener.go:78).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

# ipcache.go:40-51
FROM_K8S = "k8s"
FROM_KVSTORE = "kvstore"
FROM_AGENT_LOCAL = "agent-local"

# Modification kinds passed to listeners (ipcache.go Upsert/Delete).
UPSERT = "upsert"
DELETE = "delete"


@dataclass(frozen=True)
class IPIdentity:
    """ipcache.go:57 Identity{ID, Source}."""

    id: int
    source: str


def allow_overwrite(existing: str, new: str) -> bool:
    """ipcache.go:183."""
    if existing == FROM_K8S:
        return True
    if existing == FROM_KVSTORE:
        return new in (FROM_KVSTORE, FROM_AGENT_LOCAL)
    if existing == FROM_AGENT_LOCAL:
        return new == FROM_AGENT_LOCAL
    return True


def _parse(ip: str):
    """Returns (canonical_cidr_str, is_full_prefix, version, bare_ip).

    Mirrors the reference's net.ParseCIDR-then-ParseIP branching: a
    bare IP is an endpoint IP (full-prefix CIDR equivalent,
    endpointIPToCIDR ipcache.go:196); a "x/len" string is a CIDR.
    """
    if "/" in ip:
        net = ipaddress.ip_network(ip, strict=False)
        full = net.prefixlen == net.max_prefixlen
        return str(net), full, net.version, str(net.network_address), False
    addr = ipaddress.ip_address(ip)
    net = ipaddress.ip_network(ip)
    return str(net), True, addr.version, str(addr), True


# listener signature:
# fn(modification, cidr_str, old_host_ip, new_host_ip, old_id, new_id)
Listener = Callable[[str, str, Optional[str], Optional[str],
                     Optional[int], int], None]


class IPCache:
    """ipcache.go:66 IPCache."""

    def __init__(self) -> None:
        self.ip_to_identity: Dict[str, IPIdentity] = {}
        self.identity_to_ip: Dict[int, Set[str]] = {}
        self.ip_to_host_ip: Dict[str, Optional[str]] = {}
        self.v4_prefix_lengths: Dict[int, int] = {}
        self.v6_prefix_lengths: Dict[int, int] = {}
        self.listeners: List[Listener] = []

    # -- listeners -----------------------------------------------------------

    def add_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)
        self.dump_to_listener(listener)

    def dump_to_listener(self, listener: Listener) -> None:
        """DumpToListenerLocked (ipcache.go:327)."""
        for ip, ident in self.ip_to_identity.items():
            cidr_str, _, _, _, bare = _parse(ip)
            listener(
                UPSERT, cidr_str, None, self.ip_to_host_ip.get(ip),
                None, ident.id,
            )

    def _notify(self, *args) -> None:
        for listener in list(self.listeners):
            listener(*args)

    # -- upsert (ipcache.go:217) --------------------------------------------

    def upsert(
        self,
        ip: str,
        new_identity: IPIdentity,
        host_ip: Optional[str] = None,
    ) -> bool:
        cidr_str, full, version, bare_ip, is_bare = _parse(ip)
        old_host_ip = self.ip_to_host_ip.get(ip)
        callback = True
        old_identity: Optional[int] = None

        cached = self.ip_to_identity.get(ip)
        if cached is not None:
            if not allow_overwrite(cached.source, new_identity.source):
                return False
            if cached == new_identity and old_host_ip == host_ip:
                return True
            old_identity = cached.id

        if not is_bare:
            # CIDR form: count the prefix length.
            net = ipaddress.ip_network(ip, strict=False)
            lengths = (
                self.v4_prefix_lengths
                if version == 4
                else self.v6_prefix_lengths
            )
            lengths[net.prefixlen] = lengths.get(net.prefixlen, 0) + 1
            if full and bare_ip in self.ip_to_identity:
                # Full-prefix CIDR shadowed by an endpoint IP
                # (ipcache.go:258-265): update the cache, don't tell
                # the listeners.
                callback = False
        else:
            # Endpoint IP: does it start shadowing an equivalent CIDR?
            if cached is None:
                cidr_ident = self.ip_to_identity.get(cidr_str)
                if cidr_ident is not None and cidr_str != ip:
                    cidr_host = self.ip_to_host_ip.get(cidr_str)
                    old_host_ip = cidr_host
                    if (
                        cidr_ident.id != new_identity.id
                        or cidr_host != host_ip
                    ):
                        old_identity = cidr_ident.id
                    else:
                        callback = False

        if cached is not None:
            ips = self.identity_to_ip.get(cached.id)
            if ips is not None:
                ips.discard(ip)
                if not ips:
                    del self.identity_to_ip[cached.id]
        self.ip_to_identity[ip] = new_identity
        self.identity_to_ip.setdefault(new_identity.id, set()).add(ip)
        if host_ip is None:
            self.ip_to_host_ip.pop(ip, None)
        else:
            self.ip_to_host_ip[ip] = host_ip

        if callback:
            self._notify(
                UPSERT, cidr_str, old_host_ip, host_ip,
                old_identity, new_identity.id,
            )
        return True

    # -- delete (ipcache.go:340 deleteLocked) -------------------------------

    def delete(self, ip: str) -> None:
        cached = self.ip_to_identity.get(ip)
        if cached is None:
            return

        cidr_str, full, version, bare_ip, is_bare = _parse(ip)
        modification = DELETE
        old_host_ip = self.ip_to_host_ip.get(ip)
        new_host_ip: Optional[str] = None
        old_identity: Optional[int] = None
        new_identity = cached
        callback = True

        if not is_bare:
            net = ipaddress.ip_network(ip, strict=False)
            lengths = (
                self.v4_prefix_lengths
                if version == 4
                else self.v6_prefix_lengths
            )
            cnt = lengths.get(net.prefixlen, 0)
            if cnt <= 1:
                lengths.pop(net.prefixlen, None)
            else:
                lengths[net.prefixlen] = cnt - 1
            # CIDR shadowed by an endpoint IP: listeners never knew.
            # NB: the reference checks the network address for ANY
            # prefix length here (deleteLocked ipcache.go:376 has no
            # ones==bits guard, unlike Upsert) — reproduced as-is.
            if bare_ip in self.ip_to_identity and bare_ip != ip:
                callback = False
        else:
            # Was this endpoint IP shadowing an equivalent CIDR?
            cidr_ident = self.ip_to_identity.get(cidr_str)
            if cidr_ident is not None and cidr_str != ip:
                new_host_ip = self.ip_to_host_ip.get(cidr_str)
                if cidr_ident.id != cached.id or old_host_ip != new_host_ip:
                    # Revive the CIDR mapping (ipcache.go:393-399).
                    modification = UPSERT
                    old_identity = cached.id
                    new_identity = cidr_ident
                else:
                    callback = False

        del self.ip_to_identity[ip]
        ips = self.identity_to_ip.get(cached.id)
        if ips is not None:
            ips.discard(ip)
            if not ips:
                del self.identity_to_ip[cached.id]
        self.ip_to_host_ip.pop(ip, None)

        if callback:
            self._notify(
                modification, cidr_str, old_host_ip, new_host_ip,
                old_identity, new_identity.id,
            )

    # -- lookups (ipcache.go:438-489) ---------------------------------------

    def lookup_by_ip(self, ip: str) -> Tuple[Optional[IPIdentity], bool]:
        ident = self.ip_to_identity.get(ip)
        return ident, ident is not None

    def lookup_by_prefix(self, prefix: str) -> Tuple[Optional[IPIdentity], bool]:
        """Full prefixes also try the bare endpoint IP first
        (LookupByPrefixRLocked ipcache.go:458)."""
        if "/" in prefix:
            net = ipaddress.ip_network(prefix, strict=False)
            if net.prefixlen == net.max_prefixlen:
                ident = self.ip_to_identity.get(str(net.network_address))
                if ident is not None:
                    return ident, True
        ident = self.ip_to_identity.get(prefix)
        return ident, ident is not None

    def lookup_by_identity(self, num_id: int) -> Tuple[Optional[Set[str]], bool]:
        ips = self.identity_to_ip.get(num_id)
        return ips, ips is not None
