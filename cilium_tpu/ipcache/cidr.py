"""CIDR → local identity allocation.

Behavioral port of /root/reference/pkg/ipcache/cidr.go (AllocateCIDRs
cidr.go:29, ReleaseCIDRs cidr.go:58) and
pkg/identity/cidr/identity.go (AllocateCIDRIdentities): every CIDR
referenced by policy gets a *local* identity (never published to the
cluster store, allocator.go:112) labeled with its full prefix ladder
(labels.get_cidr_labels), and an ipcache mapping so the datapath can
resolve flows hitting that prefix.
"""

from __future__ import annotations

import ipaddress
from typing import Iterable, List, Tuple

from cilium_tpu import labels as lbl
from cilium_tpu.identity import Identity, IdentityAllocator
from cilium_tpu.ipcache.ipcache import FROM_AGENT_LOCAL, IPCache, IPIdentity
from cilium_tpu.labels import Labels


def allocate_cidr_identities(
    allocator: IdentityAllocator, prefixes: Iterable[str]
) -> List[Identity]:
    """identity/cidr/identity.go:32 — one local identity per prefix,
    keyed by the CIDR label set."""
    out = []
    for prefix in prefixes:
        net = ipaddress.ip_network(prefix, strict=False)
        arr = lbl.get_cidr_labels(net)
        labels_map = Labels({l.key: l for l in arr})
        ident, _ = allocator.allocate(labels_map, local_only=True)
        out.append(ident)
    return out


def allocate_cidrs(
    ipcache: IPCache,
    allocator: IdentityAllocator,
    prefixes: Iterable[str],
) -> List[Identity]:
    """ipcache/cidr.go:29 AllocateCIDRs: labels→ID mappings, then
    CIDR→ID ipcache mappings (kvstore upsert in the reference; local
    upsert here — the kvstore layer replays it cluster-wide)."""
    prefixes = list(prefixes)
    identities = allocate_cidr_identities(allocator, prefixes)
    for prefix, ident in zip(prefixes, identities):
        net = ipaddress.ip_network(prefix, strict=False)
        ipcache.upsert(str(net), IPIdentity(ident.id, FROM_AGENT_LOCAL))
    return identities


def release_cidrs(
    ipcache: IPCache,
    allocator: IdentityAllocator,
    prefixes: Iterable[str],
) -> None:
    """ipcache/cidr.go:58 ReleaseCIDRs."""
    for prefix in prefixes:
        net = ipaddress.ip_network(prefix, strict=False)
        arr = lbl.get_cidr_labels(net)
        labels_map = Labels({l.key: l for l in arr})
        ident = allocator.lookup_by_labels(labels_map)
        if ident is None:
            continue
        if allocator.release(ident):
            ipcache.delete(str(net))
