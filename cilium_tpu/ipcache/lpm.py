"""Device longest-prefix-match: DIR-24-8 two-level direct tables.

TPU-first replacement for the kernel's `cilium_ipcache` LPM trie
(bpf/lib/eps.h:70 ipcache_lookup4; unrolled fallback eps.h:86-108).
Instead of a trie walk or a per-prefix-length probe loop (bounded at
40 lengths, rule_validation.go:29), the classic DIR-24-8 router layout
gives LPM in exactly TWO gathers per lookup:

  l1  u32 [2^24]       indexed by ip >> 8:
                         bit31 clear → identity for all of ip>>8
                         bit31 set   → block index into l2
  l2  u32 [blocks, 256] indexed by (block, ip & 0xFF) → identity

Identity 0 (IdentityUnknown) marks "no entry", matching the datapath's
WORLD_ID fallback decision happening elsewhere (bpf_netdev.c derives
identity, defaulting to world when the ipcache misses).

Build is host-side NumPy range-painting, shortest prefix first, so
longer prefixes overwrite — exactly longest-match semantics.  IPv6
uses the same structure on the top 24 bits of a host-side-hashed /64?
No: IPv6 is resolved host-side for now (the reference's LPM map is
v4+v6; v6 flow volume is the minority path) — device v6 tables are a
TODO tracked in SURVEY §7.

The `LPMBuilder` listener subscribes to the host IPCache and mirrors
pkg/datapath/ipcache/listener.go:78 (BPFListener): it accumulates the
listener-visible mappings and lowers them to device tables on flush.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

L1_BITS = 24
L1_SIZE = 1 << L1_BITS
BLOCK_FLAG = np.uint32(1 << 31)
# ipcache.go:36 MaxEntries — table capacity envelope of the reference.
MAX_ENTRIES = 512_000


@dataclass
class LPMTables:
    """Device-resident DIR-24-8 tables (pytree)."""

    l1: np.ndarray  # u32 [2^24]
    l2: np.ndarray  # u32 [n_blocks, 256]

    def tree_flatten(self):
        return ((self.l1, self.l2), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            LPMTables,
            lambda t: t.tree_flatten(),
            lambda aux, ch: LPMTables.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def build_lpm(prefix_to_id: Dict[str, int]) -> LPMTables:
    """Lower {ipv4 cidr string → identity} to DIR-24-8 tables.

    Prefixes are painted shortest-first; each /24 cell that contains a
    >24-bit prefix is expanded into a 256-entry L2 block seeded with
    the best ≤24-bit cover.
    """
    parsed = []
    for cidr, num_id in prefix_to_id.items():
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4:
            continue  # v6 resolved host-side (module docstring)
        if num_id >= 1 << 31:
            raise ValueError(f"identity {num_id} exceeds 31-bit LPM range")
        parsed.append((net.prefixlen, int(net.network_address), num_id))
    parsed.sort()

    l1 = np.zeros(L1_SIZE, dtype=np.uint32)
    blocks = []  # list of np.ndarray(256, u32)
    block_of_cell: Dict[int, int] = {}

    for plen, base, num_id in parsed:
        if plen <= L1_BITS:
            lo = base >> (32 - L1_BITS)
            span = 1 << (L1_BITS - plen)
            cells = np.arange(lo, lo + span)
            # Paint plain cells; descend into already-expanded blocks.
            ptr_mask = (l1[cells] & BLOCK_FLAG) != 0
            l1[cells[~ptr_mask]] = num_id
            for cell in cells[ptr_mask]:
                blocks[int(l1[cell] & ~BLOCK_FLAG)][:] = num_id
        else:
            cell = base >> 8
            bi = block_of_cell.get(cell)
            if bi is None:
                bi = len(blocks)
                seed = l1[cell]
                if seed & BLOCK_FLAG:
                    raise AssertionError("cell already a block")
                blocks.append(np.full(256, seed, dtype=np.uint32))
                block_of_cell[cell] = bi
                l1[cell] = BLOCK_FLAG | np.uint32(bi)
            lo = base & 0xFF
            span = 1 << (32 - plen)
            blocks[bi][lo : lo + span] = num_id

    l2 = (
        np.stack(blocks)
        if blocks
        else np.zeros((1, 256), dtype=np.uint32)
    )
    return LPMTables(l1=l1, l2=l2)


def _lookup_kernel(tables: LPMTables, ips):
    import jax.numpy as jnp

    v1 = tables.l1[(ips >> 8).astype(jnp.int32)]
    is_block = (v1 & BLOCK_FLAG) != 0
    block = jnp.where(is_block, v1 & ~BLOCK_FLAG, 0).astype(jnp.int32)
    v2 = tables.l2[block, (ips & 0xFF).astype(jnp.int32)]
    return jnp.where(is_block, v2, v1)


def lpm_lookup(tables: LPMTables, ips) -> "jax.Array":
    """Batched IPv4 → identity (u32; 0 = no entry).  Two gathers."""
    import jax

    return jax.jit(_lookup_kernel)(tables, ips)


def lookup_host(prefix_to_id: Dict[str, int], ip: str) -> int:
    """Host reference LPM (the oracle for build_lpm/lpm_lookup)."""
    addr = ipaddress.ip_address(ip)
    best_len, best_id = -1, 0
    for cidr, num_id in prefix_to_id.items():
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != addr.version:
            continue
        if addr in net and net.prefixlen > best_len:
            best_len, best_id = net.prefixlen, num_id
    return best_id


class LPMBuilder:
    """IPCache listener accumulating the listener-visible CIDR→identity
    view and lowering it to device tables — the analog of the
    BPFListener keeping `cilium_ipcache` in sync
    (pkg/datapath/ipcache/listener.go:78)."""

    def __init__(self) -> None:
        self.mappings: Dict[str, int] = {}
        self._dirty = True
        self._tables: Optional[LPMTables] = None

    def __call__(
        self,
        modification: str,
        cidr: str,
        old_host_ip,
        new_host_ip,
        old_id,
        new_id: int,
    ) -> None:
        if modification == "upsert":
            self.mappings[cidr] = new_id
        else:
            self.mappings.pop(cidr, None)
        self._dirty = True

    def tables(self) -> LPMTables:
        if self._dirty or self._tables is None:
            self._tables = build_lpm(self.mappings)
            self._dirty = False
        return self._tables
